"""Serving-fleet router: health-aware load balancing, hedged failover,
canary-gated delta checkpoint distribution (docs/SERVING.md "serving
fleet").

The router speaks the SAME ``dsgd.Serving`` service as a replica
(rpc/service.py ``_SERVE_METHODS``), so clients — and kube Services —
cannot tell one node from a fleet:

- **Predict** routes to one of N shared-nothing replicas by
  power-of-two-choices: sample two eligible replicas, send to the one
  with the lower ``EWMA latency x (1 + in-flight)`` score.  Eligible =
  last ``ServeHealth`` ok AND per-replica circuit breaker not suppressing
  (reusing rpc/service.py ``RpcPolicy``/``CircuitBreaker`` — the PR-4
  control-plane policy).  A failed call fails over to the next-best
  replica (the client sees ONE answer or one typed error, never a
  dropped request); with ``hedge_ms`` set, a reply slower than the hedge
  deadline additionally races a duplicate on the next-best replica and
  the first success wins — the in-flight tail of a dying replica drains
  onto the rest of the fleet.
- **PushWeights** is the fleet's checkpoint-distribution entry point: the
  trainer's master streams versioned weight updates (full tensor or the
  sparse absolute-value ``WeightDelta`` codec the sync broadcast plane
  uses, rpc/codec.py) to the ROUTER, which fans them out — through its
  canary gate when configured.  A new version lands on the first
  ``ceil(canary_fraction x N)`` replicas only; the router then evaluates
  the held-out probe set against a canary replica and compares the probe
  loss to the promoted baseline (core/loss_check.py ``LossChecker``
  best-loss tracking, the HealthMonitor's ratio-x-best rule).  Pass ->
  the push fans out to the rest and the version is PROMOTED; regression
  -> the canaries are rolled back to the promoted weights, the version
  is rejected (re-pushes NACK), and ``router.canary.rollback`` counts it.
- **ServeHealth** aggregates the fleet (ok = any replica serving);
  **Metrics** snapshots the router's own registry, and an optional
  telemetry endpoint re-exports every replica's registry — scraped over
  their ``Metrics`` RPC — as ONE merged /metrics exposition
  (telemetry/aggregate.py), so per-replica QPS / latency quantiles /
  ``serve.model.version`` land on a single page.

Wired into main.py as ``DSGD_ROLE=route``; knobs in config.py
(``DSGD_SERVE_TARGETS`` etc.); in-process fleet harness in
serving/fleet.py.
"""

from __future__ import annotations

import json
import logging
import math
import os
import random
import threading
import time
from typing import List, Optional, Sequence, Tuple

import grpc
import numpy as np

from distributed_sgd_tpu.rpc import codec
from distributed_sgd_tpu.rpc import dsgd_pb2 as pb
from distributed_sgd_tpu.rpc.service import (
    RpcPolicy,
    ServeStub,
    add_serve_servicer,
    new_channel,
    new_server,
)
from distributed_sgd_tpu.trace import flight
from distributed_sgd_tpu.utils import measure
from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.serving")

# gRPC codes that are the CALLER's fault (or backpressure), not the
# replica's: they never feed the replica's circuit breaker, and
# INVALID_ARGUMENT is not even worth a failover (every replica serves the
# same model dimension).
_NOT_PEER_FAILURE = frozenset({
    grpc.StatusCode.INVALID_ARGUMENT,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
})


class _Replica:
    """One backend's routing state: stub + EWMA latency + in-flight count
    + health + the shared per-peer breaker."""

    EWMA_ALPHA = 0.2  # same smoothing family as core/master._LatencyEwma

    def __init__(self, host: str, port: int, policy: RpcPolicy):
        self.host, self.port = host, int(port)
        self.key = (host, int(port))
        self.channel = new_channel(host, int(port))
        self.stub = ServeStub(self.channel)
        self.breaker = policy.breaker(self.key)
        # optimistic prior: an unmeasured replica must be pickable, and a
        # small prior latency lets the first real measurements dominate
        self.ewma_s = 0.010
        self.inflight = 0
        self._lock = threading.Lock()
        # healthy only after a ServeHealth returns ok=True — the router
        # never routes to a replica it has not seen alive
        self.healthy = False
        self.model_step = 0

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def score(self) -> float:
        """Power-of-two-choices score: lower is better.  EWMA latency
        weighted by the in-flight count, so a slow replica AND a busy
        replica both lose the coin flip."""
        return self.ewma_s * (1.0 + self.inflight)

    def begin(self) -> None:
        with self._lock:
            self.inflight += 1

    def end(self) -> None:
        with self._lock:
            self.inflight -= 1

    def ok(self, latency_s: float) -> None:
        self.ewma_s += self.EWMA_ALPHA * (latency_s - self.ewma_s)
        self.breaker.record_ok()

    def close(self) -> None:
        self.channel.close()


def load_probe(path: str) -> List[Tuple[np.ndarray, np.ndarray, float]]:
    """Load a canary probe set from an .npz of padded 2-D `indices` /
    `values` plus 1-D `labels`; zero-VALUE cells are padding (the same
    inert-pad convention as serving/bucketing.py) and are stripped per
    row.  Returns the [(indices, values, label)] rows the router wants."""
    with np.load(path) as z:
        idx, val, y = z["indices"], z["values"], z["labels"]
    rows = []
    for i in range(len(y)):
        nz = val[i] != 0
        rows.append((np.asarray(idx[i][nz], np.int32),
                     np.asarray(val[i][nz], np.float32), float(y[i])))
    return rows


def probe_from_dataset(data, n: int = 64) -> List[Tuple[np.ndarray, np.ndarray, float]]:
    """First `n` rows of a Dataset as probe rows (held-out split — the
    canary baseline must not be the training data)."""
    rows = []
    for i in range(min(n, len(data))):
        idx, val = data.indices[i], data.values[i]
        nz = val != 0
        rows.append((np.asarray(idx[nz], np.int32),
                     np.asarray(val[nz], np.float32), float(data.labels[i])))
    return rows


class ServingRouter:
    """N-replica Predict router + canary-gated PushWeights fan-out."""

    # canary regression rule (the HealthMonitor/parity-gate family): the
    # probe loss of a new version regresses when it exceeds
    # max(ratio * best, best + abs_floor) — the absolute floor keeps the
    # relative bound meaningful near zero loss (docs/COMPRESSION.md).
    CANARY_ABS_FLOOR = 0.02

    def __init__(
        self,
        replicas: Sequence[Tuple[str, int]],
        port: int = 0,
        host: str = "0.0.0.0",
        model: str = "hinge",
        lam: float = 1e-5,
        canary_fraction: float = 0.0,
        canary_ratio: float = 1.05,
        probe: Optional[Sequence[Tuple[np.ndarray, np.ndarray, float]]] = None,
        hedge_ms: float = 0.0,
        health_s: float = 1.0,
        request_timeout_s: float = 30.0,
        policy: Optional[RpcPolicy] = None,
        metrics=None,
        telemetry_port: Optional[int] = None,
        seed: int = 0,
        state_path: Optional[str] = None,
        probe_path: Optional[str] = None,
        probe_refresh_s: float = 0.0,
        probe_source=None,
        probe_source_refresh_s: float = 0.0,
    ):
        if not replicas:
            raise ValueError("a router needs at least one replica endpoint")
        if probe_refresh_s > 0 and not probe_path:
            raise ValueError(
                "probe_refresh_s needs probe_path: the refresh re-reads "
                "the probe file on its cadence")
        if probe_source_refresh_s > 0 and probe_source is None:
            raise ValueError(
                "probe_source_refresh_s needs probe_source: the cadence "
                "rotates the traffic reservoir into the probe set")
        if probe_source is not None and probe_refresh_s > 0:
            raise ValueError(
                "probe_source and probe_refresh_s are mutually exclusive: "
                "the reservoir REPLACES the operator-rotated probe file — "
                "two refresh feeds would fight over the canary baseline")
        if not 0.0 <= canary_fraction <= 1.0:
            raise ValueError("canary_fraction must be in [0, 1]")
        if metrics is None:
            # fresh per-router registry, NOT the process global: an HA pair
            # runs two routers in one process (tests, fleet drills), and
            # two ClusterExporters re-exporting one shared registry would
            # double-count every counter under BOTH `route:<port>` node
            # labels on the cluster /metrics page.  Same isolation the
            # serve:<port> replicas got in the fleet runner — the route
            # role was the one gap (ISSUE 20).
            metrics = metrics_mod.Metrics()
        self.metrics = metrics
        self._policy = policy or RpcPolicy(seed=seed, metrics=metrics)
        self._replicas = [_Replica(h, p, self._policy) for h, p in replicas]
        self._rng = random.Random(seed)
        self._timeout = float(request_timeout_s)
        self._hedge_s = max(0.0, float(hedge_ms)) / 1000.0
        self.health_s = float(health_s)
        self._stop = threading.Event()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="route-health")

        # -- canary state (all under _push_lock) ---------------------------
        # RLock: the HA coordinator's lease refresh can detect a failover
        # while a PushWeights verdict (which holds this lock) asks
        # is_decider(), and the assume-lease re-pin then re-enters
        self._push_lock = threading.RLock()
        self.canary_fraction = float(canary_fraction)
        self.canary_ratio = float(canary_ratio)
        self._probe = list(probe) if probe else None
        # probe-set refresh (ROADMAP 3c, DSGD_SERVE_PROBE_REFRESH_S): with
        # a cadence > 0 the health loop re-reads `probe_path` every
        # refresh period (mtime-gated — an untouched file costs a stat)
        # and rotates the fresh held-out rows in through refresh_probe(),
        # re-anchoring the canary baseline on the PROMOTED version's loss
        # over the new rows.  0 (default): fixed probe set, byte-identical
        # canary behavior.
        self._probe_path = probe_path
        self._probe_refresh_s = max(0.0, float(probe_refresh_s))
        self._probe_mtime: Optional[float] = None
        self._probe_next_check = 0.0
        if probe_path:
            try:
                self._probe_mtime = os.path.getmtime(probe_path)
            except OSError:
                self._probe_mtime = None
        # live probe sourcing (autopilot/probe_source.py, DSGD_AUTOPILOT):
        # with a reservoir attached, every routed Predict feeds it, and
        # the health loop rotates the sampled rows in through
        # refresh_probe() on its own cadence — each rotation re-probes the
        # PROMOTED version on traffic sampled just now, so the refresh
        # loss series (probe_losses()) is the drift signal the autopilot
        # controller watches.  None (default): the Predict path is
        # untouched and no series accumulates.
        self._probe_source = probe_source
        self._probe_source_refresh_s = max(0.0, float(probe_source_refresh_s))
        self._source_next_check = 0.0
        self._probe_loss_hist: List[float] = []
        self._model_name, self._lam = model, float(lam)
        self._probe_model = None  # built lazily (losses_from_margins only)
        self._promoted_version: Optional[int] = None
        self._w_promoted: Optional[np.ndarray] = None
        self._rejected: set = set()
        # probe-loss baseline across promoted versions: LossChecker's
        # best-loss tracking (core/loss_check.py), leaky=1.0 — each
        # version is judged on its RAW probe loss against the best ever
        from distributed_sgd_tpu.core.loss_check import LossChecker

        self._checker = LossChecker(leaky_loss=1.0)
        # promoted-state persistence (ROADMAP 3b, DSGD_SERVE_STATE): a
        # JSON sidecar rewritten atomically on every promote/rollback.  A
        # RESTARTED router restores the promoted version, the probe-loss
        # baseline, and the rejected set — so when the distributor
        # re-streams the already-promoted version it RE-PINS it (ungated
        # fan-out) instead of re-canarying it, and an already-rejected
        # version stays rejected.  None (default): in-memory only.
        self._state_path = state_path
        # serving-plane HA (serving/ha.py, DSGD_SERVE_HA): the sidecar is
        # a VERSIONED record — `seq` numbers every promote/rollback/
        # baseline transition monotonically, so two LIVE routers (and a
        # rejoining one) can totally order their records and the higher
        # seq wins every exchange.  Only the decider lease holder bumps
        # seq (single-writer counter); the mirror's record advances by
        # ADOPTING the decider's over SyncServeState.  _ha is the
        # attached HACoordinator (None = HA off, nothing here runs);
        # _ha_pending caches the weights of a push this router DEFERRED
        # as non-decider, so the peer-synced promotion can pin them.
        self._state_seq = 0
        self._ha = None
        self._ha_pending: Optional[Tuple[int, np.ndarray]] = None
        self._restore_state()

        self._server = new_server(port, host=host)
        add_serve_servicer(self._server, self,
                           node=f"route:{self._server.bound_port}")
        self._node = f"route:{self._server.bound_port}"

        # optional fleet telemetry endpoint: replicas' registries scraped
        # over their Metrics RPC, merged with the router's own
        # (telemetry/aggregate.py semantics — per-replica labels, exact
        # cluster bucket sums)
        self.telemetry = None
        self.telemetry_exporter = None
        if telemetry_port is not None:
            from distributed_sgd_tpu.telemetry.aggregate import (
                ClusterExporter,
                ClusterTelemetry,
            )

            self.telemetry = ClusterTelemetry(
                self.metrics, node=self._node, role="route")
            members = [(r.key, r.stub) for r in self._replicas]
            self.telemetry_exporter = ClusterExporter(
                self.telemetry.prometheus_text, telemetry_port,
                refresh=lambda: self.telemetry.scrape(
                    members, self._policy, min_age_s=0.5))

    # -- replica selection ---------------------------------------------------

    def _eligible(self, exclude: Sequence["_Replica"] = ()) -> List["_Replica"]:
        return [
            r for r in self._replicas
            if r not in exclude and r.healthy and not r.breaker.suppressed()
        ]

    def _pick(self, exclude: Sequence["_Replica"] = ()) -> Optional["_Replica"]:
        """Power-of-two-choices over the eligible set; falls back to ANY
        non-excluded replica when the eligible set is empty (a request in
        hand beats a perfect rotation — the call itself is the probe)."""
        pool = self._eligible(exclude)
        if not pool:
            pool = [r for r in self._replicas if r not in exclude]
        if not pool:
            return None
        if len(pool) == 1:
            return pool[0]
        a, b = self._rng.sample(pool, 2)
        return a if a.score() <= b.score() else b

    # -- the data plane ------------------------------------------------------

    def Predict(self, request, context):  # noqa: N802 - gRPC method name
        if self._probe_source is not None:
            # feed the probe reservoir from live traffic.  Canary probe
            # evaluations go straight to replica stubs (_probe_loss), not
            # through this handler, so the probe set never samples itself.
            try:
                self._probe_source.observe(
                    np.asarray(request.indices, np.int32),
                    np.asarray(request.values, np.float32))
            except Exception as e:  # noqa: BLE001 - sampling must not drop a request
                log.warning("probe-source observe failed: %s", e)
        tried: List[_Replica] = []
        last: Optional[grpc.RpcError] = None
        with measure.span("route.predict", metrics=self.metrics, root=False):
            for _attempt in range(len(self._replicas)):
                r = self._pick(exclude=tried)
                if r is None:
                    break
                try:
                    return self._call_predict(r, request)
                except grpc.RpcError as e:
                    if e.code() == grpc.StatusCode.INVALID_ARGUMENT:
                        # caller error: every replica would reject it too
                        context.abort(e.code(), e.details())
                    last = e
                    tried.append(r)
                    self.metrics.counter(metrics_mod.ROUTER_RETRIES).increment()
        if last is not None:
            context.abort(last.code() or grpc.StatusCode.UNAVAILABLE,
                          f"all replicas failed; last: {last.details()}")
        context.abort(grpc.StatusCode.UNAVAILABLE,
                      "no serving replica available")

    def _call_predict(self, r: _Replica, request):
        """One routed attempt, hedged past the tail when configured.
        Raises grpc.RpcError on failure (the failover loop owns retries);
        feeds the replica's breaker and latency EWMA."""
        t0 = time.perf_counter()
        r.begin()
        hedge: Optional[Tuple[_Replica, object]] = None
        try:
            fut = r.stub.Predict.future(request, timeout=self._timeout)
            if self._hedge_s > 0:
                try:
                    reply = fut.result(timeout=self._hedge_s)
                    r.ok(time.perf_counter() - t0)
                    return reply
                except grpc.FutureTimeoutError:
                    h = self._pick(exclude=(r,))
                    hfut = None
                    t_hedge = time.perf_counter()
                    if h is not None:
                        h.begin()
                        hedge = (h, hfut)  # end() in finally even if
                        try:               # the future never constructs
                            hfut = h.stub.Predict.future(
                                request, timeout=self._timeout)
                        except Exception:  # noqa: BLE001 - channel closed
                            hfut = None
                    if hfut is not None:
                        self.metrics.counter(
                            metrics_mod.ROUTER_HEDGES).increment()
                        winner, reply = self._race([(r, fut), (h, hfut)])
                        # each attempt's EWMA sees ITS OWN latency: a
                        # winning hedge charged from the primary's start
                        # would inflate the fast replica by hedge_ms and
                        # steer p2c away from it
                        winner.ok(time.perf_counter()
                                  - (t_hedge if winner is h else t0))
                        if winner is h:
                            self.metrics.counter(
                                metrics_mod.ROUTER_HEDGE_WINS).increment()
                        return reply
            reply = fut.result()  # raises the RpcError on failure
            r.ok(time.perf_counter() - t0)
            return reply
        except grpc.RpcError as e:
            if e.code() not in _NOT_PEER_FAILURE:
                r.breaker.record_failure()
            raise
        finally:
            r.end()
            if hedge is not None:
                hedge[0].end()

    @staticmethod
    def _race(pairs):
        """(winner, reply) of the first future to SUCCEED; the loser is
        cancelled.  When every future fails, re-raises the PRIMARY's
        error (pairs[0]) — the failover loop then excludes the primary."""
        ev = threading.Event()
        for _rep, f in pairs:
            f.add_done_callback(lambda _f: ev.set())
        while True:
            done = [(rep, f) for rep, f in pairs if f.done()]
            for rep, f in done:
                if not f.cancelled() and f.exception() is None:
                    for _rep2, f2 in pairs:
                        if f2 is not f:
                            f2.cancel()
                    return rep, f.result()
            if len(done) == len(pairs):
                raise pairs[0][1].exception()
            ev.wait(0.05)
            ev.clear()

    # -- health / draining ---------------------------------------------------

    def _health_pass(self) -> None:
        for r in self._replicas:
            try:
                h = r.stub.ServeHealth(
                    pb.Empty(), timeout=min(self._policy.deadline_s,
                                            max(self.health_s, 0.1)))
                now_ok = bool(h.ok)
                r.model_step = int(h.model_step)
                r.breaker.record_ok()
            except grpc.RpcError:
                now_ok = False
                r.breaker.record_failure()
            if r.healthy and not now_ok:
                # drain: no NEW picks route here; in-flight calls finish
                # (or fail over), so the drain drops zero requests
                self.metrics.counter(metrics_mod.ROUTER_DRAINED).increment()
                flight.record("router.replica.drained", peer=r.endpoint)
                log.warning("replica %s drained (health failed or not ready)",
                            r.endpoint)
            r.healthy = now_ok
        self.metrics.gauge(metrics_mod.ROUTER_ELIGIBLE).set(
            len(self._eligible()))

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_s):
            self._health_pass()
            if self._probe_refresh_s > 0:
                self._maybe_refresh_probe()
            if self._probe_source is not None and self._probe_source_refresh_s > 0:
                self._maybe_refresh_from_source()

    # -- canary probe-set refresh (ROADMAP 3c; docs/SERVING.md) --------------

    def refresh_probe(self, rows) -> None:
        """Rotate a fresh held-out probe set in and re-anchor the canary
        baseline (DSGD_SERVE_PROBE_REFRESH_S, docs/SERVING.md).

        The old baseline was measured on the OLD rows — comparing a new
        version's loss on the new rows against it would gate against an
        apples-to-oranges number, so the PROMOTED version is re-evaluated
        on the new rows (through the eligible replicas, exactly the
        canary probe path) and becomes the new baseline via
        `LossChecker.refresh`.  If the promoted version cannot be probed
        right now (no replica answered), the checker goes baseline-less
        and the next canary pass seeds it — a long-running fleet's gate
        tracks live traffic instead of fossilizing on the rows it started
        with.  Rejected versions STAY rejected: rejection was a verdict
        against the fleet state at the time, and un-rejecting on a probe
        rotation would re-open every previously failed version at once."""
        rows = list(rows)
        if not rows:
            raise ValueError("refresh_probe needs a non-empty probe set")
        with self._push_lock:
            self._probe = rows
            loss = None
            if self._promoted_version is not None:
                loss = self._probe_loss(self._eligible() or self._replicas,
                                        self._promoted_version)
            self._checker.refresh(best_loss=loss)
            if loss is not None and np.isfinite(loss):
                self.metrics.gauge(metrics_mod.ROUTER_CANARY_LOSS).set(loss)
            if loss is not None:
                # the refresh-loss series: promoted version vs the rows
                # live traffic produced NOW — the autopilot drift signal
                self._probe_loss_hist.append(float(loss))
            self.metrics.counter(
                metrics_mod.ROUTER_PROBE_REFRESH).increment()
            self._state_transition()
            self._persist_state()
        log.info(
            "canary probe set refreshed (%d rows): baseline re-anchored to "
            "%s", len(rows),
            f"promoted v{self._promoted_version} loss {loss:.6f}"
            if loss is not None else "none (next canary pass seeds it)")

    def _maybe_refresh_probe(self) -> None:
        """Health-loop tick: re-read `probe_path` once per refresh period,
        rotating it in only when the file actually changed (mtime)."""
        now = time.monotonic()
        if now < self._probe_next_check:
            return
        self._probe_next_check = now + self._probe_refresh_s
        try:
            mtime = os.path.getmtime(self._probe_path)
        except OSError:
            return  # rotated away mid-write / not there yet: next period
        if self._probe_mtime is not None and mtime <= self._probe_mtime:
            return
        # record the mtime up front so a persistently bad file is warned
        # about ONCE per rewrite, not re-parsed and re-warned every period
        self._probe_mtime = mtime
        try:
            rows = load_probe(self._probe_path)
            self.refresh_probe(rows)
        except Exception as e:  # noqa: BLE001 - a bad file must not kill health
            log.warning("probe refresh from %s failed: %s",
                        self._probe_path, e)

    def _maybe_refresh_from_source(self) -> None:
        """Health-loop tick: once per source-refresh period, rotate the
        traffic reservoir's current sample in as the probe set.  Unlike
        the file feed there is no mtime to gate on — the reservoir
        evolves with every request — so every period with a ready
        (min-fill reached) reservoir refreshes, and each refresh
        re-probes the promoted version on just-sampled traffic: the
        probe-loss series the autopilot controller reads."""
        now = time.monotonic()
        if now < self._source_next_check:
            return
        self._source_next_check = now + self._probe_source_refresh_s
        if not self._probe_source.ready():
            return
        rows = self._probe_source.rows()
        try:
            self.refresh_probe(rows)
        except Exception as e:  # noqa: BLE001 - a bad refresh must not kill health
            log.warning("probe refresh from traffic reservoir failed: %s", e)
            return
        self.metrics.counter(metrics_mod.ROUTER_PROBE_SOURCED).increment()
        self.metrics.gauge(metrics_mod.ROUTER_PROBE_FILL).set(
            self._probe_source.fill)

    # -- the autopilot's read side (docs/CONTINUAL.md) -----------------------

    def probe_losses(self) -> List[float]:
        """The probe-refresh loss series, oldest first: the promoted
        version's loss on each successive probe rotation.  Floats only,
        appended once per refresh — bounded by process lifetime at the
        refresh cadence, read by AutopilotController."""
        with self._push_lock:
            return list(self._probe_loss_hist)

    @property
    def promoted_version(self) -> Optional[int]:
        return self._promoted_version

    # -- checkpoint distribution + canary (PushWeights) ----------------------

    def _canary_count(self) -> int:
        if self.canary_fraction <= 0 or self._probe is None:
            return 0
        return min(len(self._replicas),
                   max(1, math.ceil(self.canary_fraction * len(self._replicas))))

    def _resolve_weights(self, request) -> Optional[np.ndarray]:
        """The pushed version's FULL weight vector, reconstructed on the
        router's own promoted cache (the rollback needs it); None = the
        delta's base is not our promoted version (NACK: the pusher
        resends full, exactly like a replica's version gap)."""
        if request.HasField("weights"):
            return codec.decode_tensor(request.weights)
        if (request.HasField("delta") and self._w_promoted is not None
                and self._promoted_version == request.delta.base_version):
            return codec.apply_weight_delta(self._w_promoted, request.delta)
        return None

    def _fan_out(self, request, replicas: Sequence["_Replica"]) -> int:
        """Concurrent PushWeights to `replicas`; returns how many acked ok.
        Send bytes are accounted per DELIVERED send (the comms.* send-side
        pattern; a transport failure ships nothing and must not pad the
        wire-savings ratio the serve bench gates); a NACK counts
        serve.push.nack (the replica already fell back to a full-file
        reload on its side)."""
        futs = []
        form = "delta" if request.HasField("delta") else "full"
        dense = 4 * (len(self._w_promoted) if self._w_promoted is not None
                     else request.weights.size)
        for r in replicas:
            try:
                futs.append((r, r.stub.PushWeights.future(
                    request, timeout=self._policy.deadline_s)))
            except Exception:  # noqa: BLE001 - channel closed under us
                self.metrics.counter(metrics_mod.SERVE_PUSH_ERRORS).increment()
        acked = 0
        for r, f in futs:
            try:
                reply = f.result()
                metrics_mod.record_push(self.metrics, form,
                                        request.ByteSize(), dense)
                if reply.ok:
                    acked += 1
                else:
                    self.metrics.counter(
                        metrics_mod.SERVE_PUSH_NACK).increment()
            except grpc.RpcError:
                self.metrics.counter(metrics_mod.SERVE_PUSH_ERRORS).increment()
                r.breaker.record_failure()
        return acked

    def _probe_loss(self, canaries: Sequence["_Replica"],
                    version: int) -> Optional[float]:
        """Mean probe-set loss served by a canary replica at `version`;
        None when no canary answered the whole probe (treated as a failed
        canary by the caller)."""
        if self._probe_model is None:
            from distributed_sgd_tpu.models.linear import make_model

            # losses_from_margins is all the router needs: margin -> loss
            # is dimension-free, so n_features=1 and no regularizer
            self._probe_model = make_model(
                self._model_name, self._lam, 1, regularizer="none")
        import jax.numpy as jnp

        for r in canaries:
            margins, ys = [], []
            try:
                for idx, val, y in self._probe:
                    reply = r.stub.Predict(
                        pb.PredictRequest(indices=idx, values=val),
                        timeout=self._policy.deadline_s)
                    if reply.model_step != version:
                        raise ValueError(
                            f"canary {r.endpoint} answered from step "
                            f"{reply.model_step}, not {version}")
                    margins.append(reply.margin)
                    ys.append(y)
            except (grpc.RpcError, ValueError) as e:
                log.warning("canary probe against %s failed: %s", r.endpoint, e)
                continue
            losses = self._probe_model.losses_from_margins(
                jnp.asarray(margins, jnp.float32), jnp.asarray(ys, jnp.float32))
            return float(jnp.mean(losses))
        return None

    def _regressed(self, loss: float) -> bool:
        if not np.isfinite(loss):
            return True  # NaN/Inf probe margins: a genuinely poisoned model
        best = self._checker.best_loss
        if best == float("inf"):
            return False  # no baseline yet: first version promotes
        return loss > max(self.canary_ratio * best, best + self.CANARY_ABS_FLOOR)

    # -- promoted-state persistence (ROADMAP 3b, DSGD_SERVE_STATE) ----------

    def _restore_state(self) -> None:
        """Load the promoted-state sidecar (no-op when unset/absent).  The
        promoted WEIGHTS are not persisted — only their version and the
        probe baseline — so the restored router NACKs deltas against the
        unknown base (the pusher resends full, its normal gap path) and
        re-pins the promoted version ungated when it arrives."""
        if not self._state_path or not os.path.exists(self._state_path):
            return
        try:
            with open(self._state_path) as f:
                state = json.load(f)
            # conversions INSIDE the guard: a sidecar that parses as JSON
            # but carries garbage values (hand edit, foreign writer) must
            # also land on the starting-fresh path, not crash startup
            promoted = state.get("promoted_version")
            promoted = None if promoted is None else int(promoted)
            rejected = set(int(v) for v in state.get("rejected", []))
            best = state.get("best_loss")
            best = None if best is None else float(best)
            seq = int(state.get("seq", 0))
        except (OSError, ValueError, TypeError, AttributeError) as e:
            # quarantine, don't delete: the operator can inspect what a
            # crashed/foreign writer left behind, and the rename also
            # stops every subsequent restart from re-parsing (and
            # re-warning about) the same bad bytes
            quarantine = self._state_path + ".corrupt"
            try:
                os.replace(self._state_path, quarantine)
            except OSError:
                quarantine = "<quarantine failed>"
            log.warning("router state %s unreadable (%s); quarantined to "
                        "%s and starting fresh", self._state_path, e,
                        quarantine)
            return
        self._state_seq = seq
        if promoted is not None:
            self._promoted_version = promoted
        self._rejected = rejected
        if best is not None:
            # seed the LossChecker baseline without weights: best_loss is
            # the only field the canary rule reads (leaky=1.0 checker)
            self._checker.best_loss = best
        if self._probe_source is not None and state.get("probe_source"):
            # restore the traffic reservoir: counters + rows + pending
            # lane, so the counter-derived Algorithm-R draw resumes the
            # exact sampling sequence the pre-restart router was on
            try:
                self._probe_source.load_state(state["probe_source"])
            except (KeyError, ValueError, TypeError) as e:
                log.warning("probe-source state in %s unreadable (%s); "
                            "reservoir starts empty", self._state_path, e)
        log.info(
            "router state restored from %s: promoted version %s, "
            "baseline %s, %d rejected", self._state_path,
            self._promoted_version, best, len(self._rejected))

    def _persist_state(self) -> None:
        """Atomically rewrite the sidecar (tmp + replace) after every
        promote/rollback; called under _push_lock."""
        if not self._state_path:
            return
        best = self._checker.best_loss
        state = {
            "seq": self._state_seq,
            "promoted_version": self._promoted_version,
            "best_loss": best if best != float("inf") else None,
            "rejected": sorted(self._rejected),
        }
        if self._probe_source is not None:
            # bounded by construction (capacity + label_delay rows), so
            # the sidecar stays a small JSON file
            state["probe_source"] = self._probe_source.state_dict()
        try:
            from distributed_sgd_tpu.utils.fsio import atomic_write_json

            atomic_write_json(self._state_path, state)
        except OSError as e:  # persistence must never fail a push
            log.warning("router state write to %s failed: %s",
                        self._state_path, e)

    def _state_transition(self) -> None:
        """Under _push_lock, on every promote/rollback/baseline change:
        advance the versioned record's seq and wake the HA sync loop so
        the peer mirrors the transition NOW, not a sync interval later.
        Only the decider bumps — a mirror's local edits (e.g. its own
        probe refresh) must not outrank the decider's verdicts."""
        if self._ha is None or self._ha.is_decider():
            self._state_seq += 1
        if self._ha is not None:
            self._ha.notify()

    def _promote(self, version: int, w: np.ndarray,
                 loss: Optional[float]) -> None:
        self._promoted_version = int(version)
        self._w_promoted = np.asarray(w, np.float32)
        if loss is not None and np.isfinite(loss):
            self._checker.check(loss, 0.0, self._w_promoted, step=version)
            self.metrics.gauge(metrics_mod.ROUTER_CANARY_LOSS).set(loss)
        self.metrics.counter(metrics_mod.ROUTER_CANARY_PROMOTED).increment()
        self._state_transition()
        self._persist_state()
        log.info("version %d promoted fleet-wide (probe loss %s)",
                 version, f"{loss:.6f}" if loss is not None else "n/a")

    def _repin(self, canaries: Sequence["_Replica"]) -> bool:
        """Re-install the promoted weights on the canary subset (a full
        push — apply_push is authoritative at any version).  Returns
        whether a re-pin was actually sent."""
        if self._w_promoted is None:
            # restored-state router that has not yet re-received the
            # promoted weights: nothing to re-install — the canaries heal
            # when the promoted version is re-streamed (re-pin path / gap
            # fallback); callers must not claim a re-pin happened
            log.warning("cannot re-pin canaries: promoted weights not in "
                        "cache yet (restored state)")
            return False
        req = pb.PushWeightsRequest(version=self._promoted_version)
        req.weights.CopyFrom(codec.encode_tensor(self._w_promoted))
        self._fan_out(req, canaries)
        return True

    def _rollback(self, version: int, canaries: Sequence["_Replica"],
                  loss: float) -> None:
        self._rejected.add(int(version))
        self._state_transition()
        self._persist_state()
        self.metrics.counter(metrics_mod.ROUTER_CANARY_ROLLBACK).increment()
        flight.record("router.canary.rollback", version=int(version),
                      probe_loss=loss, baseline=self._checker.best_loss)
        repinned = self._repin(canaries)
        log.warning(
            "version %d ROLLED BACK (probe loss %.6f vs baseline %.6f): %s",
            version, loss, self._checker.best_loss,
            f"canaries re-pinned to promoted version {self._promoted_version}"
            if repinned else
            f"canaries still serve the rejected weights until promoted "
            f"version {self._promoted_version} is re-streamed (restored "
            f"state has no weight cache)")

    def PushWeights(self, request, context):  # noqa: N802 - gRPC method name
        with self._push_lock:
            version = int(request.version)
            current = self._promoted_version or 0
            if version in self._rejected:
                # a rejected version stays rejected: the trainer's next
                # checkpoint gets a fresh canary instead
                return pb.PushWeightsReply(ok=False, model_step=current)
            w_new = self._resolve_weights(request)
            if w_new is None:
                self.metrics.counter(metrics_mod.SERVE_PUSH_NACK).increment()
                return pb.PushWeightsReply(ok=False, model_step=current)
            if self._ha is not None and not self._ha.is_decider():
                # non-decider LIVE router (DSGD_SERVE_HA): promote/
                # rollback/canary verdicts belong to the lease holder —
                # two routers fronting the same replicas must not both
                # canary the same version.  The promoted version's
                # re-stream just refreshes the weight cache (the
                # post-failover re-pin needs it); anything newer is
                # DEFERRED: cache the weights and NACK, and the verdict
                # arrives over SyncServeState within one sync interval.
                w_new = np.asarray(w_new, np.float32)
                if version == self._promoted_version:
                    self._w_promoted = w_new
                    return pb.PushWeightsReply(ok=True, model_step=version)
                self._ha_pending = (version, w_new)
                self.metrics.counter(
                    metrics_mod.ROUTER_HA_DEFERRED).increment()
                return pb.PushWeightsReply(ok=False, model_step=current)
            # reply `ok` is the ROUTER's accept/reject decision ONLY
            # (promoted vs canary-rejected/version-gap) — NOT fan-out
            # completeness: a down replica is the router's problem (its
            # health loop drains it, and the replica's own version-gap
            # file fallback heals it on rejoin).  Folding partial fan-out
            # failure into ok would make the pusher treat every push
            # during one replica's outage as a NACK — full-form resends
            # of already-promoted versions, re-running the canary probe
            # and forfeiting the delta savings the feature exists for.
            if (self._promoted_version is not None
                    and version == self._promoted_version
                    and self._w_promoted is None):
                # the already-promoted version re-streamed after a router
                # restart (DSGD_SERVE_STATE): RE-PIN it — ungated fan-out
                # + refresh the promoted weight cache.  Re-canarying the
                # version the fleet is already serving would burn a probe
                # pass per restart and could roll back the live baseline
                # on one noisy probe.
                self._fan_out(request, self._replicas)
                self._w_promoted = np.asarray(w_new, np.float32)
                log.info("version %d re-pinned (already promoted before "
                         "restart)", version)
                return pb.PushWeightsReply(ok=True, model_step=version)
            n_canary = self._canary_count()
            gated = n_canary > 0 and self._promoted_version is not None
            if not gated:
                acked = self._fan_out(request, self._replicas)
                loss = (self._probe_loss(self._eligible() or self._replicas,
                                         version)
                        if self._probe is not None else None)
                self._promote(version, w_new, loss)
            else:
                # canaries come from the ELIGIBLE (healthy, breaker-quiet)
                # set first: a statically-indexed canary that happens to be
                # the dead replica would make every probe unevaluable and
                # freeze fleet updates while 2/3 of the fleet is healthy
                pool = self._eligible() or list(self._replicas)
                canaries = pool[:n_canary]
                rest = [r for r in self._replicas if r not in canaries]
                acked = self._fan_out(request, canaries)
                loss = self._probe_loss(canaries, version)
                if loss is None:
                    # the probe could not RUN (canaries unreachable):
                    # re-pin the canaries but do NOT reject the version —
                    # rejection is a verdict, and no verdict was reached;
                    # the pusher's next attempt retries on a fresh set
                    self._repin(canaries)
                    self.metrics.counter(
                        metrics_mod.SERVE_PUSH_ERRORS).increment()
                    log.warning("version %d not promoted: canary probe "
                                "unevaluable (no canary answered); will "
                                "retry on the next push", version)
                    return pb.PushWeightsReply(ok=False, model_step=current)
                if self._regressed(loss):
                    self._rollback(version, canaries, loss)
                    return pb.PushWeightsReply(ok=False, model_step=current)
                acked += self._fan_out(request, rest) if rest else 0
                self._promote(version, w_new, loss)
            if acked < len(self._replicas):
                log.warning("version %d promoted with %d/%d replicas acked "
                            "(the rest heal via gap fallback)",
                            version, acked, len(self._replicas))
            return pb.PushWeightsReply(ok=True, model_step=version)

    # -- serving-plane HA (serving/ha.py, DSGD_SERVE_HA) ---------------------

    def attach_ha(self, coordinator) -> "ServingRouter":
        """Wire an HACoordinator onto a constructed router (the
        coordinator derives its node label from the bound port, so this
        runs post-construction).  The caller start()s the coordinator;
        stop() here tears it down with the router."""
        self._ha = coordinator
        coordinator.attach(self)
        return self

    def export_ha_state(self) -> dict:
        """The versioned promoted-state record the sync loop ships:
        {seq, promoted, best, rejected}."""
        with self._push_lock:
            best = self._checker.best_loss
            return {
                "seq": self._state_seq,
                "promoted": self._promoted_version,
                "best": None if best == float("inf") else best,
                "rejected": sorted(self._rejected),
            }

    def _apply_ha_locked(self, record) -> bool:
        """Adopt a peer's record iff it is STRICTLY newer (higher seq) —
        the no-resurrection rule: a rollback outranks the promote it
        reverted, so a rejoining router replaying a stale promote can
        never resurrect the rolled-back version.  Called under
        _push_lock from the RPC handler and the sync loop."""
        if self._ha is None or int(record.seq) <= self._state_seq:
            return False
        self._state_seq = int(record.seq)
        promoted = (int(record.promoted_version) if record.has_promoted
                    else None)
        if promoted != self._promoted_version:
            self._promoted_version = promoted
            # the record carries no weights: pin the deferred-push cache
            # if it matches, else the cache empties and the promoted
            # version's next re-stream (or the gap fallback) refills it
            self._w_promoted = None
        self._rejected = set(int(v) for v in record.rejected)
        self._checker.best_loss = (float(record.best_loss)
                                   if record.has_best else float("inf"))
        if self._ha_pending is not None:
            pv, pw = self._ha_pending
            if promoted == pv:
                self._w_promoted = pw
                self._ha_pending = None
            elif pv in self._rejected:
                self._ha_pending = None
        self._persist_state()
        self.metrics.counter(metrics_mod.ROUTER_HA_APPLIED).increment()
        log.info("HA record seq %d adopted from peer: promoted=%s, "
                 "%d rejected", self._state_seq, promoted,
                 len(self._rejected))
        return True

    def apply_ha_record(self, record) -> bool:
        with self._push_lock:
            return self._apply_ha_locked(record)

    def SyncServeState(self, request, context):  # noqa: N802 - gRPC method name
        """Peer routers exchange versioned promoted-state records; both
        directions carry the FULL record, so one exchange converges the
        pair no matter which side is stale.  With HA off this router
        adopts nothing (applied=False) but still answers with its local
        record — a misconfigured peer learns our state instead of
        getting a hang."""
        if self._ha is not None and request.node:
            self._ha.observe_peer(str(request.node))
        with self._push_lock:
            applied = (self._apply_ha_locked(request)
                       if self._ha is not None else False)
            reply = pb.SyncServeStateReply(applied=applied,
                                           seq=self._state_seq)
            if self._promoted_version is not None:
                reply.has_promoted = True
                reply.promoted_version = int(self._promoted_version)
            best = self._checker.best_loss
            if best != float("inf"):
                reply.has_best = True
                reply.best_loss = float(best)
            reply.rejected.extend(sorted(self._rejected))
        if self._ha is not None:
            self.metrics.counter(metrics_mod.ROUTER_HA_SYNCS).increment()
        return reply

    def _on_assume_lease(self) -> None:
        """The decider lease lapsed onto this router: re-pin the mirrored
        promoted state fleet-wide so every replica serves the survivor's
        truth, whatever the dead decider was midway through.  The seq is
        NOT bumped — assuming the lease is not a state transition, and a
        rejoining ex-decider whose record is genuinely newer (it finished
        a verdict before dying) must still win the next exchange.

        The network fan-out runs OUTSIDE _push_lock (only the snapshot of
        the promoted record is taken under it): holding the lock through a
        deadline x replicas push would stall every is_decider() read,
        PushWeights, and SyncServeState exactly when the survivor must
        take over."""
        with self._push_lock:
            if self._promoted_version is None:
                return
            if self._w_promoted is None:
                # restored-state router that has not yet re-received the
                # promoted weights: nothing to re-install — the fleet
                # heals when the promoted version is re-streamed
                log.warning("cannot re-pin fleet on lease assumption: "
                            "promoted weights not in cache yet "
                            "(restored state)")
                return
            req = pb.PushWeightsRequest(version=self._promoted_version)
            req.weights.CopyFrom(codec.encode_tensor(self._w_promoted))
            replicas = list(self._replicas)
        self._fan_out(req, replicas)

    # -- fleet membership (autoscale: serving/ha.py ReplicaAutoscaler) -------

    def add_replica(self, host: str, port: int) -> "_Replica":
        """Join a replica to the live fleet (autoscale spin-up / operator
        add).  It is warmed with the cached promoted weights (full push)
        so it serves the fleet's version from its first health pass
        instead of waiting out the next checkpoint."""
        r = _Replica(host, int(port), self._policy)
        with self._push_lock:
            if self._w_promoted is not None:
                req = pb.PushWeightsRequest(version=self._promoted_version)
                req.weights.CopyFrom(codec.encode_tensor(self._w_promoted))
                self._fan_out(req, [r])
            self._replicas.append(r)
        log.info("replica %s joined the fleet (%d total)", r.endpoint,
                 len(self._replicas))
        return r

    def remove_replica(self, endpoint: str) -> bool:
        """Drain a replica out of the fleet (autoscale spin-down): it
        leaves the pick pool immediately, and any call racing the channel
        close fails over exactly like a died replica — zero drops."""
        with self._push_lock:
            victims = [r for r in self._replicas if r.endpoint == endpoint]
            if not victims:
                return False
            if len(self._replicas) - len(victims) < 1:
                raise ValueError("cannot drain the last replica")
            self._replicas = [r for r in self._replicas
                              if r.endpoint != endpoint]
        for r in victims:
            r.close()
        log.info("replica %s drained from the fleet (%d left)", endpoint,
                 len(self._replicas))
        return True

    # -- fleet health + telemetry -------------------------------------------

    def ServeHealth(self, request, context):  # noqa: N802 - gRPC method name
        serving = [r for r in self._replicas if r.healthy]
        step = (self._promoted_version
                if self._promoted_version is not None
                else max((r.model_step for r in serving), default=0))
        return pb.ServeHealthReply(
            ok=bool(serving),
            model_step=int(step),
            queue_depth=sum(r.inflight for r in self._replicas),
        )

    def Metrics(self, request, context):  # noqa: N802 - gRPC method name
        from distributed_sgd_tpu.telemetry.aggregate import snapshot_metrics

        return snapshot_metrics(self.metrics, role="route", node=self._node)

    # -- lifecycle -----------------------------------------------------------

    @property
    def bound_port(self) -> int:
        return self._server.bound_port

    def start(self) -> "ServingRouter":
        self._health_pass()  # route nothing before one synchronous look
        self._health_thread.start()
        self._server.start()
        if self.telemetry_exporter is not None:
            self.telemetry_exporter.start()
        log.info("routing on :%d over %d replicas (%s); canary=%g hedge=%gms",
                 self.bound_port, len(self._replicas),
                 ", ".join(r.endpoint for r in self._replicas),
                 self.canary_fraction, self._hedge_s * 1e3)
        return self

    def await_termination(self) -> None:
        self._server.wait_for_termination()

    def stop(self, grace: float = 1.0) -> None:
        self._stop.set()
        if self._ha is not None:
            self._ha.stop()
        self._server.stop(grace).wait()
        if self._health_thread.is_alive():
            self._health_thread.join(timeout=self.health_s + 1.0)
        if self.telemetry_exporter is not None:
            self.telemetry_exporter.stop()
        for r in self._replicas:
            r.close()

    def __enter__(self) -> "ServingRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
