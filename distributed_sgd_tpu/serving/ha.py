"""Serving-plane HA: dual LIVE routers with a leased decider, peer-synced
promoted state, client failover, and load-adaptive replica autoscale
(docs/SERVING.md "HA" / "Autoscale"; ROADMAP item 4).

The router was the serving plane's one SPOF: a crash took down the whole
Predict path and lost in-flight canary decisions.  This module makes two
(or more) routers LIVE at once while keeping exactly one of them the
*decider* for promote/rollback/canary verdicts:

- **Lease** (``FileLease`` / ``PeerLease``): who decides.  The file
  backend is a wall-clock TTL record on shared disk (atomic_write_json —
  the sidecar discipline); the peer backend needs no shared disk: the
  lowest-ranked endpoint among the peers seen alive within the TTL holds
  the lease, liveness fed by the sync exchanges themselves.  Peers are
  presumed alive at boot, so the low-ranked router decides from the
  start and the other defers — no boot split-brain window.
- **HACoordinator**: the sync loop.  Every ``sync_s`` (and immediately
  after every local transition — ``notify()``), it renews the lease and
  exchanges the router's versioned state record with each peer over the
  ``SyncServeState`` RPC.  Both directions carry the FULL record and the
  monotonically-numbered ``seq`` totally orders transitions, so one
  exchange converges the pair no matter which side is stale and a
  rejoining router can never resurrect a rolled-back version.  When the
  lease lapses, the survivor assumes it (``router.ha.failovers``) and
  re-pins its mirrored promoted state fleet-wide.
- **FailoverServeClient**: the client-side two-target stub — tries the
  last-good router first and fails over to the next on any transport
  error, mirroring how the router already fails over between replicas.
- **ReplicaAutoscaler**: the router's existing EWMA-latency x in-flight
  signal (``router_load_ms``) driven against a p99 SLO
  (``DSGD_SERVE_SLO_MS``): sustained breach spins a replica up through
  the warm spin-up path (PR 11's compile cache makes that cheap),
  sustained idle drains one — with consecutive-tick hysteresis and a
  post-action cooldown so chaos weather cannot flap the fleet.

Default-off behind ``DSGD_SERVE_HA=peers:<host:port,...>`` and
``DSGD_SERVE_SLO_MS``; with both unset no coordinator exists, no
``SyncServeState`` RPC is ever issued, and the serving wire is
byte-identical to the single-router plane (tests/test_serve_ha.py).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import grpc

from distributed_sgd_tpu.rpc import dsgd_pb2 as pb
from distributed_sgd_tpu.rpc.service import RpcPolicy, ServeStub, new_channel
from distributed_sgd_tpu.utils import metrics as metrics_mod
from distributed_sgd_tpu.utils.fsio import atomic_write_json

log = logging.getLogger("dsgd.serving")


def _dur(s: str) -> float:
    """'250ms' / '1.5s' / bare seconds -> float seconds (the chaos plan
    grammar's duration tokens, kept local so ha needs no chaos import)."""
    s = str(s).strip()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    return float(s)


def parse_ha_spec(spec: str) -> Dict[str, object]:
    """``DSGD_SERVE_HA`` grammar ->
    ``{peers, node, sync_s, lease_ttl_s, lease_path}``.

    ``peers:<host:port,...>`` names the OTHER routers (required), then
    optional ``;``-separated tokens: ``self=<host:port>`` (this router's
    own advertised endpoint — it must match what the peers list for us,
    since the peer lease ranks endpoints; defaults to
    ``127.0.0.1:<bound port>`` at attach time), ``sync=<dur>`` (state
    sync / lease renew cadence, default 250ms), ``ttl=<dur>`` (lease
    TTL, default 4x sync), ``lease=<path>`` (shared-disk file lease
    instead of the peer lease)."""
    from distributed_sgd_tpu.serving.push import parse_targets

    spec = str(spec).strip()
    if not spec.startswith("peers:"):
        raise ValueError(
            f"DSGD_SERVE_HA spec {spec!r} must start with 'peers:' "
            f"(peers:<host:port,...>[;self=...][;sync=...][;ttl=...]"
            f"[;lease=...])")
    head, *extras = spec.split(";")
    peers = parse_targets(head[len("peers:"):])
    out: Dict[str, object] = {
        "peers": [f"{h}:{p}" for h, p in peers],
        "node": None, "sync_s": 0.25, "lease_ttl_s": None,
        "lease_path": None,
    }
    for token in filter(None, (t.strip() for t in extras)):
        if "=" not in token:
            raise ValueError(f"bad DSGD_SERVE_HA token {token!r} "
                             f"(want key=value)")
        key, val = (s.strip() for s in token.split("=", 1))
        if key == "self":
            parse_targets(val)  # endpoint typo fails at construction
            out["node"] = val
        elif key == "sync":
            out["sync_s"] = _dur(val)
        elif key == "ttl":
            out["lease_ttl_s"] = _dur(val)
        elif key == "lease":
            out["lease_path"] = val
        else:
            raise ValueError(f"unknown DSGD_SERVE_HA key {key!r}")
    if float(out["sync_s"]) <= 0:
        raise ValueError("DSGD_SERVE_HA sync cadence must be > 0")
    if out["lease_ttl_s"] is not None and float(out["lease_ttl_s"]) <= 0:
        raise ValueError("DSGD_SERVE_HA lease ttl must be > 0")
    return out


def _rank(endpoint: str) -> Tuple[str, int]:
    """Total order over endpoints for the peer lease (numeric port, so
    'h:9' < 'h:10' the way an operator expects)."""
    host, _, port = endpoint.rpartition(":")
    return (host, int(port))


class FileLease:
    """Shared-disk decider lease: a wall-clock TTL record rewritten
    atomically (the sidecar discipline), last writer wins.  ``acquire``
    renews our own lease, takes an absent/expired one, and defers to a
    live foreign holder."""

    def __init__(self, path: str, node: str, ttl_s: float = 1.0,
                 clock: Callable[[], float] = time.time):
        if ttl_s <= 0:
            raise ValueError("lease ttl_s must be > 0")
        self.path, self.node = str(path), str(node)
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self.term = 0

    def _read(self) -> Optional[Dict]:
        try:
            with open(self.path) as f:
                rec = json.load(f)
            return {"holder": str(rec["holder"]),
                    "expiry": float(rec["expiry"]),
                    "term": int(rec.get("term", 0))}
        except (OSError, ValueError, TypeError, KeyError):
            return None  # absent or torn/corrupt: claimable

    def observe(self, peer: str) -> None:
        """Liveness rides the file, not the sync exchanges."""

    def holder(self) -> Optional[str]:
        rec = self._read()
        if rec is None or rec["expiry"] < self._clock():
            return None
        return rec["holder"]

    def acquire(self) -> bool:
        now = self._clock()
        rec = self._read()
        if rec is not None and rec["holder"] != self.node:
            if rec["expiry"] >= now:
                self.term = rec["term"]
                return False  # live foreign holder: defer
            self.term = rec["term"] + 1  # lapsed: take it over
        try:
            atomic_write_json(self.path, {
                "holder": self.node, "expiry": now + self.ttl_s,
                "term": self.term})
        except OSError as e:
            log.warning("lease write to %s failed: %s", self.path, e)
            return False  # cannot prove the claim: act as non-decider
        # read-back check: two routers racing the same expired record can
        # both atomic_write_json their claim — last writer wins, so only
        # the router the file NAMES after the dust settles may decide
        # (the loser sees the winner's record and defers immediately
        # instead of a full term of silent split-brain)
        rec = self._read()
        if rec is None or rec["holder"] != self.node:
            if rec is not None:
                self.term = rec["term"]
            return False
        return True

    def release(self) -> None:
        rec = self._read()
        if rec is not None and rec["holder"] == self.node:
            try:
                os.remove(self.path)
            except OSError:
                pass


class PeerLease:
    """Disk-free decider lease: the lowest-ranked endpoint among the
    peers seen alive within the TTL holds it.  Liveness is fed by the
    sync exchanges (``observe``); peers are presumed alive at boot so
    the low-ranked router decides from the start and the other defers —
    a dead peer simply lapses one TTL later."""

    def __init__(self, node: str, peers: Sequence[str], ttl_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if ttl_s <= 0:
            raise ValueError("lease ttl_s must be > 0")
        self.node = str(node)
        self.peers = [str(p) for p in peers]
        self.ttl_s = float(ttl_s)
        self._clock = clock
        now = clock()
        self._seen: Dict[str, float] = {p: now for p in self.peers}

    def observe(self, peer: str) -> None:
        if peer in self._seen:
            self._seen[peer] = self._clock()

    def _live(self) -> List[str]:
        now = self._clock()
        return [p for p in self.peers if now - self._seen[p] <= self.ttl_s]

    def holder(self) -> str:
        return min([self.node] + self._live(), key=_rank)

    def acquire(self) -> bool:
        return self.holder() == self.node

    def release(self) -> None:
        """Peer leases have nothing to release: rank + liveness decide."""


class HACoordinator:
    """One router's half of the dual-LIVE-router protocol: lease + the
    ``SyncServeState`` exchange loop.  Built from ``DSGD_SERVE_HA`` (or
    directly in tests/benches), attached to a started router via
    ``ServingRouter.attach_ha``, then ``start()``ed."""

    def __init__(self, peers: Sequence[str], node: Optional[str] = None,
                 sync_s: float = 0.25, lease_ttl_s: Optional[float] = None,
                 lease_path: Optional[str] = None, metrics=None,
                 policy: Optional[RpcPolicy] = None):
        if not peers:
            raise ValueError("HA needs at least one peer router endpoint")
        if sync_s <= 0:
            raise ValueError("sync_s must be > 0")
        self.peers = [str(p) for p in peers]
        self.node = node
        self.sync_s = float(sync_s)
        self.lease_ttl_s = float(lease_ttl_s) if lease_ttl_s else 4 * self.sync_s
        self._lease_path = lease_path
        self.metrics = metrics
        self._policy = policy
        self._router = None
        self._lease = None
        self._lock = threading.Lock()
        self._was_decider = False
        self._ever_deferred = False
        self._stubs: Dict[str, ServeStub] = {}
        self._channels: Dict[str, grpc.Channel] = {}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="route-ha-sync")

    @classmethod
    def from_spec(cls, spec: str, metrics=None,
                  policy: Optional[RpcPolicy] = None) -> "HACoordinator":
        kw = parse_ha_spec(spec)
        return cls(kw["peers"], node=kw["node"], sync_s=kw["sync_s"],
                   lease_ttl_s=kw["lease_ttl_s"],
                   lease_path=kw["lease_path"], metrics=metrics,
                   policy=policy)

    # -- wiring --------------------------------------------------------------

    def attach(self, router) -> None:
        """Bind to a constructed router (``ServingRouter.attach_ha`` calls
        this).  The node label defaults to the router's bound loopback
        endpoint — single-host harnesses need no ``self=`` token."""
        self._router = router
        if self.metrics is None:
            self.metrics = router.metrics
        if self._policy is None:
            self._policy = router._policy
        if self.node is None:
            self.node = f"127.0.0.1:{router.bound_port}"
        if self._lease_path:
            self._lease = FileLease(self._lease_path, self.node,
                                    ttl_s=self.lease_ttl_s)
        else:
            self._lease = PeerLease(self.node, self.peers,
                                    ttl_s=self.lease_ttl_s)
        for p in self.peers:
            host, _, port = p.rpartition(":")
            self._channels[p] = new_channel(host, int(port))
            self._stubs[p] = ServeStub(self._channels[p])
        self._refresh()
        log.info("HA coordinator on %s: peers=%s lease=%s sync=%gs ttl=%gs",
                 self.node, ", ".join(self.peers),
                 self._lease_path or "peer-rank", self.sync_s,
                 self.lease_ttl_s)

    # -- the lease -----------------------------------------------------------

    def is_decider(self) -> bool:
        """Current lease verdict (re-acquired on every read: promote/
        rollback verdicts must see a lapse the moment it happens, not a
        sync tick later)."""
        return self._refresh()

    def _refresh(self) -> bool:
        # lock order: NEVER hold _lock while taking the router's
        # _push_lock — push RPCs hold _push_lock and call is_decider()
        # (-> _lock), so detecting the lapse happens inside the critical
        # section but the assume-lease re-pin runs after releasing it.
        # _was_decider flips under _lock, so exactly one thread sees the
        # False->True edge and runs the callback.
        assumed = False
        with self._lock:
            now = self._lease.acquire()
            if not now:
                self._ever_deferred = True
            if now and not self._was_decider and self._ever_deferred:
                # the lease LAPSED onto us: the previous decider went
                # quiet for a full TTL — assume its duties and re-pin the
                # mirrored promoted state so the fleet serves one truth
                assumed = True
            self._was_decider = now
            self.metrics.gauge(metrics_mod.ROUTER_HA_DECIDER).set(
                1.0 if now else 0.0)
        if assumed:
            self.metrics.counter(
                metrics_mod.ROUTER_HA_FAILOVERS).increment()
            log.warning("HA lease assumed by %s (peer decider lapsed)",
                        self.node)
            if self._router is not None:
                self._router._on_assume_lease()
        return now

    def observe_peer(self, peer: str) -> None:
        self._lease.observe(peer)

    # -- the sync loop -------------------------------------------------------

    def notify(self) -> None:
        """A local transition happened: sync NOW instead of waiting out
        the interval (keeps the split-brain window well under sync_s)."""
        self._wake.set()

    def _record_request(self, snap: Dict) -> "pb.SyncServeStateRequest":
        req = pb.SyncServeStateRequest(
            node=self.node, seq=int(snap["seq"]),
            decider=self._was_decider)
        if snap["promoted"] is not None:
            req.has_promoted = True
            req.promoted_version = int(snap["promoted"])
        if snap["best"] is not None:
            req.has_best = True
            req.best_loss = float(snap["best"])
        req.rejected.extend(int(v) for v in snap["rejected"])
        return req

    def sync_once(self) -> int:
        """One exchange round: renew the lease, push our record to every
        peer, adopt any newer record a reply carries.  Returns how many
        peers answered."""
        self._refresh()
        if self._router is None:
            return 0
        snap = self._router.export_ha_state()
        req = self._record_request(snap)
        answered = 0
        for peer, stub in self._stubs.items():
            try:
                reply = stub.SyncServeState(
                    req, timeout=self._policy.deadline_s)
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                    # older binary without the SyncServeState splice: it
                    # cannot mirror state, but its server ANSWERED, so it
                    # is alive for lease purposes — without observe() the
                    # higher-ranked router would wrongly assume decider-
                    # ship from a merely-old peer after one TTL
                    self._lease.observe(peer)
                # either way the sync itself failed (dead/unreachable
                # peer silence is what ages the lease out)
                self.metrics.counter(
                    metrics_mod.ROUTER_HA_SYNC_ERRORS).increment()
                continue
            answered += 1
            self._lease.observe(peer)
            if reply.seq > snap["seq"]:
                # the peer is ahead (we are the rejoining/stale side):
                # adopt its record — this is the no-resurrection path
                self._router.apply_ha_record(reply)
        return answered

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.sync_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            try:
                self.sync_once()
            except Exception as e:  # noqa: BLE001 - sync must not die mid-run
                log.warning("HA sync pass failed: %s", e)

    def start(self) -> "HACoordinator":
        if self._router is None:
            raise RuntimeError("attach() the coordinator to a router first")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self.sync_s + 1.0)
        if self._lease is not None:
            self._lease.release()
        for ch in self._channels.values():
            ch.close()


class FailoverServeClient:
    """Client-side two-target failover stub: Predict against the
    last-good router first, fail over to the next on any transport error
    — the router->replica failover ladder, one level up
    (docs/FAULT_TOLERANCE.md).  Kube fronts the same pair with one
    Service; this is the harness/SDK equivalent."""

    def __init__(self, targets: Sequence[Tuple[str, int]],
                 timeout_s: float = 10.0):
        if not targets:
            raise ValueError("failover client needs at least one router")
        self._targets = [(h, int(p)) for h, p in targets]
        self._channels = [new_channel(h, p) for h, p in self._targets]
        self._stubs = [ServeStub(ch) for ch in self._channels]
        self._timeout = float(timeout_s)
        self._primary = 0
        self.failovers = 0

    def _call(self, method: str, request):
        last: Optional[grpc.RpcError] = None
        n = len(self._stubs)
        for k in range(n):
            i = (self._primary + k) % n
            try:
                reply = getattr(self._stubs[i], method)(
                    request, timeout=self._timeout)
            except grpc.RpcError as e:
                if e.code() == grpc.StatusCode.INVALID_ARGUMENT:
                    raise  # caller error: every router would reject it
                last = e
                continue
            if i != self._primary:
                self.failovers += 1
                self._primary = i  # stick with the router that answered
            return reply
        raise last

    def predict(self, indices, values) -> "pb.PredictReply":
        return self._call("Predict",
                          pb.PredictRequest(indices=indices, values=values))

    def health(self) -> "pb.ServeHealthReply":
        return self._call("ServeHealth", pb.Empty())

    def close(self) -> None:
        for ch in self._channels:
            ch.close()


def router_load_ms(router) -> Optional[float]:
    """The autoscale signal: the WORST eligible replica's p2c score
    (EWMA latency x (1 + in-flight)) in milliseconds — the router's own
    balancing currency, so 'the best available choice is already slow
    and busy' is exactly when more capacity helps.  None when no replica
    is eligible (an outage is the health loop's problem, not a scaling
    verdict)."""
    eligible = router._eligible()
    if not eligible:
        return None
    return 1000.0 * max(r.score() for r in eligible)


class ReplicaAutoscaler:
    """Load-adaptive replica count against a p99 SLO
    (``DSGD_SERVE_SLO_MS``; docs/SERVING.md "Autoscale").

    Pure controller over three callables — ``signal_ms`` (typically
    ``router_load_ms``), ``scale_up`` / ``scale_down`` (typically
    ``ServingFleet.add_replica`` / ``drain_replica``) — so the decision
    logic unit-tests synchronously.  Hysteresis: only ``up_after``
    CONSECUTIVE ticks over the SLO spin up, only ``down_after``
    consecutive ticks under ``low_water x SLO`` drain, and every action
    starts a ``cooldown_s`` dead window — chaos weather (one slow tick,
    one partition blip) cannot flap the fleet."""

    def __init__(self, signal_ms: Callable[[], Optional[float]],
                 scale_up: Callable[[], object],
                 scale_down: Callable[[], object],
                 count: Callable[[], int],
                 slo_ms: float,
                 min_replicas: int = 1, max_replicas: int = 8,
                 interval_s: float = 1.0, up_after: int = 2,
                 down_after: int = 5, low_water: float = 0.3,
                 cooldown_s: float = 5.0, metrics=None,
                 clock: Callable[[], float] = time.monotonic):
        if slo_ms <= 0:
            raise ValueError("slo_ms must be > 0 (0/unset = autoscale off)")
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if not 0.0 < low_water < 1.0:
            raise ValueError("low_water must be a fraction in (0, 1)")
        if up_after < 1 or down_after < 1:
            raise ValueError("up_after/down_after must be >= 1")
        if interval_s <= 0 or cooldown_s < 0:
            raise ValueError("interval_s must be > 0 and cooldown_s >= 0")
        self._signal, self._up, self._down = signal_ms, scale_up, scale_down
        self._count = count
        self.slo_ms = float(slo_ms)
        self.min_replicas, self.max_replicas = int(min_replicas), int(max_replicas)
        self.interval_s = float(interval_s)
        self.up_after, self.down_after = int(up_after), int(down_after)
        self.low_water = float(low_water)
        self.cooldown_s = float(cooldown_s)
        self.metrics = metrics if metrics is not None else metrics_mod.Metrics()
        self._clock = clock
        self._above = self._below = 0
        self._cooldown_until = -float("inf")
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="route-autoscale")

    def step(self) -> Optional[str]:
        """One tick: 'up', 'down', or None.  Safe to drive synchronously
        (tests) or from the interval thread."""
        sig = self._signal()
        if sig is None:
            self._above = self._below = 0
            return None
        self.metrics.gauge(metrics_mod.ROUTER_SCALE_LOAD_MS).set(float(sig))
        self.metrics.gauge(metrics_mod.ROUTER_SCALE_REPLICAS).set(
            self._count())
        if self._clock() < self._cooldown_until:
            return None
        if sig > self.slo_ms:
            self._above += 1
            self._below = 0
            if self._above >= self.up_after and self._count() < self.max_replicas:
                return self._act(self._up, metrics_mod.ROUTER_SCALE_UP, "up")
        elif sig < self.low_water * self.slo_ms:
            self._below += 1
            self._above = 0
            if (self._below >= self.down_after
                    and self._count() > self.min_replicas):
                return self._act(self._down, metrics_mod.ROUTER_SCALE_DOWN,
                                 "down")
        else:
            # inside the band: the streaks reset — hysteresis demands
            # CONSECUTIVE evidence, not eventually-accumulated evidence
            self._above = self._below = 0
        return None

    def _act(self, action, counter_name: str, verdict: str) -> str:
        action()
        self.metrics.counter(counter_name).increment()
        self.metrics.gauge(metrics_mod.ROUTER_SCALE_REPLICAS).set(
            self._count())
        self._above = self._below = 0
        self._cooldown_until = self._clock() + self.cooldown_s
        log.info("autoscale %s -> %d replicas (signal vs SLO %gms)",
                 verdict, self._count(), self.slo_ms)
        return verdict

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 - scaling must not die mid-run
                log.warning("autoscale tick failed: %s", e)

    def start(self) -> "ReplicaAutoscaler":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self.interval_s + 1.0)
