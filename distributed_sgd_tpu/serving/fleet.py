"""In-process serving fleet over real loopback gRPC — the serving sibling
of core/cluster.DevCluster.

N ``ServingServer`` replicas (each with its OWN metrics registry, so the
router's telemetry endpoint folds N distinct ``serve:<port>`` labels
instead of one shared registry counted N times — the DevCluster telemetry
discipline) behind one ``ServingRouter``, all on OS-assigned loopback
ports.  Used by tests/test_router.py, benches/bench_serve.py, and the
``DSGD_ROLE=serve`` + ``DSGD_SERVE_REPLICAS=N`` single-machine fleet mode
in main.py; the kube deployment runs the same two roles as real pods
(kube/serve.yaml).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from distributed_sgd_tpu.serving.router import ServingRouter
from distributed_sgd_tpu.serving.server import ServingServer
from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.serving")


class ServingFleet:
    def __init__(
        self,
        checkpoint_dir: str,
        n_replicas: int,
        model: str = "hinge",
        lam: float = 1e-5,
        host: str = "127.0.0.1",
        router_port: int = 0,
        max_batch: int = 64,
        max_delay_ms: float = 5.0,
        queue_depth: int = 256,
        ckpt_poll_s: float = 2.0,
        canary_fraction: float = 0.0,
        canary_ratio: float = 1.05,
        probe=None,
        hedge_ms: float = 0.0,
        health_s: float = 1.0,
        request_timeout_s: float = 30.0,
        telemetry_port: Optional[int] = None,
        metrics=None,
        seed: int = 0,
        state_path: Optional[str] = None,
        probe_path: Optional[str] = None,
        probe_refresh_s: float = 0.0,
        probe_source=None,
        probe_source_refresh_s: float = 0.0,
    ):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        # the recipe a warm spin-up reuses: add_replica() builds new
        # ServingServers exactly like the boot-time ones (autoscale,
        # serving/ha.py ReplicaAutoscaler)
        self._replica_kw = dict(
            checkpoint_dir=checkpoint_dir, model=model, lam=lam, port=0,
            host=host, max_batch=max_batch, max_delay_ms=max_delay_ms,
            queue_depth=queue_depth, ckpt_poll_s=ckpt_poll_s,
            request_timeout_s=request_timeout_s,
        )
        self._host = host
        self._started = False
        self.replicas: List[ServingServer] = [
            ServingServer(metrics=metrics_mod.Metrics(), **self._replica_kw)
            for _ in range(n_replicas)
        ]
        self.router = ServingRouter(
            [(host, r.bound_port) for r in self.replicas],
            port=router_port, host=host, model=model, lam=lam,
            canary_fraction=canary_fraction, canary_ratio=canary_ratio,
            probe=probe, hedge_ms=hedge_ms, health_s=health_s,
            request_timeout_s=request_timeout_s,
            telemetry_port=telemetry_port, metrics=metrics, seed=seed,
            state_path=state_path, probe_path=probe_path,
            probe_refresh_s=probe_refresh_s, probe_source=probe_source,
            probe_source_refresh_s=probe_source_refresh_s,
        )

    @property
    def router_port(self) -> int:
        return self.router.bound_port

    def kill_replica(self, i: int) -> None:
        """Hard-stop replica `i` mid-traffic (failover/chaos tests): its
        server goes away like a crashed pod; the router's health loop and
        breakers drain it with zero dropped requests."""
        log.warning("killing replica %d (:%d)", i, self.replicas[i].bound_port)
        self.replicas[i].stop()

    # -- elastic membership (autoscale: serving/ha.py) -----------------------

    def add_replica(self) -> ServingServer:
        """Spin up one more replica through the warm boot path (same
        compile cache, same checkpoint dir — it loads the latest file on
        start) and join it to the router's pick pool pre-warmed with the
        promoted weights."""
        r = ServingServer(metrics=metrics_mod.Metrics(), **self._replica_kw)
        if self._started:
            r.start()
        self.replicas.append(r)
        self.router.add_replica(self._host, r.bound_port)
        return r

    def drain_replica(self) -> bool:
        """Drain the newest replica back out (autoscale spin-down):
        removed from the router's pick pool first, THEN stopped — any
        racing call fails over, zero drops.  Refuses to go below one."""
        if len(self.replicas) <= 1:
            return False
        r = self.replicas.pop()
        self.router.remove_replica(f"{self._host}:{r.bound_port}")
        try:
            r.stop()
        except Exception:  # noqa: BLE001 - already-dead replica drains twice
            pass
        return True

    def start(self) -> "ServingFleet":
        for r in self.replicas:
            r.start()
        self._started = True
        self.router.start()
        log.info("serving fleet up: router :%d over %d replicas",
                 self.router_port, len(self.replicas))
        return self

    def await_termination(self) -> None:
        self.router.await_termination()

    def stop(self) -> None:
        self.router.stop()
        for r in self.replicas:
            try:
                r.stop()
            except Exception:  # noqa: BLE001 - a killed replica stops twice
                pass

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
