"""Delta checkpoint distribution — the trainer side of ``PushWeights``
(docs/SERVING.md "serving fleet").

Without this, every serving replica re-reads full checkpoint files from a
shared directory: N replicas x 4 bytes/feature per new version, plus an
orbax restore on each.  ``WeightPusher`` streams versioned weight updates
instead, encoding each version ONCE (the sync broadcast plane's
economics, core/master.py ``_BroadcastState``) and sending each target the
cheapest valid form:

- a sparse absolute-value ``WeightDelta`` vs the previous version
  (rpc/codec.py ``encode_weight_delta`` — the SAME codec the training
  broadcast uses) when the target acknowledged that previous version and
  the delta is below the dense break-even;
- the full tensor otherwise (first contact, dense-ish update, or after a
  NACK/failed push dropped the target's version claim).

A NACK (``PushWeightsReply.ok=false`` — version gap on the replica, or a
canary rollback on the router) resends the full form once; a transport
failure just drops the claim, so the NEXT push is full.  Send bytes are
accounted per target under ``serve.push.*`` (utils/metrics.py
``record_push``), which is what ``bench.py --serve`` gates the wire
savings on.

``CheckpointDistributor`` is the watch loop that turns a training run into
a push stream with no fit-loop coupling: it polls the checkpoint
directory the trainer already writes (``Checkpointer.poll_newer`` — the
same primitive the serving hot-reload poll uses) and pushes every new
step to the fleet, typically to the ROUTER so new versions ride the
canary gate (serving/router.py).  Wired in main.py via ``DSGD_SERVE_PUSH``
on the master/dev roles.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import grpc
import numpy as np

from distributed_sgd_tpu.rpc import codec
from distributed_sgd_tpu.rpc import dsgd_pb2 as pb
from distributed_sgd_tpu.rpc.service import RpcPolicy, ServeStub, new_channel
from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.serving")


def parse_targets(spec: str) -> List[Tuple[str, int]]:
    """'host:port,host:port' -> [(host, port)] (DSGD_SERVE_TARGETS /
    DSGD_SERVE_PUSH grammar; validated at Config construction)."""
    targets = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, port = part.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"serve target {part!r} must be host:port "
                f"(comma-separated list)")
        targets.append((host, int(port)))
    if not targets:
        raise ValueError("serve target list is empty")
    return targets


class WeightPusher:
    """Stateful delta-encoding sender for one fleet of targets."""

    def __init__(self, targets: Sequence[Tuple[str, int]], metrics=None,
                 policy: Optional[RpcPolicy] = None):
        if metrics is None:
            metrics = metrics_mod.global_metrics()
        self.metrics = metrics
        self._policy = policy or RpcPolicy(metrics=metrics)
        self._targets = [(h, int(p)) for h, p in targets]
        self._channels = {t: new_channel(*t) for t in self._targets}
        self._stubs = {t: ServeStub(ch) for t, ch in self._channels.items()}
        # per-target last-acknowledged version; a missing claim -> full form
        self._acked: Dict[Tuple[str, int], int] = {}
        self._prev: Optional[Tuple[int, np.ndarray]] = None

    def _send(self, target, req) -> Optional["pb.PushWeightsReply"]:
        form = "delta" if req.HasField("delta") else "full"
        dense = 4 * (len(self._prev[1]) if self._prev is not None
                     else req.weights.size)
        try:
            reply = self._policy.call_with_retry(
                self._stubs[target].PushWeights, req, peer=target, log=log)
        except grpc.RpcError as e:
            self.metrics.counter(metrics_mod.SERVE_PUSH_ERRORS).increment()
            log.warning("push v%d (%s) to %s:%d failed: %s",
                        req.version, form, *target, e)
            return None
        # accounted per DELIVERED send only: a transport failure shipped
        # nothing, and padding serve.push.bytes would skew the
        # wire-savings ratio bench.py --serve gates on these counters
        metrics_mod.record_push(self.metrics, form, req.ByteSize(), dense)
        return reply

    def push(self, version: int, weights) -> int:
        """Push `weights` as `version` to every target; returns how many
        acknowledged ok.  Encoded at most twice total (one delta, one full
        tensor), shared across all N targets."""
        w = np.ascontiguousarray(np.asarray(weights, dtype=np.float32))
        version = int(version)
        prev_version = self._prev[0] if self._prev is not None else None
        # the shared versioned weight-send plan (rpc/codec.py
        # WeightSendPlan) — the SAME delta-vs-full choice and lazy
        # single encodes the sync broadcast plane and the shard lanes
        # ride; an all-delta round never pays for the full tensor
        plan = codec.plan_weight_send(
            w, self._prev[1] if self._prev is not None else None,
            base_version=prev_version if prev_version is not None else 0)
        delta = plan.delta()
        full = None  # the request wrapper, built lazily around plan.full()

        def full_req():
            nonlocal full
            if full is None:
                full = pb.PushWeightsRequest(version=version)
                full.weights.CopyFrom(plan.full())
            return full

        delta_req = None
        if delta is not None:
            delta_req = pb.PushWeightsRequest(version=version)
            delta_req.delta.CopyFrom(delta)

        acked = 0
        for t in self._targets:
            use_delta = (delta_req is not None
                         and self._acked.get(t) == prev_version
                         and prev_version is not None)
            reply = self._send(t, delta_req if use_delta else full_req())
            if reply is not None and not reply.ok and use_delta:
                # version gap on the target (restart, missed push): one
                # full resend inside the same round
                self.metrics.counter(metrics_mod.SERVE_PUSH_NACK).increment()
                reply = self._send(t, full_req())
            if reply is not None and reply.ok:
                self._acked[t] = version
                acked += 1
            else:
                # transport failure or NACK (e.g. the router rejected the
                # version at its canary gate): drop the claim so the next
                # push starts from the full form
                if reply is not None and not reply.ok:
                    self.metrics.counter(
                        metrics_mod.SERVE_PUSH_NACK).increment()
                self._acked.pop(t, None)
        self._prev = (version, w)
        return acked

    def retarget(self, targets: Sequence[Tuple[str, int]]) -> None:
        """Re-point the pusher at a new target set mid-stream (router
        decider failover, serving/ha.py: the distributor re-targets its
        pushes to the surviving LIVE routers).  Claims and channels of
        KEPT targets survive — their delta chains stay unbroken; dropped
        targets close, new ones start claimless (first contact is a full
        send, as ever)."""
        new = [(h, int(p)) for h, p in targets]
        if not new:
            raise ValueError("retarget needs at least one target")
        for t in self._targets:
            if t not in new:
                self._channels.pop(t).close()
                self._stubs.pop(t)
                self._acked.pop(t, None)
        for t in new:
            if t not in self._channels:
                self._channels[t] = new_channel(*t)
                self._stubs[t] = ServeStub(self._channels[t])
        self._targets = new

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()


class CheckpointDistributor:
    """Checkpoint-directory watcher that streams new steps to the fleet.

    The trainer keeps writing checkpoints exactly as before (fit loops are
    untouched); this loop turns each new step into one ``WeightPusher.push``
    — so checkpoint distribution costs delta bytes on the wire while the
    replicas stay hot mid-traffic, and a router target applies its canary
    gate to every new version.
    """

    def __init__(self, checkpoint_dir: str, targets: Sequence[Tuple[str, int]],
                 poll_s: float = 1.0, metrics=None,
                 policy: Optional[RpcPolicy] = None):
        from distributed_sgd_tpu.checkpoint import Checkpointer

        if poll_s <= 0:
            raise ValueError("poll_s must be > 0")
        self._ckpt = Checkpointer(checkpoint_dir)
        self.pusher = WeightPusher(targets, metrics=metrics, policy=policy)
        self.poll_s = float(poll_s)
        self._last: Optional[int] = None
        self._stop = threading.Event()
        # serializes the watcher thread's poll against start()'s immediate
        # poll and stop()'s final sweep: WeightPusher's _prev/_acked state
        # is not thread-safe, and a push can outlive the thread-join
        # timeout when a target is down (retry backoff >> poll_s)
        self._poll_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-push")

    def poll_once(self) -> bool:
        """Push the newest unseen checkpoint step, if any.  True iff pushed
        to at least one target — a push NO target took does not advance the
        cursor, so the next poll (or the stop() final sweep) retries it
        instead of silently marking the step distributed."""
        with self._poll_lock:
            try:
                restored = self._ckpt.poll_newer(self._last)
            except Exception as e:  # noqa: BLE001 - racing a half-committed write
                log.warning("checkpoint poll for push failed (will retry): %s", e)
                return False
            if restored is None:
                return False
            step, state = restored
            acked = self.pusher.push(step, np.asarray(state["weights"]))
            if not acked:
                log.warning("checkpoint step %d reached NO fleet target; "
                            "will retry", step)
                return False
            log.info("distributed checkpoint step %d to %d/%d fleet target(s)",
                     step, acked, len(self.pusher._targets))
            self._last = step
            return True

    def retarget(self, targets: Sequence[Tuple[str, int]]) -> None:
        """Swap the fleet target set between polls (decider failover:
        drop the dead router, keep pushing to the survivors)."""
        with self._poll_lock:
            self.pusher.retarget(targets)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.poll_once()

    def start(self) -> "CheckpointDistributor":
        self.poll_once()  # push an already-present snapshot immediately
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self.poll_s + 1.0)
        self.poll_once()  # final sweep: a step saved at fit end still ships
        # the lock orders this after any poll the (possibly still-joining)
        # watcher thread had in flight; a post-close loop iteration is
        # impossible because _stop is set before the join above
        with self._poll_lock:
            self.pusher.close()
            self._ckpt.close()
