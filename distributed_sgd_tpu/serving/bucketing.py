"""Powers-of-two shape bucketing for the serving forward pass.

XLA compiles one program per input shape, so letting request batches hit
the jit boundary with their natural (batch, nnz) shapes would compile a
new executable for nearly every flush — the classic serving cold-cache
trap.  Instead both dimensions round up to powers of two (each with a
floor), exactly how the training loader buckets rows by nnz
(data/rcv1.py): a server that has seen B<=64, nnz<=128 traffic holds at
most 7 x 5 = 35 cached executables, and in practice single-digit counts,
so steady-state traffic always lands on a warm program.

Padding is semantically inert by construction: pad cells are
(index=0, value=0), which contribute 0 * w[0] to a margin (ops/sparse.py),
and all-zero pad ROWS produce margins that are sliced off before replies.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

# Floors keep the tiniest requests from fragmenting the cache into 1/2/4
# buckets that save no meaningful padding work.
MIN_BATCH_BUCKET = 4
MIN_NNZ_BUCKET = 8


def bucket_dim(n: int, minimum: int) -> int:
    """Smallest power of two >= max(n, minimum)."""
    return 1 << (max(int(n), int(minimum)) - 1).bit_length()


def bucket_shape(batch_size: int, max_nnz: int) -> Tuple[int, int]:
    """(batch bucket, nnz bucket) for a flush of `batch_size` rows whose
    widest row has `max_nnz` nonzeros."""
    return (
        bucket_dim(batch_size, MIN_BATCH_BUCKET),
        bucket_dim(max_nnz, MIN_NNZ_BUCKET),
    )


def pack_rows(
    rows: Sequence[Tuple[np.ndarray, np.ndarray]],
) -> Tuple[np.ndarray, np.ndarray]:
    """Pack variable-nnz (indices, values) rows into bucket-padded arrays.

    Returns (indices int32[B, P], values f32[B, P]) with B, P the bucketed
    dims; rows beyond len(rows) and cells beyond each row's nnz are
    (0, 0.0) pads.  The per-row fill (including the largest-|value|
    truncation policy for rows wider than the bucket) is ops.sparse.pad_rows
    — one packer for trainer and server; only the batch-dim padding is
    serving-specific.
    """
    from distributed_sgd_tpu.ops.sparse import pad_rows

    widths: List[int] = [len(idx) for idx, _ in rows]
    b, p = bucket_shape(len(rows), max(widths, default=0))
    idx, val = pad_rows(rows, p)
    out_idx = np.zeros((b, p), dtype=np.int32)
    out_val = np.zeros((b, p), dtype=np.float32)
    out_idx[: len(rows)] = idx
    out_val[: len(rows)] = val
    return out_idx, out_val
