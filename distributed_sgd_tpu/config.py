"""Typed configuration with ``DSGD_*`` environment overrides.

Mirrors the reference's 17-field pureconfig case class
(utils/Config.scala:3-21) and its per-key env override scheme
(src/main/resources/application.conf:1-52).  Role selection follows the
reference (Main.scala:122-159): if ``master_host``/``master_port`` are unset
the process runs an in-process dev cluster; if they equal the node's own
host/port the process is the master; otherwise it is a worker.

Capability supersets over the reference (documented, opt-in):
``model`` (hinge | logistic | least_squares), ``checkpoint_dir`` (orbax),
``async_mode`` (gossip | local_sgd), ``sync_period`` for on-mesh
local-SGD, ``feature_shards`` for dp x tp tensor parallelism over a 2-D
mesh (parallel/feature_sharded.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Optional


def _env(name: str, default, cast):
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    if cast is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclass
class Config:
    # -- reference-parity fields (utils/Config.scala:3-21) ------------------
    host: str = "127.0.0.1"
    port: int = 4000
    master_host: Optional[str] = None
    master_port: Optional[int] = None
    batch_size: int = 100
    learning_rate: float = 0.5
    lam: float = 1e-5  # `lambda` in the reference; keyword in Python
    node_count: int = 3
    full: bool = False
    use_async: bool = False  # `async` in the reference; keyword in Python
    record: bool = False
    data_path: str = "data"
    max_epochs: int = 10
    check_every: int = 100
    leaky_loss: float = 0.9
    conv_delta: float = 0.01
    patience: int = 5

    # -- TPU-native extensions ---------------------------------------------
    model: str = "hinge"  # hinge | svm | logistic | least_squares
    seed: int = 0
    engine: str = "mesh"  # mesh (XLA collectives) | rpc (gRPC parity topology)
    async_mode: str = "gossip"  # gossip | local_sgd
    sync_period: int = 16  # local-SGD averaging period (steps)
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 1  # sync-trainer epoch cadence
    heartbeat_s: Optional[float] = None  # master worker-failure detection period
    # consecutive heartbeat misses before eviction (was hardcoded 3 in
    # core/master.py:_heartbeat_loop; docs/FAULT_TOLERANCE.md)
    heartbeat_max_misses: int = 3
    # -- chaos-hardened sync training (docs/FAULT_TOLERANCE.md) ------------
    # quorum: rpc sync fits proceed once `quorum` of N gradient replies are
    # in hand and the straggler soft deadline fired, hedging the missing
    # workers' data slices to fast responders (Chen et al. 2016's backup-
    # replica shape).  None (default) keeps the full barrier — wire and
    # call graph byte-identical to the quorum-less engine.
    quorum: Optional[int] = None
    # soft deadline (seconds) before a quorum round degrades / a stall is
    # counted; None = p95-adaptive from the per-worker reply-latency EWMA
    straggler_soft_s: Optional[float] = None
    # deterministic fault-injection plan applied to every RPC edge of this
    # process (chaos/), e.g.
    # "seed=7;drop=0.05;delay=20ms~200ms;dup=0.01;partition=w2:10s@30s";
    # None/empty = no injection (and no wrapping at all)
    chaos: Optional[str] = None
    # -- distributed tracing + flight recorder (docs/OBSERVABILITY.md) -----
    # trace: per-round span timelines across master/worker/serving with
    # Chrome/Perfetto export (trace/).  Default off; the off path is a
    # provably zero-cost no-op (no span objects are ever allocated) and
    # the wire stays byte-identical either way (context rides gRPC
    # metadata, never the proto).
    trace: bool = False
    # per-process trace files land here (also the flight-recorder dump
    # dir); None with trace=1 defaults to ./dsgd-traces
    trace_dir: Optional[str] = None
    # per-trace_id head sampling in [0, 1]: a sampled round is traced end
    # to end on every node; 1.0 = trace everything
    trace_sample: float = 1.0
    # flight recorder ring capacity (events kept per process for the
    # post-mortem dumps: SIGUSR2, eviction, below-quorum, loop crash);
    # 0 disables recording entirely
    flight_recorder: int = 512
    # -- elastic membership + crash-safe training state (docs/ELASTICITY.md)
    # gossip topology for the async delta plane: all (reference full
    # fan-out, byte-identical default) | ring | random:k — deterministic
    # sparse peer selection per (dispatch, worker) with breaker-aware
    # reselection; the master always receives every delta
    gossip_topology: str = "all"
    # elastic async membership: resplit + re-issue assignments on ANY
    # membership change (join or leave) mid-StartAsync; off keeps the
    # merge-into-survivors eviction path and mid-fit joins idle
    elastic: bool = False
    # batch-drain master inbox: buffer async UpdateGrads and apply one
    # summed update per drain instead of one jitted apply per message
    async_drain: bool = False
    # crash-safe fit-state cadence: snapshot the FULL sync-fit loop state
    # (weights/opt/RNG/epoch/window cursor/fit-token lineage) atomically
    # every N successful windows into checkpoint_dir; 0 disables.  A
    # restarted master resumes bit-exactly from the last snapshot.
    fit_ckpt_every: int = 0
    # -- cluster telemetry plane + training-health monitor (telemetry/) ----
    # telemetry: the master scrapes every registered worker's instrument
    # registry over the Metrics RPC (heartbeat-piggybacked + on-demand)
    # and re-exports the merged series — counters summed, histogram
    # buckets summed exactly, gauges last-write per worker label — on ONE
    # cluster-level /metrics endpoint; workers additionally publish the
    # training-health gauges (gradient norm, dispatch staleness, EF
    # residual norm).  Off (default): no Metrics RPC is ever issued and
    # the wire/call graph stay byte-identical (rpc engine only; the mesh
    # engines are one process — their existing exporter IS cluster-level).
    telemetry: bool = False
    # cluster /metrics bind port on the master (0 = OS-assigned)
    telemetry_port: int = 9091
    # loss-trend watchdog on rpc sync fits (telemetry/health.py): None
    # (default) = no health observation at all; warn = log + flight dump
    # on trip; snapshot = additionally write a resumable fit-state
    # snapshot (needs DSGD_CHECKPOINT_DIR); halt = snapshot, then stop
    # the fit — a dying run leaves evidence and a checkpoint instead of
    # a flat loss curve.
    health_action: Optional[str] = None
    # -- long-horizon resource plane (telemetry/resources.py, ISSUE 20) ----
    # resource-probe cadence in seconds: a dependency-free daemon thread
    # samples /proc/self/{statm,fd,status}, gc stats, and the internal
    # pressure gauges (drain inbox, trace buffer, flight ring, admission
    # queue, compile cache) into proc.* gauges, and feeds the leak-slope
    # sentinel (Theil–Sen over each series; a trip routes through
    # health_action).  0 (default): no probe thread, no proc.* gauges, no
    # blackbox files — knobs-off byte-identical.
    resource_probe_s: float = 0.0
    # crash-surviving blackbox ring dir (telemetry/blackbox.py): each probe
    # tick appends a JSONL snapshot (resources + counters + round cursor)
    # to bounded, atomically-rotated segments; read post-mortem with
    # `python -m distributed_sgd_tpu.telemetry.blackbox`.  Requires
    # resource_probe_s > 0 (the probe is the only writer).
    blackbox_dir: Optional[str] = None
    metrics_port: Optional[int] = None  # Prometheus-style text exporter
    # InfluxDB write endpoint for the push reporter (reference parity:
    # Kamon InfluxDBReporter, application.conf:54-78), e.g.
    # http://influxdb:8086/write?db=dsgd — active when record=true
    influx_url: Optional[str] = None
    profile_dir: Optional[str] = None  # jax.profiler trace output
    pad_width: Optional[int] = None  # sparse-batch nnz padding (None = auto)
    kernel: str = "mxu"  # mxu | scalar (sync-engine sparse kernels)
    # sparse-scatter formulation inside the blocked MXU kernels
    # (ops/mxu.py, ROADMAP item 2): 'onehot' (default — the measured
    # round-4/6 winner, knobs-off training byte-identical to prior
    # rounds), 'segment' / 'twostage' / 'bf16' (the round-6 sweep,
    # selectable for hardware rematches), or 'auto' — measure all four at
    # the loaded dataset's step shape (batch x pad_width x n_features) on
    # THIS device once per process and run the winner.  Read at trace
    # time; main.py resolves it after the data loads, before any engine
    # is built.
    scatter: str = "onehot"
    virtual_workers: int = 1  # reference workers emulated per mesh device
    exact_topology: bool = False  # insist on exactly node_count workers
    optimizer: str = "sgd"  # sgd (reference) | momentum | adam (sync engine)
    momentum: float = 0.9  # used by optimizer='momentum'
    steps_per_dispatch: int = 1  # async: k local steps per gossip dispatch
    # gradient compression on the wire paths (compress/, docs/COMPRESSION.md):
    # sync Gradient replies + async delta gossip.  'none' keeps the wire
    # byte-identical to the uncompressed tree; 'topk' ships the compress_k
    # largest-magnitude coordinates with error feedback; 'qint8' ships
    # stochastically-rounded int8 with per-chunk scales.  In-mesh engines
    # (XLA collectives, no wire) ignore these with a warning.
    compress: str = "none"  # none | topk | qint8
    compress_k: float = 0.01  # topk size: fraction of dim if < 1, count if >= 1
    compress_ef: bool = True  # error-feedback residual accumulation
    # pipelined sync RPC engine (docs/SYNC_PIPELINE.md; engine=rpc sync fits
    # only — the mesh engines have no wire, async has no barrier).  Both
    # default off: the default wire stays byte-identical to the seed.
    # local_steps=K runs K device-side SGD steps per round on each worker
    # (K x fewer barriers/broadcasts per epoch, local-SGD semantics);
    # delta_broadcast replaces the per-window full dense weight broadcast
    # with versioned sparse deltas over worker-side replica caches, with
    # automatic full-broadcast fallback on any mismatch.
    local_steps: int = 1  # sync rpc: K local SGD steps per round
    delta_broadcast: bool = False  # sync rpc: versioned sparse weight broadcasts
    # streaming RPC fan-out (docs/SYNC_PIPELINE.md "Streaming transport"):
    # sync Gradient requests/replies ride ONE persistent bidirectional
    # FitStream per (master, worker) pair instead of one unary call per
    # worker per round, with the encode-ahead thread pre-staging each
    # worker's next request frame.  Bit-identical math (the rpc bench
    # gates drift 0.0); a broken stream falls back to unary per worker
    # (breaker-fed), and older worker binaries answering UNIMPLEMENTED
    # stay unary (mixed fleets keep working).  Off (default): no Frame is
    # ever constructed and the wire stays byte-identical to the seed.
    stream: bool = False  # sync rpc: persistent per-worker gradient streams
    # O(N) master plane (docs/SCALING.md; engine=rpc sync fits only).
    # Both default off: the default fan-in decode and dispatch call graphs
    # stay byte-identical to the serialized master.
    # fanin_lanes=K shards the fan-in DECODE into K lanes — each reply's
    # wire->ndarray parse runs in its own gRPC arrival callback instead of
    # queueing on one decoder lock, while the float accumulation stays one
    # send-ordered chain (weights byte-identical to K=0, asserted).
    fanin_lanes: int = 0
    # stage_pool=P stages round t+1's dispatch during round t's barrier on
    # a P-thread pool: every worker's sample draw (determinism-safe) and
    # request build (weight arm attached) leave the dispatch critical
    # path, for stream and unary fits alike.
    stage_pool: int = 0
    # aggregation tree (aggtree/, docs/AGGREGATION.md): "fanout:F" elects
    # sub-aggregator reduce nodes so the master's fan-in terminates
    # O(F) subtree sums instead of O(N) replies.  "" (default): flat
    # fan-in — no plan built, no reducer constructed, wire byte-identical.
    agg_tree: str = ""
    # feature-sharded master plane (shardedps/, docs/MASTER_SHARDING.md;
    # engine=rpc sync fits only): M >= 1 range-partitions the weight
    # vector across M master shard lanes — per-shard broadcast and
    # fan-in, global step bit-identical to the flat plane.  Composes with
    # delta_broadcast and agg_tree (one shard-colored tree per lane);
    # incompatible with stream / quorum / local_steps>1 / fanin_lanes /
    # stage_pool / compress (validated below).  0 (default): no shard
    # plan built, no shard instrument registered, wire byte-identical.
    master_shards: int = 0
    # tensor parallelism: shard the blocked weight rows over F feature
    # shards (parallel/feature_sharded.py; dev-mode sync scenario only —
    # needs workers x F devices).  1 = the 1-D DP engines (default)
    feature_shards: int = 1
    # -- elastic spin-up fast path (compile_cache.py, data/row_store.py;
    # docs/HIERARCHY.md "Elastic composition") --------------------------
    # persistent compile cache + AOT warmup: point jax's persistent
    # compilation cache at a (shareable) directory and pre-compile each
    # role's flagship shapes on a background thread at bind/build time,
    # so a joining worker / restarted master / fresh serve replica never
    # JITs under traffic.  None (default): jax's cache config untouched,
    # no warmup thread, zero files written (asserted by test + bench).
    compile_cache: Optional[str] = None
    # neighbor-range over-provisioning for host-local slices: each
    # worker loads ceil(f * slice) extra rows on both sides, so an
    # elastic resplit within the margin costs ZERO reload and a bigger
    # shift re-loads only the uncovered delta through its RowReader.
    # 0 (default) keeps exact-slice loading byte-identical.
    host_overprovision: float = 0.0
    # mmap row store (data/row_store.py): path to a packed binary corpus
    # built once from the parser (build_from_corpus).  A worker role with
    # a store maps it instead of parsing, and with host_index loads ONLY
    # its slice — the real-corpus no-egress host-local loading path.
    row_store: Optional[str] = None
    # this worker's position in the master's node_count-way contiguous
    # split (worker role + row_store): load rows host_slice(train_rows,
    # host_index, node_count) through the store's reader.  None = the
    # full train split is resident (ids pass through untouched).
    host_index: Optional[int] = None
    # hierarchical multi-host training (docs/HIERARCHY.md, engine=rpc):
    # each RPC worker becomes a D-device host — Gradient/local-window
    # batches shard over a local mesh and reduce with one in-host psum,
    # so the cross-host plane (delta broadcasts, compression, quorum)
    # runs per HOST instead of per device.  1 (default) = the flat
    # single-device worker, byte-identical wire and weights; 0 = auto
    # (jax.local_device_count(), resolved at role start-up).
    host_devices: int = 1

    # -- serving roles (serving/; docs/SERVING.md) -------------------------
    # DSGD_ROLE overrides the master_host/master_port-derived role below;
    # 'serve' (a replica / single node) and 'route' (the fleet router) are
    # the roles with no derivation rule (neither has a place in the
    # training topology), the other three make an implicit deployment
    # explicit.  None = derive (reference behavior).
    role_override: Optional[str] = None
    serve_port: int = 4100  # gRPC dsgd.Serving bind port (replica OR router)
    serve_max_batch: int = 64  # micro-batch flush size cap
    serve_max_delay_ms: float = 5.0  # coalescing window from oldest queued row
    serve_queue_depth: int = 256  # admission bound -> RESOURCE_EXHAUSTED
    serve_ckpt_poll_s: float = 2.0  # checkpoint hot-reload poll period
    # -- serving fleet (serving/router.py + serving/push.py) ---------------
    # All default-off: with every knob below unset, role=serve builds the
    # single-node server byte-identical to the pre-fleet subsystem
    # (asserted by tests/test_router.py).
    # role=serve only: N in-process replicas behind an in-process router
    # on serve_port (the one-machine fleet; kube runs real pods instead).
    # 0 = the single-node server.
    serve_replicas: int = 0
    # role=route: the replica endpoints to balance over, 'host:port,...'
    # (kube/serve.yaml lists the StatefulSet pod DNS names here)
    serve_targets: Optional[str] = None
    # master/dev roles: fleet endpoints (typically the ROUTER) the
    # trainer's checkpoint distributor streams weight deltas to
    # (serving/push.py CheckpointDistributor); needs DSGD_CHECKPOINT_DIR
    serve_push: Optional[str] = None
    # canary fraction of the fleet a pushed version lands on first; the
    # router promotes it fleet-wide only when the probe-set loss does not
    # regress vs the promoted baseline (0 = no canary gate)
    serve_canary: float = 0.0
    # held-out probe set for the canary gate: an .npz with padded 2-D
    # indices/values + 1-D labels (serving/router.py load_probe)
    serve_probe: Optional[str] = None
    # hedge deadline: a routed Predict slower than this races a duplicate
    # on the next-best replica, first success wins (0 = no hedging)
    serve_hedge_ms: float = 0.0
    serve_health_s: float = 1.0  # router ServeHealth poll period
    # promoted-state persistence (serving/router.py): a JSON sidecar the
    # router rewrites on every promote/rollback, so a RESTARTED router
    # re-pins the already-promoted serving version (and keeps its probe
    # baseline + rejected set) instead of re-canarying it.  None
    # (default): router state is in-memory only, byte-identical behavior.
    serve_state: Optional[str] = None
    # canary probe-set refresh cadence (seconds; docs/SERVING.md): with
    # f > 0 the router re-reads DSGD_SERVE_PROBE every f seconds (mtime-
    # gated) and rotates the fresh held-out rows in, re-anchoring the
    # canary baseline on the PROMOTED version's loss over the new rows —
    # a long-running fleet's gate tracks live traffic instead of
    # fossilizing on the rows it started with.  0 (default): the probe
    # set and baseline are fixed at fleet start, byte-identical behavior.
    serve_probe_refresh_s: float = 0.0
    # serving-plane HA (serving/ha.py; docs/SERVING.md "HA"): peer LIVE
    # router endpoints this router syncs promoted state with, as
    # 'peers:<host:port,...>[;self=<host:port>][;sync=<dur>][;ttl=<dur>]
    # [;lease=<path>]'.  One router holds the decider lease for promote/
    # rollback verdicts; the others mirror every transition over the
    # SyncServeState RPC within one sync interval and assume the lease if
    # it lapses.  None (default): single-router plane, no sync RPC ever
    # issued, byte-identical serving wire.
    serve_ha: Optional[str] = None
    # load-adaptive replica autoscale SLO in milliseconds (serving/ha.py
    # ReplicaAutoscaler; fleet mode, role=serve + serve_replicas > 0):
    # when the router's worst eligible-replica load signal (EWMA latency
    # x in-flight) sits over this for consecutive ticks, a replica spins
    # up through the warm boot path; sustained idle drains one.  0
    # (default): fixed fleet size.
    serve_slo_ms: float = 0.0
    # autoscale fleet-size ceiling (floor is the boot size)
    serve_scale_max: int = 8
    # dead time after every autoscale action: hysteresis against flapping
    serve_scale_cooldown_s: float = 5.0

    # -- continual-learning autopilot (autopilot/; docs/CONTINUAL.md) -------
    # All default-off: with DSGD_AUTOPILOT unset no autopilot thread
    # starts, no reservoir attaches to the router, no new instrument
    # registers, and serving wire + training weights stay byte-identical
    # (asserted by tests/test_flywheel.py).
    # master-of-switch: arm the flywheel.  dev role runs the full loop
    # (probe sourcing + drift detection + warm-start retrain through the
    # canary gate); route role attaches probe sourcing + the refresh
    # cadence to the router (the drift SIGNAL, readable over /metrics);
    # master role makes the retrain entry available.  serve/worker roles
    # have no flywheel half and reject the knob at construction.
    autopilot: bool = False
    autopilot_poll_s: float = 1.0  # controller probe-loss poll period
    autopilot_cooldown_s: float = 5.0  # post-verdict settle before re-arming
    # drift rule (controller.DriftDetector, the HealthMonitor shape):
    # EWMA(probe loss) > max(ratio * baseline, baseline + floor) for
    # `patience` consecutive refreshes after `warmup` — the floor keeps
    # the bounded-probe sampling noise (a capacity-row mean quantizes
    # loss in 1/capacity steps) from ever clearing the ratio bar when
    # the baseline lands near zero
    autopilot_drift_ratio: float = 1.5
    autopilot_drift_patience: int = 2
    autopilot_drift_warmup: int = 4
    autopilot_drift_floor: float = 0.1
    # retrain window: the newest N stream rows the warm-start fit trains
    # on (autopilot/stream.window_split — "the current distribution")
    autopilot_window: int = 4096
    autopilot_max_retrains: int = 0  # 0 = unbounded; N caps the flywheel
    autopilot_canary_timeout_s: float = 120.0  # verdict wait before giving up
    # residual settling: after a promotion re-anchors the detector, keep
    # retraining while EWMA(probe loss) stays above band * the pre-trip
    # healthy baseline — a retrain window that straddled the shift only
    # half-recovers, and the rebase would otherwise normalize the
    # plateau.  Must exceed 1; 0 disables (one retrain per trip).
    autopilot_recovery_band: float = 1.35
    # live probe sourcing (autopilot/probe_source.py): reservoir capacity,
    # the label-delay model (ground truth arrives `label_delay` requests
    # late), and the cadence at which the sampled rows rotate in as the
    # canary probe set (each rotation re-probes the promoted version —
    # the drift signal's sample rate)
    autopilot_probe_capacity: int = 64
    autopilot_label_delay: int = 0
    autopilot_source_refresh_s: float = 2.0

    _CHOICES = {
        "model": ("hinge", "svm", "logistic", "least_squares"),
        "engine": ("mesh", "rpc"),
        "async_mode": ("gossip", "local_sgd"),
        # 'dense' is auto-selected from the data layout, never configured;
        # 'pallas' is an experiment demoted from the config surface — it
        # measured slower than 'mxu' at every swept shape and VMEM-OOMs at
        # large batches (benches/pallas_sweep.py; BASELINE.md) — but stays
        # reachable through SyncEngine(kernel='pallas') for kernel work
        "kernel": ("mxu", "scalar"),
        # 'auto' defers to a runtime rematch on the actual device
        # (ops/mxu.resolve_scatter_formulation); the rest select directly
        "scatter": ("auto", "onehot", "segment", "twostage", "bf16"),
        "optimizer": ("sgd", "momentum", "adam"),
        "compress": ("none", "topk", "qint8"),
    }

    def __post_init__(self):
        for name, choices in self._CHOICES.items():
            v = getattr(self, name)
            if v not in choices:
                raise ValueError(
                    f"config field {name}={v!r} must be one of {choices}"
                )
        if self.virtual_workers < 1:
            raise ValueError("virtual_workers must be >= 1")
        if self.heartbeat_max_misses < 1:
            raise ValueError("heartbeat_max_misses must be >= 1")
        if self.quorum is not None and self.quorum < 1:
            raise ValueError("quorum must be >= 1 (or unset for a full barrier)")
        if self.straggler_soft_s is not None and self.straggler_soft_s <= 0:
            raise ValueError("straggler_soft_s must be > 0 (or unset for adaptive)")
        if self.chaos:
            # fail typos at construction, not mid-fit: the plan grammar is
            # owned by chaos.parse_plan
            from distributed_sgd_tpu.chaos import parse_plan

            parse_plan(self.chaos)
        if self.agg_tree:
            # same discipline: the tree grammar is owned by aggtree.plan
            from distributed_sgd_tpu.aggtree import parse_agg_tree

            parse_agg_tree(self.agg_tree)
        # shard-count grammar owned by shardedps.plan; the composition
        # matrix (docs/MASTER_SHARDING.md) is enforced at construction so
        # an incompatible pair fails here, not windows into a fit
        from distributed_sgd_tpu.shardedps import parse_master_shards

        if parse_master_shards(self.master_shards):
            for bad, knob in ((self.stream, "DSGD_STREAM"),
                              (self.quorum is not None, "DSGD_QUORUM"),
                              (self.local_steps > 1, "DSGD_LOCAL_STEPS"),
                              (self.fanin_lanes > 0, "DSGD_FANIN_LANES"),
                              (self.stage_pool > 0, "DSGD_STAGE_POOL"),
                              (self.compress != "none", "DSGD_COMPRESS")):
                if bad:
                    raise ValueError(
                        f"DSGD_MASTER_SHARDS does not compose with {knob} "
                        f"(docs/MASTER_SHARDING.md composition table)")
        # fail topology typos at construction; grammar owned by
        # parallel/topology.parse_topology
        from distributed_sgd_tpu.parallel.topology import parse_topology

        parse_topology(self.gossip_topology)
        if self.fit_ckpt_every < 0:
            raise ValueError("fit_ckpt_every must be >= 0 (0 disables)")
        if self.fit_ckpt_every > 0 and not self.checkpoint_dir:
            raise ValueError(
                "DSGD_FIT_CKPT_EVERY needs DSGD_CHECKPOINT_DIR: the crash "
                "snapshot lives under the checkpoint directory")
        if not 0.0 <= self.trace_sample <= 1.0:
            raise ValueError("trace_sample must be a probability in [0, 1]")
        if self.telemetry_port < 0:
            raise ValueError("telemetry_port must be >= 0 (0 = OS-assigned)")
        if self.health_action not in (None, "warn", "snapshot", "halt"):
            raise ValueError(
                f"DSGD_HEALTH_ACTION={self.health_action!r} must be one of "
                f"warn | snapshot | halt (unset = no health monitor)")
        if (self.health_action in ("snapshot", "halt")
                and not self.checkpoint_dir):
            raise ValueError(
                f"DSGD_HEALTH_ACTION={self.health_action} needs "
                f"DSGD_CHECKPOINT_DIR: the resumable trip snapshot lives "
                f"under the checkpoint directory")
        if self.flight_recorder < 0:
            raise ValueError("flight_recorder must be >= 0 (0 disables)")
        if self.resource_probe_s < 0:
            raise ValueError(
                "DSGD_RESOURCE_PROBE_S must be >= 0 (0 = no resource probe)")
        if self.blackbox_dir and self.resource_probe_s <= 0:
            raise ValueError(
                "DSGD_BLACKBOX_DIR needs DSGD_RESOURCE_PROBE_S > 0: the "
                "resource probe is the blackbox's only writer")
        if self.checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        if self.steps_per_dispatch < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        if self.local_steps < 1:
            raise ValueError("local_steps must be >= 1")
        if self.fanin_lanes < 0:
            raise ValueError(
                "DSGD_FANIN_LANES must be >= 0 (0 = single-lock fan-in "
                "decode; K shards the decode into K lanes)")
        if self.stage_pool < 0:
            raise ValueError(
                "DSGD_STAGE_POOL must be >= 0 (0 = draws and request "
                "builds on the dispatch path; P stages them on a P-thread "
                "pool during the previous barrier)")
        if self.compress_k <= 0:
            raise ValueError("compress_k must be > 0 (fraction of dim or count)")
        if self.feature_shards < 1:
            raise ValueError("feature_shards must be >= 1")
        if self.host_devices < 0:
            raise ValueError(
                "host_devices must be >= 0 (0 = auto from "
                "jax.local_device_count(); 1 = flat single-device worker)")
        # -- elastic spin-up fast path --------------------------------------
        if not 0.0 <= self.host_overprovision <= 1.0:
            raise ValueError(
                "DSGD_HOST_OVERPROVISION must be a fraction in [0, 1] "
                "(0 = exact slices; f loads ceil(f * slice) neighbor rows "
                "on each side)")
        if self.host_index is not None:
            if not self.row_store:
                raise ValueError(
                    "DSGD_HOST_INDEX needs DSGD_ROW_STORE: a host-local "
                    "slice is loaded through the store's row reader (the "
                    "full-parse path always materializes the corpus)")
            if not 0 <= self.host_index < self.node_count:
                raise ValueError(
                    f"DSGD_HOST_INDEX={self.host_index} outside "
                    f"[0, node_count={self.node_count})")
        if self.host_index is not None and self.host_devices not in (0, 1):
            raise ValueError(
                "DSGD_HOST_INDEX with DSGD_HOST_DEVICES > 1 is not "
                "supported yet: the in-host mesh binds its slice at build "
                "time (no incremental reload)")
        if self.feature_shards > 1 and self.use_async:
            raise ValueError(
                "feature_shards is a sync (2-D mesh) engine; it cannot be "
                "combined with use_async"
            )
        if self.feature_shards > 1 and self.engine == "rpc":
            raise ValueError(
                "feature_shards needs the mesh engine (2-D shard_map); the "
                "rpc topology has no feature axis"
            )
        if self.feature_shards > 1 and self.optimizer != "sgd":
            raise ValueError(
                "the feature-sharded engine runs the reference's plain SGD "
                "update; optimizer must be 'sgd' when feature_shards > 1"
            )
        if self.exact_topology and self.virtual_workers != 1:
            raise ValueError(
                "exact_topology and an explicit virtual_workers are mutually "
                "exclusive: virtual_workers pins the per-device emulation "
                "directly, so the exact-topology solver would be ignored"
            )
        if self.role_override not in (None, "dev", "master", "worker",
                                      "serve", "route"):
            raise ValueError(
                f"DSGD_ROLE={self.role_override!r} must be one of "
                f"dev | master | worker | serve | route (unset = derive from "
                f"master_host/master_port)"
            )
        if self.role_override == "serve" and not self.checkpoint_dir:
            raise ValueError(
                "role=serve needs checkpoint_dir (DSGD_CHECKPOINT_DIR): "
                "serving loads and hot-reloads the trainer's checkpoints"
            )
        if self.serve_max_batch < 1:
            raise ValueError("serve_max_batch must be >= 1")
        if self.serve_max_delay_ms < 0:
            raise ValueError("serve_max_delay_ms must be >= 0")
        if self.serve_queue_depth < 1:
            raise ValueError("serve_queue_depth must be >= 1")
        if self.serve_ckpt_poll_s <= 0:
            raise ValueError("serve_ckpt_poll_s must be > 0")
        # -- serving fleet (docs/SERVING.md "serving fleet") ----------------
        if self.serve_replicas < 0:
            raise ValueError("serve_replicas must be >= 0 (0 = single node)")
        if self.role_override == "route" and not self.serve_targets:
            raise ValueError(
                "role=route needs DSGD_SERVE_TARGETS: the router balances "
                "over an explicit replica endpoint list (host:port,...)")
        for spec in (self.serve_targets, self.serve_push):
            if spec:
                # fail endpoint-list typos at construction, not mid-route;
                # grammar owned by serving.push.parse_targets
                from distributed_sgd_tpu.serving.push import parse_targets

                parse_targets(spec)
        if self.serve_push and not self.checkpoint_dir:
            raise ValueError(
                "DSGD_SERVE_PUSH needs DSGD_CHECKPOINT_DIR: the checkpoint "
                "distributor watches the trainer's checkpoint directory")
        if not 0.0 <= self.serve_canary <= 1.0:
            raise ValueError("serve_canary must be a fraction in [0, 1]")
        if (self.serve_canary > 0 and not self.serve_probe
                and self.role_override in ("route", "serve")):
            # an armed canary with nothing to evaluate would silently
            # promote every version ungated — the operator believes a
            # gate exists; fail at construction like every other
            # cross-field dependency (fleet APIs pass probe rows
            # directly, so only the env-driven roles need the pairing)
            raise ValueError(
                "DSGD_SERVE_CANARY > 0 needs DSGD_SERVE_PROBE: the canary "
                "gate evaluates pushed versions against a held-out probe "
                "set (docs/SERVING.md)")
        if self.serve_hedge_ms < 0:
            raise ValueError("serve_hedge_ms must be >= 0 (0 = no hedging)")
        if self.serve_health_s <= 0:
            raise ValueError("serve_health_s must be > 0")
        if self.serve_probe_refresh_s < 0:
            raise ValueError(
                "DSGD_SERVE_PROBE_REFRESH_S must be >= 0 (0 = fixed probe "
                "set)")
        if (self.serve_probe_refresh_s > 0 and not self.serve_probe
                and self.role_override in ("route", "serve")):
            raise ValueError(
                "DSGD_SERVE_PROBE_REFRESH_S > 0 needs DSGD_SERVE_PROBE: "
                "the refresh re-reads the probe file on its cadence "
                "(docs/SERVING.md)")
        # -- serving-plane HA + autoscale (docs/SERVING.md "HA") ------------
        if self.serve_ha:
            if self.role_override != "route":
                raise ValueError(
                    "DSGD_SERVE_HA is a router knob (DSGD_ROLE=route): "
                    "peer promoted-state sync runs between LIVE routers")
            # fail spec typos at construction, not on the first sync;
            # grammar owned by serving.ha.parse_ha_spec
            from distributed_sgd_tpu.serving.ha import parse_ha_spec

            parse_ha_spec(self.serve_ha)
        if self.serve_slo_ms < 0:
            raise ValueError(
                "DSGD_SERVE_SLO_MS must be >= 0 (0 = autoscale off)")
        if (self.serve_slo_ms > 0 and self.role_override == "serve"
                and self.serve_replicas < 1):
            raise ValueError(
                "DSGD_SERVE_SLO_MS needs the fleet mode "
                "(DSGD_SERVE_REPLICAS >= 1): the autoscaler grows and "
                "shrinks an in-process replica fleet")
        if self.serve_scale_max < 1:
            raise ValueError("DSGD_SERVE_SCALE_MAX must be >= 1")
        if (self.serve_slo_ms > 0
                and self.serve_scale_max < max(1, self.serve_replicas)):
            raise ValueError(
                "DSGD_SERVE_SCALE_MAX must be >= the boot fleet size "
                "(DSGD_SERVE_REPLICAS): the boot size is the scale floor")
        if self.serve_scale_cooldown_s < 0:
            raise ValueError("DSGD_SERVE_SCALE_COOLDOWN_S must be >= 0")
        # -- continual-learning autopilot (docs/CONTINUAL.md) ---------------
        if self.autopilot_poll_s <= 0:
            raise ValueError("DSGD_AUTOPILOT_POLL_S must be > 0")
        if self.autopilot_cooldown_s < 0:
            raise ValueError("DSGD_AUTOPILOT_COOLDOWN_S must be >= 0")
        if self.autopilot_drift_ratio <= 1.0:
            raise ValueError(
                "DSGD_AUTOPILOT_DRIFT_RATIO must be > 1 (the drift rule "
                "compares EWMA probe loss against ratio x baseline)")
        if self.autopilot_drift_patience < 1:
            raise ValueError("DSGD_AUTOPILOT_DRIFT_PATIENCE must be >= 1")
        if self.autopilot_drift_warmup < 0:
            raise ValueError("DSGD_AUTOPILOT_DRIFT_WARMUP must be >= 0")
        if self.autopilot_drift_floor < 0:
            raise ValueError("DSGD_AUTOPILOT_DRIFT_FLOOR must be >= 0")
        if self.autopilot_window < 1:
            raise ValueError("DSGD_AUTOPILOT_WINDOW must be >= 1 rows")
        if self.autopilot_max_retrains < 0:
            raise ValueError(
                "DSGD_AUTOPILOT_MAX_RETRAINS must be >= 0 (0 = unbounded)")
        if self.autopilot_canary_timeout_s <= 0:
            raise ValueError("DSGD_AUTOPILOT_CANARY_TIMEOUT_S must be > 0")
        if self.autopilot_recovery_band and self.autopilot_recovery_band <= 1:
            raise ValueError(
                "DSGD_AUTOPILOT_RECOVERY_BAND must be > 1 (0 disables "
                "residual settling)")
        if self.autopilot_probe_capacity < 1:
            raise ValueError("DSGD_AUTOPILOT_PROBE_CAPACITY must be >= 1")
        if self.autopilot_label_delay < 0:
            raise ValueError("DSGD_AUTOPILOT_LABEL_DELAY must be >= 0")
        if self.autopilot_source_refresh_s <= 0:
            raise ValueError("DSGD_AUTOPILOT_SOURCE_REFRESH_S must be > 0")
        if self.autopilot and self.role_override in ("serve", "worker"):
            raise ValueError(
                f"DSGD_AUTOPILOT has no {self.role_override} half: the "
                f"flywheel lives in the dev/route/master roles "
                f"(docs/CONTINUAL.md)")
        if self.autopilot and self.serve_probe_refresh_s > 0:
            raise ValueError(
                "DSGD_AUTOPILOT and DSGD_SERVE_PROBE_REFRESH_S are "
                "mutually exclusive: the traffic reservoir REPLACES the "
                "operator-rotated probe file (docs/CONTINUAL.md)")

    @property
    def role(self) -> str:
        """'dev' | 'master' | 'worker' per Main.scala:122-159, or any of
        those plus 'serve' / 'route' when DSGD_ROLE overrides the
        derivation."""
        if self.role_override is not None:
            return self.role_override
        if self.master_host is None or self.master_port is None:
            return "dev"
        if (self.master_host, self.master_port) == (self.host, self.port):
            return "master"
        return "worker"

    @classmethod
    def from_env(cls, **overrides) -> "Config":
        """Build from DSGD_* env vars (application.conf:1-52 names)."""
        cfg = cls(
            host=_env("DSGD_NODE_HOST", cls.host, str),
            port=_env("DSGD_NODE_PORT", cls.port, int),
            master_host=_env("DSGD_MASTER_HOST", None, str),
            master_port=_env("DSGD_MASTER_PORT", None, int),
            batch_size=_env("DSGD_BATCH_SIZE", cls.batch_size, int),
            learning_rate=_env("DSGD_LEARNING_RATE", cls.learning_rate, float),
            lam=_env("DSGD_LAMBDA", cls.lam, float),
            node_count=_env("DSGD_NODE_COUNT", cls.node_count, int),
            full=_env("DSGD_FULL", cls.full, bool),
            use_async=_env("DSGD_ASYNC", cls.use_async, bool),
            record=_env("DSGD_RECORD", cls.record, bool),
            data_path=_env("DSGD_DATA_PATH", cls.data_path, str),
            max_epochs=_env("DSGD_MAX_EPOCHS", cls.max_epochs, int),
            check_every=_env("DSGD_CHECK_EVERY", cls.check_every, int),
            leaky_loss=_env("DSGD_LEAKY_LOSS", cls.leaky_loss, float),
            conv_delta=_env("DSGD_CONV_DELTA", cls.conv_delta, float),
            patience=_env("DSGD_PATIENCE", cls.patience, int),
            model=_env("DSGD_MODEL", cls.model, str),
            seed=_env("DSGD_SEED", cls.seed, int),
            engine=_env("DSGD_ENGINE", cls.engine, str),
            async_mode=_env("DSGD_ASYNC_MODE", cls.async_mode, str),
            sync_period=_env("DSGD_SYNC_PERIOD", cls.sync_period, int),
            checkpoint_dir=_env("DSGD_CHECKPOINT_DIR", None, str),
            checkpoint_every=_env("DSGD_CHECKPOINT_EVERY", cls.checkpoint_every, int),
            heartbeat_s=_env("DSGD_HEARTBEAT_S", None, float),
            heartbeat_max_misses=_env("DSGD_HEARTBEAT_MAX_MISSES",
                                      cls.heartbeat_max_misses, int),
            quorum=_env("DSGD_QUORUM", None, int),
            straggler_soft_s=_env("DSGD_STRAGGLER_SOFT_S", None, float),
            chaos=_env("DSGD_CHAOS", None, str),
            trace=_env("DSGD_TRACE", cls.trace, bool),
            trace_dir=_env("DSGD_TRACE_DIR", None, str),
            trace_sample=_env("DSGD_TRACE_SAMPLE", cls.trace_sample, float),
            flight_recorder=_env("DSGD_FLIGHT_RECORDER",
                                 cls.flight_recorder, int),
            gossip_topology=_env("DSGD_GOSSIP_TOPOLOGY",
                                 cls.gossip_topology, str),
            elastic=_env("DSGD_ELASTIC", cls.elastic, bool),
            async_drain=_env("DSGD_ASYNC_DRAIN", cls.async_drain, bool),
            fit_ckpt_every=_env("DSGD_FIT_CKPT_EVERY", cls.fit_ckpt_every, int),
            telemetry=_env("DSGD_TELEMETRY", cls.telemetry, bool),
            telemetry_port=_env("DSGD_TELEMETRY_PORT", cls.telemetry_port, int),
            health_action=_env("DSGD_HEALTH_ACTION", None, str),
            resource_probe_s=_env("DSGD_RESOURCE_PROBE_S",
                                  cls.resource_probe_s, float),
            blackbox_dir=_env("DSGD_BLACKBOX_DIR", None, str),
            metrics_port=_env("DSGD_METRICS_PORT", None, int),
            influx_url=_env("DSGD_INFLUX_URL", None, str),
            profile_dir=_env("DSGD_PROFILE_DIR", None, str),
            pad_width=_env("DSGD_PAD_WIDTH", None, int),
            kernel=_env("DSGD_KERNEL", cls.kernel, str),
            scatter=_env("DSGD_SCATTER", cls.scatter, str),
            virtual_workers=_env("DSGD_VIRTUAL_WORKERS", cls.virtual_workers, int),
            exact_topology=_env("DSGD_EXACT_TOPOLOGY", cls.exact_topology, bool),
            optimizer=_env("DSGD_OPTIMIZER", cls.optimizer, str),
            momentum=_env("DSGD_MOMENTUM", cls.momentum, float),
            steps_per_dispatch=_env("DSGD_STEPS_PER_DISPATCH", cls.steps_per_dispatch, int),
            compress=_env("DSGD_COMPRESS", cls.compress, str),
            compress_k=_env("DSGD_COMPRESS_K", cls.compress_k, float),
            compress_ef=_env("DSGD_COMPRESS_EF", cls.compress_ef, bool),
            local_steps=_env("DSGD_LOCAL_STEPS", cls.local_steps, int),
            delta_broadcast=_env("DSGD_DELTA_BROADCAST", cls.delta_broadcast, bool),
            stream=_env("DSGD_STREAM", cls.stream, bool),
            fanin_lanes=_env("DSGD_FANIN_LANES", cls.fanin_lanes, int),
            stage_pool=_env("DSGD_STAGE_POOL", cls.stage_pool, int),
            agg_tree=_env("DSGD_AGG_TREE", cls.agg_tree, str),
            master_shards=_env("DSGD_MASTER_SHARDS", cls.master_shards, int),
            feature_shards=_env("DSGD_FEATURE_SHARDS", cls.feature_shards, int),
            host_devices=_env("DSGD_HOST_DEVICES", cls.host_devices, int),
            compile_cache=_env("DSGD_COMPILE_CACHE", None, str),
            host_overprovision=_env("DSGD_HOST_OVERPROVISION",
                                    cls.host_overprovision, float),
            row_store=_env("DSGD_ROW_STORE", None, str),
            host_index=_env("DSGD_HOST_INDEX", None, int),
            role_override=_env("DSGD_ROLE", None, str),
            serve_port=_env("DSGD_SERVE_PORT", cls.serve_port, int),
            serve_max_batch=_env("DSGD_SERVE_MAX_BATCH", cls.serve_max_batch, int),
            serve_max_delay_ms=_env("DSGD_SERVE_MAX_DELAY_MS", cls.serve_max_delay_ms, float),
            serve_queue_depth=_env("DSGD_SERVE_QUEUE_DEPTH", cls.serve_queue_depth, int),
            serve_ckpt_poll_s=_env("DSGD_SERVE_CKPT_POLL_S", cls.serve_ckpt_poll_s, float),
            serve_replicas=_env("DSGD_SERVE_REPLICAS", cls.serve_replicas, int),
            serve_targets=_env("DSGD_SERVE_TARGETS", None, str),
            serve_push=_env("DSGD_SERVE_PUSH", None, str),
            serve_canary=_env("DSGD_SERVE_CANARY", cls.serve_canary, float),
            serve_probe=_env("DSGD_SERVE_PROBE", None, str),
            serve_hedge_ms=_env("DSGD_SERVE_HEDGE_MS", cls.serve_hedge_ms, float),
            serve_health_s=_env("DSGD_SERVE_HEALTH_S", cls.serve_health_s, float),
            serve_state=_env("DSGD_SERVE_STATE", None, str),
            serve_probe_refresh_s=_env("DSGD_SERVE_PROBE_REFRESH_S",
                                       cls.serve_probe_refresh_s, float),
            serve_ha=_env("DSGD_SERVE_HA", None, str),
            serve_slo_ms=_env("DSGD_SERVE_SLO_MS", cls.serve_slo_ms, float),
            serve_scale_max=_env("DSGD_SERVE_SCALE_MAX",
                                 cls.serve_scale_max, int),
            serve_scale_cooldown_s=_env("DSGD_SERVE_SCALE_COOLDOWN_S",
                                        cls.serve_scale_cooldown_s, float),
            autopilot=_env("DSGD_AUTOPILOT", cls.autopilot, bool),
            autopilot_poll_s=_env("DSGD_AUTOPILOT_POLL_S",
                                  cls.autopilot_poll_s, float),
            autopilot_cooldown_s=_env("DSGD_AUTOPILOT_COOLDOWN_S",
                                      cls.autopilot_cooldown_s, float),
            autopilot_drift_ratio=_env("DSGD_AUTOPILOT_DRIFT_RATIO",
                                       cls.autopilot_drift_ratio, float),
            autopilot_drift_patience=_env("DSGD_AUTOPILOT_DRIFT_PATIENCE",
                                          cls.autopilot_drift_patience, int),
            autopilot_drift_warmup=_env("DSGD_AUTOPILOT_DRIFT_WARMUP",
                                        cls.autopilot_drift_warmup, int),
            autopilot_drift_floor=_env("DSGD_AUTOPILOT_DRIFT_FLOOR",
                                       cls.autopilot_drift_floor, float),
            autopilot_window=_env("DSGD_AUTOPILOT_WINDOW",
                                  cls.autopilot_window, int),
            autopilot_max_retrains=_env("DSGD_AUTOPILOT_MAX_RETRAINS",
                                        cls.autopilot_max_retrains, int),
            autopilot_recovery_band=_env("DSGD_AUTOPILOT_RECOVERY_BAND",
                                         cls.autopilot_recovery_band, float),
            autopilot_canary_timeout_s=_env(
                "DSGD_AUTOPILOT_CANARY_TIMEOUT_S",
                cls.autopilot_canary_timeout_s, float),
            autopilot_probe_capacity=_env("DSGD_AUTOPILOT_PROBE_CAPACITY",
                                          cls.autopilot_probe_capacity, int),
            autopilot_label_delay=_env("DSGD_AUTOPILOT_LABEL_DELAY",
                                       cls.autopilot_label_delay, int),
            autopilot_source_refresh_s=_env(
                "DSGD_AUTOPILOT_SOURCE_REFRESH_S",
                cls.autopilot_source_refresh_s, float),
        )
        return dataclasses.replace(cfg, **overrides)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Config":
        return cls(**json.loads(s))
