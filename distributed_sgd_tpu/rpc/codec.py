"""numpy <-> wire codecs.

The counterpart of the reference's ScalaPB TypeMappers that marshal proto
maps into `math.Vec` (core/package.scala:11-13, proto.proto:8-11).  Dense
f32 vectors travel as raw little-endian bytes; small-support deltas can
travel as coordinate lists, chosen automatically by `encode_grad` when the
sparse form is smaller on the wire.

Lossy compressed forms (CompressedGrad: top-k coordinate lists, int8
quantization with per-chunk scales) live here as STATELESS pack/unpack
functions; the policy and state around them — which codec, error-feedback
residuals, comms accounting — is the compress/ subsystem's job
(docs/COMPRESSION.md).  `decode_grad` understands every arm, so receivers
never need to know what the sender negotiated.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from distributed_sgd_tpu.rpc import dsgd_pb2 as pb

QINT8_CHUNK = 512  # default elements per quantization scale chunk
_QINT8_LEVELS = 127.0  # int8 code range is [-127, 127]; -128 unused


def encode_tensor(x: np.ndarray) -> pb.Tensor:
    x = np.ascontiguousarray(np.asarray(x, dtype="<f4"))
    return pb.Tensor(data=x.tobytes(), size=x.shape[0])


def decode_tensor(t: pb.Tensor) -> np.ndarray:
    return np.frombuffer(t.data, dtype="<f4", count=t.size).copy()


def encode_grad(x: np.ndarray, sparse_threshold: float = 0.25) -> pb.GradUpdate:
    """Dense or sparse wire form, whichever is smaller.

    Coordinate form costs ~8 bytes/nonzero vs 4 bytes/element dense, so
    sparse wins below ~50% density; the threshold is conservative.
    """
    x = np.asarray(x, dtype=np.float32)
    nz = np.nonzero(x)[0]
    if len(nz) <= sparse_threshold * len(x):
        return pb.GradUpdate(
            sparse=pb.SparseTensor(
                indices=nz.astype(np.int32), values=x[nz], size=len(x)
            )
        )
    return pb.GradUpdate(dense=encode_tensor(x))


def encode_topk(indices: np.ndarray, values: np.ndarray, size: int) -> pb.GradUpdate:
    """Top-k support as a CompressedGrad (compress/ picks the support)."""
    return pb.GradUpdate(
        compressed=pb.CompressedGrad(
            codec="topk",
            size=int(size),
            indices=np.asarray(indices, dtype=np.int32),
            values=np.asarray(values, dtype=np.float32),
        )
    )


def quantize_qint8(
    x: np.ndarray, rng: np.random.Generator, chunk: int = QINT8_CHUNK
) -> pb.GradUpdate:
    """Stochastic int8 quantization with one f32 scale per `chunk` elements.

    Per chunk c: scale_c = max|x_c| / 127 and each element rounds to
    floor(x/scale + u), u ~ U[0,1) — unbiased (E[decode] = x) with
    per-element error < scale_c.  An all-zero chunk gets scale 0 and codes 0.
    """
    x = np.asarray(x, dtype=np.float32)
    n = len(x)
    chunk = max(1, int(chunk))
    n_chunks = -(-n // chunk) if n else 0
    pad = n_chunks * chunk - n
    xp = np.pad(x, (0, pad)).reshape(n_chunks, chunk) if n else x.reshape(0, chunk)
    scales = np.abs(xp).max(axis=1) / _QINT8_LEVELS
    safe = np.where(scales > 0, scales, 1.0)[:, None]
    q = np.floor(xp / safe + rng.random(xp.shape, dtype=np.float32))
    codes = np.clip(q, -_QINT8_LEVELS, _QINT8_LEVELS).astype(np.int8)
    codes[scales == 0] = 0
    return pb.GradUpdate(
        compressed=pb.CompressedGrad(
            codec="qint8",
            size=n,
            data=codes.reshape(-1)[:n].tobytes(),
            scales=scales.astype(np.float32),
            chunk=chunk,
        )
    )


def _scatter(indices, values, size: int) -> np.ndarray:
    """Coordinate list -> dense f32 via bulk conversion (the repeated-field
    containers support the sequence protocol, and np.asarray over them is
    ~10x fromiter on 47k-dim gossip decodes)."""
    out = np.zeros(size, dtype=np.float32)
    if len(indices):
        out[np.asarray(indices, dtype=np.int64)] = np.asarray(
            values, dtype=np.float32
        )
    return out


def decode_compressed(c: pb.CompressedGrad) -> np.ndarray:
    if c.codec == "topk":
        return _scatter(c.indices, c.values, c.size)
    if c.codec == "qint8":
        return _qint8_values(c)
    raise ValueError(f"unknown CompressedGrad codec {c.codec!r}")


def decode_grad(g: pb.GradUpdate) -> np.ndarray:
    which = g.WhichOneof("grad")
    if which == "sparse":
        return _scatter(g.sparse.indices, g.sparse.values, g.sparse.size)
    if which == "compressed":
        return decode_compressed(g.compressed)
    return decode_tensor(g.dense)


def _qint8_values(c: pb.CompressedGrad) -> np.ndarray:
    codes = np.frombuffer(c.data, dtype=np.int8, count=c.size).astype(np.float32)
    chunk = max(1, c.chunk or QINT8_CHUNK)
    scales = np.asarray(c.scales, dtype=np.float32)
    return codes * np.repeat(scales, chunk)[: c.size]


# -- versioned weight deltas (docs/SYNC_PIPELINE.md, docs/SERVING.md) ---------
#
# The ONE encode/apply pair for sparse absolute-value weight updates, shared
# by the sync broadcast plane (core/master.py _BroadcastState -> worker
# replica caches) and the serving fleet's checkpoint distribution
# (serving/push.py WeightPusher -> ModelStore.apply_push, and the router's
# own promoted-weights cache).  `values` are ABSOLUTE new weights at
# `indices` (assignment, not increment): application is idempotent and
# reconstructs the sender's vector bit-exactly.

SPARSE_BREAK_EVEN = 0.5  # changed fraction above which dense is smaller


def encode_weight_delta(
    w: np.ndarray, w_prev: Optional[np.ndarray], base_version: int,
    break_even: float = SPARSE_BREAK_EVEN,
) -> Optional[pb.WeightDelta]:
    """Sparse WeightDelta of `w` vs `w_prev`, or None when a full tensor is
    the smaller (or only possible) wire form: no previous vector, or more
    than `break_even` of the coordinates changed (8 bytes/changed
    coordinate vs 4 bytes/element dense -> break-even at 50% density)."""
    if w_prev is None or w_prev.shape != w.shape:
        return None
    changed = np.nonzero(w != w_prev)[0]
    if len(changed) > break_even * len(w):
        return None  # dense-ish: full is smaller
    return pb.WeightDelta(
        base_version=int(base_version),
        indices=changed.astype(np.int32),
        values=np.ascontiguousarray(w[changed]),
    )


def apply_weight_delta(w: np.ndarray, delta: pb.WeightDelta) -> np.ndarray:
    """New weight vector: `w` with the delta's ABSOLUTE values assigned at
    its indices.  Returns a fresh array; the caller's `w` is untouched (a
    published snapshot must never mutate under a reader).  Version
    bookkeeping (does `delta.base_version` match what `w` is?) belongs to
    the caller — this is pure application."""
    out = np.asarray(w, dtype=np.float32).copy()
    if len(delta.indices):
        out[np.asarray(delta.indices, dtype=np.int64)] = np.asarray(
            delta.values, dtype=np.float32)
    return out


class WeightSendPlan:
    """One weight version's candidate wire forms, each encoded at most
    once and shared across every recipient of that version.

    This is the ONE versioned weight-send path (previously triplicated
    by hand): the master's sync broadcast (core/master.py
    `_BroadcastState`), the serving fleet's checkpoint distribution
    (serving/push.py `WeightPusher`), and the shard lanes' range-slice
    broadcast (shardedps/coordinator.py) all resolve their delta-vs-full
    choice and their lazy single encodes here.  `w_prev=None` disables
    the sparse form entirely (an unversioned / first-contact send);
    both encodes are lazy, so an all-delta round never pays for the
    full tensor and vice versa — the economics every caller relied on
    before the extraction, byte-identical on the wire (the delta is
    `encode_weight_delta`, the full form `encode_tensor`, unchanged).
    """

    def __init__(self, w: np.ndarray, w_prev: Optional[np.ndarray] = None,
                 base_version: int = 0,
                 break_even: float = SPARSE_BREAK_EVEN):
        self._w = w
        self._w_prev = w_prev
        self.base_version = int(base_version)
        self._break_even = float(break_even)
        self._full: Optional[pb.Tensor] = None
        self._delta: Optional[pb.WeightDelta] = None
        self._delta_done = False  # "computed, dense fallback" != "not yet"

    def full(self) -> pb.Tensor:
        """The full dense tensor, encoded on first use."""
        if self._full is None:
            self._full = encode_tensor(self._w)
        return self._full

    def delta(self) -> Optional[pb.WeightDelta]:
        """The sparse WeightDelta vs `w_prev`, or None when the full
        tensor is the smaller (or only possible) wire form; computed on
        first use."""
        if not self._delta_done:
            self._delta = encode_weight_delta(
                self._w, self._w_prev, base_version=self.base_version,
                break_even=self._break_even)
            self._delta_done = True
        return self._delta

    def choose_arm(self, acked_version: Optional[int],
                   version: int) -> str:
        """The cheapest valid arm for a recipient whose last
        acknowledged version is `acked_version` (None = no claim):
        'cached' (zero bytes — the recipient already holds `version`),
        'delta' (the recipient holds exactly `base_version` and the
        sparse form exists), else 'full'."""
        if acked_version is not None and acked_version == version:
            return "cached"
        if (acked_version is not None
                and acked_version == self.base_version
                and self.delta() is not None):
            return "delta"
        return "full"


def plan_weight_send(w: np.ndarray, w_prev: Optional[np.ndarray] = None,
                     base_version: int = 0,
                     break_even: float = SPARSE_BREAK_EVEN) -> WeightSendPlan:
    """Build the shared lazy encode plan for one weight version (see
    WeightSendPlan)."""
    return WeightSendPlan(np.asarray(w, dtype=np.float32),
                          w_prev, base_version, break_even)


def parse_grad(g: pb.GradUpdate):
    """Materialize a GradUpdate's wire payload into ndarrays WITHOUT
    touching any accumulator — the expensive half of `decode_grad_into`
    (repeated-field -> numpy conversion, qint8 dequantization), split out
    so the sharded fan-in lanes (core/master.py `_ArrivalDecoder`,
    DSGD_FANIN_LANES) can run it concurrently across gRPC arrival
    callbacks while the float ACCUMULATION stays strictly send-ordered
    (and therefore bit-identical to the unsharded path).

    Returns an opaque parsed form for `add_parsed`:
      ('scatter', int64 indices, f32 values)  — sparse / topk arms
      ('add', f32 vector)                     — dense (zero-copy
                                                frombuffer view of the
                                                proto bytes) / qint8
      ('zero',)                               — empty coordinate list
    """
    which = g.WhichOneof("grad")
    if which == "sparse" or (which == "compressed" and g.compressed.codec == "topk"):
        src = g.sparse if which == "sparse" else g.compressed
        if not len(src.indices):
            return ("zero",)
        return ("scatter", np.asarray(src.indices, dtype=np.int64),
                np.asarray(src.values, dtype=np.float32))
    if which == "compressed":
        if g.compressed.codec != "qint8":
            raise ValueError(
                f"unknown CompressedGrad codec {g.compressed.codec!r}")
        return ("add", _qint8_values(g.compressed))
    if which is None and not g.dense.size:
        # armless update: an aggregation-tree child that PUSHED its
        # gradient to its parent acks the master with no payload
        # (GradUpdate.agg_forwarded, docs/AGGREGATION.md) — it
        # contributes nothing to the accumulator, not an empty vector
        return ("zero",)
    return ("add", np.frombuffer(g.dense.data, dtype="<f4", count=g.dense.size))


def add_parsed(parsed, out: np.ndarray, scale: float = 1.0) -> np.ndarray:
    """Accumulate a `parse_grad` result into `out` — the float ops are
    EXACTLY `decode_grad_into`'s (fancy-indexed `+=` over strictly unique
    indices for coordinate forms, one vector `+=` for dense forms), so
    parse-then-add is bit-identical to the fused decode whatever thread
    ran the parse."""
    kind = parsed[0]
    if kind == "scatter":
        vals = parsed[2]
        out[parsed[1]] += vals * scale if scale != 1.0 else vals
    elif kind == "add":
        v = parsed[1]
        out += v * scale if scale != 1.0 else v
    return out


def decode_grad_into(g: pb.GradUpdate, out: np.ndarray, scale: float = 1.0) -> np.ndarray:
    """Accumulate a GradUpdate into a caller-owned buffer: out += scale * g.

    The sync fan-in's former `[decode_grad(r) for r in ok]` +
    `np.mean(..., axis=0)` materialized a (workers x dim) dense stack per
    batch window just to average it; this scatters/adds each reply straight
    into one preallocated accumulator instead.  Dense payloads are read as
    zero-copy `np.frombuffer` views of the proto bytes (never written to);
    coordinate forms add O(nnz) work without a dense intermediate.  Every
    encoder in this module emits strictly unique indices (np.nonzero /
    topk support), which the fancy-indexed `+=` relies on.

    Equivalent to `out += scale * decode_grad(g)` up to float evaluation
    order; returns `out` for chaining.  Composed from `parse_grad` +
    `add_parsed` so the sharded fan-in can split the two halves across
    threads without a second decode implementation to drift.
    """
    return add_parsed(parse_grad(g), out, scale)
