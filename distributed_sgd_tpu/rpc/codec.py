"""numpy <-> wire codecs.

The counterpart of the reference's ScalaPB TypeMappers that marshal proto
maps into `math.Vec` (core/package.scala:11-13, proto.proto:8-11).  Dense
f32 vectors travel as raw little-endian bytes; small-support deltas can
travel as coordinate lists, chosen automatically by `encode_grad` when the
sparse form is smaller on the wire.
"""

from __future__ import annotations

import numpy as np

from distributed_sgd_tpu.rpc import dsgd_pb2 as pb


def encode_tensor(x: np.ndarray) -> pb.Tensor:
    x = np.ascontiguousarray(np.asarray(x, dtype="<f4"))
    return pb.Tensor(data=x.tobytes(), size=x.shape[0])


def decode_tensor(t: pb.Tensor) -> np.ndarray:
    return np.frombuffer(t.data, dtype="<f4", count=t.size).copy()


def encode_grad(x: np.ndarray, sparse_threshold: float = 0.25) -> pb.GradUpdate:
    """Dense or sparse wire form, whichever is smaller.

    Coordinate form costs ~8 bytes/nonzero vs 4 bytes/element dense, so
    sparse wins below ~50% density; the threshold is conservative.
    """
    x = np.asarray(x, dtype=np.float32)
    nz = np.nonzero(x)[0]
    if len(nz) <= sparse_threshold * len(x):
        return pb.GradUpdate(
            sparse=pb.SparseTensor(
                indices=nz.astype(np.int32), values=x[nz], size=len(x)
            )
        )
    return pb.GradUpdate(dense=encode_tensor(x))


def decode_grad(g: pb.GradUpdate) -> np.ndarray:
    if g.WhichOneof("grad") == "sparse":
        out = np.zeros(g.sparse.size, dtype=np.float32)
        if len(g.sparse.indices):
            out[np.fromiter(g.sparse.indices, dtype=np.int64)] = np.fromiter(
                g.sparse.values, dtype=np.float32
            )
        return out
    return decode_tensor(g.dense)
