"""Persistent per-worker gradient streams (DSGD_STREAM,
docs/SYNC_PIPELINE.md "Streaming transport").

The reference master fans out one unary gRPC ``Gradient`` call per worker
per batch window (Master.scala fan-out loop) — at the RPC-bound shape
every round pays per-call HTTP/2 stream setup/teardown, per-call metadata
processing, and a fresh client-future allocation.  ``FitStreamClient``
replaces that with ONE bidirectional ``FitStream`` RPC per
(master, worker) pair for the lifetime of a fit: each window's
``GradientRequest`` rides a framed envelope (``pb.Frame``, stamped with a
per-stream monotone ``seq``) down the open stream, the worker answers on
the same stream, and a reader thread matches replies to in-flight sends
by ``seq`` — exposing each send as a grpc.Future-alike so the master's
barrier machinery (``_await_futures`` / ``_await_quorum`` /
``_ArrivalDecoder``) consumes stream replies exactly as it consumes unary
callbacks.

Fault contract (the part that makes mixed fleets safe):

- A frame that gets NO reply by its deadline settles DEADLINE_EXCEEDED —
  exactly a unary call's behavior — and its late reply, if one ever
  lands, is dropped idempotently by seq (counted, like quorum's late
  replies).  The stream stays open: a lost frame is not a dead peer.
- A stream that TEARS DOWN (worker crash, chaos error, UNIMPLEMENTED
  from an older binary) settles every in-flight send, but each of those
  futures transparently re-issues its request over the
  classic unary ``Gradient`` with the remaining deadline budget — the
  window completes without burning a retry, and the failure only
  surfaces to the eviction machinery when unary fails too.  The breaker
  feed (``on_break``) is the caller's: core/master.py trips the same
  per-peer CircuitBreaker the control plane uses and stops reopening
  while it suppresses.
- UNIMPLEMENTED marks the client permanently ``unsupported``: every
  later send for that worker goes straight to unary (version skew — an
  older worker binary simply never speaks the stream).

Deadlines are enforced by one shared timeout wheel thread (lazy, like
chaos._Scheduler) rather than a timer per send: the hot path costs one
heap push per frame.
"""

from __future__ import annotations

import heapq
import queue
import threading
import time
from typing import Dict, Optional

import grpc

from distributed_sgd_tpu.rpc import dsgd_pb2 as pb
from distributed_sgd_tpu.utils import metrics as metrics_mod


class StreamRpcError(grpc.RpcError):
    """Stream-transport failure carrying the .code()/.details() surface
    the barrier classification reads off every grpc.RpcError."""

    def __init__(self, code: grpc.StatusCode, details: str):
        super().__init__()
        self._code = code
        self._details = details

    def code(self) -> grpc.StatusCode:  # noqa: D102 - grpc surface
        return self._code

    def details(self) -> str:  # noqa: D102 - grpc surface
        return self._details

    def __str__(self):
        return f"StreamRpcError({self._code}: {self._details})"


class Wheel:
    """Shared deadline wheel: one lazy daemon thread firing items at their
    absolute (time.monotonic) deadline, heapq-ordered — one heap push per
    watch, a wake-up only when the head moves earlier.

    Grown out of the stream transport's frame-deadline enforcement and
    now the ONE deadline scheduler shared with the master's liveness
    plane (core/master.py `_heartbeat_loop`, docs/SCALING.md): an item is
    either a plain callable (fired as `item()`) or a `_StreamFuture`-like
    object exposing `_expire()`.  Items fire on the wheel thread — keep
    them non-blocking (flip an event, push a deque entry); the wheel is a
    scheduler, not a worker pool."""

    def __init__(self, name: str = "deadline-wheel"):
        self._name = name
        self._cv = threading.Condition()
        self._heap: list = []
        self._seq = 0
        self._running = False

    def watch(self, deadline: float, item) -> None:
        with self._cv:
            self._seq += 1
            head = self._heap[0][0] if self._heap else None
            heapq.heappush(self._heap, (deadline, self._seq, item))
            if not self._running:
                self._running = True
                threading.Thread(target=self._run, daemon=True,
                                 name=self._name).start()
                self._cv.notify()
            elif head is None or deadline < head:
                # wake only when the head moved EARLIER: the hot path
                # (per-frame sends with equal timeouts) costs one heap
                # push, no context switch — the sleeping thread's current
                # wait already covers a later deadline
                self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._heap:
                    if not self._cv.wait(timeout=5.0) and not self._heap:
                        self._running = False
                        return  # idle: die; the next watch() respawns
                due, _, item = self._heap[0]
                now = time.monotonic()
                if due > now:
                    self._cv.wait(timeout=due - now)
                    continue
                heapq.heappop(self._heap)
            try:
                if callable(item):
                    item()
                else:
                    item._expire()
            except Exception:  # noqa: BLE001 - one item must not kill the wheel
                pass


_Wheel = Wheel  # historical private name (pre-SCALING imports)

_WHEEL = Wheel(name="fitstream-wheel")


class _StreamFuture:
    """grpc.Future-alike for one in-flight stream frame, with a built-in
    unary fallback arm.

    Settles exactly once with the matched reply (``pb.GradUpdate``), a
    DEADLINE_EXCEEDED expiry from the wheel, the stream's terminal error,
    or CANCELLED.  ``stream_dead`` discriminates a torn-down stream from
    a per-frame deadline (the worker is slow/wedged — unary semantics say
    that IS the failure, and no fallback fires).  When the STREAM dies
    under an in-flight frame (teardown / UNIMPLEMENTED skew) and the
    caller supplied a unary escape hatch (``send(..., unary_call=,
    request=)``), the future transparently re-issues the SAME request
    over the classic unary Gradient with the deadline budget the stream
    attempt left unspent — the window completes without burning a retry,
    and only a unary failure ever reaches the eviction machinery."""

    __slots__ = ("_client", "seq", "_done", "_lock", "_result", "_exception",
                 "_cancelled", "_callbacks", "stream_dead", "_deadline",
                 "_unary", "_request", "_inner")

    def __init__(self, client: "FitStreamClient", seq: int,
                 deadline: float = 0.0, unary_call=None, request=None):
        self._client = client
        self.seq = seq
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._result = None
        self._exception: Optional[Exception] = None
        self._cancelled = False
        self._callbacks: list = []
        self.stream_dead = False
        self._deadline = deadline
        self._unary = unary_call
        self._request = request
        self._inner = None  # the unary fallback future, once issued

    def _settle(self, result=None, exception=None,
                stream_dead: bool = False) -> None:
        with self._lock:
            if self._done.is_set():
                return
            self._result, self._exception = result, exception
            self.stream_dead = stream_dead
            self._done.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 - callback errors stay local
                pass

    def _stream_died(self, err: Exception) -> None:
        """Teardown path: replay over unary when an escape hatch and
        deadline budget remain, else settle with the stream's error."""
        if self._done.is_set():
            return
        remaining = self._deadline - time.monotonic()
        if self._unary is None or self._cancelled or remaining <= 0.01:
            self._settle(exception=err, stream_dead=True)
            return
        client = self._client
        if client._metrics is not None:
            client._metrics.counter(metrics_mod.STREAM_FALLBACK).increment()
        try:
            inner = self._unary.future(self._request, timeout=remaining)
        except Exception as e:  # noqa: BLE001 - channel closed under us
            self._settle(exception=e, stream_dead=True)
            return
        with self._lock:
            if self._cancelled or self._done.is_set():
                inner.cancel()
                return
            self._inner = inner
        inner.add_done_callback(self._from_inner)

    def _from_inner(self, inner) -> None:
        try:
            self._settle(result=inner.result(), stream_dead=True)
        except Exception as e:  # noqa: BLE001 - grpc.RpcError expected
            self._settle(exception=e, stream_dead=True)

    def _expire(self) -> None:
        """Wheel callback: no reply by the frame's deadline.  The seq is
        retired so a late reply is dropped (counted), like a unary reply
        arriving after DEADLINE_EXCEEDED."""
        if self._done.is_set():
            return
        self._client._retire(self.seq, expired=True)
        self._settle(exception=StreamRpcError(
            grpc.StatusCode.DEADLINE_EXCEEDED, "stream frame deadline"))

    # -- grpc.Future surface -------------------------------------------------

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise grpc.FutureTimeoutError()
        if self._exception is not None:
            raise self._exception
        return self._result

    def exception(self, timeout=None):
        if not self._done.wait(timeout):
            raise grpc.FutureTimeoutError()
        return self._exception

    def done(self) -> bool:
        return self._done.is_set()

    def cancelled(self) -> bool:
        return self._cancelled

    def running(self) -> bool:
        return not self._done.is_set()

    def cancel(self) -> bool:
        if self._done.is_set():
            return False
        self._client._retire(self.seq)
        with self._lock:
            self._cancelled = True
            inner = self._inner
        if inner is not None:
            inner.cancel()
        self._settle(exception=StreamRpcError(
            grpc.StatusCode.CANCELLED, "cancelled"))
        return True

    def add_done_callback(self, fn) -> None:
        with self._lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def traceback(self, timeout=None):
        return None


_CLOSE = object()  # request-iterator sentinel: half-close the stream


class FitStreamClient:
    """One persistent FitStream RPC against one worker.

    ``send(frame, timeout_s)`` stamps the next ``seq`` on the frame,
    queues it for the stream's request iterator (serialization happens on
    gRPC's sender thread, OFF the master's dispatch path — with the
    weight arm pre-staged by the encode-ahead thread, dispatch is one
    queue put per worker), registers a pending future, and arms the
    shared deadline wheel.  The reader thread resolves futures by the
    reply frame's ``seq``.

    Thread-safe; ``broken``/``unsupported`` are sticky — a broken client
    is never reused (the owner opens a fresh one when the breaker
    allows), an unsupported one is never replaced (version skew does not
    heal mid-process)."""

    def __init__(self, stream_callable, peer: str,
                 metrics=None, log=None, on_break=None):
        self._peer = peer
        self._metrics = metrics
        self._log = log
        self._on_break = on_break
        self._lock = threading.Lock()
        self._sendq: "queue.SimpleQueue" = queue.SimpleQueue()
        self._pending: Dict[int, _StreamFuture] = {}
        self._seq = 0
        self.broken = False
        self.unsupported = False
        self._closed = False
        if metrics is not None:
            metrics.counter(metrics_mod.STREAM_OPENED).increment()
        self._call = stream_callable(self._req_iter())
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name=f"fitstream-{peer}")
        self._reader.start()

    def _req_iter(self):
        while True:
            item = self._sendq.get()
            if item is _CLOSE:
                return
            yield item

    @property
    def usable(self) -> bool:
        # lock-free read of three monotone False->True flags: the worst
        # race admits one extra send() attempt, which re-checks under the
        # lock and returns None — dispatch fast paths stay allocation-
        # and lock-free
        return not (self._closed or self.broken or self.unsupported)

    def send(self, frame: pb.Frame, timeout_s: float,
             unary_call=None, request=None) -> Optional[_StreamFuture]:
        """Queue one request frame; returns its future, or None when the
        stream cannot carry it (broken/unsupported/closed) — the caller
        goes unary.  `unary_call`/`request` arm the future's transparent
        unary fallback for the teardown case (see _StreamFuture)."""
        deadline = time.monotonic() + float(timeout_s)
        with self._lock:
            if self._closed or self.broken or self.unsupported:
                return None
            self._seq += 1
            frame.seq = self._seq
            # envelope-level session attribution mirrors the payload's
            # authoritative token (rpc/proto/dsgd.proto Frame)
            frame.fit_token = frame.request.fit_token
            fut = _StreamFuture(self, self._seq, deadline=deadline,
                                unary_call=unary_call, request=request)
            self._pending[self._seq] = fut
        self._sendq.put(frame)
        if self._metrics is not None:
            self._metrics.counter(metrics_mod.STREAM_SENDS).increment()
        _WHEEL.watch(deadline, fut)
        return fut

    def _retire(self, seq: int, expired: bool = False) -> None:
        with self._lock:
            had = self._pending.pop(seq, None)
        if expired and had is not None and self._metrics is not None:
            self._metrics.counter(metrics_mod.STREAM_EXPIRED).increment()

    def _read_loop(self) -> None:
        err: Optional[Exception] = None
        try:
            for frame in self._call:
                with self._lock:
                    fut = self._pending.pop(frame.seq, None)
                if fut is None:
                    # a reply past its deadline (its seq was retired), or a
                    # chaos duplicate: dropped idempotently, like quorum's
                    # late unary replies
                    if self._metrics is not None:
                        self._metrics.counter(
                            metrics_mod.STREAM_LATE).increment()
                    continue
                fut._settle(result=frame.update)
        except grpc.RpcError as e:
            err = e
        except Exception as e:  # noqa: BLE001 - classify below
            err = e
        if err is None:
            # server completed the stream (worker shut down cleanly or the
            # servicer loop exited): same terminal handling as an error
            err = StreamRpcError(grpc.StatusCode.UNAVAILABLE,
                                 "stream closed by peer")
        self._tear_down(err)

    def _tear_down(self, err: Exception) -> None:
        code = err.code() if isinstance(err, grpc.RpcError) else None
        with self._lock:
            locally_closed = self._closed
            self.broken = True
            if code == grpc.StatusCode.UNIMPLEMENTED:
                # version skew: the worker binary predates FitStream — go
                # (and stay) unary for this peer, no breaker pressure (an
                # old binary is not a sick one)
                self.unsupported = True
            pending, self._pending = self._pending, {}
        if locally_closed:
            # our own close() (fit end / unregister): abandoned futures —
            # e.g. quorum stragglers nobody will read — settle dead, they
            # must NOT replay over unary after the fit moved on
            for fut in pending.values():
                fut._settle(exception=err, stream_dead=True)
            return  # not a peer failure
        for fut in pending.values():
            fut._stream_died(err)
        if self._metrics is not None:
            self._metrics.counter(metrics_mod.STREAM_BROKEN).increment()
        if self._log is not None:
            self._log.warning(
                "FitStream to %s tore down (%s)%s", self._peer,
                code or err,
                " — unary from now on (version skew)" if self.unsupported
                else "; in-flight windows fall back to unary")
        if self._on_break is not None and not self.unsupported:
            try:
                self._on_break()
            except Exception:  # noqa: BLE001 - breaker feed must not recurse
                pass

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._sendq.put(_CLOSE)
        try:
            self._call.cancel()
        except Exception:  # noqa: BLE001 - already dead is fine
            pass
