from distributed_sgd_tpu.rpc import codec  # noqa: F401
from distributed_sgd_tpu.rpc.service import (  # noqa: F401
    MasterStub,
    WorkerStub,
    add_master_servicer,
    add_worker_servicer,
    new_channel,
    new_server,
)
