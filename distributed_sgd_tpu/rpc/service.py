"""Hand-written gRPC service bindings.

grpc_tools (the protoc python-grpc plugin) is not available in this image,
so stubs and servicer registration are built from a method table using
grpc's generic API — functionally identical to generated `*_pb2_grpc.py`.
Service surface mirrors the reference IDL (proto.proto:13-49); channel and
server factories mirror core/package.scala:16-21 (plaintext).
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from distributed_sgd_tpu.rpc import dsgd_pb2 as pb

_MASTER_METHODS = {
    "RegisterSlave": (pb.Node, pb.Ack),
    "UnregisterSlave": (pb.Node, pb.Ack),
    "UpdateGrad": (pb.GradUpdate, pb.Ack),
}

_WORKER_METHODS = {
    "RegisterSlave": (pb.Node, pb.Ack),
    "UnregisterSlave": (pb.Node, pb.Ack),
    "Ping": (pb.Empty, pb.Ack),
    "Forward": (pb.ForwardRequest, pb.ForwardReply),
    "Gradient": (pb.GradientRequest, pb.GradUpdate),
    "StartAsync": (pb.StartAsyncRequest, pb.Ack),
    "StopAsync": (pb.Empty, pb.Ack),
    "UpdateGrad": (pb.GradUpdate, pb.Ack),
}

# The inference front end (serving/): no reference counterpart — the
# reference's only inference surface is the in-fit Forward above.
_SERVE_METHODS = {
    "Predict": (pb.PredictRequest, pb.PredictReply),
    "ServeHealth": (pb.Empty, pb.ServeHealthReply),
}


def _add_servicer(server, servicer, service_name: str, methods: dict) -> None:
    handlers = {}
    for name, (req, resp) in methods.items():
        fn = getattr(servicer, name)
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=req.FromString, response_serializer=resp.SerializeToString
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )


def add_master_servicer(server, servicer) -> None:
    _add_servicer(server, servicer, "dsgd.Master", _MASTER_METHODS)


def add_worker_servicer(server, servicer) -> None:
    _add_servicer(server, servicer, "dsgd.Worker", _WORKER_METHODS)


def add_serve_servicer(server, servicer) -> None:
    _add_servicer(server, servicer, "dsgd.Serving", _SERVE_METHODS)


class _Stub:
    def __init__(self, channel, service_name: str, methods: dict):
        for name, (req, resp) in methods.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{service_name}/{name}",
                    request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString,
                ),
            )


class MasterStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, "dsgd.Master", _MASTER_METHODS)


class WorkerStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, "dsgd.Worker", _WORKER_METHODS)


class ServeStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, "dsgd.Serving", _SERVE_METHODS)


class GossipSender:
    """Bounded fire-and-forget sender for async delta gossip.

    The reference gossips with no delivery guarantee (fire-and-forget gRPC,
    Slave.scala:103-105); a naive `.future(msg)` translation accumulates
    unbounded in-flight RPCs against a slow or wedged peer.  This keeps at
    most `max_inflight` outstanding UpdateGrad calls per peer: completed
    futures are pruned on every send, and when the window is still full the
    OLDEST in-flight call is cancelled — and counted under
    `slave.async.grad.dropped` once it settles as actually-cancelled (a
    call already executing server-side may still be delivered despite the
    cancel) — the same drop-oldest-under-overload policy as the in-process
    engine's bounded inbox (parallel/hogwild.py).
    """

    def __init__(self, call, metrics=None, max_inflight: int = 64):
        import threading

        self._call = call  # e.g. stub.UpdateGrad
        self._metrics = metrics
        self.max_inflight = max(1, int(max_inflight))
        self._inflight: list = []
        # close() may run on a gRPC servicer thread (peer unregistered)
        # while the async loop still holds a snapshot of this sender: the
        # lock + closed flag stop a late send() from re-populating the
        # window with a future nobody would ever cancel
        self._lock = threading.Lock()
        self._closed = False

    def send(self, msg) -> None:
        with self._lock:
            if self._closed:
                return
            self._inflight = [f for f in self._inflight if not f.done()]
            while len(self._inflight) >= self.max_inflight:
                old = self._inflight.pop(0)
                old.cancel()  # best-effort; the delta is lost, as the wire allows
                if self._metrics is not None:
                    # grpc cancel is best-effort: a call already executing
                    # server-side is still delivered, so count the drop only
                    # once the future settles as actually-cancelled —
                    # otherwise slave.async.grad.dropped overstates delta loss
                    metrics = self._metrics
                    old.add_done_callback(
                        lambda f: f.cancelled()
                        and metrics.counter("slave.async.grad.dropped").increment()
                    )
            try:
                self._inflight.append(self._call.future(msg))
            except ValueError:  # channel closed under us
                pass

    @property
    def inflight(self) -> int:
        with self._lock:
            return sum(1 for f in self._inflight if not f.done())

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for f in self._inflight:
                f.cancel()
            self._inflight.clear()


def new_server(port: int, host: str = "0.0.0.0", max_workers: int = 16) -> grpc.Server:
    """Plaintext server factory (core/package.scala:16-17). Port 0 picks a
    free port; the bound port is stored on `server.bound_port`."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 64 * 1024 * 1024),
                 ("grpc.max_send_message_length", 64 * 1024 * 1024)],
    )
    server.bound_port = server.add_insecure_port(f"{host}:{port}")
    return server


def new_channel(host: str, port: int) -> grpc.Channel:
    """Plaintext channel factory (core/package.scala:19-21)."""
    return grpc.insecure_channel(
        f"{host}:{port}",
        options=[("grpc.max_receive_message_length", 64 * 1024 * 1024),
                 ("grpc.max_send_message_length", 64 * 1024 * 1024)],
    )
