"""Hand-written gRPC service bindings.

grpc_tools (the protoc python-grpc plugin) is not available in this image,
so stubs and servicer registration are built from a method table using
grpc's generic API — functionally identical to generated `*_pb2_grpc.py`.
Service surface mirrors the reference IDL (proto.proto:13-49); channel and
server factories mirror core/package.scala:16-21 (plaintext).
"""

from __future__ import annotations

import random
import threading
import time
from concurrent import futures
from typing import Dict, Hashable, Optional

import grpc

from distributed_sgd_tpu import trace as trace_mod
from distributed_sgd_tpu.rpc import dsgd_pb2 as pb
from distributed_sgd_tpu.trace import flight


class CircuitBreaker:
    """Per-peer circuit breaker with half-open probes (docs/FAULT_TOLERANCE.md).

    CLOSED counts consecutive failures; at `failures` it OPENS and
    `allow()` refuses every call for `reset_s`.  After the cooldown the
    breaker goes HALF-OPEN and grants exactly ONE probe call; the probe's
    outcome decides — success closes the breaker, failure re-opens it for
    another full cooldown.  All transitions are thread-safe; senders that
    fire-and-forget report outcomes from future done-callbacks.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failures: int = 5, reset_s: float = 10.0,
                 metrics=None, name: str = ""):
        self.failures = max(1, int(failures))
        self.reset_s = float(reset_s)
        self._metrics = metrics
        self._name = name
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._count = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a call proceed right now?  In HALF_OPEN only one probe is
        granted at a time; callers that get True MUST report the outcome
        via record_ok/record_failure or the breaker stays probe-locked
        until the next cooldown."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = time.monotonic()
            if self._state == self.OPEN:
                if now - self._opened_at < self.reset_s:
                    return False
                self._state = self.HALF_OPEN
                self._probe_inflight = False
            # HALF_OPEN: one probe slot — but a probe whose outcome never
            # arrived (a black-holed fire-and-forget send) must not lock
            # the breaker forever, so the slot re-opens after reset_s
            if self._probe_inflight and now - self._probe_at < self.reset_s:
                return False
            self._probe_inflight = True
            self._probe_at = now
            return True

    def suppressed(self) -> bool:
        """Would `allow()` refuse a call right now?  READ-ONLY: unlike
        allow() this never transitions OPEN->HALF_OPEN and never consumes
        the half-open probe slot, so the sparse-gossip topology layer can
        route around a tripped peer (parallel/topology.py reselection)
        without stealing the probe that would eventually heal it."""
        with self._lock:
            if self._state == self.CLOSED:
                return False
            now = time.monotonic()
            if self._state == self.OPEN:
                return now - self._opened_at < self.reset_s
            return self._probe_inflight and now - self._probe_at < self.reset_s

    def record_ok(self) -> None:
        with self._lock:
            self._state = self.CLOSED
            self._count = 0
            self._probe_inflight = False

    def record_failure(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._trip()
                return
            self._count += 1
            if self._state == self.CLOSED and self._count >= self.failures:
                self._trip()

    def _trip(self) -> None:
        self._state = self.OPEN
        self._opened_at = time.monotonic()
        self._count = 0
        self._probe_inflight = False
        if self._metrics is not None:
            self._metrics.counter("rpc.breaker.open").increment()
        # post-mortem evidence: breaker trips are exactly the kind of
        # cascade precursor a dead run's flight dump must contain
        flight.record("breaker.open", peer=self._name)


class RpcPolicy:
    """One client-side RPC fault policy for the whole control plane
    (docs/FAULT_TOLERANCE.md): per-call deadline, exponential backoff
    with full jitter, a retry budget, and per-peer circuit breakers with
    half-open probes.  Replaces the scattered hardcoded ``timeout=5.0``
    and fixed-sleep retries across registration, peer introduction,
    heartbeat, StopAsync, and gossip.

    Defaults keep the reference's registration behavior as the baseline:
    a 5 s call deadline (Slave.scala:48) and a 2 s first retry delay
    (Slave.scala:56) — now growing exponentially with full jitter
    (AWS-style: sleep ~ U(0, min(cap, base * mult^attempt))) up to a
    ~30 s cap instead of retrying every 2 s forever.
    """

    def __init__(
        self,
        deadline_s: float = 5.0,            # Slave.scala:48
        initial_backoff_s: float = 2.0,     # Slave.scala:56
        max_backoff_s: float = 30.0,
        multiplier: float = 2.0,
        retries: int = 3,                   # budget for call_with_retry
        breaker_failures: int = 5,
        breaker_reset_s: float = 10.0,
        seed: Optional[int] = None,
        metrics=None,
    ):
        if deadline_s <= 0 or initial_backoff_s <= 0 or max_backoff_s <= 0:
            raise ValueError("RpcPolicy deadlines/backoffs must be > 0")
        if multiplier < 1.0:
            raise ValueError("RpcPolicy multiplier must be >= 1")
        self.deadline_s = float(deadline_s)
        self.initial_backoff_s = float(initial_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.multiplier = float(multiplier)
        self.retries = max(0, int(retries))
        self.breaker_failures = int(breaker_failures)
        self.breaker_reset_s = float(breaker_reset_s)
        self._metrics = metrics
        self._rng = random.Random(seed)
        self._breakers: Dict[Hashable, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def backoff_cap_s(self, attempt: int) -> float:
        """Deterministic exponential cap for retry `attempt` (0-based)."""
        return min(self.max_backoff_s,
                   self.initial_backoff_s * self.multiplier ** attempt)

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter sleep for retry `attempt`: U(0, cap(attempt))."""
        return self._rng.uniform(0.0, self.backoff_cap_s(attempt))

    def breaker(self, peer: Hashable) -> CircuitBreaker:
        """The per-peer breaker (created on first use)."""
        with self._lock:
            br = self._breakers.get(peer)
            if br is None:
                br = CircuitBreaker(self.breaker_failures,
                                    self.breaker_reset_s,
                                    metrics=self._metrics, name=str(peer))
                self._breakers[peer] = br
            return br

    def call_with_retry(self, call, request, peer: Hashable = None,
                        retries: Optional[int] = None, log=None):
        """Blocking unary call under the full policy: deadline per
        attempt, breaker consult (peer given), jittered backoff between
        attempts, at most `retries` re-attempts.  Raises the last
        grpc.RpcError when the budget is spent or the breaker refuses."""
        budget = self.retries if retries is None else max(0, int(retries))
        br = self.breaker(peer) if peer is not None else None
        last: Optional[Exception] = None
        for attempt in range(budget + 1):
            if br is not None and not br.allow():
                raise last if last is not None else _breaker_open_error(peer)
            try:
                reply = call(request, timeout=self.deadline_s)
                if br is not None:
                    br.record_ok()
                return reply
            except grpc.RpcError as e:
                if br is not None:
                    br.record_failure()
                last = e
                if attempt < budget:
                    delay = self.backoff_s(attempt)
                    if log is not None:
                        log.warning("rpc to %s failed (%s); retry %d/%d in %.1fs",
                                    peer, e.code(), attempt + 1, budget, delay)
                    time.sleep(delay)
        raise last


class BreakerOpenError(grpc.RpcError):
    """Raised client-side when a peer's breaker refuses the call; carries
    the .code()/.details() surface callers read off grpc.RpcError."""

    def __init__(self, peer):
        super().__init__()
        self._peer = peer

    def code(self) -> grpc.StatusCode:  # noqa: D102 - grpc surface
        return grpc.StatusCode.UNAVAILABLE

    def details(self) -> str:  # noqa: D102 - grpc surface
        return f"circuit breaker open for {self._peer}"

    def __str__(self):
        return self.details()


def _breaker_open_error(peer) -> grpc.RpcError:
    return BreakerOpenError(peer)

_MASTER_METHODS = {
    "RegisterSlave": (pb.Node, pb.Ack),
    "UnregisterSlave": (pb.Node, pb.Ack),
    "UpdateGrad": (pb.GradUpdate, pb.Ack),
    # master membership probe for the workers' re-registration watch
    # (docs/ELASTICITY.md): the worker sends its own Node identity and a
    # reachable master that does NOT know the caller answers NOT_FOUND —
    # the signal that survives a fast restart rebinding the same port
    # (plain unreachability would never trip: the new master answers).
    # Reuses the Node/Ack pair, no new proto message; an older master
    # answers UNIMPLEMENTED, which the watch treats as a miss only when
    # explicitly enabled (master_watch_s)
    "Ping": (pb.Node, pb.Ack),
}

_WORKER_METHODS = {
    "RegisterSlave": (pb.Node, pb.Ack),
    "UnregisterSlave": (pb.Node, pb.Ack),
    "Ping": (pb.Empty, pb.Ack),
    "Forward": (pb.ForwardRequest, pb.ForwardReply),
    "Gradient": (pb.GradientRequest, pb.GradUpdate),
    "StartAsync": (pb.StartAsyncRequest, pb.Ack),
    "StopAsync": (pb.Empty, pb.Ack),
    "UpdateGrad": (pb.GradUpdate, pb.Ack),
    # cluster telemetry scrape (telemetry/, docs/OBSERVABILITY.md): the
    # master pulls this node's full instrument registry; an older binary
    # without the method answers UNIMPLEMENTED, which the scraper treats
    # as a degraded-but-non-fatal miss
    "Metrics": (pb.Empty, pb.MetricsSnapshot),
    # aggregation-tree child push (DSGD_AGG_TREE, docs/AGGREGATION.md):
    # a tree child delivers its encoded subtree sum to its elected
    # parent; an older binary answers UNIMPLEMENTED, the push fails, and
    # the child replies direct-to-master tagged agg_flat (flat fallback)
    "AggregateGrad": (pb.AggGrad, pb.Ack),
}

# Bidirectional streaming surface (DSGD_STREAM, docs/SYNC_PIPELINE.md):
# registered with stream_stream handlers/multicallables instead of the
# unary tables above.  FitStream is in _OPTIONAL_METHODS — an older worker
# binary registers no handler, callers get UNIMPLEMENTED, and the master's
# stream client falls back to the unary Gradient for that worker
# (rpc/stream.py), so mixed fleets keep working across the skew.
_WORKER_STREAM_METHODS = {
    "FitStream": (pb.Frame, pb.Frame),
}

# The inference front end (serving/): no reference counterpart — the
# reference's only inference surface is the in-fit Forward above.  The
# router (serving/router.py) speaks the SAME service, so a client cannot
# tell one replica from a fleet.
_SERVE_METHODS = {
    "Predict": (pb.PredictRequest, pb.PredictReply),
    "ServeHealth": (pb.Empty, pb.ServeHealthReply),
    "Metrics": (pb.Empty, pb.MetricsSnapshot),
    # delta checkpoint distribution (docs/SERVING.md "serving fleet"): the
    # trainer's master — or the router fanning a push out — streams
    # versioned weight updates; an older replica answers UNIMPLEMENTED and
    # keeps hot-reloading from the checkpoint files instead
    "PushWeights": (pb.PushWeightsRequest, pb.PushWeightsReply),
    # serving-plane HA peer sync (DSGD_SERVE_HA, docs/SERVING.md "HA"):
    # dual LIVE routers exchange their versioned promoted-state records;
    # an older binary (or a plain replica) answers UNIMPLEMENTED and the
    # coordinator counts a missed sync instead of failing the router
    "SyncServeState": (pb.SyncServeStateRequest, pb.SyncServeStateReply),
}

# Methods a servicer may legitimately lack (older binaries, partial test
# stubs): absent -> no handler -> UNIMPLEMENTED to callers.  Everything
# else is required and fails server construction when missing.
_OPTIONAL_METHODS = frozenset(
    {"Metrics", "PushWeights", "FitStream", "AggregateGrad",
     "SyncServeState"})


def _traced_handler(fn, method: str, node: Optional[str]):
    """Server-side trace hook (docs/OBSERVABILITY.md): when the inbound
    call carries a TraceContext in its invocation metadata (the client
    side only injects for sampled traces), run the method body inside a
    server span that is a child of the caller's span — installed as the
    thread's current context, so worker-side measure.span()s become
    grandchildren automatically.  With tracing off (or an untraced call)
    this is one global read + one metadata scan, no allocation."""

    def handler(request, context):
        t = trace_mod._TRACER
        if t is None:
            return fn(request, context)
        ctx = trace_mod.extract(context.invocation_metadata())
        if ctx is None:
            return fn(request, context)
        with t.child_span(method, ctx, node=node):
            return fn(request, context)

    return handler


def _add_servicer(server, servicer, service_name: str, methods: dict,
                  node: Optional[str] = None,
                  stream_methods: Optional[dict] = None) -> None:
    handlers = {}
    for name, (req, resp) in methods.items():
        if name in _OPTIONAL_METHODS and not hasattr(servicer, name):
            # version-skew tolerance for the OPTIONAL surface only: a
            # servicer that predates it registers no handler and callers
            # get the standard UNIMPLEMENTED.  Required methods keep the
            # loud build-time AttributeError below — a typo'd core
            # handler must not become a mid-fit UNIMPLEMENTED the
            # retry/eviction machinery misreads as a dead peer.
            continue
        fn = _traced_handler(getattr(servicer, name), name, node)
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=req.FromString, response_serializer=resp.SerializeToString
        )
    for name, (req, resp) in (stream_methods or {}).items():
        if name in _OPTIONAL_METHODS and not hasattr(servicer, name):
            continue  # same skew rule as above: absent -> UNIMPLEMENTED
        # bidi streams skip the per-call trace hook: the handler runs once
        # per STREAM, not per frame, so a per-call server span would pin
        # one span open for the whole fit (per-round attribution stays on
        # the master's sync.window root spans)
        handlers[name] = grpc.stream_stream_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )


def add_master_servicer(server, servicer, node: Optional[str] = None) -> None:
    _add_servicer(server, servicer, "dsgd.Master", _MASTER_METHODS, node=node)


def add_worker_servicer(server, servicer, node: Optional[str] = None) -> None:
    _add_servicer(server, servicer, "dsgd.Worker", _WORKER_METHODS, node=node,
                  stream_methods=_WORKER_STREAM_METHODS)


def add_serve_servicer(server, servicer, node: Optional[str] = None) -> None:
    _add_servicer(server, servicer, "dsgd.Serving", _SERVE_METHODS, node=node)


class _TracingCallable:
    """Client-side trace hook around one unary-unary multicallable.

    When the calling thread is inside a sampled trace (a master fan-out
    window, a serving request, ...), each RPC through this callable gets
    its own client span — hedges and retries included, each a sibling
    child of the SAME parent span — and the context rides the gRPC
    invocation metadata (trace.METADATA_KEY), leaving the proto wire
    byte-identical.  Outside a trace (or with tracing off) the call
    passes straight through: one module-global read, zero allocation
    (tests/test_trace.py asserts the fast path never constructs a Span).
    """

    __slots__ = ("_inner", "_method", "_peer")

    def __init__(self, inner, method: str, peer: Optional[str]):
        self._inner = inner
        self._method = method
        self._peer = peer

    def _span(self, tracer, ctx):
        return tracer.child_span(f"rpc.{self._method}", ctx, peer=self._peer)

    @staticmethod
    def _inject(kwargs, span):
        md = tuple(kwargs.get("metadata") or ()) + trace_mod.inject(span.ctx)
        kwargs["metadata"] = md
        return kwargs

    @staticmethod
    def _end_from_future(span, fut) -> None:
        try:
            if fut.cancelled():
                span.end(error="cancelled")
                return
            exc = fut.exception()
        except Exception as e:  # noqa: BLE001 - unreadable future = failed
            span.end(error=repr(e))
            return
        span.end(error=str(exc) if exc is not None else None)

    def __call__(self, request, timeout=None, **kwargs):
        t = trace_mod._TRACER
        ctx = trace_mod.current() if t is not None else None
        if ctx is None:
            return self._inner(request, timeout=timeout, **kwargs)
        span = self._span(t, ctx)
        try:
            reply = self._inner(request, timeout=timeout,
                                **self._inject(kwargs, span))
            span.end()
            return reply
        except Exception as e:
            span.end(error=repr(e))
            raise

    def future(self, request, timeout=None, **kwargs):
        t = trace_mod._TRACER
        ctx = trace_mod.current() if t is not None else None
        if ctx is None:
            return self._inner.future(request, timeout=timeout, **kwargs)
        span = self._span(t, ctx)
        try:
            fut = self._inner.future(request, timeout=timeout,
                                     **self._inject(kwargs, span))
        except Exception as e:  # ValueError: channel closed under us
            span.end(error=repr(e))
            raise
        fut.add_done_callback(lambda f: self._end_from_future(span, f))
        return fut


class _Stub:
    def __init__(self, channel, service_name: str, methods: dict,
                 stream_methods: Optional[dict] = None):
        # channel factories stamp their endpoint on the channel
        # (new_channel below) so client spans can name their peer
        target = getattr(channel, "dsgd_target", None)
        peer = f"{target[0]}:{target[1]}" if target else None
        self.dsgd_peer = peer
        for name, (req, resp) in methods.items():
            setattr(
                self,
                name,
                _TracingCallable(
                    channel.unary_unary(
                        f"/{service_name}/{name}",
                        request_serializer=req.SerializeToString,
                        response_deserializer=resp.FromString,
                    ),
                    name,
                    peer,
                ),
            )
        for name, (req, resp) in (stream_methods or {}).items():
            # bidi multicallable, untraced (one call per STREAM — per-frame
            # spans would cost per-round allocation on the hot path; the
            # master's sync.window root spans keep round attribution)
            setattr(
                self,
                name,
                channel.stream_stream(
                    f"/{service_name}/{name}",
                    request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString,
                ),
            )


class MasterStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, "dsgd.Master", _MASTER_METHODS)


class WorkerStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, "dsgd.Worker", _WORKER_METHODS,
                         stream_methods=_WORKER_STREAM_METHODS)


class ServeStub(_Stub):
    def __init__(self, channel):
        super().__init__(channel, "dsgd.Serving", _SERVE_METHODS)


class GossipSender:
    """Bounded fire-and-forget sender for async delta gossip.

    The reference gossips with no delivery guarantee (fire-and-forget gRPC,
    Slave.scala:103-105); a naive `.future(msg)` translation accumulates
    unbounded in-flight RPCs against a slow or wedged peer.  This keeps at
    most `max_inflight` outstanding UpdateGrad calls per peer: completed
    futures are pruned on every send, and when the window is still full the
    OLDEST in-flight call is cancelled — and counted under
    `slave.async.grad.dropped` once it settles as actually-cancelled (a
    call already executing server-side may still be delivered despite the
    cancel) — the same drop-oldest-under-overload policy as the in-process
    engine's bounded inbox (parallel/hogwild.py).

    With a `breaker` (CircuitBreaker), sends to a partitioned peer are
    SUPPRESSED while the breaker is open — one half-open probe per
    cooldown instead of 64 in-flight cancels — counted under
    `slave.async.grad.suppressed`; every real send's outcome feeds the
    breaker from its done-callback (a cancel from the drop-oldest window
    is NOT a peer failure and reports nothing).  `deadline_s` bounds each
    send so a black-holed peer's futures FAIL (DEADLINE_EXCEEDED) instead
    of hanging forever — without it nothing would ever reach the breaker
    on a silent partition, because the only exit for a hung future is our
    own drop-oldest cancel, which deliberately reports nothing.
    """

    def __init__(self, call, metrics=None, max_inflight: int = 64,
                 breaker: Optional[CircuitBreaker] = None,
                 deadline_s: Optional[float] = None):
        self._call = call  # e.g. stub.UpdateGrad
        self._metrics = metrics
        self.max_inflight = max(1, int(max_inflight))
        self.breaker = breaker
        self.deadline_s = deadline_s
        self._inflight: list = []
        # close() may run on a gRPC servicer thread (peer unregistered)
        # while the async loop still holds a snapshot of this sender: the
        # lock + closed flag stop a late send() from re-populating the
        # window with a future nobody would ever cancel
        self._lock = threading.Lock()
        self._closed = False

    def _report_to_breaker(self, fut) -> None:
        if fut.cancelled():
            return  # our own drop-oldest window, not the peer's fault
        try:
            failed = fut.exception() is not None
        except Exception:  # noqa: BLE001 - treat an unreadable future as failed
            failed = True
        (self.breaker.record_failure if failed else self.breaker.record_ok)()

    def send(self, msg) -> None:
        with self._lock:
            if self._closed:
                return
            if self.breaker is not None and not self.breaker.allow():
                if self._metrics is not None:
                    self._metrics.counter(
                        "slave.async.grad.suppressed").increment()
                return
            self._inflight = [f for f in self._inflight if not f.done()]
            while len(self._inflight) >= self.max_inflight:
                old = self._inflight.pop(0)
                old.cancel()  # best-effort; the delta is lost, as the wire allows
                if self._metrics is not None:
                    # grpc cancel is best-effort: a call already executing
                    # server-side is still delivered, so count the drop only
                    # once the future settles as actually-cancelled —
                    # otherwise slave.async.grad.dropped overstates delta loss
                    metrics = self._metrics
                    old.add_done_callback(
                        lambda f: f.cancelled()
                        and metrics.counter("slave.async.grad.dropped").increment()
                    )
            try:
                if self.deadline_s is not None:
                    fut = self._call.future(msg, timeout=self.deadline_s)
                else:
                    fut = self._call.future(msg)
            except ValueError:  # channel closed under us
                return
            self._inflight.append(fut)
            if self.breaker is not None:
                fut.add_done_callback(self._report_to_breaker)

    @property
    def inflight(self) -> int:
        with self._lock:
            return sum(1 for f in self._inflight if not f.done())

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for f in self._inflight:
                f.cancel()
            self._inflight.clear()


def new_server(port: int, host: str = "0.0.0.0", max_workers: int = 16) -> grpc.Server:
    """Plaintext server factory (core/package.scala:16-17). Port 0 picks a
    free port; the bound port is stored on `server.bound_port`."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 64 * 1024 * 1024),
                 ("grpc.max_send_message_length", 64 * 1024 * 1024)],
    )
    server.bound_port = server.add_insecure_port(f"{host}:{port}")
    return server


def new_channel(host: str, port: int, origin=None) -> grpc.Channel:
    """Plaintext channel factory (core/package.scala:19-21).

    `origin` (the caller's own (host, port), optional) labels the edge
    for the fault-injection layer: when a chaos plan is installed
    (chaos/, DSGD_CHAOS) the channel is wrapped so every RPC through it
    passes the plan's drop/delay/dup/partition decisions — a no-op
    returning the raw channel otherwise."""
    channel = grpc.insecure_channel(
        f"{host}:{port}",
        options=[("grpc.max_receive_message_length", 64 * 1024 * 1024),
                 ("grpc.max_send_message_length", 64 * 1024 * 1024)],
    )
    # endpoint label for client trace spans (read back through the chaos
    # proxy's __getattr__ when a plan wraps the channel)
    channel.dsgd_target = (host, int(port))
    from distributed_sgd_tpu import chaos

    return chaos.wrap_channel(channel, target=(host, int(port)), origin=origin)
