"""Persistent compile cache + AOT warmup: the elastic spin-up fast path.

The reference's only join story is a registration retry loop
(Slave.scala:40-77) — a joining worker pays full data load and, in this
JAX reproduction, full XLA compilation before its first contribution.
That makes elastic membership (docs/ELASTICITY.md) and autoscaling
latency-bound on SPIN-UP rather than on steady-state math: the kernels a
fresh worker compiles are byte-identical to the ones every previous
worker already compiled.

``DSGD_COMPILE_CACHE=<dir>`` turns that waste into a hit:

- **persistent cache** — ``configure(dir)`` points jax's persistent
  compilation cache at a shared directory (min-compile-time/min-size
  floors dropped so every training/serving kernel is eligible).  XLA
  backend compiles are keyed by the lowered HLO, so a joining worker, a
  restarted master, or a fresh serve replica re-compiling a known
  flagship shape reads the executable from disk instead of re-running
  XLA.  jax's own monitoring events feed the
  ``compile.cache.hits``/``compile.cache.misses`` counters
  (utils/metrics.py), so the instruments cover every compile in the
  process — not just the warmed ones.
- **AOT warmup** — ``warmup_async(name, thunks)`` runs a role's flagship
  compile thunks on ONE background daemon thread at bind/build time
  (worker ``_grad_fn``/``_window_fn`` per capacity bucket and the hier
  psum kernels via ``WorkerNode.warmup_thunks``, the mesh BoundSync epoch
  program via ``BoundSync.warmup_thunks``, the serving per-bucket Predict
  via ``PredictEngine.warmup_thunks``) so a joining node compiles while
  it registers/loads instead of under its first request.  Worker/serving
  thunks execute the real jitted callable once on inert zero inputs, so
  they populate the IN-PROCESS dispatch cache too: the first real
  dispatch after warmup performs no tracing at all
  (tests/test_compile_cache.py proves it with a poisoned-trace spy).

Knobs-off contract: with ``DSGD_COMPILE_CACHE`` unset nothing here runs —
``configure`` is never called, jax's cache config keeps its defaults, no
warmup thread starts, and no file is ever written (asserted by
tests/test_compile_cache.py and ``bench.py --spinup``).

Concurrency: a real dispatch arriving while its shape is still warming is
safe — both threads call the same jitted callable and jax serializes /
deduplicates the underlying executable; the race costs at most one
redundant compile (which the persistent cache then absorbs), never a
wrong result.  ``python bench.py --spinup`` gates the payoff: >= 2x
faster time-to-first-contribution for a warm-cache join vs a cold one.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

log = logging.getLogger("dsgd.compile_cache")

# one warmup thunk: (label, zero-arg callable that triggers the compile)
WarmupThunk = Tuple[str, Callable[[], object]]

_configured_dir: Optional[str] = None
_listener_installed = False


def configured_dir() -> Optional[str]:
    """The active cache directory, or None when the knob is off."""
    return _configured_dir


def enabled() -> bool:
    return _configured_dir is not None


def configure(cache_dir: str, metrics=None) -> None:
    """Enable jax's persistent compilation cache at `cache_dir` and start
    counting its hits/misses.  Must run BEFORE the first jit dispatch of
    the process (main.py calls it right after config load); idempotent.
    """
    global _configured_dir
    import jax

    from distributed_sgd_tpu.utils import metrics as metrics_mod

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # every kernel is spin-up-relevant: drop the "only cache slow/large
    # compiles" floors so the per-capacity worker kernels (fast compiles
    # individually, the whole set is what a join waits on) are eligible
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _configured_dir = cache_dir
    _install_listener(metrics or metrics_mod.global_metrics())
    log.info("persistent compile cache on: %s", cache_dir)


def _install_listener(metrics) -> None:
    """Feed jax's compilation-cache monitoring events into our counters.
    Registered once per process; a jax without the private monitoring
    surface just leaves the counters at zero (the cache still works)."""
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax._src import monitoring
    except Exception as e:  # noqa: BLE001 - instruments are best-effort
        log.warning("compile-cache hit/miss counters unavailable (%s)", e)
        return

    from distributed_sgd_tpu.utils import metrics as metrics_mod

    hits = metrics.counter(metrics_mod.COMPILE_CACHE_HITS)
    misses = metrics.counter(metrics_mod.COMPILE_CACHE_MISSES)

    def _on_event(event: str, **kwargs) -> None:
        if event.endswith("/cache_hits"):
            hits.increment()
        elif event.endswith("/cache_misses"):
            misses.increment()

    monitoring.register_event_listener(_on_event)
    _listener_installed = True


def run_warmup(name: str, thunks: Sequence[WarmupThunk],
               metrics=None) -> int:
    """Run `thunks` synchronously; returns how many compiled cleanly.

    One failed thunk never kills the rest (or the caller): warmup is an
    optimization, and the dispatch path compiles lazily exactly as it
    would have without it — the failure is logged and counted."""
    from distributed_sgd_tpu.utils import metrics as metrics_mod

    if metrics is None:
        metrics = metrics_mod.global_metrics()
    t0 = time.perf_counter()
    done = 0
    for label, thunk in thunks:
        t1 = time.perf_counter()
        try:
            thunk()
        except Exception as e:  # noqa: BLE001 - see docstring
            metrics.counter(metrics_mod.COMPILE_WARMUP_ERRORS).increment()
            log.warning("warmup %s/%s failed: %s", name, label, e)
            continue
        done += 1
        metrics.counter(metrics_mod.COMPILE_WARMUP_KERNELS).increment()
        log.info("warmed %s/%s in %.3fs", name, label,
                 time.perf_counter() - t1)
    metrics.gauge(metrics_mod.COMPILE_WARMUP_SECONDS).set(
        time.perf_counter() - t0)
    return done


def warmup_async(name: str, thunks: Sequence[WarmupThunk],
                 metrics=None) -> Optional[threading.Thread]:
    """Start the AOT warmup pass for one role on a background daemon
    thread (None when there is nothing to warm).  The caller keeps
    spinning up — registration, data load, serving bind — while the
    flagship shapes compile; join() the returned thread to run warmup
    synchronously (the spin-up bench's warm path does, so its measured
    first contribution is the pure post-warmup cost)."""
    thunks = list(thunks)
    if not thunks:
        return None
    t = threading.Thread(
        target=run_warmup, args=(name, thunks, metrics),
        daemon=True, name=f"warmup-{name}")
    t.start()
    return t


def cache_file_count() -> int:
    """Number of entries in the configured cache dir (0 when off/empty);
    the cross-process reuse tests assert this stops growing on a rerun."""
    import os

    if _configured_dir is None or not os.path.isdir(_configured_dir):
        return 0
    return len(os.listdir(_configured_dir))
