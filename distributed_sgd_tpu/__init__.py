"""tpu-dsgd: a TPU-native distributed SGD framework.

A ground-up JAX/XLA re-design of the capabilities of the JVM reference
``zifeo/distributed-sgd`` (see SURVEY.md):

- synchronous data-parallel SGD (master-coordinated per-batch gradient
  aggregation -> `jax.lax.psum` over a device mesh, reference
  core/Master.scala:179-198),
- asynchronous Hogwild SGD with peer gossip of weight deltas (reference
  core/Slave.scala:79-111), both as a host-driven gossip mode and as an
  on-mesh local-SGD mode,
- sparse hinge-loss SVM on RCV1 (804,414 samples x 47,236 features,
  reference core/ml/SparseSVM.scala, utils/Dataset.scala),
- cluster membership/readiness over gRPC (reference proto.proto),
- early stopping, split strategies, leaky-smoothed async loss checking
  with best-weights tracking, typed env-overridable config,
  span/counter/histogram observability, checkpointing (superset).

The compute hot path is compiled XLA: padded-sparse batched matvec +
segment-scatter gradients on device, collectives over ICI/DCN instead of
message-passing reduce.
"""

__version__ = "0.1.0"

from distributed_sgd_tpu.config import Config  # noqa: F401
