"""Application entry point: config-driven role selection and scenario.

Mirror of the reference Main (Main.scala:18-159): no CLI flags — behavior
is driven entirely by DSGD_* env config.  Role selection
(Main.scala:122-159):

- master_host/master_port unset        -> dev mode (in-process cluster)
- (master_host, master_port) == self   -> master process
- otherwise                            -> worker process
- DSGD_ROLE overrides the derivation; DSGD_ROLE=serve (the only role with
  no derivation rule) runs the online-inference front end over the
  trainer's checkpoints (serving/, docs/SERVING.md)

Dev mode picks the execution engine via DSGD_ENGINE:

- ``mesh`` (default): the TPU-native fast path — in-mesh collectives
  (parallel/sync.py or parallel/local_sgd.py / parallel/hogwild.py for
  async) with no RPC data plane;
- ``rpc``: reference-parity topology — an in-process gRPC cluster
  (core/cluster.py), master fanning batches out to worker processes'
  servicers exactly like the reference dev mode (Main.scala:143-158).

The scenario (Main.scala:70-120): initial eval at w0 = 0, fit (sync or
async per config), final weights + local test loss/acc logged.

Run: ``python -m distributed_sgd_tpu.main``
"""

from __future__ import annotations

import logging
import os
import socket
import sys

import jax
import numpy as np

from distributed_sgd_tpu.config import Config
from distributed_sgd_tpu.core.early_stopping import no_improvement
from distributed_sgd_tpu.data.rcv1 import Dataset, dim_sparsity, load_rcv1, train_test_split
from distributed_sgd_tpu.data.synthetic import rcv1_like
from distributed_sgd_tpu.models.linear import make_model
from distributed_sgd_tpu.utils import measure
from distributed_sgd_tpu.utils import metrics as metrics_mod
from distributed_sgd_tpu.utils.log import setup as setup_logging

log = logging.getLogger("dsgd.main")


def load_data(cfg: Config) -> Dataset:
    """RCV1 from cfg.data_path, or synthetic via DSGD_SYNTHETIC=<n> when the
    corpus is absent (no-egress environments)."""
    synthetic = os.environ.get("DSGD_SYNTHETIC")
    train_file = os.path.join(cfg.data_path, "lyrl2004_vectors_train.dat")
    if synthetic or not os.path.exists(train_file):
        n = int(synthetic or 100_000)
        log.info("RCV1 not found or DSGD_SYNTHETIC set: generating %d synthetic rows", n)
        # ltc/IDF value weighting, like real RCV1-v2 term weighting — the
        # shipped default lr=0.5 only descends smoothly with it
        # (benches/zipf_oscillation.py, BASELINE.md round 4)
        return rcv1_like(n, seed=cfg.seed, idf_values=True)
    return load_rcv1(cfg.data_path, full=cfg.full, pad_width=cfg.pad_width)


def build(cfg: Config):
    data = measure.duration_log("data loaded", lambda: load_data(cfg), log)
    train, test = train_test_split(data)
    ds = measure.duration_log("dim sparsity", lambda: dim_sparsity(train), log)
    model = make_model(cfg.model, cfg.lam, train.n_features, dim_sparsity=ds)
    return train, test, model


def _make_checkpointer(cfg: Config):
    """cfg.checkpoint_dir -> Checkpointer (or None): the sync trainer saves
    at cfg.checkpoint_every epoch cadence and resumes from the latest
    snapshot; async engines persist each new best-weights snapshot via
    their LossChecker and resume from the latest best."""
    if not cfg.checkpoint_dir:
        return None
    from distributed_sgd_tpu.checkpoint import Checkpointer

    return Checkpointer(cfg.checkpoint_dir)


def _restore_weights(ckpt):
    """Latest checkpointed weights (async resume / autopilot warm
    start), or None."""
    if ckpt is None:
        return None
    restored = ckpt.restore_latest()
    if restored is None:
        return None
    step, state = restored
    log.info("warm start from checkpoint at step %d", step)
    return np.asarray(state["weights"])


def select_topology(
    node_count: int, n_devices: int, use_async: bool,
    virtual_workers: int = 1, exact_topology: bool = False,
):
    """(mesh devices, virtual workers per device) for the sync path.

    Cover the full reference worker count even on fewer chips — remaining
    workers are emulated per device (parallel/sync.py virtual_workers).
    Default: use ALL available devices with ceil-division virtual workers —
    the total may exceed node_count by < n_devices, but no device sits
    idle.  exact_topology=True (DSGD_EXACT_TOPOLOGY) instead insists on
    exactly node_count workers via the largest divisor <= n_devices (which
    can idle most of the mesh — e.g. node_count=7 on 6 chips runs 1 chip).
    Async engines ignore virtual_workers, so they always get every device.
    """
    n_max = min(node_count, n_devices)
    virtual = virtual_workers
    if not use_async and virtual == 1 and node_count > n_max:
        if exact_topology:
            n = max(d for d in range(1, n_max + 1) if node_count % d == 0)
            virtual = node_count // n
            if n < n_max:
                log.warning(
                    "exact_topology: shrank the mesh to %d device(s) (the "
                    "largest divisor of node_count=%d that is <= %d; %d "
                    "device(s) idle) to run exactly %d workers",
                    n, node_count, n_max, n_max - n, node_count,
                )
        else:
            n = n_max
            virtual = -(-node_count // n)  # ceil
            if n * virtual != node_count:
                log.warning(
                    "node_count=%d rounded up to %d workers (%d devices x %d "
                    "virtual) to keep every device busy; set "
                    "DSGD_EXACT_TOPOLOGY=1 for exactly node_count workers",
                    node_count, n * virtual, n, virtual,
                )
    else:
        n = n_max
    return n, virtual


def scenario_mesh(cfg: Config, train: Dataset, test: Dataset, model) -> None:
    """Dev-mode fast path: in-mesh engines, no RPC data plane."""
    from distributed_sgd_tpu.parallel.mesh import make_mesh

    n, virtual = select_topology(
        cfg.node_count, len(jax.devices()), cfg.use_async,
        cfg.virtual_workers, cfg.exact_topology,
    )
    mesh = make_mesh(n)
    criterion = no_improvement(patience=cfg.patience, min_delta=cfg.conv_delta)
    if cfg.compress != "none" and not (cfg.use_async and cfg.async_mode == "gossip"):
        # the sync / local-SGD / feature-sharded mesh engines exchange
        # gradients through XLA collectives — there is no wire to compress
        # (docs/COMPRESSION.md "when NOT to compress"); only the gossip
        # engine and the rpc topology honor DSGD_COMPRESS
        log.warning(
            "DSGD_COMPRESS=%s ignored: in-mesh engines have no wire path "
            "(use engine=rpc or async_mode=gossip)", cfg.compress)
    if (cfg.local_steps > 1 or cfg.delta_broadcast or cfg.stream
            or cfg.fanin_lanes or cfg.stage_pool or cfg.agg_tree
            or cfg.master_shards):
        # the pipelined sync levers shape RPC wire traffic; the mesh
        # engines exchange gradients through XLA collectives
        log.warning(
            "DSGD_LOCAL_STEPS/DSGD_DELTA_BROADCAST/DSGD_STREAM/"
            "DSGD_FANIN_LANES/DSGD_STAGE_POOL/DSGD_AGG_TREE/"
            "DSGD_MASTER_SHARDS ignored: the pipelined sync engine is "
            "the rpc topology's (use engine=rpc; the mesh local-SGD "
            "equivalent is async_mode=local_sgd / sync_period)")
    if cfg.quorum is not None or cfg.chaos:
        # quorum barriers gate RPC fan-ins and chaos wraps RPC stubs; an
        # in-mesh XLA collective has neither
        log.warning(
            "DSGD_QUORUM/DSGD_CHAOS ignored: the quorum barrier and the "
            "fault-injection layer live on the rpc topology's wire "
            "(use engine=rpc)")
    if cfg.elastic or cfg.async_drain or cfg.fit_ckpt_every:
        # elastic membership, the batch-drain inbox, and the crash-safe
        # fit-state snapshot all live on the rpc control plane
        log.warning(
            "DSGD_ELASTIC/DSGD_ASYNC_DRAIN/DSGD_FIT_CKPT_EVERY ignored: "
            "the elastic + crash-recovery subsystem is the rpc topology's "
            "(use engine=rpc; docs/ELASTICITY.md)")
    if (cfg.gossip_topology != "all"
            and not (cfg.use_async and cfg.async_mode == "gossip")):
        # only the gossip plane has peer fan-out to sparsify
        log.warning(
            "DSGD_GOSSIP_TOPOLOGY=%s ignored: only the gossip engines "
            "(async_mode=gossip or engine=rpc async) have a peer fan-out",
            cfg.gossip_topology)
    if cfg.telemetry or cfg.health_action:
        # the telemetry plane scrapes over the Metrics RPC and the health
        # monitor rides fit_sync's fan-in; a one-process mesh engine has
        # neither (its existing /metrics exporter IS the cluster view)
        log.warning(
            "DSGD_TELEMETRY/DSGD_HEALTH_ACTION ignored: the cluster "
            "telemetry plane is the rpc topology's (use engine=rpc; "
            "docs/OBSERVABILITY.md)")
    if cfg.host_devices != 1:
        # the mesh engines ARE an all-device mesh already; the in-host
        # psum layer under an RPC plane is the rpc topology's
        log.warning(
            "DSGD_HOST_DEVICES ignored: the mesh engine already spans "
            "every device — the hierarchical in-host layer is the rpc "
            "topology's (use engine=rpc; docs/HIERARCHY.md)")
    log.info(
        "engine=mesh devices=%d virtual_workers=%d kernel=%s model=%s async=%s",
        n, virtual, cfg.kernel, cfg.model, cfg.use_async,
    )

    ckpt = _make_checkpointer(cfg)
    if cfg.feature_shards > 1:
        # dp x tp: config.__post_init__ already rejected async/rpc combos
        from distributed_sgd_tpu.parallel.feature_sharded import (
            FeatureShardedEngine,
            make_mesh_2d,
        )

        n_devs = len(jax.devices())
        n_w = max(1, n_devs // cfg.feature_shards)
        log.info("engine=mesh 2-D dp=%d x tp=%d (feature_shards)",
                 n_w, cfg.feature_shards)
        eng = FeatureShardedEngine(
            model, make_mesh_2d(n_w, cfg.feature_shards),
            batch_size=cfg.batch_size, learning_rate=cfg.learning_rate,
        )
        res = eng.fit(train, test, cfg.max_epochs, criterion,
                      checkpointer=ckpt, checkpoint_every=cfg.checkpoint_every,
                      seed=cfg.seed)
        _finish(cfg, res, saved=ckpt is not None)
        return
    if cfg.use_async and cfg.async_mode == "gossip":
        from distributed_sgd_tpu.parallel.hogwild import HogwildEngine

        eng = HogwildEngine(
            model, n_workers=cfg.node_count, batch_size=cfg.batch_size,
            learning_rate=cfg.learning_rate, check_every=cfg.check_every,
            leaky_loss=cfg.leaky_loss, seed=cfg.seed, checkpointer=ckpt,
            steps_per_dispatch=cfg.steps_per_dispatch,
            optimizer=cfg.optimizer, momentum=cfg.momentum,
            compress=cfg.compress, compress_k=cfg.compress_k,
            compress_ef=cfg.compress_ef,
            gossip_topology=cfg.gossip_topology,
        )
        res = eng.fit(train, test, cfg.max_epochs, criterion,
                      initial_weights=_restore_weights(ckpt))
    elif cfg.use_async:
        from distributed_sgd_tpu.parallel.local_sgd import LocalSGDEngine

        eng = LocalSGDEngine(
            model, mesh, batch_size=cfg.batch_size,
            learning_rate=cfg.learning_rate, sync_period=cfg.sync_period,
            check_every=cfg.check_every, leaky_loss=cfg.leaky_loss, seed=cfg.seed,
            kernel=cfg.kernel, checkpointer=ckpt,
            optimizer=cfg.optimizer, momentum=cfg.momentum,
        )
        res = eng.fit(train, test, cfg.max_epochs, criterion,
                      initial_weights=_restore_weights(ckpt))
    else:
        from distributed_sgd_tpu.core.trainer import SyncTrainer

        trainer = SyncTrainer(
            model, mesh, batch_size=cfg.batch_size,
            learning_rate=cfg.learning_rate, seed=cfg.seed,
            kernel=cfg.kernel, virtual_workers=virtual,
            checkpointer=ckpt, checkpoint_every=cfg.checkpoint_every,
            optimizer=cfg.optimizer, momentum=cfg.momentum,
            profile_dir=cfg.profile_dir,
        )
        res = trainer.fit(train, test, cfg.max_epochs, criterion)

    _finish(cfg, res, saved=ckpt is not None)


def _fit_state_args(cfg: Config) -> dict:
    """DSGD_FIT_CKPT_EVERY -> fit_sync crash-snapshot kwargs (empty when
    disabled; config validation already required checkpoint_dir).  ANY
    health action also gets the path (with fit_ckpt_every=0 it is the
    path alone, so no cadence snapshots run): 'snapshot'/'halt' write the
    trip snapshot there, and every action — 'warn' included — RESTORES
    one a previous halted run left, so restarting after a halt resumes
    regardless of which action the restart runs with."""
    if not (cfg.fit_ckpt_every or cfg.health_action) or not cfg.checkpoint_dir:
        return {}
    from distributed_sgd_tpu.checkpoint import fit_state_path

    return {"fit_state_path": fit_state_path(cfg.checkpoint_dir),
            "fit_state_every": cfg.fit_ckpt_every}


def _resolve_host_devices(cfg: Config, dev_workers: int = 0) -> int:
    """DSGD_HOST_DEVICES -> the worker's in-host mesh width
    (docs/HIERARCHY.md): 0 = auto — every local device on a standalone
    worker role, the per-worker share of the local mesh in dev mode
    (`dev_workers` in-process workers divide what one process can see);
    1 = the flat single-device worker, D = exactly D devices."""
    if cfg.host_devices == 0:
        d = jax.local_device_count()
        if dev_workers:
            d = max(1, d // dev_workers)
        log.info("DSGD_HOST_DEVICES=0: auto-sized the in-host mesh to "
                 "%d device(s)", d)
        return d
    return cfg.host_devices


def _health_monitor(cfg: Config, metrics=None):
    """DSGD_HEALTH_ACTION -> telemetry.HealthMonitor (None when unset)."""
    if not cfg.health_action:
        return None
    from distributed_sgd_tpu.telemetry.health import HealthMonitor

    log.info("training-health monitor on: action=%s", cfg.health_action)
    monitor = HealthMonitor(metrics=metrics, action=cfg.health_action)
    # the leak-slope sentinel (resource probe, ISSUE 20) routes its trips
    # through the same DSGD_HEALTH_ACTION machinery as a loss divergence
    from distributed_sgd_tpu.telemetry import resources

    probe = resources.active()
    if probe is not None and probe.sentinel is not None:
        probe.sentinel.attach_health(monitor)
    return monitor


def scenario_rpc(cfg: Config, train: Dataset, test: Dataset, model) -> None:
    """Dev-mode reference-parity path: in-process gRPC cluster."""
    from distributed_sgd_tpu.core.cluster import DevCluster

    criterion = no_improvement(patience=cfg.patience, min_delta=cfg.conv_delta)
    host_devices = _resolve_host_devices(cfg, dev_workers=cfg.node_count)
    with DevCluster(model, train, test, n_workers=cfg.node_count, seed=cfg.seed,
                    heartbeat_s=cfg.heartbeat_s,
                    heartbeat_max_misses=cfg.heartbeat_max_misses,
                    steps_per_dispatch=cfg.steps_per_dispatch,
                    compress=cfg.compress, compress_k=cfg.compress_k,
                    compress_ef=cfg.compress_ef, chaos=cfg.chaos,
                    gossip_topology=cfg.gossip_topology,
                    telemetry_port=cfg.telemetry_port if cfg.telemetry
                    else None,
                    host_devices=host_devices,
                    host_overprovision=cfg.host_overprovision) as c:
        if cfg.compile_cache:
            # dev-mode spin-up fast path: every in-process worker warms
            # its flagship shapes in the background before the fit's
            # first fan-out reaches it
            from distributed_sgd_tpu import compile_cache

            for i, w in enumerate(c.workers):
                compile_cache.warmup_async(
                    f"worker[w{i}]",
                    w.warmup_thunks(cfg.batch_size, cfg.local_steps))
        w0 = np.zeros(model.n_features, dtype=np.float32)
        loss0, acc0 = c.master.local_loss(w0, test=False)
        log.info("initial loss=%.6f acc=%.4f", loss0, acc0)
        ckpt = _make_checkpointer(cfg)
        if cfg.use_async:
            res = c.master.fit_async(
                cfg.max_epochs, cfg.batch_size, cfg.learning_rate, criterion,
                check_every=cfg.check_every, leaky_loss=cfg.leaky_loss,
                initial_weights=_restore_weights(ckpt), checkpointer=ckpt,
                optimizer=cfg.optimizer, momentum=cfg.momentum,
                elastic=cfg.elastic, batch_drain=cfg.async_drain,
            )
        else:
            res = c.master.fit_sync(
                cfg.max_epochs, cfg.batch_size, cfg.learning_rate, criterion,
                checkpointer=ckpt, checkpoint_every=cfg.checkpoint_every,
                optimizer=cfg.optimizer, momentum=cfg.momentum,
                local_steps=cfg.local_steps,
                delta_broadcast=cfg.delta_broadcast,
                stream=cfg.stream,
                fanin_lanes=cfg.fanin_lanes, stage_pool=cfg.stage_pool,
                agg_tree=cfg.agg_tree,
                master_shards=cfg.master_shards,
                quorum=cfg.quorum, straggler_soft_s=cfg.straggler_soft_s,
                health=_health_monitor(cfg, metrics=c.master.metrics),
                **_fit_state_args(cfg),
            )
        _finish(cfg, res, evaluator=lambda w: c.master.local_loss(w, test=True),
                saved=ckpt is not None)


def _finish(cfg: Config, res, evaluator=None, saved: bool = False) -> None:
    w = res.state.weights
    log.info("fit done: %d epochs, final loss=%.6f, %d updates",
             res.epochs_run, res.state.loss, res.state.updates)
    if evaluator is None:
        log.info("test losses: %s", ", ".join(f"{x:.6f}" for x in res.test_losses))
    else:
        tl, ta = evaluator(np.asarray(w))
        log.info("final test loss=%.6f acc=%.4f", tl, ta)
    # safety net: every scenario path now wires its checkpointer into the
    # fit itself (mesh + RPC, sync + async), so this exit-time snapshot only
    # runs for future paths added without in-fit wiring
    if cfg.checkpoint_dir and not saved:
        from distributed_sgd_tpu.checkpoint import Checkpointer

        Checkpointer(cfg.checkpoint_dir).save(res.epochs_run, w)


def main() -> None:
    setup_logging()
    cfg = Config.from_env()
    log.info("host: %s (%s)", socket.gethostname(), sys.platform)
    log.info("config: %s", cfg.to_json())
    np.random.seed(cfg.seed)  # Main.scala:32 Random.setSeed(0)

    # elastic spin-up fast path (compile_cache.py): point jax's persistent
    # compilation cache at the shared directory BEFORE the first jit of
    # the process, so every XLA compile below — warmup thunks and live
    # traffic alike — reads/writes the cache.  Unset: nothing happens (no
    # config touch, no files; asserted by tests/test_compile_cache.py).
    if cfg.compile_cache:
        from distributed_sgd_tpu import compile_cache

        compile_cache.configure(cfg.compile_cache)

    # observability plumbing (docs/OBSERVABILITY.md), BEFORE any channel or
    # server exists so every RPC edge is covered:
    # - DSGD_TRACE: per-round span timelines, Chrome/Perfetto export
    # - DSGD_FLIGHT_RECORDER: always-on post-mortem ring (SIGUSR2 dumps)
    from distributed_sgd_tpu import trace as trace_mod
    from distributed_sgd_tpu.trace import flight

    role = cfg.role
    trace_dir = cfg.trace_dir or ("dsgd-traces" if cfg.trace else None)
    if cfg.trace:
        trace_mod.configure(enabled=True, dir=trace_dir,
                            sample=cfg.trace_sample,
                            service=f"{role}-{cfg.port}")
        log.info("tracing on: sample=%g dir=%s (merge with "
                 "`python -m distributed_sgd_tpu.trace.merge %s`)",
                 cfg.trace_sample, trace_dir, trace_dir)
    flight.configure(capacity=cfg.flight_recorder,
                     service=f"{role}-{cfg.port}", dir=trace_dir or ".")
    flight.install_signal_handler()

    # long-horizon resource plane (telemetry/resources.py, ISSUE 20):
    # DSGD_RESOURCE_PROBE_S > 0 starts the per-process probe thread —
    # /proc + pressure gauges every tick, the leak-slope sentinel riding
    # the series (trip action = DSGD_HEALTH_ACTION, default warn), and
    # (DSGD_BLACKBOX_DIR) the crash-surviving blackbox ring.  Unset: no
    # thread, no gauges, no files — byte-identical (asserted by test).
    probe = None
    if cfg.resource_probe_s > 0:
        from distributed_sgd_tpu.telemetry import blackbox as blackbox_mod
        from distributed_sgd_tpu.telemetry import resources, slope

        sentinel = slope.LeakSentinel(metrics=metrics_mod.global_metrics())
        box = (blackbox_mod.Blackbox(cfg.blackbox_dir,
                                     service=f"{role}-{cfg.port}")
               if cfg.blackbox_dir else None)
        probe = resources.configure(cfg.resource_probe_s,
                                    metrics=metrics_mod.global_metrics(),
                                    sentinel=sentinel, blackbox=box)
        log.info("resource probe on: every %gs (blackbox=%s)",
                 cfg.resource_probe_s, cfg.blackbox_dir or "off")

    # record=true enables metric SHIPPING (the reference's Kamon reporter
    # flag, Main.scala:40-43); the transports are orthogonal and may both
    # run: DSGD_METRICS_PORT serves Prometheus pull, DSGD_INFLUX_URL pushes
    # line protocol every second (reference parity, application.conf:54-78)
    exporter = None
    pusher = None
    if cfg.record:
        if cfg.metrics_port is not None:
            from distributed_sgd_tpu.utils.metrics import PrometheusExporter

            exporter = PrometheusExporter(
                metrics_mod.global_metrics(), cfg.metrics_port).start()
            log.info("metrics exporter on :%d", exporter.port)
        if cfg.influx_url:
            from distributed_sgd_tpu.utils.metrics import InfluxPusher

            pusher = InfluxPusher(metrics_mod.global_metrics(), cfg.influx_url).start()
            log.info("influx pusher -> %s", cfg.influx_url)
        if exporter is None and pusher is None:
            log.warning(
                "DSGD_RECORD=1 but neither DSGD_METRICS_PORT nor "
                "DSGD_INFLUX_URL is set: metrics are collected but not shipped")

    try:
        _run_role(cfg, role)
    except Exception:
        # an uncaught exception in any engine loop that surfaces here
        # leaves flight-recorder evidence before the process dies
        flight.dump("exception")
        raise
    finally:
        # stop + final flush on EVERY exit path: a crashed run's tail
        # metrics (incl. metrics.push.errors) are the ones that matter —
        # same for the trace buffer
        trace_mod.flush()
        if probe is not None:
            probe.stop()
        if exporter is not None:
            exporter.stop()
        if pusher is not None:
            pusher.stop()


def _install_chaos(cfg: Config) -> None:
    """DSGD_CHAOS on a standalone master/worker process: install the plan
    before any channel exists so every outgoing stub is wrapped (chaos/).
    Partition specs reference endpoints as host:port in multi-process
    deployments; dev mode's DevCluster also names them w0..wN/master."""
    if not cfg.chaos:
        return
    from distributed_sgd_tpu import chaos

    chaos.install(cfg.chaos, metrics=metrics_mod.global_metrics())
    log.warning("chaos plan active on this node: %s", cfg.chaos)


def _select_scatter(cfg: Config, data: Dataset) -> None:
    """DSGD_SCATTER -> the process-wide scatter formulation (ops/mxu.py),
    resolved after the data loads but BEFORE any engine or jitted
    function is built — the formulation is read at trace time.  'auto'
    runs the one-time runtime rematch at THIS dataset's step shape
    (batch x pad_width x n_features — the T depth and R block count that
    decide the race) on this process's device; the default 'onehot' is a
    no-op (knobs-off training byte-identical)."""
    if cfg.scatter == "onehot":
        return
    from distributed_sgd_tpu.ops import mxu

    if cfg.scatter == "auto" and data.is_dense:
        # dense-layout data runs plain matmuls — there is no sparse
        # scatter to rematch (and pad_width is 0)
        log.info("DSGD_SCATTER=auto: dense-layout data has no sparse "
                 "scatter; keeping 'onehot'")
        return
    resolved = mxu.resolve_scatter_formulation(
        cfg.scatter, batch_size=cfg.batch_size, nnz=max(1, data.pad_width),
        n_features=data.n_features)
    mxu.set_scatter_formulation(resolved)
    log.info("scatter formulation: %s%s", resolved,
             " (DSGD_SCATTER=auto rematch)" if cfg.scatter == "auto" else "")


def _load_probe(cfg: Config):
    """DSGD_SERVE_PROBE -> canary probe rows (None when unset)."""
    if not cfg.serve_probe:
        return None
    from distributed_sgd_tpu.serving.router import load_probe

    probe = load_probe(cfg.serve_probe)
    log.info("canary probe set: %d rows from %s", len(probe), cfg.serve_probe)
    return probe


def _autopilot_probe_source(cfg: Config):
    """DSGD_AUTOPILOT on the route role -> (ProbeReservoir, refresh_s):
    live probe sourcing (autopilot/probe_source.py) replaces the
    operator-rotated probe file.  The env-driven role joins ground truth
    through the seeded DriftingStream oracle — the documented assumption
    (docs/CONTINUAL.md) that the traffic IS the synthetic stream, which
    is exactly what the dev role and the flywheel bench send; a
    production integrator supplies its own labeler (feedback-log join)
    programmatically."""
    if not cfg.autopilot:
        return None, 0.0
    from distributed_sgd_tpu.autopilot import DriftingStream, ProbeReservoir

    stream = DriftingStream(seed=cfg.seed)
    reservoir = ProbeReservoir(
        stream.oracle_labeler(), capacity=cfg.autopilot_probe_capacity,
        seed=cfg.seed, label_delay=cfg.autopilot_label_delay,
        recency=2 * cfg.autopilot_probe_capacity,
        min_fill=max(1, cfg.autopilot_probe_capacity // 2))
    log.info(
        "autopilot probe sourcing: reservoir capacity=%d label_delay=%d "
        "refresh=%gs", reservoir.capacity, reservoir.label_delay,
        cfg.autopilot_source_refresh_s)
    return reservoir, cfg.autopilot_source_refresh_s


def _autopilot_stream_build(cfg: Config):
    """DSGD_AUTOPILOT on the master role -> the stream plane
    (autopilot/stream.py): the resident corpus is the newest
    DSGD_AUTOPILOT_WINDOW rows of the seeded drifting stream and the
    eval set is pinned to the window's trailing edge, so the existing
    early-stopping machinery judges convergence against the CURRENT
    distribution.  A master relaunch warm-starts automatically from the
    epoch-cadence checkpoint (fit_sync's restore path); grant it a
    raised DSGD_MAX_EPOCHS budget and the relaunch IS one flywheel
    retrain round (the dev role and benches/bench_flywheel.py run the
    full closed loop hands-free in one process)."""
    from distributed_sgd_tpu.autopilot import DriftingStream

    stream = DriftingStream(seed=cfg.seed)
    train = measure.duration_log(
        "stream window materialized",
        lambda: stream.rows(0, cfg.autopilot_window), log)
    test = stream.eval_set(max(256, cfg.autopilot_window // 8),
                           at=cfg.autopilot_window)
    ds = dim_sparsity(train)
    model = make_model(cfg.model, cfg.lam, train.n_features,
                       dim_sparsity=ds)
    return train, test, model


def _run_dev_flywheel(cfg: Config) -> None:
    """DSGD_ROLE=dev + DSGD_AUTOPILOT: the full closed loop in one
    process (autopilot/flywheel.py).  A DevCluster trains on the stream
    window, a ServingFleet serves the checkpoints, the router sources
    its probe set from its own traffic, and the controller drives drift
    -> retrain -> canary -> promote hands-free.  Pumps one complete
    shift through the fleet (the stream's schedule decides when), waits
    for the controller to settle, logs the summary, and exits."""
    from distributed_sgd_tpu.autopilot import (
        DriftDetector,
        DriftingStream,
        Flywheel,
    )

    stream = DriftingStream(seed=cfg.seed)
    horizon = stream.shift_at + 2 * cfg.autopilot_window
    detector = DriftDetector(
        ratio=cfg.autopilot_drift_ratio,
        patience=cfg.autopilot_drift_patience,
        warmup=cfg.autopilot_drift_warmup,
        abs_floor=cfg.autopilot_drift_floor)
    fly = Flywheel(
        stream, horizon_rows=horizon, window_rows=cfg.autopilot_window,
        model=cfg.model, lam=cfg.lam, n_workers=2,
        n_replicas=max(2, cfg.serve_replicas),
        max_epochs=cfg.max_epochs, batch_size=cfg.batch_size,
        learning_rate=cfg.learning_rate, patience=cfg.patience,
        conv_delta=cfg.conv_delta,
        probe_capacity=cfg.autopilot_probe_capacity,
        label_delay=cfg.autopilot_label_delay,
        source_refresh_s=cfg.autopilot_source_refresh_s,
        canary_fraction=cfg.serve_canary or 0.5,
        detector=detector, poll_s=cfg.autopilot_poll_s,
        cooldown_s=cfg.autopilot_cooldown_s,
        canary_timeout_s=cfg.autopilot_canary_timeout_s,
        max_retrains=cfg.autopilot_max_retrains,
        recovery_band=cfg.autopilot_recovery_band,
        seed=cfg.seed, ckpt_dir=cfg.checkpoint_dir or None,
        telemetry_port=cfg.telemetry_port if cfg.telemetry else None,
    )
    log.info("dev flywheel: horizon=%d rows (%s shift at %d), window=%d",
             horizon, stream.schedule, stream.shift_at,
             cfg.autopilot_window)
    fly.start()
    try:
        summary = fly.run()
    finally:
        fly.stop()
    log.info(
        "flywheel done: served=%d dropped=%d retrains=%d promoted=%d "
        "rolled_back=%d state=%s", summary["served"], summary["dropped"],
        summary["retrains"], summary["promoted"], summary["rolled_back"],
        summary["state"])


def _serve_distributor(cfg: Config):
    """DSGD_SERVE_PUSH on a training role -> started CheckpointDistributor
    (None when unset): every checkpoint the fit writes streams to the
    fleet as a versioned weight delta (docs/SERVING.md "serving fleet");
    config validation already required checkpoint_dir."""
    if not cfg.serve_push:
        return None
    from distributed_sgd_tpu.serving.push import CheckpointDistributor, parse_targets

    targets = parse_targets(cfg.serve_push)
    log.info("checkpoint distributor on: %s -> %s",
             cfg.checkpoint_dir, cfg.serve_push)
    return CheckpointDistributor(
        cfg.checkpoint_dir, targets,
        metrics=metrics_mod.global_metrics()).start()


def _build_worker_row_store(cfg: Config):
    """DSGD_ROW_STORE on the worker role: map the packed corpus
    (data/row_store.py) instead of parsing it, and with DSGD_HOST_INDEX
    load ONLY this worker's host slice (+ the DSGD_HOST_OVERPROVISION
    neighbor margin) through the store's RowReader — the no-egress
    real-corpus host-local spin-up path (docs/HIERARCHY.md "Elastic
    composition").  Returns (data, model, worker kwargs).

    A missing store next to an existing corpus is built once (the one
    parse every later spin-up amortizes); the train split's dim-sparsity
    vector rides the store's sidecar so no worker re-scans the corpus to
    build its model."""
    from distributed_sgd_tpu.data import host_shard
    from distributed_sgd_tpu.data.row_store import (
        RowStore,
        build_from_corpus,
        meta_path,
    )

    if not os.path.exists(meta_path(cfg.row_store)):
        log.info("row store %s missing: building from %s (one-time parse)",
                 cfg.row_store, cfg.data_path)
        measure.duration_log(
            "row store built",
            lambda: build_from_corpus(cfg.data_path, cfg.row_store,
                                      full=cfg.full,
                                      pad_width=cfg.pad_width), log)
    store = RowStore(cfg.row_store)
    ds = store.dim_sparsity()
    if ds is None:
        log.warning("row store has no dim-sparsity sidecar: the model "
                    "falls back to the plain l2 regularizer")
    model = make_model(cfg.model, cfg.lam, store.n_features,
                       dim_sparsity=ds)
    n_train = store.train_rows
    if cfg.host_index is None:
        # full train split resident, straight off the mmap — no parse,
        # no reader needed (ids pass through untouched)
        data = store.read_rows(0, n_train)
        log.info("row store mapped: %d train rows resident (full split)",
                 n_train)
        return data, model, {}
    lo, hi, start, end = host_shard.overprovisioned_slice(
        n_train, cfg.host_index, cfg.node_count,
        overprovision=cfg.host_overprovision)
    data = host_shard.load_host_shard(
        store.reader, n_train, store.n_features, store.pad_width,
        lo, hi, labels_dtype=store.labels_dtype)
    log.info(
        "host-local slice %d/%d loaded through the row store: rows "
        "[%d, %d) resident (nominal [%d, %d) + overprovision %g)",
        cfg.host_index, cfg.node_count, lo, hi, start, end,
        cfg.host_overprovision)
    return data, model, dict(
        data_offset=lo, row_reader=store.reader, total_rows=n_train,
        host_overprovision=cfg.host_overprovision)


def _warmup_worker(cfg: Config, worker) -> None:
    """DSGD_COMPILE_CACHE on the worker role: kick the background AOT
    pass over the worker's flagship shapes while registration runs."""
    if not cfg.compile_cache:
        return
    from distributed_sgd_tpu import compile_cache

    compile_cache.warmup_async(
        f"worker[:{cfg.port}]",
        worker.warmup_thunks(cfg.batch_size, cfg.local_steps))


def _run_role(cfg: Config, role: str) -> None:
    if role == "route":
        # Serving-fleet router (serving/router.py; DSGD_ROLE=route): fans
        # Predict traffic over DSGD_SERVE_TARGETS with health-aware
        # power-of-two-choices balancing, and gates pushed checkpoint
        # versions through the canary fraction (docs/SERVING.md).
        from distributed_sgd_tpu.serving.push import parse_targets
        from distributed_sgd_tpu.serving.router import ServingRouter

        # DSGD_AUTOPILOT: live probe sourcing — the router reservoir-
        # samples its own Predict traffic into the canary probe set
        # (autopilot/probe_source.py, docs/CONTINUAL.md)
        probe_source, source_refresh_s = _autopilot_probe_source(cfg)
        router = ServingRouter(
            parse_targets(cfg.serve_targets), port=cfg.serve_port,
            model=cfg.model, lam=cfg.lam,
            canary_fraction=cfg.serve_canary, probe=_load_probe(cfg),
            hedge_ms=cfg.serve_hedge_ms, health_s=cfg.serve_health_s,
            telemetry_port=cfg.telemetry_port if cfg.telemetry else None,
            metrics=metrics_mod.global_metrics(), seed=cfg.seed,
            # DSGD_SERVE_STATE: a restarted router re-pins the promoted
            # version instead of re-canarying it (docs/SERVING.md)
            state_path=cfg.serve_state,
            # DSGD_SERVE_PROBE_REFRESH_S: rotate fresh held-out probe rows
            # in from the probe file on a cadence (ROADMAP 3c)
            probe_path=cfg.serve_probe,
            probe_refresh_s=cfg.serve_probe_refresh_s,
            probe_source=probe_source,
            probe_source_refresh_s=source_refresh_s,
        ).start()
        if cfg.serve_ha:
            # DSGD_SERVE_HA: dual LIVE routers — attach the lease-based
            # coordinator and start the promoted-state peer-sync loop
            # (serving/ha.py, docs/SERVING.md "HA")
            from distributed_sgd_tpu.serving.ha import HACoordinator

            router.attach_ha(HACoordinator.from_spec(
                cfg.serve_ha, metrics=metrics_mod.global_metrics()))
            router._ha.start()
        log.info("routing on :%d over %s (canary=%g, hedge=%gms)",
                 router.bound_port, cfg.serve_targets, cfg.serve_canary,
                 cfg.serve_hedge_ms)
        try:
            router.await_termination()
        finally:
            router.stop()
        return
    if role == "serve" and cfg.serve_replicas > 0:
        # One-machine fleet (serving/fleet.py): DSGD_SERVE_REPLICAS
        # in-process replicas behind an in-process router on serve_port —
        # the kube deployment runs the same two roles as real pods.
        from distributed_sgd_tpu.serving.fleet import ServingFleet

        fleet = ServingFleet(
            cfg.checkpoint_dir, cfg.serve_replicas, model=cfg.model,
            lam=cfg.lam, router_port=cfg.serve_port,
            max_batch=cfg.serve_max_batch,
            max_delay_ms=cfg.serve_max_delay_ms,
            queue_depth=cfg.serve_queue_depth,
            ckpt_poll_s=cfg.serve_ckpt_poll_s,
            canary_fraction=cfg.serve_canary, probe=_load_probe(cfg),
            hedge_ms=cfg.serve_hedge_ms, health_s=cfg.serve_health_s,
            telemetry_port=cfg.telemetry_port if cfg.telemetry else None,
            metrics=metrics_mod.global_metrics(), seed=cfg.seed,
            state_path=cfg.serve_state,
            probe_path=cfg.serve_probe,
            probe_refresh_s=cfg.serve_probe_refresh_s,
        ).start()
        autoscaler = None
        if cfg.serve_slo_ms > 0:
            # DSGD_SERVE_SLO_MS: load-adaptive replica autoscale — the
            # router's EWMA-latency x in-flight signal against the p99
            # SLO, warm spin-up / drain with hysteresis + cooldown
            # (serving/ha.py ReplicaAutoscaler, docs/SERVING.md)
            from distributed_sgd_tpu.serving.ha import (
                ReplicaAutoscaler,
                router_load_ms,
            )

            autoscaler = ReplicaAutoscaler(
                signal_ms=lambda: router_load_ms(fleet.router),
                scale_up=fleet.add_replica, scale_down=fleet.drain_replica,
                count=lambda: len(fleet.replicas), slo_ms=cfg.serve_slo_ms,
                min_replicas=cfg.serve_replicas,
                max_replicas=cfg.serve_scale_max,
                cooldown_s=cfg.serve_scale_cooldown_s,
                metrics=metrics_mod.global_metrics()).start()
        log.info("serving fleet: router :%d over %d in-process replicas",
                 fleet.router_port, cfg.serve_replicas)
        try:
            fleet.await_termination()
        finally:
            if autoscaler is not None:
                autoscaler.stop()
            fleet.stop()
        return
    if role == "serve":
        # Online inference front end (serving/; DSGD_ROLE=serve): no
        # training data, no cluster membership — it loads weights from
        # cfg.checkpoint_dir and hot-reloads as the trainer saves new ones.
        from distributed_sgd_tpu.serving.server import ServingServer

        server = ServingServer.from_config(
            cfg, metrics=metrics_mod.global_metrics()).start()
        log.info(
            "serving model=%s on :%d (ckpt=%s, max_batch=%d, "
            "max_delay_ms=%g, queue_depth=%d)",
            cfg.model, server.bound_port, cfg.checkpoint_dir,
            cfg.serve_max_batch, cfg.serve_max_delay_ms,
            cfg.serve_queue_depth,
        )
        try:
            server.await_termination()
        finally:
            server.stop()
        return
    if role == "dev":
        if cfg.autopilot:
            # the full train/serve flywheel in one process — drift ->
            # retrain -> canary -> promote hands-free (docs/CONTINUAL.md)
            _run_dev_flywheel(cfg)
            return
        train, test, model = build(cfg)
        _select_scatter(cfg, train)
        distributor = _serve_distributor(cfg)
        try:
            if cfg.engine == "rpc":
                scenario_rpc(cfg, train, test, model)
            else:
                scenario_mesh(cfg, train, test, model)
        finally:
            if distributor is not None:
                distributor.stop()
    elif role == "master":
        from distributed_sgd_tpu.core.master import MasterNode

        _install_chaos(cfg)
        if cfg.autopilot:
            # stream plane: corpus = the newest stream window, eval
            # pinned to its trailing edge (docs/CONTINUAL.md)
            train, test, model = _autopilot_stream_build(cfg)
        else:
            train, test, model = build(cfg)
        _select_scatter(cfg, train)
        master = MasterNode(
            cfg.host, cfg.port, train, test, model,
            expected_workers=cfg.node_count, seed=cfg.seed,
        ).start(heartbeat_s=cfg.heartbeat_s,
                heartbeat_max_misses=cfg.heartbeat_max_misses)
        if cfg.telemetry:
            # cluster telemetry plane (telemetry/): scrape aggregator +
            # the ONE cluster-level /metrics endpoint
            master.enable_telemetry(cfg.telemetry_port)
        criterion = no_improvement(patience=cfg.patience, min_delta=cfg.conv_delta)
        if cfg.autopilot:
            from distributed_sgd_tpu.autopilot import continual_criterion

            # continual eval: convergence judged on the last few evals
            # only — a warm-started retrain must not be stopped by a
            # best earned on a distribution that no longer exists
            criterion = continual_criterion(
                criterion, horizon=2 * cfg.patience + 1)
        master.await_ready()
        ckpt = _make_checkpointer(cfg)
        distributor = _serve_distributor(cfg)
        try:
            if cfg.use_async:
                res = master.fit_async(
                    cfg.max_epochs, cfg.batch_size, cfg.learning_rate, criterion,
                    check_every=cfg.check_every, leaky_loss=cfg.leaky_loss,
                    initial_weights=_restore_weights(ckpt), checkpointer=ckpt,
                    optimizer=cfg.optimizer, momentum=cfg.momentum,
                    elastic=cfg.elastic, batch_drain=cfg.async_drain,
                )
            else:
                res = master.fit_sync(
                    cfg.max_epochs, cfg.batch_size, cfg.learning_rate, criterion,
                    checkpointer=ckpt, checkpoint_every=cfg.checkpoint_every,
                    optimizer=cfg.optimizer, momentum=cfg.momentum,
                    local_steps=cfg.local_steps,
                    delta_broadcast=cfg.delta_broadcast,
                    stream=cfg.stream,
                    fanin_lanes=cfg.fanin_lanes, stage_pool=cfg.stage_pool,
                    agg_tree=cfg.agg_tree,
                    master_shards=cfg.master_shards,
                    quorum=cfg.quorum, straggler_soft_s=cfg.straggler_soft_s,
                    health=_health_monitor(cfg, metrics=master.metrics),
                    **_fit_state_args(cfg),
                )
            _finish(cfg, res,
                    evaluator=lambda w: master.local_loss(w, test=True),
                    saved=ckpt is not None)
        finally:
            if distributor is not None:
                # stop() runs one final sweep, so the terminal checkpoint
                # the fit wrote still reaches the fleet — on EVERY exit
                # path, like the dev branch
                distributor.stop()
        master.stop()
    else:  # worker
        from distributed_sgd_tpu.core.worker import WorkerNode

        _install_chaos(cfg)
        host_devices = _resolve_host_devices(cfg)
        extra = {}
        if cfg.row_store:
            # mmap row store + optional host-local slice (spin-up fast
            # path): no parse, and with DSGD_HOST_INDEX no full-corpus
            # materialization either
            if cfg.host_index is not None and host_devices > 1:
                raise ValueError(
                    "DSGD_HOST_INDEX with a multi-device in-host mesh is "
                    "not supported (the mesh binds its slice at build "
                    "time); set DSGD_HOST_DEVICES=1")
            train, model, extra = _build_worker_row_store(cfg)
        else:
            train, _, model = build(cfg)
        _select_scatter(cfg, train)
        worker = WorkerNode(
            cfg.host, cfg.port, cfg.master_host, cfg.master_port, train, model,
            seed=cfg.seed, steps_per_dispatch=cfg.steps_per_dispatch,
            compress=cfg.compress, compress_k=cfg.compress_k,
            compress_ef=cfg.compress_ef,
            # DSGD_PROFILE_DIR on the worker role: device trace of the
            # first dispatches — where distributed time actually goes
            profile_dir=cfg.profile_dir,
            gossip_topology=cfg.gossip_topology,
            # elastic deployments survive a master restart: the watch
            # probes Master.Ping and re-enters the jittered registration
            # loop on sustained loss (docs/ELASTICITY.md)
            master_watch_s=(cfg.heartbeat_s or 5.0) if cfg.elastic else None,
            # cluster telemetry: publish the per-dispatch health gauges
            # the master's Metrics-RPC scrape re-exports per worker
            telemetry=cfg.telemetry,
            # hierarchical in-host mesh (docs/HIERARCHY.md): this worker
            # becomes a D-device host — batches shard over the local
            # devices, gradients reduce with one in-host psum, and the
            # master's split turns host-granular via Node.devices
            host_devices=host_devices,
            # host-local row-store slice (data_offset/row_reader/...)
            **extra,
        )
        # AOT warmup races registration, not traffic: the flagship shapes
        # compile (or disk-cache-hit) while the master is still
        # introducing this worker to the membership
        _warmup_worker(cfg, worker)
        worker.start()
        worker.await_termination()


if __name__ == "__main__":
    main()
