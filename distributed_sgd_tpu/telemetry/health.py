"""Training-health monitor (docs/OBSERVABILITY.md, ISSUE 7).

The aggregate metrics say how fast a run is going; this module says
whether it is DYING.  A :class:`HealthMonitor` instance rides one
``fit_sync`` (core/master.py) and watches the two signal classes that
precede a flat loss curve:

- **per-round signals** (``observe_round``): the fan-in gradient norm
  and the round's reply staleness, published as gauges so the cluster
  telemetry plane re-exports them per node.  A non-finite gradient norm
  is the NaN/Inf sentinel — it trips immediately, before the poisoned
  update can be applied.
- **loss trend** (``observe_loss``, once per epoch eval): an EWMA of the
  raw loss.  The watchdog trips when the EWMA exceeds
  ``divergence_ratio`` x its best-so-far value for ``patience``
  consecutive checks (after ``warmup`` observations — the first epochs
  legitimately move fast), or immediately on a non-finite loss.

On trip the monitor leaves evidence — a flight-recorder event + dump
(``flight-*-health.json``) and a trace instant event when a trace is
active — and latches: one dump per fit, no repeated I/O from a run that
keeps diverging.  What happens NEXT is ``action`` (``DSGD_HEALTH_ACTION``):

- ``warn`` (default): log loudly, keep training (pure observation);
- ``snapshot``: additionally write a resumable fit-state snapshot via
  PR 6's ``save_fit_state`` (the caller owns the path), keep training;
- ``halt``: snapshot, then stop the fit — a dying run ends with evidence
  and a resumable checkpoint instead of a flat loss curve.

The monitor itself never writes the snapshot (it has no access to the fit
loop's cursor/RNG state); ``fit_sync`` reads ``action``/``tripped`` and
does the snapshotting at the exact loop state the trip interrupted.
"""

from __future__ import annotations

import logging
import math
from typing import Optional

from distributed_sgd_tpu import trace as trace_mod
from distributed_sgd_tpu.trace import flight
from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.health")

ACTIONS = ("warn", "snapshot", "halt")


class HealthMonitor:
    def __init__(
        self,
        metrics: Optional[metrics_mod.Metrics] = None,
        action: str = "warn",
        alpha: float = 0.3,
        divergence_ratio: float = 2.0,
        warmup: int = 3,
        patience: int = 2,
    ):
        if action not in ACTIONS:
            raise ValueError(
                f"health action {action!r} must be one of {ACTIONS}")
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        if divergence_ratio <= 1.0:
            raise ValueError("divergence_ratio must be > 1")
        self.metrics = metrics or metrics_mod.global_metrics()
        self.action = action
        self.alpha = float(alpha)
        self.divergence_ratio = float(divergence_ratio)
        self.warmup = max(0, int(warmup))
        self.patience = max(1, int(patience))
        self._ewma: Optional[float] = None
        self._best = math.inf
        self._checks = 0
        self._over = 0
        self.tripped = False
        self.trip_reason: Optional[str] = None

    # -- per-round signals --------------------------------------------------

    def observe_round(self, grad_norm: float,
                      staleness_s: Optional[float] = None) -> bool:
        """Record one fan-in round's gauges; returns True for EVERY
        non-finite round (the caller must NOT apply the update).  The
        trip itself — evidence dump, counter, action — still latches to
        once per fit, but the sentinel verdict does not: a run that keeps
        producing NaN rounds under action='warn' must keep dropping them,
        not apply round two onward silently."""
        self.metrics.gauge(metrics_mod.HEALTH_GRAD_NORM).set(grad_norm)
        if staleness_s is not None:
            self.metrics.gauge(metrics_mod.HEALTH_STALENESS).set(staleness_s)
        if not math.isfinite(grad_norm):
            self._trip("non_finite_grad", grad_norm=str(grad_norm))
            return True
        return False

    # -- loss-trend watchdog ------------------------------------------------

    def observe_loss(self, loss: float) -> bool:
        """Record one loss evaluation; returns True when the watchdog
        trips (divergence or non-finite loss)."""
        if not math.isfinite(loss):
            return self._trip("non_finite_loss", loss=str(loss))
        ewma = (loss if self._ewma is None
                else self.alpha * loss + (1 - self.alpha) * self._ewma)
        self._ewma = ewma
        self._checks += 1
        self.metrics.gauge(metrics_mod.HEALTH_LOSS_EWMA).set(ewma)
        if self._checks <= self.warmup:
            self._best = min(self._best, ewma)
            return False
        if ewma > self.divergence_ratio * self._best:
            self._over += 1
            if self._over >= self.patience:
                return self._trip("loss_divergence", ewma=round(ewma, 6),
                                  best=round(self._best, 6),
                                  ratio=self.divergence_ratio)
        else:
            self._over = 0
            self._best = min(self._best, ewma)
        return False

    # -- trip ---------------------------------------------------------------

    def trip_external(self, reason: str, **info) -> bool:
        """Trip on an EXTERNAL verdict (the leak-slope sentinel,
        telemetry/slope.py): routes through the same latched evidence +
        DSGD_HEALTH_ACTION machinery as a loss divergence, so fit_sync's
        snapshot/halt handling covers resource leaks too."""
        return self._trip(reason, **info)

    def _trip(self, reason: str, **info) -> bool:
        if self.tripped:
            return False  # latched: one dump / one action per fit
        self.tripped = True
        self.trip_reason = reason
        self.metrics.counter(metrics_mod.HEALTH_TRIPPED).increment()
        log.error("training-health watchdog tripped: %s %s (action=%s)",
                  reason, info, self.action)
        # evidence first, policy second: the flight dump is what a
        # post-mortem reads even when the action is just 'warn'
        trace_mod.event(trace_mod.EVENT_HEALTH_TRIPPED, reason=reason, **info)
        flight.record("health.tripped", reason=reason,
                      action=self.action, **info)
        flight.dump("health")
        return True
