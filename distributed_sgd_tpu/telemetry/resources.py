"""Per-process resource probe: the long-horizon half of the telemetry
plane (docs/OBSERVABILITY.md "Resource plane & blackbox", ISSUE 20).

The aggregate metrics say how fast a run is going and the health monitor
says whether the MATH is dying — but nothing watched whether the PROCESS
is dying: RSS creeping a few MB a minute, file descriptors leaking one
per reconnect, the drain inbox or trace buffer slowly filling.  A fleet
serving millions of users dies from slopes, not spikes, and before this
module no process even sampled its own RSS on a cadence.

:class:`ResourceProbe` is a dependency-free daemon thread
(``DSGD_RESOURCE_PROBE_S`` sets the cadence; unset, nothing here ever
runs) that each tick:

- reads ``/proc/self/{statm,fd,status}`` into the ``proc.rss_bytes`` /
  ``proc.fds`` / ``proc.threads`` gauges (graceful no-op off-Linux: the
  gauges stay never-set NaN and off the wire — the probe must not crash
  a macOS dev box), plus ``proc.gc.gen2`` and a ``threading`` fallback
  for the thread count, which are platform-independent;
- samples the INTERNAL pressure gauges from the live structures whose
  slow fill precedes an hours-horizon death: the tracer's event buffer,
  the flight-recorder ring, the compile-cache dir, and any structure
  registered through :func:`register_pressure` (the master's async
  drain inbox, the serving batcher's admission queue);
- feeds the :class:`~distributed_sgd_tpu.telemetry.slope.LeakSentinel`
  (when attached) the rss/fd/thread series, and appends one snapshot to
  the :class:`~distributed_sgd_tpu.telemetry.blackbox.Blackbox` (when
  attached) so a crashed process leaves its last minutes on disk.

All gauges land on the process registry, so the existing cluster
telemetry plane (telemetry/aggregate.py) re-exports them per node with
the usual ``role``/``worker`` labels — the hours-horizon view merges
onto the same ``/metrics`` page as everything else for free.

Pressure sources hold only a weakref-compatible callable: a source that
raises or returns ``None`` is dropped from that tick (and a source whose
owner died unregisters itself by returning ``None``), so a forgotten
registration can never wedge the probe.
"""

from __future__ import annotations

import gc
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.resources")

try:  # one syscall at import; off-Linux (or restricted) fall back to 4K
    _PAGE = os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):  # pragma: no cover
    _PAGE = 4096


# -- pressure-source registry --------------------------------------------------
#
# name -> {token -> fn}: multiple structures may publish under one name
# (a fleet runs several batchers in-process); their depths SUM — "rows
# queued in this process" is the pressure signal, not any one queue.

_PRESSURE: Dict[str, Dict[int, Callable[[], Optional[float]]]] = {}
_PRESSURE_LOCK = threading.Lock()
_NEXT_TOKEN = [0]


def register_pressure(name: str, fn: Callable[[], Optional[float]]) -> int:
    """Register a depth callable under an instrument name; returns the
    token for :func:`unregister_pressure`.  Registration is always cheap
    and thread-free — the callable is only ever invoked by a running
    probe, so knobs-off runs pay nothing."""
    with _PRESSURE_LOCK:
        _NEXT_TOKEN[0] += 1
        token = _NEXT_TOKEN[0]
        _PRESSURE.setdefault(name, {})[token] = fn
        return token


def unregister_pressure(name: str, token: int) -> None:
    with _PRESSURE_LOCK:
        srcs = _PRESSURE.get(name)
        if srcs is not None:
            srcs.pop(token, None)
            if not srcs:
                _PRESSURE.pop(name, None)


def _sample_pressures() -> Dict[str, float]:
    """Sum every live registered source per name; a source that raises or
    returns None (dead owner) is dropped from this tick and removed."""
    with _PRESSURE_LOCK:
        items = [(name, dict(srcs)) for name, srcs in _PRESSURE.items()]
    out: Dict[str, float] = {}
    for name, srcs in items:
        total = None
        for token, fn in srcs.items():
            try:
                v = fn()
            except Exception:  # noqa: BLE001 - a broken source must not kill the probe
                v = None
            if v is None:
                unregister_pressure(name, token)
                continue
            total = (total or 0.0) + float(v)
        if total is not None:
            out[name] = total
    return out


# -- raw sampling --------------------------------------------------------------


def sample_resources() -> Dict[str, float]:
    """One dependency-free sample of the process: /proc-backed values
    (absent from the dict off-Linux), interpreter-level values, and the
    internal-pressure sums.  Shared by the probe tick, the flight-dump
    ``resources`` section (trace/flight.py), and the soak bench — one
    sampler, three consumers, no spelling drift."""
    out: Dict[str, float] = {}
    try:
        with open("/proc/self/statm") as f:
            # field 2 of statm is resident pages
            out[metrics_mod.PROC_RSS] = float(f.read().split()[1]) * _PAGE
    except (OSError, IndexError, ValueError):
        pass
    try:
        out[metrics_mod.PROC_FDS] = float(len(os.listdir("/proc/self/fd")))
    except OSError:
        pass
    threads = None
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("Threads:"):
                    threads = float(line.split()[1])
                    break
    except (OSError, IndexError, ValueError):
        pass
    if threads is None:  # off-Linux: the Python-level count still moves
        threads = float(threading.active_count())
    out[metrics_mod.PROC_THREADS] = threads
    try:
        out[metrics_mod.PROC_GC_GEN2] = float(gc.get_stats()[2]["collections"])
    except (IndexError, KeyError, AttributeError):  # pragma: no cover
        pass

    # internal pressure: structures the probe can reach without hooks...
    from distributed_sgd_tpu import trace as trace_mod

    tracer = trace_mod.active()
    if tracer is not None:
        out[metrics_mod.PROC_PRESSURE_TRACE_BUFFER] = float(tracer.buffered())
    from distributed_sgd_tpu.trace import flight

    out[metrics_mod.PROC_PRESSURE_FLIGHT_RING] = float(flight.get().ring_len())
    from distributed_sgd_tpu import compile_cache

    if compile_cache.enabled():
        try:
            out[metrics_mod.PROC_PRESSURE_COMPILE_CACHE] = float(
                compile_cache.cache_file_count())
        except OSError:  # pragma: no cover - dir vanished mid-listdir
            pass
    # ...and the registered ones (drain inbox, admission queues)
    out.update(_sample_pressures())
    return out


class ResourceProbe:
    """Daemon sampling loop: gauges + sentinel feed + blackbox append.

    ``plant`` is the planted-leak test hook: a callable merged into every
    sample (its keys override), so a test can drive a synthetic growing
    series through the EXACT production path — gauges, sentinel,
    blackbox — without waiting hours for a real leak.
    """

    # sentinel watch list: sample key -> short series name
    WATCHED = {
        metrics_mod.PROC_RSS: "rss",
        metrics_mod.PROC_FDS: "fds",
        metrics_mod.PROC_THREADS: "threads",
    }

    def __init__(self, metrics: Optional[metrics_mod.Metrics] = None,
                 interval_s: float = 10.0, sentinel=None, blackbox=None,
                 plant: Optional[Callable[[], Dict[str, float]]] = None):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0 (unset = no probe)")
        self.metrics = metrics or metrics_mod.global_metrics()
        self.interval_s = float(interval_s)
        self.sentinel = sentinel
        self.blackbox = blackbox
        self.plant = plant
        self.ticks = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="resource-probe")

    def tick(self) -> Dict[str, float]:
        """One sample -> gauges -> sentinel -> blackbox; public so tests
        (and the soak bench) can drive the probe deterministically."""
        sample = sample_resources()
        if self.plant is not None:
            try:
                sample.update(self.plant())
            except Exception:  # noqa: BLE001 - a test hook must not kill the loop
                pass
        for name, value in sample.items():
            self.metrics.gauge(name).set(value)
        now = time.monotonic()
        if self.sentinel is not None:
            for key, series in self.WATCHED.items():
                if key in sample:
                    self.sentinel.observe(series, now, sample[key])
            # planted series beyond the watch list reach the sentinel too
            for key in sample.keys() - self.WATCHED.keys():
                if key.startswith("plant."):
                    self.sentinel.observe(key, now, sample[key])
        if self.blackbox is not None:
            self.blackbox.append(self._snapshot(sample))
        self.ticks += 1
        return sample

    def _snapshot(self, sample: Dict[str, float]) -> Dict:
        """Blackbox record: resources + every counter (the round cursor —
        master.sync.rounds — rides along) + the set gauges."""
        counters = {c.name: c.value for c in self.metrics.counters()}
        gauges = {g.name: g.value for g in self.metrics.gauges()
                  if g.value == g.value}
        return {
            "resources": sample,
            "counters": counters,
            "gauges": gauges,
            "round": counters.get(metrics_mod.SYNC_ROUNDS, 0),
        }

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - the probe must outlive any one tick
                log.warning("resource probe tick failed: %s", e)

    def start(self) -> "ResourceProbe":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self.interval_s + 1.0)
        if self.blackbox is not None:
            self.blackbox.close()


# -- module-level wiring (main.py; the zero-cost gate) -------------------------

_PROBE: Optional[ResourceProbe] = None
_PROBE_LOCK = threading.Lock()


def configure(interval_s: float, metrics: Optional[metrics_mod.Metrics] = None,
              sentinel=None, blackbox=None) -> Optional[ResourceProbe]:
    """Install (interval_s > 0) or remove (<= 0) the process probe."""
    global _PROBE
    with _PROBE_LOCK:
        if _PROBE is not None:
            _PROBE.stop()
            _PROBE = None
        if interval_s <= 0:
            return None
        _PROBE = ResourceProbe(metrics=metrics, interval_s=interval_s,
                               sentinel=sentinel, blackbox=blackbox).start()
        return _PROBE


def active() -> Optional[ResourceProbe]:
    return _PROBE
