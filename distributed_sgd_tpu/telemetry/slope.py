"""Leak-slope sentinel: robust trend detection over the resource series
(docs/OBSERVABILITY.md "Resource plane & blackbox", ISSUE 20).

A leak is a SLOPE, and an hours-horizon slope is invisible to threshold
alerts: RSS that grows 2 MB/minute is fine for an hour and fatal
overnight, while a single GC spike that a naive least-squares fit would
chase is noise.  :class:`LeakSentinel` therefore runs a Theil–Sen
estimator — the median of all pairwise slopes, breakdown point ~29%,
immune to the isolated spikes that /proc sampling produces — over a
bounded per-series window, and only judges a series once two guards
pass:

- **minimum horizon** (``min_horizon_s``): a slope extrapolated from
  seconds of data is an extrapolation, not a measurement;
- **minimum samples** (``min_samples``): the median of a handful of
  pairs is itself noise.

The threshold is RELATIVE by default — slope/hour compared against the
series' own median level, so one rule covers RSS in bytes and fds in
single digits — with optional per-series ABSOLUTE units/s overrides
(``thresholds``), which the soak bench uses to pin its calibrated bars.

A trip LATCHES per series: "rss" tripping once must not re-dump the
flight recorder every tick, but must also never silence a later,
independent "fds" leak.  The trip path is the health-monitor pattern
(telemetry/health.py): counter + slope gauge + trace event + flight
record + flight dump, then — when a :class:`HealthMonitor` is attached —
``trip_external`` routes the verdict through the existing
``DSGD_HEALTH_ACTION`` warn/snapshot/halt machinery, so a leak can halt
a run exactly the way a loss blow-up can.
"""

from __future__ import annotations

import logging
import statistics
import threading
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.slope")


def theil_sen(ts, vs) -> float:
    """Median of all pairwise slopes (Theil–Sen).  O(n^2) pairs, but the
    sentinel windows are bounded (default 64 samples -> <= 2016 pairs
    per judged series per tick, microseconds of work).  NaN when fewer
    than two distinct timestamps."""
    slopes = []
    n = len(ts)
    for i in range(n):
        for j in range(i + 1, n):
            dt = ts[j] - ts[i]
            if dt > 0:
                slopes.append((vs[j] - vs[i]) / dt)
    if not slopes:
        return float("nan")
    return statistics.median(slopes)


class LeakSentinel:
    """Per-series windowed Theil–Sen watch with latched trips.

    ``thresholds`` maps series name -> absolute slope bar in units/s;
    series not listed fall back to the relative rule:
    ``slope * 3600 > rel_slope_per_hour * max(|median level|, rel_floor)``.
    """

    def __init__(self, metrics: Optional[metrics_mod.Metrics] = None,
                 window: int = 64, min_samples: int = 12,
                 min_horizon_s: float = 30.0,
                 rel_slope_per_hour: float = 0.10, rel_floor: float = 1.0,
                 thresholds: Optional[Dict[str, float]] = None):
        if window < 2:
            raise ValueError("window must be >= 2")
        if min_samples < 2:
            raise ValueError("min_samples must be >= 2")
        self.metrics = metrics or metrics_mod.global_metrics()
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.min_horizon_s = float(min_horizon_s)
        self.rel_slope_per_hour = float(rel_slope_per_hour)
        self.rel_floor = float(rel_floor)
        self.thresholds = dict(thresholds or {})
        self.tripped_series: set = set()
        self._series: Dict[str, Deque[Tuple[float, float]]] = {}
        self._lock = threading.Lock()
        self._monitor = None

    def attach_health(self, monitor) -> None:
        """Route future trips through a HealthMonitor's DSGD_HEALTH_ACTION
        machinery (telemetry/health.py) in addition to the local latch."""
        self._monitor = monitor

    # -- accessors ---------------------------------------------------------

    def slope(self, series: str) -> float:
        """Current Theil–Sen slope estimate in units/s (NaN if the window
        is still below the sample/horizon guards)."""
        with self._lock:
            win = self._series.get(series)
            if win is None or len(win) < self.min_samples:
                return float("nan")
            ts = [t for t, _ in win]
            vs = [v for _, v in win]
        if ts[-1] - ts[0] < self.min_horizon_s:
            return float("nan")
        return theil_sen(ts, vs)

    def tripped(self, series: Optional[str] = None) -> bool:
        if series is None:
            return bool(self.tripped_series)
        return series in self.tripped_series

    # -- the watch ---------------------------------------------------------

    def observe(self, series: str, t_s: float, value: float) -> bool:
        """Feed one sample; returns True when THIS observation trips the
        (previously untripped) series."""
        with self._lock:
            win = self._series.get(series)
            if win is None:
                win = self._series[series] = deque(maxlen=self.window)
            win.append((float(t_s), float(value)))
            if series in self.tripped_series:
                return False
            if len(win) < self.min_samples:
                return False
            ts = [t for t, _ in win]
            vs = [v for _, v in win]
        horizon = ts[-1] - ts[0]
        if horizon < self.min_horizon_s:
            return False
        slope = theil_sen(ts, vs)
        if slope != slope or slope <= 0:  # NaN or shrinking: no leak
            return False
        bar = self.thresholds.get(series)
        if bar is not None:
            leaking = slope > bar
        else:
            level = abs(statistics.median(vs))
            leaking = (slope * 3600.0
                       > self.rel_slope_per_hour * max(level, self.rel_floor))
        if not leaking:
            return False
        self._trip(series, slope, horizon, vs[-1])
        return True

    def _trip(self, series: str, slope: float, horizon: float,
              level: float) -> None:
        with self._lock:
            if series in self.tripped_series:  # lost the race: already latched
                return
            self.tripped_series.add(series)
        self.metrics.counter(metrics_mod.HEALTH_LEAK_SUSPECT).increment()
        # the slope gauge family carries the offending estimate onto the
        # /metrics page (health.leak.slope.<series>)
        self.metrics.gauge(
            f"{metrics_mod.HEALTH_LEAK_SLOPE}.{series}").set(slope)
        log.error("leak suspect: series=%s slope=%.6g/s over %.1fs "
                  "(level %.6g)", series, slope, horizon, level)
        from distributed_sgd_tpu import trace as trace_mod

        trace_mod.event(trace_mod.EVENT_LEAK_SUSPECT, series=series,
                        slope_per_s=slope, horizon_s=horizon, level=level)
        from distributed_sgd_tpu.trace import flight

        flight.record("leak.suspect", series=series, slope_per_s=slope,
                      horizon_s=horizon, level=level)
        flight.dump("leak")
        if self._monitor is not None:
            try:
                self._monitor.trip_external(
                    f"leak:{series}", slope_per_s=slope, horizon_s=horizon)
            except Exception:  # noqa: BLE001 - the sentinel must not die on a monitor bug
                log.exception("leak sentinel: health-monitor routing failed")
