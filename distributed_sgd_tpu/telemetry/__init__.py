"""Cluster telemetry plane (docs/OBSERVABILITY.md, ISSUE 7).

PR 5's tracing answers "show me round N end to end" and utils/metrics.py
gives every process its own exporter — but nobody sees the cluster as ONE
system, and the signals that predict a dying run (gradient norms, EF
residual growth, update staleness, loss divergence) were not measured at
all.  This package closes both gaps:

- ``aggregate``: the master scrapes every registered worker's full
  instrument registry over the new ``dsgd.Worker.Metrics`` RPC
  (heartbeat-piggybacked + on-demand at scrape time, breaker-consulting
  but never breaker-feeding) and re-exports the merged series on one
  cluster-level ``/metrics`` endpoint with ``worker``/``role`` labels —
  counters SUM, histogram buckets SUM exactly, gauges last-write per
  label.
- ``health``: the training-health monitor — per-round gradient-norm /
  staleness / EF-residual / drain-backlog gauges plus a loss-trend
  watchdog (EWMA divergence + NaN/Inf sentinel) that, on trip, leaves
  flight-recorder evidence, attaches a trace event, and (per
  ``DSGD_HEALTH_ACTION``) snapshots resumable fit state before
  optionally halting the fit.
- ``provision``: the generator for the committed Grafana dashboard and
  Prometheus alert rules under ``kube/observability/`` — dashboards and
  alerts are DERIVED from the instrument-name constants, and
  tests/test_observability.py fails the build when they drift.
- ``resources`` / ``slope`` / ``blackbox`` (ISSUE 20): the long-horizon
  resource plane — a per-process ``ResourceProbe`` sampling /proc +
  internal-pressure gauges at ``DSGD_RESOURCE_PROBE_S``, a
  ``LeakSentinel`` running Theil–Sen slope detection over those series,
  and a crash-surviving on-disk ``Blackbox`` snapshot ring under
  ``DSGD_BLACKBOX_DIR`` with a post-mortem CLI.

Everything is default-off: with ``DSGD_TELEMETRY`` unset no Metrics RPC
is ever issued and the wire stays byte-identical (tests/test_telemetry.py
asserts both).
"""

from distributed_sgd_tpu.telemetry.aggregate import (  # noqa: F401
    ClusterExporter,
    ClusterTelemetry,
    snapshot_metrics,
)
# NOTE: blackbox is deliberately NOT imported here — it is a `-m`-runnable
# post-mortem CLI, and a package-level import would put the submodule in
# sys.modules before runpy executes it (RuntimeWarning on every CLI use).
from distributed_sgd_tpu.telemetry.health import HealthMonitor  # noqa: F401
from distributed_sgd_tpu.telemetry.resources import (  # noqa: F401
    ResourceProbe,
    register_pressure,
    sample_resources,
    unregister_pressure,
)
from distributed_sgd_tpu.telemetry.slope import LeakSentinel  # noqa: F401
