"""Crash-surviving blackbox timeseries (docs/OBSERVABILITY.md "Resource
plane & blackbox", ISSUE 20).

The /metrics page dies with the process — exactly when the hours-horizon
question ("what was RSS doing for the last ten minutes?") matters most.
The blackbox is the flight recorder's timeseries sibling: the resource
probe appends one JSON snapshot per tick (resources + every counter +
the round cursor) to an on-disk ring under ``DSGD_BLACKBOX_DIR``, and a
post-mortem reads the dead process's last minutes with::

    python -m distributed_sgd_tpu.telemetry.blackbox summary <dir>

Crash-survival discipline, mirrored from trace/flight.py:

- every append is open → write one line → flush → close, so the newest
  complete snapshot is always on disk; a crash can lose at most the
  snapshot being written, and a torn final line is skipped by readers;
- rotation is ``os.replace`` of the live segment to a numbered one — a
  reader never observes a half-rotated file — and segments beyond the
  ring bound are unlinked oldest-first, so the footprint is bounded at
  roughly ``max_segments * max_segment_bytes`` per process forever;
- :meth:`append` never raises: a full disk degrades the blackbox, not
  the training run.

Files are ``bb-<service>-<pid>.jsonl`` (live) and
``bb-<service>-<pid>.<seq>.jsonl`` (rotated, seq ascending with age of
rotation — higher seq is NEWER).  The CLI merges every segment of every
process in the dir and orders records by wall time.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import re
import sys
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.blackbox")

_SEG_RE = re.compile(r"^bb-(?P<service>.+)-(?P<pid>\d+)"
                     r"(?:\.(?P<seq>\d+))?\.jsonl$")


class Blackbox:
    """Bounded on-disk ring of JSONL snapshot segments."""

    def __init__(self, dir: str, service: Optional[str] = None,
                 max_segment_bytes: int = 262144, max_segments: int = 4,
                 metrics: Optional[metrics_mod.Metrics] = None):
        if max_segment_bytes <= 0 or max_segments < 1:
            raise ValueError("blackbox ring bounds must be positive")
        self.dir = dir
        self.service = service or "proc"
        self.max_segment_bytes = int(max_segment_bytes)
        self.max_segments = int(max_segments)
        # same registry the probe snapshots from, so the write count
        # rides along inside each snapshot's counters section
        self.metrics = metrics or metrics_mod.global_metrics()
        self._seq = 0
        self._lock = threading.Lock()
        self._failed = False
        self._path = os.path.join(
            dir, f"bb-{self.service}-{os.getpid()}.jsonl")
        try:
            os.makedirs(dir, exist_ok=True)
        except OSError as e:
            log.warning("blackbox dir %s unusable: %s", dir, e)
            self._failed = True

    def append(self, snapshot: Dict) -> None:
        """Stamp and persist one snapshot.  Never raises — an unwritable
        blackbox logs once and goes quiet."""
        if self._failed:
            return
        rec = dict(snapshot)
        rec["t_wall"] = time.time()
        rec["t_mono"] = time.monotonic()
        # the ring's own write count rides along INSIDE each snapshot, so
        # a tail of a rotated-away ring still knows how much history the
        # process ever produced
        counter = self.metrics.counter(metrics_mod.BLACKBOX_SNAPSHOTS)
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError) as e:  # pragma: no cover
            log.warning("blackbox snapshot not serializable: %s", e)
            return
        with self._lock:
            try:
                with open(self._path, "a") as f:
                    f.write(line + "\n")
                    f.flush()
                counter.increment()
                if os.path.getsize(self._path) >= self.max_segment_bytes:
                    self._rotate()
            except OSError as e:
                log.warning("blackbox write failed (%s); disabling", e)
                self._failed = True

    def _rotate(self) -> None:
        """Atomically move the live segment into the numbered ring and
        unlink the oldest segment past the bound.  Caller holds _lock."""
        self._seq += 1
        base, ext = os.path.splitext(self._path)
        os.replace(self._path, f"{base}.{self._seq}{ext}")
        drop = self._seq - (self.max_segments - 1)
        if drop >= 1:
            try:
                os.unlink(f"{base}.{drop}{ext}")
            except OSError:  # pragma: no cover - already gone
                pass

    def close(self) -> None:  # symmetry with probe.stop(); nothing held open
        pass


# -- post-mortem readers (CLI) -------------------------------------------------


def _segments(dir: str) -> List[str]:
    """Every blackbox segment in dir, oldest-first per process (rotated
    seqs ascending, then the live segment)."""
    try:
        names = os.listdir(dir)
    except OSError:
        return []
    found: List[Tuple[str, int, float, str]] = []
    for name in names:
        m = _SEG_RE.match(name)
        if m is None:
            continue
        seq = int(m.group("seq")) if m.group("seq") else sys.maxsize
        found.append((m.group("service"), int(m.group("pid")), seq,
                      os.path.join(dir, name)))
    found.sort()
    return [path for _, _, _, path in found]


def read_records(dir: str) -> List[Dict]:
    """All parseable snapshots from every segment of every process,
    ordered by wall time.  Torn final lines (crash mid-write) skip."""
    records: List[Dict] = []
    for path in _segments(dir):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        continue  # torn write: the crash-survival contract
        except OSError:
            continue
    records.sort(key=lambda r: r.get("t_wall", 0.0))
    return records


def summarize(records: Iterable[Dict]) -> Dict:
    """Span, round cursor travel, and Theil–Sen slopes of the watched
    resource series — the one-screen post-mortem answer."""
    recs = list(records)
    if not recs:
        return {"snapshots": 0}
    out: Dict = {
        "snapshots": len(recs),
        "span_s": recs[-1].get("t_wall", 0.0) - recs[0].get("t_wall", 0.0),
        "first_round": recs[0].get("round", 0),
        "last_round": recs[-1].get("round", 0),
        "slopes_per_s": {},
        "last": recs[-1].get("resources", {}),
    }
    from distributed_sgd_tpu.telemetry import slope as slope_mod

    for key in (metrics_mod.PROC_RSS, metrics_mod.PROC_FDS,
                metrics_mod.PROC_THREADS):
        ts, vs = [], []
        for r in recs:
            v = r.get("resources", {}).get(key)
            if v is not None:
                ts.append(float(r.get("t_wall", 0.0)))
                vs.append(float(v))
        if len(ts) >= 2:
            s = slope_mod.theil_sen(ts, vs)
            if s == s:
                out["slopes_per_s"][key] = s
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m distributed_sgd_tpu.telemetry.blackbox",
        description="Read a dead process's blackbox ring.")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, help_ in (("tail", "print the newest N snapshots"),
                        ("merge", "print every snapshot, time-ordered"),
                        ("summary", "span, rounds, and leak slopes")):
        sp = sub.add_parser(name, help=help_)
        sp.add_argument("dir", help="DSGD_BLACKBOX_DIR to read")
        if name == "tail":
            sp.add_argument("-n", type=int, default=10,
                            help="snapshots to print (default 10)")
    args = p.parse_args(argv)
    records = read_records(args.dir)
    if args.cmd == "tail":
        for rec in records[-max(args.n, 0):]:
            print(json.dumps(rec))
    elif args.cmd == "merge":
        for rec in records:
            print(json.dumps(rec))
    else:
        print(json.dumps(summarize(records), indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess test
    sys.exit(main())
