"""Master-aggregated cluster metrics (docs/OBSERVABILITY.md).

``snapshot_metrics`` serializes one node's full instrument registry into
the ``MetricsSnapshot`` proto (served by the ``Metrics`` RPC on the
Worker and Serving services); ``ClusterTelemetry`` holds the latest
snapshot per worker on the master and renders ONE cluster-level
Prometheus exposition; ``ClusterExporter`` is the HTTP endpoint, which
refreshes the scrape on demand so a Prometheus pull always sees data no
older than its own period.

Merge semantics (tested in tests/test_telemetry.py):

- **counters SUM** across nodes into a ``role="cluster"`` series.
  Snapshots are cumulative and REPLACE the previous snapshot per worker,
  so scraping twice equals scraping once — a faster scrape cadence can
  never inflate a counter.
- **histogram buckets SUM**: bucket counts index the fixed shared bounds
  (utils/metrics.py ``Histogram.BUCKET_BOUNDS``), so cross-worker sums
  are exact, and the cluster ``<name>_hist_bucket`` family supports
  server-side ``histogram_quantile``.  Reservoir quantiles deliberately
  do NOT cross the wire: subsampled quantiles do not merge; buckets do.
- **gauges last-write per label**: a gauge is an instantaneous per-node
  value (gradient norm, staleness) — it appears per ``worker`` label and
  is never aggregated.

Scrapes consult the per-peer circuit breakers READ-ONLY (a tripped
training peer is not scraped — one line of degradation instead of a
blocking failed call) and never FEED them: a flaky metrics reply must
not open the breaker the training RPCs depend on.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import grpc

from distributed_sgd_tpu.rpc import dsgd_pb2 as pb
from distributed_sgd_tpu.utils import metrics as metrics_mod
from distributed_sgd_tpu.utils.metrics import (
    Histogram,
    Metrics,
    PrometheusExporter,
    _prom_escape,
    prom_name as _mangle,
)


def snapshot_metrics(metrics: Metrics, role: str, node: str) -> "pb.MetricsSnapshot":
    """Serialize one registry into the wire snapshot (cheap: one pass over
    the instrument lists, no locks held across the encode)."""
    snap = pb.MetricsSnapshot(role=role, node=node)
    for c in metrics.counters():
        snap.counters.add(name=c.name, value=c.value)
    for g in metrics.gauges():
        if g.value == g.value:  # never-set gauges (NaN) stay off the wire
            snap.gauges.add(name=g.name, value=g.value)
    for h in metrics.histograms():
        if not h.count:
            continue
        hm = snap.hists.add(name=h.name, count=h.count, sum=h.sum,
                            min=h.min, max=h.max, last=h.last)
        hm.buckets.extend(h.bucket_counts())
    return snap


def _labels(snap) -> str:
    return (f'role="{_prom_escape(snap.role)}",'
            f'worker="{_prom_escape(snap.node)}"')


def cluster_prometheus_text(snaps: List["pb.MetricsSnapshot"]) -> str:
    """Render the merged cluster exposition from per-node snapshots.

    Per family: one ``# TYPE`` line, the per-node samples (labeled
    ``role``/``worker``), then — for counters and histograms — the
    cluster aggregate labeled ``role="cluster"``.  Histogram ``le``
    buckets are emitted at the CLUSTER level only (exact sums over the
    shared bounds); per node the cheap scalars (_count/_sum/_min/_max/
    _last) carry the node-local view.  Deterministic ordering: families
    sorted by name, samples by node label.
    """
    snaps = sorted(snaps, key=lambda s: (s.role, s.node))
    lines: List[str] = []

    gauges: Dict[str, List[Tuple[str, float]]] = {}
    counters: Dict[str, List[Tuple[str, int]]] = {}
    hists: Dict[str, List[Tuple[str, "pb.MetricHistogram"]]] = {}
    for s in snaps:
        lab = _labels(s)
        for g in s.gauges:
            gauges.setdefault(g.name, []).append((lab, g.value))
        for c in s.counters:
            counters.setdefault(c.name, []).append((lab, c.value))
        for h in s.hists:
            hists.setdefault(h.name, []).append((lab, h))

    for name in sorted(gauges):
        base = _mangle(name)
        lines.append(f"# TYPE {base} gauge")
        for lab, v in gauges[name]:
            lines.append(f"{base}{{{lab}}} {v}")

    for name in sorted(counters):
        base = _mangle(name)
        lines.append(f"# TYPE {base}_total counter")
        for lab, v in counters[name]:
            lines.append(f"{base}_total{{{lab}}} {v}")
        total = sum(v for _, v in counters[name])
        lines.append(f'{base}_total{{role="cluster"}} {total}')

    n_bounds = len(Histogram.BUCKET_BOUNDS)
    for name in sorted(hists):
        base = _mangle(name)
        per_node = hists[name]
        for lab, h in per_node:
            lines.append(f"{base}_count{{{lab}}} {h.count}")
            lines.append(f"{base}_sum{{{lab}}} {h.sum}")
            lines.append(f"{base}_min{{{lab}}} {h.min}")
            lines.append(f"{base}_max{{{lab}}} {h.max}")
            lines.append(f"{base}_last{{{lab}}} {h.last}")
        # cluster merge: counts/sums SUM, min/max fold, buckets SUM exactly
        count = sum(h.count for _, h in per_node)
        total = sum(h.sum for _, h in per_node)
        lo = min(h.min for _, h in per_node)
        hi = max(h.max for _, h in per_node)
        merged = [0] * n_bounds
        for _, h in per_node:
            for i, b in enumerate(h.buckets[:n_bounds]):
                merged[i] += b
        lines.append(f'{base}_count{{role="cluster"}} {count}')
        lines.append(f'{base}_sum{{role="cluster"}} {total}')
        lines.append(f'{base}_min{{role="cluster"}} {lo}')
        lines.append(f'{base}_max{{role="cluster"}} {hi}')
        lines.append(f"# TYPE {base}_hist histogram")
        cum = 0
        for le, n in zip(Histogram.BUCKET_BOUNDS, merged):
            cum += n
            lines.append(
                f'{base}_hist_bucket{{role="cluster",le="{le:.9g}"}} {cum}')
        lines.append(f'{base}_hist_bucket{{role="cluster",le="+Inf"}} {count}')
        lines.append(f'{base}_hist_sum{{role="cluster"}} {total}')
        lines.append(f'{base}_hist_count{{role="cluster"}} {count}')
    return "\n".join(lines) + "\n"


class ClusterTelemetry:
    """Latest-snapshot-per-worker store + scrape fan-out on the master.

    ``scrape(members, rpc_policy)`` issues concurrent ``Metrics`` futures
    to every member whose breaker is not suppressing calls, waits at most
    one RPC deadline, and replaces each worker's stored snapshot with the
    reply.  Failures degrade: they are counted under
    ``master.telemetry.scrape.errors`` and the dead worker's LAST
    snapshot stays visible until membership drops it
    (``unregister_worker`` -> :meth:`drop`).  ``min_age_s`` throttles
    concurrent refresh triggers (heartbeat piggyback + endpoint pulls).
    """

    def __init__(self, metrics: Metrics, node: str = "master",
                 role: str = "master"):
        self.metrics = metrics  # the master's own registry (also scraped-in)
        self.node = node
        self.role = role
        self._snaps: Dict[Tuple[str, int], "pb.MetricsSnapshot"] = {}
        self._lock = threading.Lock()
        self._last_scrape = -float("inf")

    def observe(self, key, snap: "pb.MetricsSnapshot") -> None:
        """Replace `key`'s snapshot (counters are cumulative: replacement —
        not accumulation — is what makes repeated scrapes idempotent)."""
        with self._lock:
            self._snaps[key] = snap
            self.metrics.gauge(metrics_mod.TELEMETRY_WORKERS).set(
                len(self._snaps))

    def drop(self, key) -> None:
        """Membership removed the worker: its series leave the exposition."""
        with self._lock:
            self._snaps.pop(key, None)
            self.metrics.gauge(metrics_mod.TELEMETRY_WORKERS).set(
                len(self._snaps))

    def scrape(self, members, rpc_policy, deadline_s: Optional[float] = None,
               min_age_s: float = 0.0) -> int:
        """One scrape fan-out over [(key, stub)]; returns snapshots merged.
        Never raises and never blocks past the RPC deadline — a dead or
        wedged worker costs one deadline shared across the concurrent
        futures, not one per worker."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_scrape < min_age_s:
                return 0
            self._last_scrape = now
        deadline = deadline_s if deadline_s is not None else rpc_policy.deadline_s
        errors = self.metrics.counter(metrics_mod.TELEMETRY_SCRAPE_ERRORS)
        futs = []
        for key, stub in members:
            # read-only breaker consult (CircuitBreaker.suppressed): skip a
            # tripped peer without consuming its half-open probe slot, and
            # never report scrape outcomes back — the breaker belongs to
            # the training RPCs
            if rpc_policy.breaker(key).suppressed():
                self.metrics.counter(
                    metrics_mod.TELEMETRY_SCRAPE_SKIPPED).increment()
                continue
            try:
                futs.append((key, stub.Metrics.future(pb.Empty(),
                                                      timeout=deadline)))
            except (ValueError, AttributeError):  # channel closed under us
                errors.increment()
        got = 0
        for key, fut in futs:
            try:
                self.observe(key, fut.result())
                got += 1
            except grpc.RpcError:
                # includes UNIMPLEMENTED from an older worker: degraded,
                # never fatal, never fed to the breaker
                errors.increment()
        self.metrics.counter(metrics_mod.TELEMETRY_SCRAPES).increment()
        return got

    def prometheus_text(self) -> str:
        """The cluster exposition: every stored worker snapshot plus a
        fresh snapshot of the master's own registry."""
        with self._lock:
            snaps = list(self._snaps.values())
        snaps.append(snapshot_metrics(self.metrics, self.role, self.node))
        return cluster_prometheus_text(snaps)


class ClusterExporter(PrometheusExporter):
    """HTTP endpoint for the cluster exposition (one per master): the
    shared PrometheusExporter plumbing (routing, headers, threading) with
    a custom `render` and a `refresh` hook — each GET first runs the
    master's throttled scrape, so a Prometheus pull is never staler than
    the scrape throttle even when the heartbeat (the other scrape
    trigger) is off."""

    def __init__(self, render: Callable[[], str], port: int,
                 host: str = "0.0.0.0",
                 refresh: Optional[Callable[[], None]] = None):
        super().__init__(None, port, host=host, render=render,
                         refresh=refresh)
