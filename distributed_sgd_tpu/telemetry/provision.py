"""Provisioned dashboards + alerts, generated from the instrument names
(docs/OBSERVABILITY.md, ISSUE 7).

The reference ships a hand-written Grafana JSON (kube/grafana); hand-
written dashboards drift from the code the moment an instrument is
renamed.  Here the cluster dashboard (``kube/observability/
grafana-dashboard-cluster.json``) and the Prometheus alert rules
(``kube/observability/prometheus-alerts.yaml``) are GENERATED from the
constants in utils/metrics.py, and tests/test_observability.py asserts
(a) the committed files match a fresh generation byte-for-byte, (b) every
instrument either file references is recorded somewhere in the package,
and (c) the curated core set below IS referenced — so dashboards, alerts,
and code cannot drift apart in any direction.

Regenerate after changing panels/rules or renaming an instrument:

    python -m distributed_sgd_tpu.telemetry.provision
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from distributed_sgd_tpu.utils import metrics as mm
from distributed_sgd_tpu.utils.metrics import prom_name as _prom

OUT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "kube", "observability")
DASHBOARD_FILE = "grafana-dashboard-cluster.json"
ALERTS_FILE = "prometheus-alerts.yaml"


# _prom is utils/metrics.prom_name — the ONE mangling rule shared with
# both expositions, so the artifacts cannot drift from the exporters.

# -- the single source both artifacts draw from -------------------------------
#
# (instrument, kind): kind picks the family suffix the cluster exposition
# emits — counters gain `_total`, gauges are bare, histograms are read
# through their `_sum`/`_count` scalars (telemetry/aggregate.py).
_C, _G, _H = "counter", "gauge", "histogram"

REFERENCED_INSTRUMENTS: Dict[str, str] = {
    mm.SYNC_ROUNDS: _C,
    mm.SYNC_BCAST_BYTES: _C,
    mm.SYNC_GRAD_BYTES: _C,
    mm.QUORUM_DEGRADED: _C,
    mm.QUORUM_HEDGES: _C,
    mm.QUORUM_HEDGE_WINS: _C,
    mm.SYNC_STALLED: _C,
    mm.BREAKER_OPEN: _C,
    mm.TELEMETRY_SCRAPES: _C,
    mm.TELEMETRY_SCRAPE_ERRORS: _C,
    mm.TELEMETRY_SCRAPE_SKIPPED: _C,
    mm.TELEMETRY_WORKERS: _G,
    mm.HEALTH_GRAD_NORM: _G,
    mm.HEALTH_STALENESS: _G,
    mm.HEALTH_EF_RESIDUAL_NORM: _G,
    mm.HEALTH_DRAIN_BACKLOG: _G,
    mm.HEALTH_LOSS_EWMA: _G,
    mm.HEALTH_TRIPPED: _C,
    # long-horizon resource plane (telemetry/resources.py, ISSUE 20)
    mm.PROC_RSS: _G,
    mm.PROC_FDS: _G,
    mm.PROC_THREADS: _G,
    mm.HEALTH_LEAK_SUSPECT: _C,
    "master.sync.loss": _H,
    "master.sync.batch.duration": _H,
}

# The curated core set the consistency gate enforces in BOTH directions:
# these must exist in code AND appear in the dashboard/alert artifacts.
CORE_INSTRUMENTS = (
    mm.SYNC_ROUNDS,
    mm.HEALTH_GRAD_NORM,
    mm.HEALTH_STALENESS,
    mm.HEALTH_LOSS_EWMA,
    mm.HEALTH_TRIPPED,
    mm.QUORUM_DEGRADED,
    mm.TELEMETRY_SCRAPE_ERRORS,
    mm.BREAKER_OPEN,
    mm.PROC_RSS,
    mm.HEALTH_LEAK_SUSPECT,
)


def _panel(pid: int, title: str, targets: List[Tuple[str, str]],
           x: int, y: int) -> dict:
    return {
        "id": pid,
        "type": "timeseries",
        "title": title,
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "targets": [{"expr": expr, "legendFormat": legend}
                    for expr, legend in targets],
    }


def dashboard() -> dict:
    """The cluster dashboard: every expr is built through _prom() from a
    REFERENCED_INSTRUMENTS key, so a renamed instrument fails the
    consistency gate instead of silently blanking a panel."""
    rounds = _prom(mm.SYNC_ROUNDS, "_total")
    loss_sum = _prom("master.sync.loss", "_sum")
    loss_cnt = _prom("master.sync.loss", "_count")
    dur_sum = _prom("master.sync.batch.duration", "_sum")
    dur_cnt = _prom("master.sync.batch.duration", "_count")
    panels = [
        _panel(1, "training rounds / s (cluster)", [
            (f'rate({rounds}{{role="cluster"}}[1m])', "rounds/s"),
        ], 0, 0),
        _panel(2, "loss (per-epoch mean + health EWMA)", [
            (f'rate({loss_sum}{{role="cluster"}}[5m]) / '
             f'rate({loss_cnt}{{role="cluster"}}[5m])', "epoch loss (5m)"),
            (_prom(mm.HEALTH_LOSS_EWMA), "health EWMA {{worker}}"),
        ], 12, 0),
        _panel(3, "gradient norm per worker", [
            (f'{_prom(mm.HEALTH_GRAD_NORM)}{{role="worker"}}', "{{worker}}"),
        ], 0, 8),
        _panel(4, "reply staleness per worker (s)", [
            (f'{_prom(mm.HEALTH_STALENESS)}{{role="worker"}}', "{{worker}}"),
        ], 12, 8),
        _panel(5, "EF residual norm per worker", [
            (f'{_prom(mm.HEALTH_EF_RESIDUAL_NORM)}{{role="worker"}}',
             "{{worker}}"),
        ], 0, 16),
        _panel(6, "quorum pressure (cluster)", [
            (f'rate({_prom(mm.QUORUM_DEGRADED, "_total")}{{role="cluster"}}[1m])',
             "degraded rounds/s"),
            (f'rate({_prom(mm.QUORUM_HEDGES, "_total")}{{role="cluster"}}[1m])',
             "hedges/s"),
            (f'rate({_prom(mm.QUORUM_HEDGE_WINS, "_total")}{{role="cluster"}}[1m])',
             "hedge wins/s"),
            (f'rate({_prom(mm.SYNC_STALLED, "_total")}{{role="cluster"}}[1m])',
             "stalled barriers/s"),
        ], 12, 16),
        _panel(7, "wire bytes / s (cluster)", [
            (f'rate({_prom(mm.SYNC_BCAST_BYTES, "_total")}{{role="cluster"}}[1m])',
             "broadcast B/s"),
            (f'rate({_prom(mm.SYNC_GRAD_BYTES, "_total")}{{role="cluster"}}[1m])',
             "fan-in B/s"),
        ], 0, 24),
        _panel(8, "round duration (s, cluster mean)", [
            (f'rate({dur_sum}{{role="cluster"}}[1m]) / '
             f'rate({dur_cnt}{{role="cluster"}}[1m])', "batch duration (1m)"),
        ], 12, 24),
        _panel(9, "telemetry plane health", [
            (f'rate({_prom(mm.TELEMETRY_SCRAPES, "_total")}[5m])', "scrapes/s"),
            (f'rate({_prom(mm.TELEMETRY_SCRAPE_ERRORS, "_total")}[5m])',
             "scrape errors/s"),
            (f'rate({_prom(mm.TELEMETRY_SCRAPE_SKIPPED, "_total")}[5m])',
             "breaker-skipped/s"),
            (_prom(mm.TELEMETRY_WORKERS), "workers scraped"),
        ], 0, 32),
        _panel(10, "failure signals", [
            (f'increase({_prom(mm.HEALTH_TRIPPED, "_total")}[10m])',
             "health trips (10m)"),
            (f'increase({_prom(mm.BREAKER_OPEN, "_total")}[10m])',
             "breaker opens (10m)"),
            (_prom(mm.HEALTH_DRAIN_BACKLOG), "drain backlog"),
        ], 12, 32),
        _panel(11, "process resources per node (rss / fds / threads)", [
            (_prom(mm.PROC_RSS), "rss {{role}} {{worker}}"),
            (_prom(mm.PROC_FDS), "fds {{role}} {{worker}}"),
            (_prom(mm.PROC_THREADS), "threads {{role}} {{worker}}"),
        ], 0, 40),
        _panel(12, "leak suspects (slope sentinel trips)", [
            (f'increase({_prom(mm.HEALTH_LEAK_SUSPECT, "_total")}[10m])',
             "leak trips (10m) {{role}} {{worker}}"),
        ], 12, 40),
    ]
    return {
        "uid": "dsgd-cluster",
        "title": "distributed-sgd cluster telemetry",
        "timezone": "browser",
        "refresh": "5s",
        "time": {"from": "now-15m", "to": "now"},
        "schemaVersion": 39,
        "panels": panels,
    }


def alert_rules() -> str:
    """Prometheus rule file (YAML text, no yaml dependency): every metric
    identifier comes through _prom(), same drift discipline as the
    dashboard."""
    rules = [
        ("DsgdHealthWatchdogTripped", "critical", "2m",
         f'increase({_prom(mm.HEALTH_TRIPPED, "_total")}[10m]) > 0',
         "the training-health watchdog tripped (loss divergence or "
         "NaN/Inf): read the flight-*-health.json dump and the fit-state "
         "snapshot before restarting"),
        ("DsgdTrainingRoundsFlat", "critical", "5m",
         f'rate({_prom(mm.SYNC_ROUNDS, "_total")}{{role="cluster"}}[5m]) == 0',
         "no sync rounds completed for 5m while the master is up — a "
         "stalled barrier or a dead fan-out"),
        ("DsgdTelemetryScrapeFailing", "warning", "5m",
         f'rate({_prom(mm.TELEMETRY_SCRAPE_ERRORS, "_total")}[5m]) > 0.5',
         "worker metric scrapes are failing: the cluster view is partial "
         "(dead worker, version skew, or network trouble)"),
        ("DsgdBreakerOpen", "warning", "1m",
         f'increase({_prom(mm.BREAKER_OPEN, "_total")}[5m]) > 0',
         "a per-peer circuit breaker opened: one or more RPC edges are "
         "failing repeatedly"),
        ("DsgdQuorumDegradedSustained", "warning", "10m",
         f'rate({_prom(mm.QUORUM_DEGRADED, "_total")}{{role="cluster"}}[5m]) > 0.5',
         "most rounds are closing below full strength: a persistent "
         "straggler is being hedged around — check its node"),
        ("DsgdSyncBarrierStalled", "warning", "5m",
         f'rate({_prom(mm.SYNC_STALLED, "_total")}{{role="cluster"}}[5m]) > 0.2',
         "soft-deadline overruns without quorum relief: the cluster is "
         "slower than its straggler budget"),
        ("DsgdDrainBacklogSaturated", "warning", "2m",
         f'{_prom(mm.HEALTH_DRAIN_BACKLOG)} > 900',
         "the async drain inbox is near its 1024 cap: arrivals outrun "
         "the drain thread and deltas will fall back to per-message "
         "apply"),
        ("DsgdLeakSuspect", "warning", "1m",
         f'increase({_prom(mm.HEALTH_LEAK_SUSPECT, "_total")}[10m]) > 0',
         "the leak-slope sentinel tripped on a process resource series "
         "(rss/fds/threads): read the flight-*-leak.json dump and the "
         "blackbox ring (python -m distributed_sgd_tpu.telemetry.blackbox "
         "summary $DSGD_BLACKBOX_DIR) before the process dies of it"),
        ("DsgdEfResidualGrowing", "warning", "10m",
         f'{_prom(mm.HEALTH_EF_RESIDUAL_NORM)} > 10 * '
         f'{_prom(mm.HEALTH_GRAD_NORM)}',
         "a worker's error-feedback residual dwarfs its gradient: "
         "compression is starving coordinates — lower DSGD_COMPRESS_K "
         "pressure or disable EF"),
    ]
    lines = [
        "# GENERATED by `python -m distributed_sgd_tpu.telemetry.provision`",
        "# from the instrument-name constants in utils/metrics.py — edit",
        "# the generator, not this file (tests/test_observability.py",
        "# fails the build when they drift).",
        "groups:",
        "  - name: dsgd-cluster-telemetry",
        "    rules:",
    ]
    for name, severity, for_, expr, summary in rules:
        lines += [
            f"      - alert: {name}",
            f"        expr: {expr}",
            f"        for: {for_}",
            "        labels:",
            f"          severity: {severity}",
            "        annotations:",
            f"          summary: >-",
            f"            {summary}",
        ]
    return "\n".join(lines) + "\n"


def render_dashboard() -> str:
    return json.dumps(dashboard(), indent=2, sort_keys=True) + "\n"


def main(out_dir: str = OUT_DIR) -> None:
    os.makedirs(out_dir, exist_ok=True)
    dash_path = os.path.join(out_dir, DASHBOARD_FILE)
    with open(dash_path, "w") as f:
        f.write(render_dashboard())
    alerts_path = os.path.join(out_dir, ALERTS_FILE)
    with open(alerts_path, "w") as f:
        f.write(alert_rules())
    print(f"wrote {dash_path}\nwrote {alerts_path}")


if __name__ == "__main__":
    main()
