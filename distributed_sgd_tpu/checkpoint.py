"""Checkpoint / resume via orbax.

A strict capability superset of the reference, which persists nothing —
its only recovery mechanism is the async master's in-memory best-weights
tracking (MasterAsync.scala:66-69,130-139; SURVEY.md §5.4).  Wiring
(`Config.checkpoint_dir`, built in main.py):

- SyncTrainer saves weights (plus optimizer state and the newest-first
  test-loss history) every `checkpoint_every` epochs and resumes from the
  latest snapshot, continuing the same batch-sampling stream, momentum
  buffers, and early-stopping window;
- the async drivers (Hogwild gossip, local-SGD, gRPC MasterNode.fit_async)
  hand their Checkpointer to LossChecker, which persists the best-so-far
  weights + full smoothing history on every improvement and every
  `save_every`-th plateau check — so the reference's "return best"
  behavior survives a process kill — and main.py feeds the latest snapshot
  back as `initial_weights` on restart.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - baked into the image, but stay safe
    _HAVE_ORBAX = False

log = logging.getLogger("dsgd.checkpoint")


class Checkpointer:
    """Epoch-cadence training-state checkpointing."""

    def __init__(self, directory: str, keep: int = 3):
        if not _HAVE_ORBAX:
            raise RuntimeError("orbax is not available")
        import os
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )

    def save(self, step: int, weights, extra: Optional[Dict[str, Any]] = None) -> bool:
        state = {"weights": np.asarray(weights)}
        if extra:
            state.update({k: np.asarray(v) for k, v in extra.items()})
        saved = self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()
        if saved:
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
        else:  # orbax declines e.g. writes to an already-existing step
            log.warning("checkpoint at step %d NOT saved (step exists?)", step)
        return bool(saved)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        step = self._mgr.latest_step()
        if step is None:
            return None
        state = self._mgr.restore(step)
        state["weights"] = jnp.asarray(state["weights"])
        return step, state

    def close(self) -> None:
        self._mgr.close()
