"""Checkpoint / resume via orbax.

A strict capability superset of the reference, which persists nothing —
its only recovery mechanism is the async master's in-memory best-weights
tracking (MasterAsync.scala:66-69,130-139; SURVEY.md §5.4).  Wiring
(`Config.checkpoint_dir`, built in main.py):

- SyncTrainer saves weights (plus optimizer state and the newest-first
  test-loss history) every `checkpoint_every` epochs and resumes from the
  latest snapshot, continuing the same batch-sampling stream, momentum
  buffers, and early-stopping window;
- the async drivers (Hogwild gossip, local-SGD, gRPC MasterNode.fit_async)
  hand their Checkpointer to LossChecker, which persists the best-so-far
  weights + full smoothing history on every improvement and every
  `save_every`-th plateau check — so the reference's "return best"
  behavior survives a process kill — and main.py feeds the latest snapshot
  back as `initial_weights` on restart.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # pragma: no cover - baked into the image, but stay safe
    _HAVE_ORBAX = False

log = logging.getLogger("dsgd.checkpoint")


class Checkpointer:
    """Epoch-cadence training-state checkpointing."""

    def __init__(self, directory: str, keep: int = 3):
        if not _HAVE_ORBAX:
            raise RuntimeError("orbax is not available")
        import os
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )

    def save(self, step: int, weights, extra: Optional[Dict[str, Any]] = None) -> bool:
        from distributed_sgd_tpu.utils.measure import span

        with span("ckpt.save", step=step):
            state = {"weights": np.asarray(weights)}
            if extra:
                state.update({k: np.asarray(v) for k, v in extra.items()})
            saved = self._mgr.save(step, args=ocp.args.StandardSave(state))
            self._mgr.wait_until_finished()
        if saved:
            log.info("checkpoint saved at step %d -> %s", step, self.directory)
        else:  # orbax declines e.g. writes to an already-existing step
            log.warning("checkpoint at step %d NOT saved (step exists?)", step)
        return bool(saved)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def reload(self) -> None:
        """Re-read the step list from disk.

        orbax's CheckpointManager caches the directory listing at
        construction and after its own saves; a process that only READS a
        directory another process writes (the serving hot-reload poll,
        serving/model_store.py) must drop that cache to observe new steps.
        """
        self._mgr.reload()

    def poll_newer(self, than: Optional[int]) -> Optional[Tuple[int, Dict[str, Any]]]:
        """One reader-side poll step: drop the cached directory listing,
        and restore the latest snapshot iff its step is newer than `than`
        (None = anything counts as newer).  Returns None when nothing
        newer exists — or when the newest snapshot was deleted between
        listing and restore (restore_latest re-lists).  The shared dance
        of every directory WATCHER: the serving hot-reload poll
        (serving/model_store.py) and the fleet checkpoint distributor
        (serving/push.py CheckpointDistributor)."""
        self.reload()
        step = self.latest_step()
        if step is None or (than is not None and step <= than):
            return None
        return self.restore_latest()

    def restore_latest(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        from distributed_sgd_tpu.utils.measure import span

        step = self._mgr.latest_step()
        if step is None:
            return None
        # explicit StandardRestore args: arg-less restore() only works on a
        # manager that already SAVED this process (saving registers the item
        # handler as a side effect) — a restore-only process (resume at
        # startup, the serving hot-reload poll) needs the args spelled out
        with span("ckpt.restore", step=step):
            state = self._mgr.restore(step, args=ocp.args.StandardRestore())
        state["weights"] = jnp.asarray(state["weights"])
        return step, state

    def close(self) -> None:
        self._mgr.close()


# -- shared sync-fit snapshot contract ------------------------------------
#
# Both sync engines (mesh SyncTrainer, core/trainer.py; RPC
# MasterNode.fit_sync, core/master.py) persist the same state keys —
# weights, newest-first test-loss history, optimizer kind tag, flat
# optimizer-state leaves — so their checkpoints are interchangeable.  The
# contract lives here, once.


def opt_kind_tag(optimizer) -> str:
    """Checkpoint tag for structural resume validation: string-configured
    optimizers validate by name; arbitrary optax transformations all tag
    'custom' (their identity is not recoverable from a string)."""
    if isinstance(optimizer, str):
        return optimizer
    return "sgd" if optimizer is None else "custom"


def sync_fit_extra(
    test_losses_newest_first, opt_kind: str, opt_leaves
) -> Dict[str, Any]:
    """Build the `extra` dict saved alongside the weights."""
    extra: Dict[str, Any] = {}
    if test_losses_newest_first:
        extra["test_losses_nf"] = np.asarray(test_losses_newest_first, np.float32)
    extra["opt_kind"] = np.frombuffer(opt_kind.encode(), dtype=np.uint8)
    for i, leaf in enumerate(opt_leaves):
        extra[f"opt_{i}"] = np.asarray(leaf)
    return extra


def decode_sync_fit_state(state: Dict[str, Any], opt_kind: str, expected_leaves):
    """Decode + validate a sync-fit snapshot against the configured optimizer.

    Returns (test_losses_newest_first, opt_leaves).  Refuses a snapshot
    written under a different optimizer kind, leaf count, or leaf shape
    (e.g. a kernel-layout change) rather than silently resuming with
    zeroed or misassembled optimizer state.
    """
    test_nf = (
        [float(x) for x in np.asarray(state["test_losses_nf"])]
        if "test_losses_nf" in state else []
    )
    saved_kind = (
        bytes(np.asarray(state["opt_kind"], np.uint8)).decode()
        if "opt_kind" in state else "sgd"
    )
    if saved_kind != opt_kind:
        raise ValueError(
            f"checkpoint was written with optimizer {saved_kind!r} but this "
            f"run is configured with {opt_kind!r}; resume with the original "
            f"optimizer or point at a fresh checkpoint_dir"
        )
    opt_leaves = []
    while f"opt_{len(opt_leaves)}" in state:
        opt_leaves.append(state[f"opt_{len(opt_leaves)}"])
    expected = list(expected_leaves)
    shapes_ok = len(opt_leaves) == len(expected) and all(
        np.shape(g) == np.shape(e) for g, e in zip(opt_leaves, expected)
    )
    if not shapes_ok:
        raise ValueError(
            f"checkpointed optimizer-state leaves "
            f"{[np.shape(x) for x in opt_leaves]} do not match the configured "
            f"optimizer/kernel layout {[np.shape(x) for x in expected]}; "
            f"resume with the original optimizer and kernel, or use a fresh "
            f"checkpoint_dir"
        )
    return test_nf, opt_leaves


# -- the sync-fit snapshot PROTOCOL, single-sourced --------------------------
# Three fit loops speak it (mesh SyncTrainer, RPC fit_sync, the 2-D
# FeatureShardedEngine), and their checkpoints interchange BECAUSE they all
# go through these helpers: weights + newest-first test-loss history (the
# early-stopping window) + optimizer kind/leaves, saved every
# `checkpoint_every` epochs plus once at any off-cadence end.


def restore_sync_fit(checkpointer, opt_kind: str, expected_leaves):
    """Restore the latest sync-fit snapshot, validated against the
    configured optimizer.  Returns (start_epoch, weights_np,
    test_losses_newest_first, opt_leaves), or None when there is no
    checkpointer or no snapshot."""
    if checkpointer is None:
        return None
    restored = checkpointer.restore_latest()
    if restored is None:
        return None
    start_epoch, state = restored
    test_nf, opt_leaves = decode_sync_fit_state(state, opt_kind, expected_leaves)
    return start_epoch, np.asarray(state["weights"]), test_nf, opt_leaves


def save_sync_fit(checkpointer, epoch: int, weights, test_losses_newest_first,
                  opt_kind: str = "sgd", opt_leaves=()) -> None:
    checkpointer.save(epoch, weights, extra=sync_fit_extra(
        test_losses_newest_first, opt_kind, list(opt_leaves)))


# -- crash-safe FULL fit state (docs/ELASTICITY.md; DSGD_FIT_CKPT_EVERY) -----
#
# The epoch-cadence snapshots above capture weights + optimizer state at
# epoch boundaries; a master killed MID-epoch replays the whole epoch on
# restart.  The fit-state snapshot captures everything the fit_sync loop
# needs to resume BIT-EXACTLY from the last completed window: weights,
# optimizer leaves, the epoch + window cursor, the np.random.Generator
# bit-generator state (so the resumed run replays the identical sample
# draws), the early-stopping history, the broadcast version, and the
# fit_token lineage (every token that has driven this fit — a restarted
# master issues a NEW token from its per-incarnation nonce, so long-lived
# workers reset stale per-fit state, and the lineage records the chain).
# Written ATOMICALLY (tmp + os.replace): a crash mid-write leaves the
# previous snapshot intact, never a torn file.

FIT_STATE_FILE = "fit_state.npz"


def fit_state_path(directory: str) -> str:
    """Canonical fit-state snapshot location under a checkpoint dir."""
    return os.path.join(directory, FIT_STATE_FILE)


@dataclasses.dataclass
class FitState:
    """Decoded crash-recovery snapshot of one fit_sync loop."""

    epoch: int
    batch: int                    # window cursor within `epoch`
    weights: np.ndarray
    rng_state: Dict[str, Any]     # np.random.Generator.bit_generator.state
    test_losses_nf: List[float]   # newest-first early-stopping history
    opt_leaves: List[np.ndarray]
    bcast_version: int
    fit_tokens: List[int]         # lineage: tokens that have driven this fit
    # terminal marker: the CONVERGENCE CRITERION ended this fit at
    # epoch < max_epochs — a restart must take the nothing-to-run path
    # even though the epoch cursor says budget remains (resuming a
    # converged fit would train PAST convergence).  Budget exhaustion is
    # deliberately NOT marked: the epoch cursor already carries it, and
    # an unmarked terminal snapshot lets a re-run with a raised
    # max_epochs resume training
    finished: bool = False


def save_fit_state(
    path: str,
    *,
    weights,
    epoch: int,
    batch: int,
    rng_state: Dict[str, Any],
    test_losses_nf,
    opt_kind: str,
    opt_leaves,
    bcast_version: int = 0,
    fit_tokens=(),
    finished: bool = False,
) -> None:
    """Atomic full-fit-state snapshot (see the section comment above)."""
    from distributed_sgd_tpu.utils.measure import span

    with span("ckpt.save", step=int(epoch), batch=int(batch)):
        state: Dict[str, Any] = {
            "weights": np.asarray(weights, np.float32),
            "epoch": np.int64(epoch),
            "batch": np.int64(batch),
            "rng_state": np.frombuffer(
                json.dumps(rng_state).encode(), dtype=np.uint8),
            "opt_kind": np.frombuffer(opt_kind.encode(), dtype=np.uint8),
            "bcast_version": np.int64(bcast_version),
            "fit_tokens": np.asarray(list(fit_tokens), dtype=np.int64),
            "finished": np.int64(1 if finished else 0),
        }
        if test_losses_nf:
            state["test_losses_nf"] = np.asarray(test_losses_nf, np.float32)
        for i, leaf in enumerate(opt_leaves):
            state[f"opt_{i}"] = np.asarray(leaf)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **state)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic on POSIX: old snapshot or new, never torn


def restore_fit_state(path: Optional[str], opt_kind: str,
                      expected_leaves) -> Optional[FitState]:
    """Load + validate a fit-state snapshot; None when absent.  Optimizer
    kind/leaf validation reuses decode_sync_fit_state, so a snapshot from
    a differently-configured fit refuses loudly instead of resuming with
    misassembled state."""
    if not path or not os.path.exists(path):
        return None
    from distributed_sgd_tpu.utils.measure import span

    with span("ckpt.restore", step=-1):
        with np.load(path) as z:
            state = {k: z[k] for k in z.files}
    test_nf, opt_leaves = decode_sync_fit_state(state, opt_kind, expected_leaves)
    return FitState(
        epoch=int(state["epoch"]),
        batch=int(state["batch"]),
        weights=np.asarray(state["weights"], np.float32),
        rng_state=json.loads(bytes(np.asarray(state["rng_state"],
                                              np.uint8)).decode()),
        test_losses_nf=test_nf,
        opt_leaves=opt_leaves,
        bcast_version=int(state.get("bcast_version", 0)),
        fit_tokens=[int(t) for t in state.get("fit_tokens", [])],
        finished=bool(int(state.get("finished", 0))),
    )


def save_sync_fit_final(checkpointer, epochs_run: int, start_epoch: int,
                        checkpoint_every: int, weights,
                        test_losses_newest_first, opt_kind: str = "sgd",
                        opt_leaves=()) -> None:
    """Off-cadence end (early stop, or max_epochs not a multiple of
    `checkpoint_every`): persist the final state so no run with a
    checkpointer ends unsaved.

    `weights` may be a zero-arg callable, resolved only when the save
    actually happens — so a caller whose weight materialization is
    expensive (the feature-sharded engine's device->host gather) pays
    nothing on the no-save path."""
    if (
        checkpointer is not None
        and epochs_run > start_epoch
        and epochs_run % checkpoint_every != 0
    ):
        if callable(weights):
            weights = weights()
        save_sync_fit(checkpointer, epochs_run, weights,
                      test_losses_newest_first, opt_kind, opt_leaves)
