"""Online drifting stream plane (docs/CONTINUAL.md).

:class:`DriftingStream` generalizes ``data/synthetic.rcv1_like`` from a
fixed corpus into an unbounded, seeded stream whose ROW AXIS IS THE TIME
AXIS: row ``r`` is drawn from the distribution at stream-time ``r``, and
the planted separator drifts along it under a named shift schedule —

- ``step``:      w jumps from w0 toward w1 at ``shift_at`` (concept
                 shift, the flywheel bench's injected fault);
- ``ramp``:      w slides linearly over ``ramp_rows`` starting at
                 ``shift_at`` (slow drift — the regime where a
                 persistence window matters);
- ``recurring``: w alternates every ``period_rows`` (seasonality — a
                 promoted model goes stale on a clock).

Rows are generated in fixed ``BLOCK``-row chunks, each from its own
counter-derived RNG (``default_rng((seed, block))`` — the master's
``(seed, epoch)`` idiom), so any row range is RANDOM-ACCESS
deterministic: two readers at different cursors, or a reader restarted
mid-stream, see byte-identical rows.  Feature statistics (Zipf
popularity, frozen IDF weights) are stationary; only the labeling
concept moves.  That separation is deliberate — the canary probe loss
measures the CONCEPT gap, not a vocabulary artifact.

Training consumes the stream as a sliding window instead of a fixed
epoch partition: :func:`window_split` restricts the existing
``SplitFn`` contract to ``[lo, hi)``, so a warm-start retrain is just
``fit_sync(split=window_split(...), initial_weights=...)`` over the rows
the current distribution produced.  Continual eval rides the existing
early-stopping machinery: :func:`continual_criterion` truncates the
newest-first loss history to an eval horizon so "converged" is judged
against the CURRENT distribution, and ``DriftingStream.eval_set`` draws
a held-out set (a disjoint block lane) pinned to the distribution at a
chosen stream-time for ``master.test`` re-anchoring.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from distributed_sgd_tpu.core.early_stopping import Criterion
from distributed_sgd_tpu.data.rcv1 import Dataset

SCHEDULES = ("step", "ramp", "recurring")

BLOCK = 256  # row-generation granularity: the random-access unit
# eval sets draw from a disjoint block lane so held-out rows can never
# collide with training rows at any cursor
_EVAL_LANE = 1 << 30


class DriftingStream:
    def __init__(
        self,
        n_features: int = 16384,
        nnz: int = 8,
        noise: float = 0.05,
        seed: int = 0,
        schedule: str = "step",
        shift_at: int = 4096,
        shift_magnitude: float = 1.0,
        ramp_rows: int = 4096,
        period_rows: int = 8192,
        idf_rows: int = 2048,
    ):
        if schedule not in SCHEDULES:
            raise ValueError(
                f"shift schedule {schedule!r} must be one of {SCHEDULES}")
        if not 0.0 <= shift_magnitude <= 1.0:
            raise ValueError("shift_magnitude must be in [0, 1]")
        if ramp_rows < 1 or period_rows < 1:
            raise ValueError("ramp_rows and period_rows must be >= 1")
        self.n_features = int(n_features)
        self.nnz = int(nnz)
        self.noise = float(noise)
        self.seed = int(seed)
        self.schedule = schedule
        self.shift_at = int(shift_at)
        self.shift_magnitude = float(shift_magnitude)
        self.ramp_rows = int(ramp_rows)
        self.period_rows = int(period_rows)
        self.cursor = 0

        # stationary feature statistics: Zipf popularity like term
        # frequencies (matches rcv1_like) and IDF weights frozen from a
        # one-time reference draw, so value magnitudes cannot drift and
        # masquerade as concept shift
        pop = 1.0 / np.arange(1, self.n_features + 1, dtype=np.float64)
        self._pop = pop / pop.sum()
        rng = np.random.default_rng((self.seed, _EVAL_LANE - 1))
        ref = rng.choice(self.n_features, size=(int(idf_rows), self.nnz),
                         p=self._pop).astype(np.int32)
        ref.sort(axis=1)
        dup = np.zeros_like(ref, dtype=bool)
        dup[:, 1:] = ref[:, 1:] == ref[:, :-1]
        df = np.bincount(ref[~dup], minlength=self.n_features)
        self._idf = np.log(
            int(idf_rows) / np.maximum(df, 1.0)).astype(np.float32)
        # the two endpoint separators: w0 is the pre-shift concept, the
        # drifted concept is the (magnitude-scaled) blend toward w1
        self._w0 = rng.normal(size=self.n_features).astype(np.float32)
        self._w1 = rng.normal(size=self.n_features).astype(np.float32)

    # -- schedule -----------------------------------------------------------

    def phase(self, row: int) -> float:
        """Shift phase in [0, 1] at stream-time `row` (0 = pre-shift
        concept, 1 = fully shifted)."""
        if self.schedule == "step":
            return 1.0 if row >= self.shift_at else 0.0
        if self.schedule == "ramp":
            return float(np.clip((row - self.shift_at) / self.ramp_rows,
                                 0.0, 1.0))
        return float((row // self.period_rows) % 2)  # recurring

    def separator(self, row: int) -> np.ndarray:
        """The planted separator in force at stream-time `row` (the blend
        whose sign labels that row, before noise)."""
        a = self.phase(row) * self.shift_magnitude
        return ((1.0 - a) * self._w0 + a * self._w1).astype(np.float32)

    # -- generation ---------------------------------------------------------

    def _gen_block(self, block: int, phases: np.ndarray):
        """One BLOCK-row chunk from its counter-derived RNG; `phases` is
        the per-row shift phase (len BLOCK)."""
        rng = np.random.default_rng((self.seed, block))
        idx = rng.choice(self.n_features, size=(BLOCK, self.nnz),
                         p=self._pop).astype(np.int32)
        idx.sort(axis=1)
        val = np.abs(rng.normal(size=(BLOCK, self.nnz))).astype(np.float32)
        dup = np.zeros_like(idx, dtype=bool)
        dup[:, 1:] = idx[:, 1:] == idx[:, :-1]
        val *= self._idf[idx]
        val[dup] = 0.0
        val /= np.maximum(np.linalg.norm(val, axis=1, keepdims=True), 1e-12)
        # blended margin per row: sign is invariant to normalizing the
        # blended separator, so labels are exactly the blend's labels
        m0 = np.einsum("np,np->n", val, self._w0[idx])
        m1 = np.einsum("np,np->n", val, self._w1[idx])
        a = phases.astype(np.float64) * self.shift_magnitude
        margins = (1.0 - a) * m0 + a * m1
        # threshold at 0 (not the batch median): E[margin] = 0 under the
        # symmetric planted draw, and a per-batch median would couple a
        # row's label to which batch read it — breaking random access
        y = np.where(margins > 0.0, 1, -1).astype(np.int32)
        flip = rng.random(BLOCK) < self.noise
        y[flip] = -y[flip]
        return idx, val, y

    def rows(self, start: int, n: int) -> Dataset:
        """Rows [start, start+n) — deterministic regardless of call
        history or chunking."""
        if n <= 0:
            raise ValueError("n must be positive")
        lo_b, hi_b = start // BLOCK, (start + n - 1) // BLOCK + 1
        parts = []
        for b in range(lo_b, hi_b):
            t0 = b * BLOCK
            phases = np.array([self.phase(t0 + i) for i in range(BLOCK)])
            parts.append(self._gen_block(b, phases))
        idx = np.concatenate([p[0] for p in parts])
        val = np.concatenate([p[1] for p in parts])
        y = np.concatenate([p[2] for p in parts])
        off = start - lo_b * BLOCK
        return Dataset(indices=idx[off:off + n], values=val[off:off + n],
                       labels=y[off:off + n], n_features=self.n_features)

    def take(self, n: int) -> Dataset:
        """Next `n` rows at the cursor; advances stream-time."""
        out = self.rows(self.cursor, n)
        self.cursor += n
        return out

    def eval_set(self, n: int, at: Optional[int] = None) -> Dataset:
        """Held-out eval rows pinned to the distribution at stream-time
        `at` (default: the cursor).  Drawn from a disjoint block lane —
        never overlaps training rows — and does not advance the cursor."""
        at = self.cursor if at is None else int(at)
        phase = np.full(BLOCK, self.phase(at))
        n_blocks = (n - 1) // BLOCK + 1
        # lane blocks keyed by (eval draw position, pinned time) so two
        # eval sets at different times share no rows either
        base = _EVAL_LANE + (at // BLOCK) * 4096
        parts = [self._gen_block(base + b, phase) for b in range(n_blocks)]
        idx = np.concatenate([p[0] for p in parts])[:n]
        val = np.concatenate([p[1] for p in parts])[:n]
        y = np.concatenate([p[2] for p in parts])[:n]
        return Dataset(indices=idx, values=val, labels=y,
                       n_features=self.n_features)

    def oracle_labeler(
        self, start: int = 0,
    ) -> Callable[[np.ndarray, np.ndarray], Optional[float]]:
        """The ground-truth join for :class:`~distributed_sgd_tpu.autopilot
        .probe_source.ProbeReservoir`: labels the t-th row it is asked
        about with the sign of the planted separator IN FORCE at
        stream-time ``start + t`` — truth as the world holds it when the
        delayed label finally arrives, which is exactly what a click/log
        join would return.  Noise-free (the join returns truth, not the
        stream's noisy training label), and order-robust: the counter
        only selects the phase, which moves on a thousands-of-rows
        clock, so modest request reordering under concurrent clients
        cannot mislabel."""
        lock = threading.Lock()
        clock = [int(start)]

        def labeler(indices: np.ndarray,
                    values: np.ndarray) -> Optional[float]:
            with lock:
                t = clock[0]
                clock[0] += 1
            w = self.separator(t)
            margin = float(np.dot(np.asarray(values, np.float64),
                                  w[np.asarray(indices, np.int64)]))
            return 1.0 if margin > 0.0 else -1.0

        return labeler


# -- training over a stream window -----------------------------------------


def window_split(lo: int, hi: int):
    """A ``SplitFn`` that trains only rows [lo, hi) of the resident
    corpus: the sliding-window view of an unbounded stream.  The window
    is vanilla-split (contiguous, reference semantics) across workers;
    rows outside it simply never enter a dispatch — the fixed-partition
    contract (ids index the resident corpus) is unchanged, which is what
    lets PR 11's incremental re-sharding slide the resident slice along
    with the window."""
    if not 0 <= lo < hi:
        raise ValueError(f"bad stream window [{lo}, {hi})")

    def split(n_samples: int, n_workers: int) -> List[np.ndarray]:
        from distributed_sgd_tpu.core.split import vanilla_split

        hi_eff = min(hi, n_samples)
        if hi_eff <= lo:
            raise ValueError(
                f"stream window [{lo}, {hi}) is past the resident corpus "
                f"({n_samples} rows)")
        return [p + lo for p in vanilla_split(hi_eff - lo, n_workers)]

    return split


def continual_criterion(inner: Criterion, horizon: int) -> Criterion:
    """Early stopping judged on the CURRENT distribution only: truncate
    the newest-first loss history to the last `horizon` evals before
    applying `inner` (core/early_stopping.py).  Without this, a
    no-improvement scan keeps comparing against minima earned on a
    distribution that no longer exists — a retrain after a shift would
    stop instantly because the pre-shift best looks unbeatable."""
    if horizon < 1:
        raise ValueError("horizon must be >= 1")

    def criterion(losses: Sequence[float]) -> bool:
        return inner(list(losses)[:horizon])

    return criterion
