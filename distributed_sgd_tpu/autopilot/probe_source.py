"""Live canary-probe sourcing (docs/CONTINUAL.md).

The router's canary gate is only as honest as its probe set, and an
operator-rotated ``.npz`` (the PR 13 cadence) goes stale the moment the
traffic drifts.  :class:`ProbeReservoir` replaces the file: the router
feeds every live ``Predict`` request through :meth:`observe`, and the
reservoir keeps a BOUNDED sample of the traffic — classic Algorithm R
(uniform over history, or uniform over a trailing ``recency`` horizon
so the sample tracks a drifting stream), with every replace decision
drawn from a
COUNTER-DERIVED RNG (``default_rng((seed, t))``), so the sample is a
pure function of (seed, arrival order).  A router restart that restores
the counters from the ``DSGD_SERVE_STATE`` sidecar resumes the exact
sampling sequence — no RNG state blob to persist, no post-restart
divergence (asserted in tests/test_probe_source.py).

Ground truth is NOT on the Predict wire (``PredictRequest`` carries
features only), and in production it would not exist yet at request
time.  The label-delay model makes that explicit: an observed row sits
in a pending lane for ``label_delay`` further requests — the stand-in
for the hours a click/log join takes — and only then is the ``labeler``
(the ground-truth join: a stream oracle in the benches, a feedback log
in production) asked for its label.  Rows whose truth never arrives
(labeler returns None) are dropped, never guessed.  Consequence worth
stating: the probe set always trails live traffic by the label delay,
so a drift detector reading probe loss fires at least that late — the
caveat documented in docs/CONTINUAL.md.

``rows()`` emits the router's probe-row format (``(indices, values,
label)`` triples), so a reservoir snapshot drops straight into the
existing ``ServingRouter.refresh_probe`` -> ``LossChecker.refresh``
re-anchor path: rejected versions stay rejected, the baseline re-anchors
on the sampled set.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, List, Optional, Tuple

import numpy as np

# a row as the router's probe path consumes it
ProbeRow = Tuple[np.ndarray, np.ndarray, float]
Labeler = Callable[[np.ndarray, np.ndarray], Optional[float]]


class ProbeReservoir:
    def __init__(
        self,
        labeler: Labeler,
        capacity: int = 64,
        seed: int = 0,
        label_delay: int = 0,
        min_fill: Optional[int] = None,
        recency: Optional[int] = None,
    ):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if label_delay < 0:
            raise ValueError("label_delay must be >= 0")
        if recency is not None and recency < capacity:
            raise ValueError("recency must be >= capacity")
        self.labeler = labeler
        self.capacity = int(capacity)
        self.seed = int(seed)
        self.label_delay = int(label_delay)
        self.recency = None if recency is None else int(recency)
        self.min_fill = int(min_fill) if min_fill is not None else int(capacity)
        if not 1 <= self.min_fill <= self.capacity:
            raise ValueError("min_fill must be in [1, capacity]")
        self._lock = threading.Lock()
        self._rows: List[ProbeRow] = []
        # rows awaiting ground truth: (arrival ordinal, indices, values);
        # bounded by construction — every observe drains all aged entries,
        # so at most label_delay + 1 are ever pending
        self._pending: deque = deque()
        self._seen = 0     # requests observed (pending-lane clock)
        self._labeled = 0  # labeled rows admitted to the Algorithm-R draw

    # -- the hot path -------------------------------------------------------

    def observe(self, indices: np.ndarray, values: np.ndarray) -> None:
        """Feed one live request.  Called from the router's Predict
        handler (concurrent); one short critical section, no labeler
        call unless a pending row just aged past the label delay."""
        idx = np.asarray(indices, dtype=np.int32).copy()
        val = np.asarray(values, dtype=np.float32).copy()
        with self._lock:
            self._seen += 1
            self._pending.append((self._seen, idx, val))
            aged = []
            while self._pending and self._pending[0][0] <= self._seen - self.label_delay:
                aged.append(self._pending.popleft())
        for _, a_idx, a_val in aged:
            y = self.labeler(a_idx, a_val)
            if y is None:
                continue  # truth never arrived: drop, never guess
            self._admit(a_idx, a_val, float(y))

    def _admit(self, idx: np.ndarray, val: np.ndarray, y: float) -> None:
        with self._lock:
            self._labeled += 1
            t = self._labeled
            if len(self._rows) < self.capacity:
                self._rows.append((idx, val, y))
                return
            # Algorithm R, decision t: keep with probability capacity/t —
            # or capacity/recency once t passes the recency horizon, the
            # biased-reservoir variant that lets old rows decay
            # geometrically so the sample TRACKS the traffic instead of
            # averaging over all history (a uniform-over-history sample
            # would dilute a distribution shift forever).  Counter-derived
            # draw — a function of (seed, t) alone — so a restart that
            # restores `labeled` resumes the same sequence.
            horizon = t if self.recency is None else min(t, self.recency)
            j = int(np.random.default_rng((self.seed, t)).integers(0, horizon))
            if j < self.capacity:
                self._rows[j] = (idx, val, y)

    # -- the probe-refresh side --------------------------------------------

    def ready(self) -> bool:
        with self._lock:
            return len(self._rows) >= self.min_fill

    @property
    def fill(self) -> int:
        with self._lock:
            return len(self._rows)

    @property
    def seen(self) -> int:
        with self._lock:
            return self._seen

    def rows(self) -> List[ProbeRow]:
        """Snapshot of the sampled probe set, router probe-row format."""
        with self._lock:
            return list(self._rows)

    # -- DSGD_SERVE_STATE persistence --------------------------------------

    def state_dict(self) -> dict:
        """JSON-serializable state for the router's sidecar: counters +
        rows + pending lane.  Bounded by construction (capacity +
        label_delay rows), so the sidecar stays small."""
        with self._lock:
            return {
                "seen": self._seen,
                "labeled": self._labeled,
                "rows": [[r[0].tolist(), r[1].tolist(), r[2]]
                         for r in self._rows],
                "pending": [[t, i.tolist(), v.tolist()]
                            for t, i, v in self._pending],
            }

    def load_state(self, state: dict) -> None:
        with self._lock:
            self._seen = int(state["seen"])
            self._labeled = int(state["labeled"])
            self._rows = [
                (np.asarray(i, np.int32), np.asarray(v, np.float32), float(y))
                for i, v, y in state["rows"]]
            self._pending = deque(
                (int(t), np.asarray(i, np.int32), np.asarray(v, np.float32))
                for t, i, v in state["pending"])
