"""Continual-learning autopilot (docs/CONTINUAL.md).

The train/serve flywheel as a subsystem: an online drifting stream plane
(:mod:`stream`), live canary-probe sourcing from serving traffic
(:mod:`probe_source`), and the controller state machine that closes the
loop — drift detected at the serving edge triggers a warm-start retrain
whose checkpoint flows through the existing ``CheckpointDistributor`` →
canary → promote path with zero operator actions (:mod:`controller`).

Default-off behind ``DSGD_AUTOPILOT``: with the knob unset nothing here
is imported on any hot path, no thread starts, and no instrument
registers (asserted in tests/test_flywheel.py).
"""

from distributed_sgd_tpu.autopilot.controller import (  # noqa: F401
    AutopilotController,
    DriftDetector,
    STATES,
)
from distributed_sgd_tpu.autopilot.flywheel import Flywheel  # noqa: F401
from distributed_sgd_tpu.autopilot.probe_source import ProbeReservoir  # noqa: F401
from distributed_sgd_tpu.autopilot.stream import (  # noqa: F401
    DriftingStream,
    SCHEDULES,
    continual_criterion,
    window_split,
)
