"""One-process flywheel assembly (docs/CONTINUAL.md).

:class:`Flywheel` wires the whole continual-learning loop into a single
process, the way ``bench_serve`` wires the serving SLO loop: a loopback
DevCluster TRAINS on a window of a :class:`~distributed_sgd_tpu.autopilot
.stream.DriftingStream`, a ServingFleet SERVES the checkpoints behind
its router, the router reservoir-samples its own Predict traffic into
the canary probe set, and an :class:`~distributed_sgd_tpu.autopilot
.controller.AutopilotController` watches the resulting probe-loss
series and drives retrain -> canary -> promote with zero operator
actions.

Two integrators share it:

- ``DSGD_ROLE=dev DSGD_AUTOPILOT=1`` (main.py) runs :meth:`run` — one
  complete shift -> detect -> retrain -> promote cycle as an
  env-driven demo;
- ``benches/bench_flywheel.py`` drives :meth:`pump` itself and asserts
  recovery, zero drops, and the leak slope.

The retrain half is the part worth reading: :meth:`retrain` slides the
training window to the NEWEST ``window_rows`` rows the traffic pump
has served (``window_split``), re-pins ``master.test`` to an eval set
drawn at the window's trailing edge (continual eval: "converged" means
converged on the current distribution), warm-starts from the latest
checkpoint (PR 11's fast path), and checkpoints every epoch so the
CheckpointDistributor streams each round into the fleet's canary gate.
The controller — not this class — decides WHEN it runs and reads the
verdict from the router's own promote/rollback counters.
"""

from __future__ import annotations

import logging
import tempfile
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from distributed_sgd_tpu.autopilot.controller import (
    AutopilotController,
    DriftDetector,
)
from distributed_sgd_tpu.autopilot.probe_source import ProbeReservoir
from distributed_sgd_tpu.autopilot.stream import (
    DriftingStream,
    continual_criterion,
    window_split,
)
from distributed_sgd_tpu.core.early_stopping import no_improvement
from distributed_sgd_tpu.data.rcv1 import dim_sparsity
from distributed_sgd_tpu.models.linear import make_model
from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.autopilot")


class Flywheel:
    def __init__(
        self,
        stream: DriftingStream,
        horizon_rows: int,
        window_rows: int,
        model: str = "hinge",
        lam: float = 1e-5,
        n_workers: int = 2,
        n_replicas: int = 2,
        max_epochs: int = 4,
        batch_size: int = 16,
        learning_rate: float = 0.5,
        patience: int = 2,
        conv_delta: float = 1e-4,
        eval_rows: int = 256,
        grad_timeout_s: float = 10.0,
        grad_retries: int = 2,
        probe_capacity: int = 64,
        label_delay: int = 0,
        source_refresh_s: float = 0.5,
        canary_fraction: float = 0.5,
        health_s: float = 0.25,
        detector: Optional[DriftDetector] = None,
        poll_s: float = 0.5,
        cooldown_s: float = 2.0,
        canary_timeout_s: float = 60.0,
        max_retrains: int = 0,
        recovery_band: float = 1.35,
        seed: int = 0,
        ckpt_dir: Optional[str] = None,
        metrics: Optional[metrics_mod.Metrics] = None,
        telemetry_port: Optional[int] = None,
        chaos: Optional[str] = None,
    ):
        if window_rows < 1 or horizon_rows < window_rows:
            raise ValueError("need horizon_rows >= window_rows >= 1")
        self.stream = stream
        self.horizon_rows = int(horizon_rows)
        self.window_rows = int(window_rows)
        self.max_epochs = int(max_epochs)
        self.batch_size = int(batch_size)
        self.learning_rate = float(learning_rate)
        self.patience = int(patience)
        self.conv_delta = float(conv_delta)
        self.eval_rows = int(eval_rows)
        # gradient-plane resilience: a chaos drop black-holes an RPC for
        # its full timeout, so a weathered fit needs a short deadline +
        # retries instead of the clear-sky defaults
        self.grad_timeout_s = float(grad_timeout_s)
        self.grad_retries = int(grad_retries)
        self.seed = int(seed)
        self.metrics = metrics or metrics_mod.global_metrics()
        # the pump serves rows [window_rows, horizon_rows): train on the
        # past, serve the future.  Every probe row is therefore
        # out-of-sample, so the detector's baseline anchors on the true
        # fresh-traffic loss instead of the (near-zero) training-row
        # loss — the contrast a concept shift has to clear.
        self.serve_from = self.window_rows
        self.served = 0  # rows sent (stream-time = serve_from + served)
        self._retrain_lock = threading.Lock()

        # the resident corpus covers the whole traffic horizon up front;
        # window_split decides which slice of it each fit trains on (the
        # sliding-window view — rows outside the window never dispatch)
        from distributed_sgd_tpu.checkpoint import Checkpointer
        from distributed_sgd_tpu.core.cluster import DevCluster
        from distributed_sgd_tpu.serving.fleet import ServingFleet
        from distributed_sgd_tpu.serving.push import CheckpointDistributor

        corpus = stream.rows(0, self.horizon_rows)
        mdl = make_model(model, lam, stream.n_features,
                         dim_sparsity=dim_sparsity(corpus))
        # chaos (a plan spec or scenario:NAME) lands on the TRAINING
        # plane only — the drift detector must not confuse transport
        # weather with concept shift (the bench's false-positive gate)
        self.cluster = DevCluster(
            mdl, corpus, stream.eval_set(self.eval_rows, at=self.window_rows),
            n_workers=n_workers, seed=seed, chaos=chaos)
        self.ckpt_dir = ckpt_dir or tempfile.mkdtemp(prefix="dsgd-flywheel-")
        self.ckpt = Checkpointer(self.ckpt_dir)

        # live probe sourcing: ground truth joins through the stream's
        # oracle (labels as the CURRENT concept holds them, label_delay
        # requests late); recency-bounded so the sample turns over with
        # the traffic instead of averaging over all history
        self.reservoir = ProbeReservoir(
            stream.oracle_labeler(start=self.serve_from),
            capacity=probe_capacity, seed=seed,
            label_delay=label_delay, recency=2 * probe_capacity,
            min_fill=max(1, probe_capacity // 2))
        self.fleet = ServingFleet(
            self.ckpt_dir, n_replicas=n_replicas,
            model=model, lam=lam,
            ckpt_poll_s=60.0,  # push-driven: the distributor is the feed
            canary_fraction=canary_fraction, health_s=health_s,
            probe_source=self.reservoir,
            probe_source_refresh_s=source_refresh_s,
            metrics=self.metrics, seed=seed,
            telemetry_port=telemetry_port,
        )
        self._distributor_factory = lambda: CheckpointDistributor(
            self.ckpt_dir, [("127.0.0.1", self.fleet.router_port)],
            poll_s=0.25, metrics=self.metrics)
        self.distributor = None
        self.controller = AutopilotController(
            self.fleet.router,
            self.retrain, detector=detector, poll_s=poll_s,
            cooldown_s=cooldown_s, canary_timeout_s=canary_timeout_s,
            max_retrains=max_retrains, recovery_band=recovery_band,
            metrics=self.metrics)
        self._channel = None
        self._stub = None

    # -- lifecycle -----------------------------------------------------------

    def start(self, ready_timeout_s: float = 120.0) -> "Flywheel":
        """Initial fit on window [0, window_rows), then fleet + distributor
        + controller; returns once the first version is promoted and the
        fleet answers ServeHealth."""
        from distributed_sgd_tpu.rpc import dsgd_pb2 as pb
        from distributed_sgd_tpu.rpc.service import ServeStub, new_channel

        self.cluster.master.fit_sync(
            max_epochs=self.max_epochs, batch_size=self.batch_size,
            learning_rate=self.learning_rate,
            criterion=self._criterion(),
            split=window_split(0, self.window_rows),
            grad_timeout_s=self.grad_timeout_s,
            grad_retries=self.grad_retries,
            checkpointer=self.ckpt, checkpoint_every=1)
        self.fleet.start()
        self.distributor = self._distributor_factory().start()
        self._channel = new_channel("127.0.0.1", self.fleet.router_port)
        self._stub = ServeStub(self._channel)
        deadline = time.time() + ready_timeout_s
        while time.time() < deadline:
            try:
                if self._stub.ServeHealth(pb.Empty(), timeout=2).ok:
                    break
            except Exception:  # noqa: BLE001 - fleet still warming
                pass
            time.sleep(0.1)
        else:
            raise AssertionError(
                "fleet never became ready (no version promoted)")
        self.controller.start()
        return self

    def stop(self) -> None:
        self.controller.stop()
        if self.distributor is not None:
            self.distributor.stop()
        if self._channel is not None:
            self._channel.close()
        self.fleet.stop()
        self.cluster.stop()
        self.ckpt.close()

    def __enter__(self) -> "Flywheel":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the traffic pump ----------------------------------------------------

    def pump(self, n: int, pace_s: float = 0.0,
             timeout_s: float = 10.0) -> Tuple[List[float], List[str]]:
        """Send the next `n` stream rows as Predict requests (features
        only — the wire carries no labels; ground truth reaches the
        reservoir through the label-delay join).  Returns (latencies,
        dropped) so callers can assert the zero-drop SLO."""
        from distributed_sgd_tpu.rpc import dsgd_pb2 as pb

        n = min(n, self.horizon_rows - self.serve_from - self.served)
        if n <= 0:
            return [], []
        rows = self.stream.rows(self.serve_from + self.served, n)
        latencies: List[float] = []
        dropped: List[str] = []
        for i in range(n):
            idx = np.asarray(rows.indices[i], np.int32)
            val = np.asarray(rows.values[i], np.float32)
            t0 = time.perf_counter()
            try:
                self._stub.Predict(
                    pb.PredictRequest(indices=idx, values=val),
                    timeout=timeout_s)
                latencies.append(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001 - the zero-drop assert
                dropped.append(repr(e))
            self.served += 1
            if pace_s:
                time.sleep(pace_s)
        return latencies, dropped

    @property
    def stream_time(self) -> int:
        """Stream position of the next row the pump will serve."""
        return self.serve_from + self.served

    @property
    def exhausted(self) -> bool:
        return self.stream_time >= self.horizon_rows

    # -- the retrain half (called by the controller) -------------------------

    def _criterion(self):
        # continual eval: no-improvement judged on the last few evals
        # only, so a warm-started fit is never stopped by a best earned
        # on a distribution that no longer exists
        return continual_criterion(
            no_improvement(patience=self.patience, min_delta=self.conv_delta),
            horizon=2 * self.patience + 1)

    def retrain(self):
        """Warm-start fit over the newest window_rows the pump served,
        evaluated against the distribution at the window's trailing
        edge.  The fit RESUMES from the latest epoch checkpoint (the
        warm start — and the reason the epoch budget is raised past the
        restored step: resumed epochs continue the checkpoint version
        stream, so every retrain round reaches the fleet as a strictly
        newer version).  The restored loss history was earned on the
        pre-shift eval set, so convergence is judged on THIS fit's evals
        only — comparing against a best from a distribution that no
        longer exists would stop the retrain instantly.  Epoch-cadence
        checkpoints stream to the fleet through the distributor as they
        land — the controller observes the canary verdict, never this
        return value."""
        with self._retrain_lock:
            hi = max(1, min(self.stream_time, self.horizon_rows))
            lo = max(0, hi - self.window_rows)
            restored = self.ckpt.restore_latest()
            prior = int(restored[0]) if restored is not None else 0
            self.cluster.master.test = self.stream.eval_set(
                self.eval_rows, at=hi)
            log.info("flywheel retrain: window [%d, %d), resuming at "
                     "epoch %d (+%d epoch budget)",
                     lo, hi, prior, self.max_epochs)
            inner = no_improvement(patience=self.patience,
                                   min_delta=self.conv_delta)

            def fresh_evals_only(losses):
                return inner(list(losses)[:max(0, len(losses) - prior)])

            return self.cluster.master.fit_sync(
                max_epochs=prior + self.max_epochs,
                batch_size=self.batch_size,
                learning_rate=self.learning_rate,
                criterion=fresh_evals_only,
                split=window_split(lo, hi),
                grad_timeout_s=self.grad_timeout_s,
                grad_retries=self.grad_retries,
                checkpointer=self.ckpt, checkpoint_every=1)

    # -- the env-driven demo loop (DSGD_ROLE=dev DSGD_AUTOPILOT=1) -----------

    def run(self, chunk: int = 64, pace_s: float = 0.0,
            settle_timeout_s: float = 300.0) -> dict:
        """Pump the whole horizon through the fleet, then wait for the
        controller to settle back to SERVING; returns a summary dict
        (the dev role logs it, the bench asserts on richer state)."""
        dropped: List[str] = []
        while not self.exhausted:
            _, drops = self.pump(chunk, pace_s=pace_s)
            dropped.extend(drops)
        deadline = time.time() + settle_timeout_s
        while time.time() < deadline:
            if (self.controller.state == "SERVING"
                    and self.controller.retrains > 0):
                break
            time.sleep(0.2)
        c = self.metrics.counter
        return {
            "served": self.served,
            "dropped": len(dropped),
            "retrains": self.controller.retrains,
            "promoted": int(c(metrics_mod.AUTOPILOT_PROMOTED).value),
            "rolled_back": int(c(metrics_mod.AUTOPILOT_ROLLED_BACK).value),
            "probe_losses": self.fleet.router.probe_losses(),
            "state": self.controller.state,
        }
