"""The autopilot controller (docs/CONTINUAL.md).

A state machine that closes the train/serve loop with zero operator
actions:

    SERVING -> DRIFT_DETECTED -> RETRAINING -> CANARY
                                                 |-> PROMOTED    -> SERVING
                                                 '-> ROLLED_BACK -> SERVING

It watches the router's probe-loss series — each probe-source refresh
re-probes the promoted version against freshly sampled live traffic
(serving/router.py), so that series IS "how well does the model serving
right now fit the traffic arriving right now".  :class:`DriftDetector`
applies the HealthMonitor rule shape (telemetry/health.py) to it: an
EWMA over the series, tripped when it exceeds
``max(ratio * baseline, baseline + abs_floor)`` for ``patience``
consecutive observations after ``warmup``.  Two deliberate differences
from the training watchdog: the baseline is RE-ANCHORABLE (``rebase()``
after every promotion — the new model's loss on the new distribution is
the new normal), and the absolute floor keeps sub-resolution wiggle at
tiny losses — quorum-timing noise, reservoir churn — from ever clearing
the ratio bar (the false-positive gate in tests/test_autopilot.py).

On a trip the controller runs the ``retrain`` callback (a warm-start
``fit_sync`` from the latest FitState over the current stream window —
PR 11's spin-up fast path is what makes this cheap), then WAITS: the new
checkpoint flows through the existing ``CheckpointDistributor`` ->
router canary -> promote/rollback machinery, and the controller only
observes the verdict through the router's own counters.  It never
bypasses the canary gate — a retrain that regressed on the live probe
set rolls back exactly like an operator push would, and the controller
cools down instead of hot-looping on a distribution it cannot fit.

One cycle may take SEVERAL retrains: a trip that fires while the sliding
window still straddles the shift warm-starts a model that only
half-recovers, and the post-promotion rebase would happily call that the
new normal.  The settling rule (``recovery_band``, :meth:`_residual`)
holds the pre-trip healthy baseline across the cycle and keeps
retraining — each round on newer, purer traffic — until the EWMA is back
inside the band (bounded by ``max_retrains``).

Every transition gets a metrics counter, a trace instant event, and a
flight record; rollbacks and retrain failures also dump the flight ring
(evidence first, policy second — the HealthMonitor discipline).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Callable, Optional

from distributed_sgd_tpu import trace as trace_mod
from distributed_sgd_tpu.trace import flight
from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.autopilot")

STATES = ("SERVING", "DRIFT_DETECTED", "RETRAINING", "CANARY",
          "PROMOTED", "ROLLED_BACK")


class DriftDetector:
    def __init__(
        self,
        alpha: float = 0.3,
        ratio: float = 1.5,
        patience: int = 2,
        warmup: int = 4,
        abs_floor: float = 0.1,
        metrics: Optional[metrics_mod.Metrics] = None,
    ):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        if ratio <= 1.0:
            raise ValueError("ratio must be > 1")
        if abs_floor < 0.0:
            raise ValueError("abs_floor must be >= 0")
        self.alpha = float(alpha)
        self.ratio = float(ratio)
        self.patience = max(1, int(patience))
        self.warmup = max(0, int(warmup))
        self.abs_floor = float(abs_floor)
        self.metrics = metrics
        self._ewma: Optional[float] = None
        self._baseline = math.inf
        self._checks = 0
        self._over = 0

    def observe(self, loss: float) -> bool:
        """Feed one probe-loss observation; True when drift trips.  A
        non-finite probe loss trips immediately — a model that NaNs on
        live traffic is the most drifted a model can be."""
        if not math.isfinite(loss):
            return True
        ewma = (loss if self._ewma is None
                else self.alpha * loss + (1 - self.alpha) * self._ewma)
        self._ewma = ewma
        self._checks += 1
        if self.metrics is not None:
            self.metrics.gauge(metrics_mod.AUTOPILOT_DRIFT_EWMA).set(ewma)
        if self._checks <= self.warmup:
            self._baseline = min(self._baseline, ewma)
            return False
        bar = max(self.ratio * self._baseline, self._baseline + self.abs_floor)
        if ewma > bar:
            self._over += 1
            return self._over >= self.patience
        self._over = 0
        self._baseline = min(self._baseline, ewma)
        return False

    def rebase(self) -> None:
        """Re-anchor after a promotion (or rollback cooldown): the next
        observations define the new normal."""
        self._ewma = None
        self._baseline = math.inf
        self._checks = 0
        self._over = 0


class AutopilotController:
    """One daemon thread driving the flywheel against an in-process
    :class:`~distributed_sgd_tpu.serving.router.ServingRouter`.

    ``retrain`` is the training half, supplied by the integrator (a
    warm-start ``fit_sync`` over the current stream window that writes a
    checkpoint into the distributor's directory); the controller owns
    WHEN it runs and what happens to its verdict, never HOW it trains.
    """

    def __init__(
        self,
        router,
        retrain: Callable[[], object],
        detector: Optional[DriftDetector] = None,
        poll_s: float = 0.5,
        cooldown_s: float = 2.0,
        canary_timeout_s: float = 120.0,
        max_retrains: int = 0,
        recovery_band: float = 1.35,
        metrics: Optional[metrics_mod.Metrics] = None,
    ):
        if poll_s <= 0:
            raise ValueError("poll_s must be > 0")
        if recovery_band and recovery_band <= 1.0:
            raise ValueError("recovery_band must be > 1 (or 0 to disable)")
        self.router = router
        self.retrain = retrain
        self.metrics = metrics or metrics_mod.global_metrics()
        self.detector = detector or DriftDetector(metrics=self.metrics)
        if self.detector.metrics is None:
            self.detector.metrics = self.metrics
        self.poll_s = float(poll_s)
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.canary_timeout_s = float(canary_timeout_s)
        self.max_retrains = max(0, int(max_retrains))  # 0 = unbounded
        self.recovery_band = float(recovery_band)  # 0 disables settling
        self.state = "SERVING"
        self.retrains = 0
        self._consumed = 0  # probe-loss entries already fed to the detector
        # the pre-trip healthy baseline, held across a retrain cycle until
        # the post-promotion EWMA settles back inside recovery_band of it
        self._settle_baseline: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.metrics.gauge(metrics_mod.AUTOPILOT_STATE).set(
            STATES.index(self.state))

    # -- transitions --------------------------------------------------------

    def _to(self, state: str, **info) -> None:
        prev, self.state = self.state, state
        self.metrics.gauge(metrics_mod.AUTOPILOT_STATE).set(
            STATES.index(state))
        self.metrics.counter(metrics_mod.AUTOPILOT_TRANSITIONS).increment()
        log.info("autopilot: %s -> %s %s", prev, state, info or "")
        trace_mod.event(trace_mod.EVENT_AUTOPILOT_TRANSITION,
                        frm=prev, to=state, **info)
        flight.record("autopilot.transition", frm=prev, to=state, **info)

    # -- the loop -----------------------------------------------------------

    def start(self) -> "AutopilotController":
        self._thread = threading.Thread(
            target=self._loop, name="autopilot-controller", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)

    def __enter__(self) -> "AutopilotController":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _drifted(self) -> bool:
        """Feed any new probe-loss observations; True when drift trips."""
        series = self.router.probe_losses()
        tripped = False
        for loss in series[self._consumed:]:
            self._consumed += 1
            if self.detector.observe(loss):
                tripped = True
        return tripped

    def _rebase(self) -> None:
        # losses measured before/at the verdict describe the old model:
        # skip them, or the fresh baseline would anchor on stale pain
        self.detector.rebase()
        self._consumed = len(self.router.probe_losses())

    def _residual(self) -> bool:
        """The rebase after a promotion deliberately makes the retrained
        model's loss the new normal — which would also normalize a retrain
        that only HALF-recovered (trained on a window still contaminated
        with pre-shift rows).  So across a cycle the controller holds the
        pre-trip healthy baseline: once the post-rebase EWMA has re-warmed,
        either it is back inside recovery_band of that baseline (cycle
        closed) or the residual drift earns another retrain — by which
        time the window has slid onto purer post-shift traffic."""
        if not self.recovery_band or self._settle_baseline is None:
            return False
        d = self.detector
        if d._ewma is None or d._checks <= d.warmup:
            return False
        if d._ewma <= self.recovery_band * self._settle_baseline:
            self._settle_baseline = None  # recovered: cycle closed
            return False
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            self._step()

    def _step(self) -> None:
        """One poll: feed new observations, decide, run the flywheel.
        Factored out of the thread loop so the state machine is testable
        synchronously (tests/test_autopilot.py drives it directly)."""
        if self.state != "SERVING":
            return  # mid-cycle: the flywheel owns the state until SERVING
        tripped = self._drifted()
        residual = not tripped and self._residual()
        if not (tripped or residual):
            return
        if self.max_retrains and self.retrains >= self.max_retrains:
            return  # budget spent: observe-only from here on
        if (tripped and self.recovery_band
                and self._settle_baseline is None
                and math.isfinite(self.detector._baseline)):
            self._settle_baseline = self.detector._baseline
        self._to("DRIFT_DETECTED", ewma=round(self.detector._ewma or 0, 6),
                 baseline=round(self.detector._baseline, 6),
                 **({"reason": "residual"} if residual else {}))
        self.metrics.counter(
            metrics_mod.AUTOPILOT_DRIFT_TRIPPED).increment()
        self._run_flywheel()

    def _run_flywheel(self) -> None:
        mm = metrics_mod
        promoted0 = self.router.metrics.counter(
            mm.ROUTER_CANARY_PROMOTED).value
        rolled0 = self.router.metrics.counter(
            mm.ROUTER_CANARY_ROLLBACK).value
        self._to("RETRAINING", retrain=self.retrains + 1)
        self.metrics.counter(mm.AUTOPILOT_RETRAINS).increment()
        try:
            self.retrain()
            self.retrains += 1
        except Exception as e:  # noqa: BLE001 - the loop must survive a bad fit
            self.metrics.counter(mm.AUTOPILOT_RETRAIN_ERRORS).increment()
            log.exception("autopilot retrain failed")
            flight.record("autopilot.retrain_failed", error=repr(e))
            flight.dump("autopilot")
            self._to("SERVING", reason="retrain_failed")
            self._cooldown()
            return

        # the verdict belongs to the canary gate: wait for the router's
        # own counters to move (promotion or rollback), never pre-judge
        self._to("CANARY")
        deadline = time.monotonic() + self.canary_timeout_s
        verdict = None
        while time.monotonic() < deadline and not self._stop.is_set():
            if self.router.metrics.counter(
                    mm.ROUTER_CANARY_PROMOTED).value > promoted0:
                verdict = "PROMOTED"
                break
            if self.router.metrics.counter(
                    mm.ROUTER_CANARY_ROLLBACK).value > rolled0:
                verdict = "ROLLED_BACK"
                break
            time.sleep(min(0.05, self.poll_s))

        if verdict == "PROMOTED":
            self._to("PROMOTED", version=self.router.promoted_version)
            self.metrics.counter(mm.AUTOPILOT_PROMOTED).increment()
        elif verdict == "ROLLED_BACK":
            self._to("ROLLED_BACK")
            self.metrics.counter(mm.AUTOPILOT_ROLLED_BACK).increment()
            flight.record("autopilot.rolled_back",
                          retrain=self.retrains)
            flight.dump("autopilot")
        else:
            # canary never concluded (distributor stalled, no eligible
            # canaries): treat like a rollback — evidence + cooldown
            self._to("ROLLED_BACK", reason="canary_timeout")
            self.metrics.counter(mm.AUTOPILOT_ROLLED_BACK).increment()
            flight.record("autopilot.canary_timeout",
                          timeout_s=self.canary_timeout_s)
            flight.dump("autopilot")
        self._to("SERVING")
        self._rebase()
        self._cooldown()

    def _cooldown(self) -> None:
        self._stop.wait(self.cooldown_s)
        # observations that arrived during the cooldown describe the
        # transition window, not steady state
        self._consumed = len(self.router.probe_losses())
