"""Deterministic fault injection for RPC edges (docs/FAULT_TOLERANCE.md).

The repo's fault-tolerance machinery (heartbeat eviction, async stall
watchdog, quorum barriers, retry/breaker policy) needs faults it can be
tested AGAINST, reproducibly.  This package injects them at the client
stub layer: when a plan is installed, `rpc.service.new_channel` wraps
every channel it creates in a `ChaosChannel`, whose multicallables apply
a seeded fault plan to each outgoing RPC — drop (black hole until the
caller's deadline), delay (uniform within a range), duplicate,
error-code injection, and timed partitions of named endpoints — before
the call ever reaches gRPC.  Receivers see exactly what a lossy network
would deliver; senders see exactly the futures/errors gRPC would give
them, so no production code path knows chaos exists.

Plan syntax (DSGD_CHAOS):

    seed=7;drop=0.05;delay=20ms~200ms;dup=0.01;error=0.002;partition=w2:10s@30s

- ``seed=N``       seeds every per-edge RNG stream (decisions replay
                   given the same per-edge call order)
- ``drop=P``       per-call probability the RPC is black-holed: the
                   future never completes until the caller's deadline
                   fires (DEADLINE_EXCEEDED), exactly like a lost packet
- ``delay=A~B``    per-call latency added uniformly in [A, B] before the
                   real send (``delay=50ms`` = fixed)
- ``dup=P``        per-call probability the request is sent TWICE (the
                   duplicate is fire-and-forget) — exercises idempotence
- ``error=P``      per-call probability of an immediate injected
                   UNAVAILABLE (a fast failure, unlike drop's slow one)
- ``partition=NAME:DUR@AT``  (comma-repeatable) every RPC touching the
                   endpoint named NAME (see `name_endpoint`) is dropped
                   during [AT, AT+DUR) measured from `arm()` time
- ``grace=D``      no faults for the first D after install (lets a
                   cluster form before the weather starts; `arm()`
                   resets the clock explicitly instead)
- ``scope=named``  blast radius: faults apply only to edges that touch a
                   NAMED endpoint (see `name_endpoint`; DevCluster names
                   its master/workers) — un-named planes (a serving
                   fleet, a bench load generator) run clear.  Default
                   ``scope=all``.  A ``scenario:NAME`` spec accepts
                   trailing overrides: ``scenario:flaky-rack;scope=named``

Durations accept ``20ms``, ``1.5s``, or bare seconds.  Determinism: each
(origin, target, method) edge draws from its own `random.Random` stream
seeded by (plan seed, edge key), so a fixed plan + fixed per-edge call
order replays the same faults; wall-clock only enters through the
partition/grace windows.

Installed per process (`install`; main.py installs from the DSGD_CHAOS
config field, DevCluster from its `chaos=` parameter), consulted at call
time — so a plan installed before a node builds its channels covers
every stub it ever creates, including rejoin channels.
"""

from __future__ import annotations

import heapq
import re
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import grpc

from distributed_sgd_tpu import trace as trace_mod
from distributed_sgd_tpu.trace import flight

_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s)?$")


def _parse_duration(tok: str) -> float:
    """'20ms' | '1.5s' | '3' -> seconds."""
    m = _DUR_RE.match(tok.strip())
    if not m:
        raise ValueError(f"bad duration {tok!r} (want e.g. 20ms, 1.5s)")
    v = float(m.group(1))
    return v / 1000.0 if m.group(2) == "ms" else v


@dataclass(frozen=True)
class Partition:
    name: str     # endpoint name (name_endpoint) or "host:port"
    dur_s: float  # window length
    at_s: float   # offset from arm() time


@dataclass(frozen=True)
class FaultPlan:
    seed: int = 0
    drop: float = 0.0
    delay: Optional[Tuple[float, float]] = None
    dup: float = 0.0
    error: float = 0.0
    grace_s: float = 0.0
    partitions: Tuple[Partition, ...] = ()
    scope: str = "all"

    def __post_init__(self):
        for name in ("drop", "dup", "error"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"chaos {name}={p} must be a probability")
        if self.delay is not None and not (0 <= self.delay[0] <= self.delay[1]):
            raise ValueError(f"chaos delay range {self.delay} must be 0 <= lo <= hi")
        if self.scope not in ("all", "named"):
            raise ValueError(
                f"chaos scope={self.scope!r} must be 'all' or 'named'")


# -- named scenario library (ROADMAP 3c; DSGD_CHAOS=scenario:NAME) -----------
# The soak bench's weather, promoted to named seeded plans so a bench run,
# a bug report, and a CI job all mean the SAME faults when they say
# "flaky-rack".  Each value is a full plan-grammar spec (seed included —
# naming a scenario pins its randomness), resolved by parse_plan before
# parsing, so config validation and every install path accept the names.
SCENARIOS: Dict[str, str] = {
    # lossy ToR switch: steady low drop + jittery small delays + the
    # occasional duplicated frame, no partitions — transport noise only
    "flaky-rack": "seed=23;drop=0.03;delay=2ms~20ms;dup=0.02",
    # one slow device in the I/O path: long-tail delays with a grace
    # window so startup traffic clears before the weather starts
    "slow-disk": "seed=31;delay=10ms~150ms;grace=2s",
    # asymmetric partition: two workers black-holed at different,
    # non-overlapping times, riding steady transport noise — the
    # quorum/hedge plane's worst weather.  Windows are sized to be
    # absorbable by a correctly-budgeted deployment (heartbeat budget
    # > 1.5s, quorum slack >= 1, and only one worker dark at a time)
    "asym-partition": "seed=47;drop=0.02;delay=3ms~15ms;dup=0.01;"
                      "partition=w1:1.5s@6s,w2:1.5s@9s",
    # correlated blip then mass rejoin: three workers vanish TOGETHER and
    # return together — the re-registration/resplit thundering herd
    "thundering-rejoin": "seed=59;drop=0.02;delay=1ms~10ms;"
                         "partition=w1:2s@3s,w2:2s@3s,w3:2s@3s",
    # flapping decider router (docs/SERVING.md "HA"): repeated SHORT
    # kills + restarts of the node named `router` under scope=named, each
    # gap just past a typical HA lease TTL — the survivor assumes the
    # decider lease, then the flapping router rejoins (and must adopt the
    # survivor's newer promoted-state record, never resurrect its own),
    # three times in a row, over mild transport jitter
    "router-flap": "seed=71;scope=named;delay=1ms~8ms;"
                   "partition=router:0.8s@2s,router:0.8s@5s,router:0.8s@8s",
}


def resolve_scenario(spec: str) -> str:
    """Expand a ``scenario:NAME`` spec to its plan string; pass anything
    else through untouched.  Tokens after the name override/extend the
    scenario (``scenario:flaky-rack;scope=named``) — the seeded weather
    stays the library's, the caller adjusts only its blast radius."""
    if not spec.startswith("scenario:"):
        return spec
    name, _, extra = spec[len("scenario:"):].partition(";")
    name = name.strip()
    if name not in SCENARIOS:
        raise ValueError(
            f"unknown chaos scenario {name!r}; known: "
            f"{', '.join(sorted(SCENARIOS))}")
    return SCENARIOS[name] + (f";{extra}" if extra else "")


def parse_plan(spec: str) -> FaultPlan:
    """DSGD_CHAOS spec string -> FaultPlan (raises ValueError on typos).
    Accepts ``scenario:NAME`` for the named seeded library above."""
    spec = resolve_scenario(spec)
    kw: Dict[str, object] = {}
    parts: List[Partition] = []
    for token in filter(None, (t.strip() for t in spec.split(";"))):
        if "=" not in token:
            raise ValueError(f"bad chaos token {token!r} (want key=value)")
        key, val = (s.strip() for s in token.split("=", 1))
        if key == "seed":
            kw["seed"] = int(val)
        elif key in ("drop", "dup", "error"):
            kw[key] = float(val)
        elif key == "delay":
            lo, _, hi = val.partition("~")
            a = _parse_duration(lo)
            b = _parse_duration(hi) if hi else a
            kw["delay"] = (a, b)
        elif key == "grace":
            kw["grace_s"] = _parse_duration(val)
        elif key == "scope":
            kw["scope"] = val
        elif key == "partition":
            for p in filter(None, (s.strip() for s in val.split(","))):
                name, _, window = p.rpartition(":")
                at = ""
                dur, _, at = window.partition("@")
                if not name or not at:
                    raise ValueError(
                        f"bad partition {p!r} (want NAME:DUR@AT, e.g. w2:10s@30s)")
                parts.append(Partition(name, _parse_duration(dur),
                                       _parse_duration(at)))
        else:
            raise ValueError(f"unknown chaos key {key!r}")
    return FaultPlan(partitions=tuple(parts), **kw)


class _Scheduler:
    """One shared timer thread (heapq) for delayed sends and black-hole
    deadlines — avoids a thread per injected fault."""

    def __init__(self):
        self._cv = threading.Condition()
        self._heap: list = []
        self._seq = 0
        # the liveness flag (not Thread.is_alive) decides respawn: both
        # the idle-exit and this flag flip under the SAME lock, so a
        # schedule() racing a dying thread always sees the truth
        self._running = False

    def schedule(self, delay_s: float, fn) -> None:
        with self._cv:
            self._seq += 1
            heapq.heappush(self._heap, (time.monotonic() + delay_s, self._seq, fn))
            if not self._running:
                self._running = True
                threading.Thread(
                    target=self._run, daemon=True, name="chaos-timer").start()
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._heap:
                    if not self._cv.wait(timeout=5.0) and not self._heap:
                        self._running = False
                        return  # idle: let the thread die; next schedule respawns
                due, _, fn = self._heap[0]
                now = time.monotonic()
                if due > now:
                    self._cv.wait(timeout=due - now)
                    continue
                heapq.heappop(self._heap)
            try:
                fn()
            except Exception:  # noqa: BLE001 - a fault must not kill the timer
                pass


_SCHEDULER = _Scheduler()


class ChaosRpcError(grpc.RpcError):
    """Injected failure carrying the .code()/.details() surface every
    caller in this repo reads off a grpc.RpcError."""

    def __init__(self, code: grpc.StatusCode, details: str = "chaos-injected"):
        super().__init__()
        self._code = code
        self._details = details

    def code(self) -> grpc.StatusCode:  # noqa: D102 - grpc surface
        return self._code

    def details(self) -> str:  # noqa: D102 - grpc surface
        return self._details

    def __str__(self):
        return f"ChaosRpcError({self._code})"


class _ChaosFuture:
    """grpc.Future-alike for injected/delayed calls.

    Three lifecycles: settled at birth (injected error), black hole
    (settles with DEADLINE_EXCEEDED when the caller's deadline fires, or
    never, if the call carried none — exactly a lost packet under a
    deadline-less fire-and-forget send), and delayed (the real call
    starts after `delay`; from then on this proxies the inner future).
    """

    def __init__(self):
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._inner = None
        self._exception: Optional[Exception] = None
        self._result = None
        self._cancelled = False
        self._callbacks: list = []

    # -- settle paths --------------------------------------------------------

    def _settle(self, result=None, exception=None) -> None:
        with self._lock:
            if self._done.is_set():
                return
            self._result, self._exception = result, exception
            self._done.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 - callback errors stay local
                pass

    def _adopt(self, inner) -> None:
        """A delayed real call started: proxy its completion."""
        with self._lock:
            if self._cancelled:
                inner.cancel()
                return
            self._inner = inner
        inner.add_done_callback(self._from_inner)

    def _from_inner(self, inner) -> None:
        if inner.cancelled():
            self._settle(exception=ChaosRpcError(
                grpc.StatusCode.CANCELLED, "cancelled"))
            with self._lock:
                self._cancelled = True
            return
        exc = inner.exception()
        if exc is not None:
            self._settle(exception=exc)
        else:
            self._settle(result=inner.result())

    # -- grpc.Future surface -------------------------------------------------

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise grpc.FutureTimeoutError()
        if self._exception is not None:
            raise self._exception
        return self._result

    def exception(self, timeout=None):
        if not self._done.wait(timeout):
            raise grpc.FutureTimeoutError()
        return self._exception

    def done(self) -> bool:
        return self._done.is_set()

    def cancelled(self) -> bool:
        return self._cancelled

    def running(self) -> bool:
        return not self._done.is_set()

    def cancel(self) -> bool:
        with self._lock:
            inner = self._inner
            if inner is None and not self._done.is_set():
                self._cancelled = True
        if inner is not None:
            return inner.cancel()
        self._settle(exception=ChaosRpcError(
            grpc.StatusCode.CANCELLED, "cancelled"))
        return True

    def add_done_callback(self, fn) -> None:
        with self._lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def traceback(self, timeout=None):
        return None


class ChaosState:
    """One installed plan: clock, endpoint names, per-edge RNG streams."""

    def __init__(self, plan: FaultPlan, metrics=None, armed: bool = True):
        self.plan = plan
        self._names: Dict[Tuple[str, int], str] = {}
        self._rngs: Dict[Tuple, "_Rng"] = {}
        self._lock = threading.Lock()
        self._metrics = metrics
        self._t0 = time.monotonic() if armed else None

    def arm(self) -> None:
        """Start (or restart) the fault clock — partitions/grace measure
        from here.  A state installed with armed=False injects nothing
        until armed."""
        self._t0 = time.monotonic()

    @property
    def armed(self) -> bool:
        return self._t0 is not None

    def elapsed(self) -> float:
        return 0.0 if self._t0 is None else time.monotonic() - self._t0

    def name_endpoint(self, host: str, port: int, name: str) -> None:
        with self._lock:
            self._names[(host, int(port))] = name

    def _endpoint_names(self, endpoint) -> Tuple[str, ...]:
        if endpoint is None:
            return ()
        with self._lock:
            named = self._names.get(endpoint)
        canonical = f"{endpoint[0]}:{endpoint[1]}"
        return (named, canonical) if named else (canonical,)

    def partitioned(self, *endpoints) -> bool:
        if not self.plan.partitions or self._t0 is None:
            return False
        t = self.elapsed()
        names = set()
        for ep in endpoints:
            names.update(self._endpoint_names(ep))
        return any(
            p.name in names and p.at_s <= t < p.at_s + p.dur_s
            for p in self.plan.partitions
        )

    def active(self) -> bool:
        return self._t0 is not None and self.elapsed() >= self.plan.grace_s

    def in_scope(self, origin, target) -> bool:
        """scope=named confines the weather to edges touching a named
        endpoint (the plane the caller registered via `name_endpoint`);
        every other edge — a serving fleet, a bench load generator
        sharing the process — runs clear."""
        if self.plan.scope == "all":
            return True
        with self._lock:
            return any(ep in self._names
                       for ep in (origin, target) if ep is not None)

    def _canonical(self, endpoint) -> Optional[str]:
        """Stable edge identity: the registered name when one exists
        (DevCluster: master/w0..wN — OS-assigned ports differ every run,
        which would silently break stream determinism), host:port
        otherwise (multi-process deployments pin their ports)."""
        if endpoint is None:
            return None
        with self._lock:
            named = self._names.get(endpoint)
        return named if named else f"{endpoint[0]}:{endpoint[1]}"

    def rng(self, origin, target, method: str):
        """Deterministic per-edge stream: keyed by the canonical
        (origin, target, method) so a fixed plan and per-edge call order
        replay the same drop/delay/dup decisions regardless of sibling
        edges — and regardless of which ephemeral ports the OS hands a
        dev cluster (endpoints resolve through their registered names)."""
        import random

        key = (self._canonical(origin), self._canonical(target), method)
        with self._lock:
            r = self._rngs.get(key)
            if r is None:
                r = random.Random(
                    (self.plan.seed << 32)
                    ^ zlib.crc32(repr(key).encode()))
                self._rngs[key] = r
            return r

    def count(self, kind: str, **edge) -> None:
        """Account one injected fault.  `edge` (method/origin/target) flows
        into the flight recorder and — when the caller sits inside a
        sampled trace span, e.g. a master fan-out window — into the trace
        as an instant event, so an injected delay is visibly ATTRIBUTED in
        the timeline instead of masquerading as a slow worker."""
        if self._metrics is not None:
            self._metrics.counter(f"chaos.injected.{kind}").increment()
        flight.record(f"chaos.{kind}", **edge)
        trace_mod.event(f"chaos.{kind}", **edge)


class _ChaosCallable:
    """Wraps one unary-unary multicallable with the plan's faults."""

    def __init__(self, inner, method: str, target, origin, state: ChaosState):
        self._inner = inner
        self._method = method
        self._target = target
        self._origin = origin
        self._state = state

    def _decide(self):
        """-> (action, param): ('pass'|'drop'|'error'|'delay'|'dup', x).
        One uniform draw per candidate fault keeps the stream deterministic
        even as the plan's probabilities change."""
        st = self._state
        if not st.active() or not st.in_scope(self._origin, self._target):
            return ("pass", None)
        rng = st.rng(self._origin, self._target, self._method)
        # draws happen in a FIXED order so the stream replays
        u_drop = rng.random()
        u_err = rng.random()
        u_dup = rng.random()
        d = (rng.uniform(*st.plan.delay) if st.plan.delay is not None else 0.0)
        edge = {"method": self._method,
                "origin": st._canonical(self._origin),
                "target": st._canonical(self._target)}
        if st.partitioned(self._target, self._origin):
            st.count("partition", **edge)
            return ("drop", None)
        if u_drop < st.plan.drop:
            st.count("drop", **edge)
            return ("drop", None)
        if u_err < st.plan.error:
            st.count("error", **edge)
            return ("error", None)
        if u_dup < st.plan.dup:
            st.count("dup", delay_s=round(d, 6), **edge)
            return ("dup", d)
        if d > 0:
            st.count("delay", delay_s=round(d, 6), **edge)
            return ("delay", d)
        return ("pass", None)

    # -- blocking call -------------------------------------------------------

    def __call__(self, request, timeout=None, **kwargs):
        action, param = self._decide()
        if action == "pass":
            return self._inner(request, timeout=timeout, **kwargs)
        if action == "drop":
            # black hole: the caller's deadline is the only way out
            time.sleep(timeout if timeout is not None else 1.0)
            raise ChaosRpcError(grpc.StatusCode.DEADLINE_EXCEEDED,
                                "chaos drop")
        if action == "error":
            raise ChaosRpcError(grpc.StatusCode.UNAVAILABLE, "chaos error")
        if action == "dup":
            try:  # duplicate is fire-and-forget; the primary is the answer
                self._inner.future(request, timeout=timeout, **kwargs)
            except Exception:  # noqa: BLE001 - best-effort duplicate
                pass
            if param:
                time.sleep(param)
            return self._inner(request, timeout=timeout, **kwargs)
        # delay
        time.sleep(param)
        if timeout is not None:
            remaining = timeout - param
            if remaining <= 0:
                raise ChaosRpcError(grpc.StatusCode.DEADLINE_EXCEEDED,
                                    "chaos delay past deadline")
            timeout = remaining
        return self._inner(request, timeout=timeout, **kwargs)

    # -- future call ---------------------------------------------------------

    def future(self, request, timeout=None, **kwargs):
        action, param = self._decide()
        if action == "pass":
            return self._inner.future(request, timeout=timeout, **kwargs)
        fut = _ChaosFuture()
        if action == "drop":
            if timeout is not None:
                _SCHEDULER.schedule(timeout, lambda: fut._settle(
                    exception=ChaosRpcError(
                        grpc.StatusCode.DEADLINE_EXCEEDED, "chaos drop")))
            # no deadline (fire-and-forget gossip): stays pending forever,
            # like a lost packet — the bounded sender cancels it eventually
            return fut
        if action == "error":
            fut._settle(exception=ChaosRpcError(
                grpc.StatusCode.UNAVAILABLE, "chaos error"))
            return fut
        if action == "dup":
            def start_dup():
                try:
                    self._inner.future(request, timeout=timeout, **kwargs)
                    fut._adopt(self._inner.future(request, timeout=timeout,
                                                  **kwargs))
                except Exception as e:  # noqa: BLE001 - surface to the future
                    fut._settle(exception=e)
            if param:
                _SCHEDULER.schedule(param, start_dup)
            else:
                start_dup()
            return fut
        # delay: schedule the real send without blocking the caller
        def start():
            try:
                inner_timeout = timeout
                if inner_timeout is not None:
                    inner_timeout = max(1e-3, inner_timeout - param)
                fut._adopt(self._inner.future(request, timeout=inner_timeout,
                                              **kwargs))
            except Exception as e:  # noqa: BLE001 - surface to the future
                fut._settle(exception=e)
        _SCHEDULER.schedule(param, start)
        return fut


class _ChaosStreamCallable:
    """Wraps one stream-stream multicallable: the plan applies to every
    OUTGOING message of the request stream, so stream writes see exactly
    the drop/delay/dup/partition weather unary calls do
    (docs/SYNC_PIPELINE.md "Streaming transport").

    Per-message semantics mirror the unary actions on an ordered pipe:

    - drop/partition: the frame is silently not written — the caller's
      per-frame deadline is the only way out, exactly a lost packet
      (later frames still flow: a lost frame is not a dead stream);
    - delay: the writer sleeps before the frame (and, as on a real
      ordered transport, everything queued behind it waits too);
    - dup: the frame is written twice — receivers must drop the second
      reply idempotently (rpc/stream.py does, by seq);
    - error: the request iterator raises, which gRPC surfaces as a
      TERMINATED stream — the client's reader sees the teardown and the
      transport falls back to unary (the failure mode a mid-stream
      connection reset produces).

    Decisions draw from the same deterministic per-(origin, target,
    method) RNG stream as unary calls, one draw set per message.
    """

    def __init__(self, inner, method: str, target, origin, state: ChaosState):
        self._inner = inner
        self._method = method
        self._target = target
        self._origin = origin
        self._state = state
        # reuse the unary decision procedure (same fixed draw order)
        self._decider = _ChaosCallable(None, method, target, origin, state)

    def _wrap(self, request_iterator):
        st = self._state
        edge = {"method": self._method}
        for msg in request_iterator:
            action, param = self._decider._decide()
            if action == "drop":
                continue  # lost frame; the stream itself stays healthy
            if action == "error":
                st.count("stream_teardown",
                         origin=st._canonical(self._origin),
                         target=st._canonical(self._target), **edge)
                raise ChaosRpcError(grpc.StatusCode.UNAVAILABLE,
                                    "chaos stream teardown")
            if action == "dup":
                yield msg
                yield msg
                continue
            if action == "delay":
                time.sleep(param)
            yield msg

    def __call__(self, request_iterator, timeout=None, **kwargs):
        return self._inner(self._wrap(request_iterator), timeout=timeout,
                           **kwargs)


class ChaosChannel:
    """Channel proxy whose multicallables inject the plan."""

    def __init__(self, inner: grpc.Channel, target, origin, state: ChaosState):
        self._inner = inner
        self._target = target
        self._origin = origin
        self._state = state

    def unary_unary(self, path, request_serializer=None,
                    response_deserializer=None, **kwargs):
        call = self._inner.unary_unary(
            path, request_serializer=request_serializer,
            response_deserializer=response_deserializer, **kwargs)
        method = path.rsplit("/", 1)[-1]
        return _ChaosCallable(call, method, self._target, self._origin,
                              self._state)

    def stream_stream(self, path, request_serializer=None,
                      response_deserializer=None, **kwargs):
        call = self._inner.stream_stream(
            path, request_serializer=request_serializer,
            response_deserializer=response_deserializer, **kwargs)
        method = path.rsplit("/", 1)[-1]
        return _ChaosStreamCallable(call, method, self._target, self._origin,
                                    self._state)

    def close(self) -> None:
        self._inner.close()

    def __getattr__(self, item):  # subscribe, unary_stream, ... pass through
        return getattr(self._inner, item)


# -- module-level installation -----------------------------------------------

_STATE: Optional[ChaosState] = None
_STATE_LOCK = threading.Lock()


def install(plan, metrics=None, armed: bool = True) -> ChaosState:
    """Install a plan (FaultPlan or spec string) for this process.  Every
    channel `rpc.service.new_channel` creates from now on is wrapped."""
    global _STATE
    if isinstance(plan, str):
        plan = parse_plan(plan)
    with _STATE_LOCK:
        _STATE = ChaosState(plan, metrics=metrics, armed=armed)
        return _STATE


def uninstall() -> None:
    global _STATE
    with _STATE_LOCK:
        _STATE = None


def state() -> Optional[ChaosState]:
    return _STATE


def wrap_channel(channel: grpc.Channel, target, origin=None):
    """Wrap `channel` if a plan is installed; otherwise return it as-is.
    Called by rpc.service.new_channel — production code never imports this."""
    st = _STATE
    if st is None:
        return channel
    return ChaosChannel(channel, target, origin, st)


def name_endpoint(host: str, port: int, name: str) -> None:
    """Register a human name ('w2', 'master') for an endpoint so partition
    specs can reference it; no-op when no plan is installed."""
    st = _STATE
    if st is not None:
        st.name_endpoint(host, port, name)


def arm() -> None:
    """Start the installed plan's fault clock (see ChaosState.arm)."""
    st = _STATE
    if st is not None:
        st.arm()
