from distributed_sgd_tpu.models.linear import (  # noqa: F401
    LeastSquares,
    LinearModel,
    LogisticRegression,
    SparseSVM,
    make_model,
)
