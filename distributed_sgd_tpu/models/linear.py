"""Linear model family on sparse batches: hinge SVM, logistic, least squares.

``SparseSVM`` reproduces the reference model exactly, sign quirks included
(core/ml/SparseSVM.scala:14-31):

- ``forward(w, x) = signum(x . w) * (-1)``            (SparseSVM.scala:14)
- ``loss(pred, y) = max(0, 1 - y * pred)``            (SparseSVM.scala:16)
- objective ``lambda * ||w||^2 + mean sample loss``   (SparseSVM.scala:20-23)
- subgradient ``backward(w,x,y) = 0 if y*(x.w) < 0 else y*x``
                                                      (SparseSVM.scala:26-29)
- ``regularize(g, w) = g + 1[g != 0] * (lambda*2*(w . dimSparsity))``
                                                      (SparseSVM.scala:31)

The `1[g != 0]` factor mirrors `Vec.valueLike`: the reference adds the
scalar only at the sparse gradient's stored keys (Vec.scala:60-75), and
Sparse construction drops |x| <= 1e-20 entries (Sparse.scala:104-114), so
"stored keys" == "nonzero after summation" — which `g != 0` reproduces.

Known reference quirk NOT reproduced: the reference's dimSparsity vector is
built on 0-based indices while data vectors keep the file's 1-based feature
ids (Main.scala:54-65 `buff(idx - 1)` vs Dataset.scala:24-33), so its
`w . dimSparsity` mixes shifted coordinates.  We index consistently
(0-based everywhere); the regularizer magnitude is unchanged to first
order.  Documented here so the parity delta is a known quantity.

All models share the structure: per-sample gradient = coeff(margin, y) * x,
so a whole-batch gradient is one `scatter_add` — the design that lets the
entire backward pass compile to gather + elementwise + segment-sum on TPU,
replacing the reference's per-sample boxed map loop (Slave.scala:147-152).

LogisticRegression and LeastSquares are documented capability supersets
(BASELINE.md configs 3 and 5; the reference ships hinge only).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sgd_tpu.ops import mxu
from distributed_sgd_tpu.ops.sparse import SparseBatch, matvec, scatter_add


class LinearModel:
    """Shared machinery: margins, batched gradients, regularization.

    Subclasses define `predict(margins)`, `sample_loss(preds, y)` and
    `grad_coeff(margins, y)` as pure jnp functions.  `regularizer` is one of
    'dim_sparsity' (reference parity), 'l2' (standard 2*lam*w), 'none'.
    """

    def __init__(
        self,
        lam: float,
        n_features: int,
        dim_sparsity: Optional[jax.Array] = None,
        regularizer: str = "dim_sparsity",
    ):
        self.lam = float(lam)
        self.n_features = int(n_features)
        self.regularizer = regularizer
        if regularizer == "dim_sparsity":
            if dim_sparsity is None:
                raise ValueError("dim_sparsity regularizer needs the dim_sparsity vector")
            self.dim_sparsity = jnp.asarray(dim_sparsity, dtype=jnp.float32)
        else:
            self.dim_sparsity = None

    # -- abstract ----------------------------------------------------------
    def predict(self, margins: jax.Array) -> jax.Array:
        raise NotImplementedError

    def sample_loss(self, preds: jax.Array, y: jax.Array) -> jax.Array:
        raise NotImplementedError

    def grad_coeff(self, margins: jax.Array, y: jax.Array) -> jax.Array:
        raise NotImplementedError

    # -- shared ------------------------------------------------------------
    def margins(self, w: jax.Array, batch: SparseBatch) -> jax.Array:
        if batch.is_dense:
            return self.margins_dense(w, batch.values)
        return matvec(batch, w)

    def sample_losses(self, w: jax.Array, batch: SparseBatch, y: jax.Array) -> jax.Array:
        """Per-sample losses (no regularization term), vectorized."""
        return self.losses_from_margins(self.margins(w, batch), y)

    def losses_from_margins(self, margins: jax.Array, y: jax.Array) -> jax.Array:
        """Per-sample losses given precomputed margins — lets eval paths
        compute margins with whichever kernel fits the weight layout."""
        return self.sample_loss(self.predict(margins), y)

    def forward(self, w: jax.Array, batch: SparseBatch) -> jax.Array:
        return self.predict(self.margins(w, batch))

    def objective(self, w: jax.Array, batch: SparseBatch, y: jax.Array) -> jax.Array:
        """lambda*||w||^2 + mean sample loss (SparseSVM.scala:20-23)."""
        preds = self.forward(w, batch)
        reg = self.lam * jnp.sum(w.astype(jnp.float32) ** 2)
        return reg + jnp.mean(self.sample_loss(preds, y))

    def accuracy(self, w: jax.Array, batch: SparseBatch, y: jax.Array) -> jax.Array:
        """fraction(forward == y) (Master.scala:98-101)."""
        return jnp.mean((self.forward(w, batch) == y.astype(jnp.float32)).astype(jnp.float32))

    def grad_sum(self, w: jax.Array, batch: SparseBatch, y: jax.Array) -> jax.Array:
        """Sum of per-sample backward over the batch (Slave.scala:147-153)."""
        if batch.is_dense:
            return self.grad_dense(w, batch.values, y, reduce="sum")
        coeff = self.grad_coeff(self.margins(w, batch), y)
        return scatter_add(batch, coeff, self.n_features)

    def grad_mean(self, w: jax.Array, batch: SparseBatch, y: jax.Array) -> jax.Array:
        """Mean of per-sample backward (async path, Slave.scala:93-98)."""
        return self.grad_sum(w, batch, y) / batch.batch_size

    # -- dense fast path ----------------------------------------------------
    #
    # When rows are fully dense (Dataset.dense layout: values[B, D], no
    # index array), gather/scatter degenerate to plain matmuls — the shape
    # the MXU was built for.  Same math as the sparse kernels on a row
    # whose indices are arange(D) (BASELINE.md config 5).

    def margins_dense(self, w: jax.Array, x: jax.Array) -> jax.Array:
        """Per-sample dots for dense rows: x[B, D] @ w[D].

        Precision HIGHEST keeps f32 products on TPU (default matmul
        precision would truncate operands to bf16), preserving the
        invariant that every kernel backend produces identical updates up
        to float summation order (sync.py docstring)."""
        return jnp.dot(
            x.astype(jnp.float32), w.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        )

    def grad_dense(
        self, w: jax.Array, x: jax.Array, y: jax.Array, reduce: str = "sum"
    ) -> jax.Array:
        """Batched backward for dense rows: coeff[B] @ x[B, D] — one MXU
        matmul replacing gather + scatter (Slave.scala:147-153 semantics)."""
        coeff = self.grad_coeff(self.margins_dense(w, x), y)
        if reduce == "mean":
            coeff = coeff / x.shape[0]
        return jnp.dot(
            coeff.astype(jnp.float32), x.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        )

    def regularize(self, grad: jax.Array, w: jax.Array) -> jax.Array:
        """SparseSVM.scala:31 semantics (see module docstring)."""
        if self.regularizer == "dim_sparsity":
            scalar = self.lam * 2.0 * jnp.dot(
                w.astype(jnp.float32), self.dim_sparsity
            )
            return grad + jnp.where(grad != 0, scalar, 0.0)
        if self.regularizer == "l2":
            return grad + 2.0 * self.lam * w
        return grad

    # -- blocked (MXU one-hot) fast path -----------------------------------
    #
    # Same math on the [R, 128] lane-blocked weight view (ops/mxu.py):
    # the training engines keep weights blocked across their compiled scans
    # and convert at the jit boundary.  Semantics match the scalar path
    # bit-for-bit up to float summation order.

    @property
    def dim_sparsity_blocked(self) -> Optional[jax.Array]:
        if self.dim_sparsity is None:
            return None
        if not hasattr(self, "_ds_blocked_np"):
            # cache the HOST array; the jnp conversion must happen inside
            # each trace (caching a traced array would leak the tracer)
            self._ds_blocked_np = mxu.to_blocked_np(
                np.asarray(self.dim_sparsity), self.n_features
            )
        return jnp.asarray(self._ds_blocked_np)

    def margins_blocked(self, w2: jax.Array, batch: SparseBatch) -> jax.Array:
        return mxu.matvec(batch, w2)

    def grad_blocked(
        self, w2: jax.Array, batch: SparseBatch, y: jax.Array, reduce: str = "sum"
    ) -> jax.Array:
        """Batched backward on blocked weights: one fused gather + coeff +
        scatter with the one-hot operands built once (ops/mxu.py).

        reduce='sum' is the sync worker reply (Slave.scala:147-153);
        reduce='mean' is the async local step (Slave.scala:93-98).
        """
        oh = mxu.OneHotBatch(batch, w2.shape[0])
        coeff = self.grad_coeff(oh.margins(w2), y)
        if reduce == "mean":
            coeff = coeff / batch.batch_size
        return oh.scatter_add(coeff)

    def grad_regularized(
        self,
        w: jax.Array,
        batch: SparseBatch,
        y: jax.Array,
        reduce: str = "sum",
        blocked: bool = False,
    ) -> jax.Array:
        """Dense-in/dense-out worker gradient (backward reduce + regularize,
        Slave.scala:142-157): one entry point for callers that hold dense
        weights, routed through the blocked MXU kernels when `blocked`.
        Engines that carry blocked weights across a scan call the blocked
        methods directly instead.  Dense-layout batches route to the
        plain-matmul fast path regardless of `blocked`."""
        if batch.is_dense:
            g = self.grad_dense(w, batch.values, y, reduce=reduce)
            return self.regularize(g, w)
        if blocked:
            w2 = mxu.to_blocked(w, self.n_features)
            g2 = self.grad_blocked(w2, batch, y, reduce=reduce)
            return mxu.from_blocked(self.regularize_blocked(g2, w2), self.n_features)
        g = self.grad_sum(w, batch, y) if reduce == "sum" else self.grad_mean(w, batch, y)
        return self.regularize(g, w)

    def regularize_blocked(self, g2: jax.Array, w2: jax.Array) -> jax.Array:
        """`regularize` on the blocked view; zero pad lanes stay zero
        because the scalar is only added where g2 != 0."""
        if self.regularizer == "dim_sparsity":
            scalar = self.lam * 2.0 * jnp.sum(
                w2.astype(jnp.float32) * self.dim_sparsity_blocked
            )
            return g2 + jnp.where(g2 != 0, scalar, 0.0)
        if self.regularizer == "l2":
            return g2 + 2.0 * self.lam * w2
        return g2


class SparseSVM(LinearModel):
    """Reference-exact hinge model (see module docstring)."""

    def predict(self, margins: jax.Array) -> jax.Array:
        # signum(x.w) * -1  (SparseSVM.scala:14); preds in {-1, 0, +1}
        return jnp.sign(margins) * -1.0

    def sample_loss(self, preds: jax.Array, y: jax.Array) -> jax.Array:
        return jnp.maximum(0.0, 1.0 - y.astype(jnp.float32) * preds)

    def grad_coeff(self, margins: jax.Array, y: jax.Array) -> jax.Array:
        # backward = 0 if y*(x.w) < 0 else y*x  (SparseSVM.scala:26-29)
        yf = y.astype(jnp.float32)
        activity = yf * margins
        return jnp.where(activity < 0, 0.0, yf)


class LogisticRegression(LinearModel):
    """Binary logistic loss on +/-1 labels (superset; BASELINE.md config 3)."""

    def predict(self, margins: jax.Array) -> jax.Array:
        return jnp.where(margins >= 0, 1.0, -1.0)

    def sample_loss(self, preds: jax.Array, y: jax.Array) -> jax.Array:
        del preds  # logistic loss is margin-based; see losses_from_margins
        raise NotImplementedError("use losses_from_margins()/objective()")

    def losses_from_margins(self, margins: jax.Array, y: jax.Array) -> jax.Array:
        yf = y.astype(jnp.float32)
        return jnp.logaddexp(0.0, -yf * margins)  # log(1 + exp(-y m)), stable

    def objective(self, w: jax.Array, batch: SparseBatch, y: jax.Array) -> jax.Array:
        reg = self.lam * jnp.sum(w.astype(jnp.float32) ** 2)
        return reg + jnp.mean(self.sample_losses(w, batch, y))

    def grad_coeff(self, margins: jax.Array, y: jax.Array) -> jax.Array:
        yf = y.astype(jnp.float32)
        return -yf * jax.nn.sigmoid(-yf * margins)


class LeastSquares(LinearModel):
    """Squared-error regression (superset; BASELINE.md config 5)."""

    def predict(self, margins: jax.Array) -> jax.Array:
        return margins

    def sample_loss(self, preds: jax.Array, y: jax.Array) -> jax.Array:
        return (preds - y.astype(jnp.float32)) ** 2

    def grad_coeff(self, margins: jax.Array, y: jax.Array) -> jax.Array:
        return 2.0 * (margins - y.astype(jnp.float32))

    def accuracy(self, w: jax.Array, batch: SparseBatch, y: jax.Array) -> jax.Array:
        # accuracy is meaningless for regression; report negative MSE
        preds = self.forward(w, batch)
        return -jnp.mean((preds - y.astype(jnp.float32)) ** 2)


def make_model(
    name: str,
    lam: float,
    n_features: int,
    dim_sparsity: Optional[jax.Array] = None,
    regularizer: Optional[str] = None,
) -> LinearModel:
    kinds = {
        "hinge": SparseSVM,
        "svm": SparseSVM,
        "logistic": LogisticRegression,
        "least_squares": LeastSquares,
    }
    if name not in kinds:
        raise ValueError(f"unknown model {name!r}; choose from {sorted(kinds)}")
    if regularizer is None:
        regularizer = "dim_sparsity" if dim_sparsity is not None else "l2"
    return kinds[name](lam, n_features, dim_sparsity=dim_sparsity, regularizer=regularizer)
