"""Collate per-process trace files into one openable timeline.

Each traced process writes ``trace-<service>-<pid>.json`` under
``DSGD_TRACE_DIR`` (trace/__init__.py).  This tool merges them into a
single Chrome/Perfetto trace-event JSON — every record carries
``args.trace_id``, so a multi-process round (master window + worker
server spans + serving calls) lands on one coherent timeline; node
identity renders as one ``pid`` lane per node.

Usage:

    python -m distributed_sgd_tpu.trace.merge [DIR] [-o OUT]
        [--trace-id ID] [--list] [--profile-dir DIR]

- ``DIR``            directory of trace-*.json files (default:
                     $DSGD_TRACE_DIR, else ".")
- ``-o OUT``         output path (default: DIR/merged-trace.json)
- ``--trace-id ID``  keep only one trace (one round end to end); metadata
                     records are always kept so lanes stay named
- ``--list``         print the distinct trace ids (with span counts and
                     root span names) instead of writing a merge
- ``--profile-dir``  correlate with a jax.profiler capture
                     (DSGD_PROFILE_DIR): the device-side
                     ``*.trace.json.gz`` files found there are listed and
                     recorded in the merged file's ``otherData`` so the
                     two timelines can be opened side by side in Perfetto

Open the result at https://ui.perfetto.dev (or chrome://tracing).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def read_events(path: str) -> List[dict]:
    """One trace file -> its event list (accepts both the wrapped
    {"traceEvents": [...]} object form and a bare JSON array)."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        return list(data.get("traceEvents", []))
    return list(data)


def trace_files(dir: str) -> List[str]:
    return sorted(glob.glob(os.path.join(dir, "trace-*.json")))


def merge_paths(paths: List[str], trace_id: Optional[str] = None) -> dict:
    """Concatenate + time-sort the given files' events; with `trace_id`,
    keep only that trace's records (plus 'M' metadata, which carries the
    process-name lanes)."""
    events: List[dict] = []
    for p in paths:
        events.extend(read_events(p))
    if trace_id is not None:
        events = [e for e in events
                  if e.get("ph") == "M"
                  or e.get("args", {}).get("trace_id") == trace_id]
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("ph") != "M"))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"sources": paths}}


def merge_dir(dir: str, trace_id: Optional[str] = None) -> dict:
    return merge_paths(trace_files(dir), trace_id=trace_id)


def list_traces(events: List[dict]) -> Dict[str, dict]:
    """trace_id -> {spans, events, roots} summary."""
    out: Dict[str, dict] = {}
    for e in events:
        tid = e.get("args", {}).get("trace_id")
        if tid is None:
            continue
        entry = out.setdefault(tid, {"spans": 0, "events": 0, "roots": set()})
        if e.get("ph") == "X":
            entry["spans"] += 1
            if not e.get("args", {}).get("parent_id"):
                entry["roots"].add(e.get("name", "?"))
        elif e.get("ph") == "i":
            entry["events"] += 1
    return out


def profile_captures(profile_dir: str) -> List[str]:
    """jax.profiler output files worth opening next to the merge."""
    pats = ("**/*.trace.json.gz", "**/*.xplane.pb")
    found: List[str] = []
    for pat in pats:
        found.extend(glob.glob(os.path.join(profile_dir, pat), recursive=True))
    return sorted(found)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_sgd_tpu.trace.merge",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("dir", nargs="?",
                    default=os.environ.get("DSGD_TRACE_DIR", "."))
    ap.add_argument("-o", "--out", default=None)
    ap.add_argument("--trace-id", default=None)
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--profile-dir",
                    default=os.environ.get("DSGD_PROFILE_DIR"))
    args = ap.parse_args(argv)

    paths = trace_files(args.dir)
    if not paths:
        log(f"no trace-*.json files under {args.dir!r} "
            f"(run with DSGD_TRACE=1 and DSGD_TRACE_DIR set)")
        return 1
    merged = merge_paths(paths, trace_id=args.trace_id)
    log(f"{len(paths)} file(s), {len(merged['traceEvents'])} event(s)"
        + (f" for trace {args.trace_id}" if args.trace_id else ""))

    if args.list:
        for tid, info in sorted(list_traces(merged["traceEvents"]).items()):
            roots = ",".join(sorted(info["roots"])) or "?"
            print(f"{tid}  spans={info['spans']} events={info['events']} "
                  f"root={roots}")
        return 0

    if args.profile_dir:
        captures = profile_captures(args.profile_dir)
        merged["otherData"]["jax_profile_captures"] = captures
        if captures:
            log(f"jax.profiler captures to open alongside "
                f"({len(captures)}): " + ", ".join(captures[:4])
                + (" ..." if len(captures) > 4 else ""))
        else:
            log(f"no jax.profiler captures under {args.profile_dir!r}")

    out = args.out or os.path.join(args.dir, "merged-trace.json")
    with open(out, "w") as f:
        json.dump(merged, f)
    log(f"wrote {out} — open it at https://ui.perfetto.dev")
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
