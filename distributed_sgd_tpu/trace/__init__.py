"""Distributed tracing: per-round span timelines across master / worker /
serving processes (docs/OBSERVABILITY.md).

The repo's metrics (utils/metrics.py) reproduce the reference's AGGREGATE
observability surface; after the quorum/chaos layers the interesting
failures are CAUSAL — a stalled barrier, a hedge that lost to a late
reply, a chaos-injected delay masquerading as a slow kernel.  This module
is the Dapper-style answer: spans with a `TraceContext` propagated across
process boundaries via gRPC invocation metadata (rpc/service.py — the
proto wire stays byte-identical), exported as Chrome/Perfetto
trace-event JSON, one file per process, collated by
``python -m distributed_sgd_tpu.trace.merge``.

Design rules:

- **Default-off, zero-cost off.**  With no tracer configured every public
  entry point returns the shared ``NOOP_SPAN`` singleton after one module
  global read — no Span object is ever allocated
  (tests/test_trace.py asserts this by making Span.__init__ raise).
- **Head sampling per trace_id** (``DSGD_TRACE_SAMPLE``): the keep/drop
  decision is a pure function of the trace_id, so a sampled round is
  traced end-to-end on every node it touches — the master decides once
  per round and only sampled rounds ever put metadata on the wire, so
  workers need no local decision at all.
- **One trace per causal unit**: a sync fan-out window (one per
  step_version), an eval fan-out, a serving batch, an async gossip
  dispatch, a checkpoint save.  Chaos injections and quorum events attach
  as instant events inside the owning trace, so an injected fault is
  visibly attributed in the timeline.

Chrome trace-event mapping: spans are ``"ph": "X"`` complete events
(``ts`` wall-clock microseconds, ``dur`` from a perf_counter pair),
events are ``"ph": "i"`` instants; every record carries
``args.trace_id`` so the merge tool can collate and filter.  Node
identity (master / w0 / serve:PORT) maps onto the ``pid`` lane with a
``process_name`` metadata record, so a single-process DevCluster still
renders one lane per node in Perfetto.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
import zlib
from typing import Dict, List, NamedTuple, Optional, Tuple

# gRPC invocation-metadata key carrying "trace_id-span_id" (lowercase
# ASCII per the gRPC metadata rules); absence = untraced call
METADATA_KEY = "dsgd-trace"

# -- span/event name constants (consistency-tested like the metrics
# constants: tests/test_observability.py greps that each is recorded) ------
SPAN_SYNC_WINDOW = "sync.window"        # master: one fan-out round
SPAN_EVAL_FORWARD = "eval.forward"      # master: one predict fan-out
EVENT_QUORUM_DEGRADED = "quorum.degraded"  # round closed < full strength
EVENT_QUORUM_HEDGE = "quorum.hedge"        # hedge request issued
EVENT_QUORUM_HEDGE_WIN = "quorum.hedge_win"  # slice covered by a hedge
EVENT_QUORUM_LATE = "quorum.late"          # late reply discarded
EVENT_BARRIER_STALLED = "barrier.stalled"  # soft deadline overrun, no relief
EVENT_BCAST_STALE = "bcast.stale"          # stale replica -> full fallback
EVENT_EF_ROLLBACK = "ef.rollback"          # worker rolled back an EF drain
EVENT_TOPOLOGY_RESELECT = "topology.reselect"  # gossip edge re-routed past a breaker
EVENT_HEALTH_TRIPPED = "health.tripped"        # training-health watchdog trip
EVENT_AUTOPILOT_TRANSITION = "autopilot.transition"  # flywheel state change
EVENT_SCATTER_SELECTED = "kernel.scatter"      # which scatter formulation ran
EVENT_LEAK_SUSPECT = "leak.suspect"            # resource-slope sentinel trip


class TraceContext(NamedTuple):
    """Propagated identity of one span: (trace_id, span_id, parent_id)."""

    trace_id: str
    span_id: str
    parent_id: str = ""


class _NoopSpan:
    """Shared do-nothing span for every sampled-off / tracing-off path.
    A singleton: the fast path allocates NOTHING (asserted by test)."""

    __slots__ = ()
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def event(self, name: str, **args) -> None:
        pass

    def set(self, **args) -> None:
        pass

    def end(self, error: Optional[str] = None) -> None:
        pass


NOOP_SPAN = _NoopSpan()

_local = threading.local()


def _stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current() -> Optional[TraceContext]:
    """The calling thread's active TraceContext (None outside any span)."""
    st = getattr(_local, "stack", None)
    return st[-1][0] if st else None


def current_node() -> Optional[str]:
    """The node label of the calling thread's active span, if any."""
    st = getattr(_local, "stack", None)
    return st[-1][1] if st else None


class Span:
    """One timed operation.  Created ONLY by a live Tracer for a sampled
    trace; `end()` is idempotent and may run on any thread (client RPC
    spans end from gRPC future callbacks).  Entering as a context manager
    additionally installs the span as the thread's current context."""

    __slots__ = ("_tracer", "name", "ctx", "node", "args",
                 "_t0_wall_ns", "_t0_pc", "_ended", "_entered")

    def __init__(self, tracer: "Tracer", name: str, ctx: TraceContext,
                 node: str, args: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.ctx = ctx
        self.node = node
        self.args = dict(args) if args else {}
        self._t0_wall_ns = time.time_ns()
        self._t0_pc = time.perf_counter()
        self._ended = False
        self._entered = False

    def set(self, **args) -> None:
        self.args.update(args)

    def event(self, name: str, **args) -> None:
        """Attach an instant event inside this span's trace."""
        self._tracer._emit_instant(name, self.ctx, self.node, args)

    def end(self, error: Optional[str] = None) -> None:
        if self._ended:
            return
        self._ended = True
        if error is not None:
            self.args["error"] = error
        dur_us = (time.perf_counter() - self._t0_pc) * 1e6
        self._tracer._emit_span(self, dur_us)

    def __enter__(self) -> "Span":
        _stack().append((self.ctx, self.node))
        self._entered = True
        return self

    def __exit__(self, etype, evalue, tb):
        if self._entered:
            _stack().pop()
            self._entered = False
        self.end(error=repr(evalue) if evalue is not None else None)
        return False


class Tracer:
    """Per-process span collector writing one Chrome trace-event file."""

    MAX_EVENTS = 200_000  # hard buffer cap; beyond it spans are counted, dropped

    def __init__(self, dir: Optional[str] = None, sample: float = 1.0,
                 service: Optional[str] = None):
        self.dir = dir
        self.sample = float(sample)
        self.service = service or f"proc-{os.getpid()}"
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._dropped = 0
        self._pids: Dict[str, int] = {}
        self._ids = threading.local()
        self.path = None
        if dir:
            os.makedirs(dir, exist_ok=True)
            self.path = os.path.join(
                dir, f"trace-{self.service}-{os.getpid()}.json")

    # -- ids / sampling ------------------------------------------------------

    def _new_id(self) -> str:
        # cheap per-thread counter mixed with entropy once per thread: ids
        # must be unique, not unguessable
        st = self._ids
        base = getattr(st, "base", None)
        if base is None:
            base = st.base = os.urandom(6).hex()
            st.n = 0
        st.n += 1
        return f"{base}{st.n:x}"

    def sampled(self, trace_id: str) -> bool:
        """Deterministic head sampling: a pure function of the trace_id, so
        every process keeps or drops the same rounds."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        return zlib.crc32(trace_id.encode()) / 2**32 < self.sample

    # -- span construction ---------------------------------------------------

    def root_span(self, name: str, node: Optional[str] = None, **args):
        """Start a NEW trace (fresh trace_id, head-sampled)."""
        trace_id = self._new_id()
        if not self.sampled(trace_id):
            return NOOP_SPAN
        ctx = TraceContext(trace_id, self._new_id(), "")
        return Span(self, name, ctx, node or self.service, args)

    def child_span(self, name: str, parent: TraceContext,
                   node: Optional[str] = None, **args):
        ctx = TraceContext(parent.trace_id, self._new_id(), parent.span_id)
        return Span(self, name, ctx, node or current_node() or self.service,
                    args)

    def span(self, name: str, node: Optional[str] = None, root: bool = True,
             **args):
        """Child of the thread's current context; with no context, a new
        sampled root when ``root=True`` (a designated causal unit) or
        NOOP_SPAN when ``root=False`` (a helper span: rooting here would
        emit orphan one-span fragment traces on every unsampled or
        untraced call — the sampling decision belongs to the unit that
        owns the round)."""
        parent = current()
        if parent is None:
            if not root:
                return NOOP_SPAN
            return self.root_span(name, node=node, **args)
        return self.child_span(name, parent, node=node, **args)

    # -- emit ----------------------------------------------------------------

    def _pid_for(self, node: str) -> int:
        with self._lock:
            pid = self._pids.get(node)
            if pid is None:
                pid = 1 + zlib.crc32(node.encode()) % 1_000_000
                self._pids[node] = pid
                self._events.append({
                    "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                    "args": {"name": node},
                })
        return pid

    def _append(self, record: dict) -> None:
        with self._lock:
            if len(self._events) >= self.MAX_EVENTS:
                self._dropped += 1
                return
            self._events.append(record)

    def _emit_span(self, span: Span, dur_us: float) -> None:
        args = span.args
        args["trace_id"] = span.ctx.trace_id
        args["span_id"] = span.ctx.span_id
        if span.ctx.parent_id:
            args["parent_id"] = span.ctx.parent_id
        self._append({
            "ph": "X", "name": span.name, "cat": "dsgd",
            "ts": span._t0_wall_ns / 1000.0, "dur": dur_us,
            "pid": self._pid_for(span.node), "tid": threading.get_native_id(),
            "args": args,
        })

    def _emit_instant(self, name: str, ctx: TraceContext, node: str,
                      args: dict) -> None:
        args = dict(args)
        args["trace_id"] = ctx.trace_id
        args["span_id"] = ctx.span_id
        self._append({
            "ph": "i", "name": name, "cat": "dsgd", "s": "t",
            "ts": time.time_ns() / 1000.0,
            "pid": self._pid_for(node), "tid": threading.get_native_id(),
            "args": args,
        })

    # -- export --------------------------------------------------------------

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def buffered(self) -> int:
        """Events currently held (lock-free: len() of a list is GIL-atomic).
        The resource probe's trace-buffer pressure gauge — a buffer that
        only ever grows until flush is exactly the kind of slow fill the
        long-horizon plane exists to see."""
        return len(self._events)

    def flush(self) -> Optional[str]:
        """Write the full buffer as one Chrome trace-event JSON file
        (atomic replace; repeat flushes rewrite the same path)."""
        if self.path is None:
            return None
        with self._lock:
            snapshot = list(self._events)
            dropped = self._dropped
        payload = {"traceEvents": snapshot, "displayTimeUnit": "ms",
                   "otherData": {"service": self.service,
                                 "pid": os.getpid(),
                                 "dropped_events": dropped}}
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)
        return self.path


# -- module-level configuration (the zero-cost gate) --------------------------
#
# _TRACER is None when tracing is off; every hot-path helper checks that
# one global before doing anything else.  main.py configures from
# DSGD_TRACE / DSGD_TRACE_DIR / DSGD_TRACE_SAMPLE; tests call configure()
# directly.

_TRACER: Optional[Tracer] = None
_ATEXIT_REGISTERED = False


def configure(enabled: bool = False, dir: Optional[str] = None,
              sample: float = 1.0, service: Optional[str] = None
              ) -> Optional[Tracer]:
    """Install (or remove, enabled=False) the process tracer."""
    global _TRACER, _ATEXIT_REGISTERED
    if not enabled:
        _TRACER = None
        return None
    _TRACER = Tracer(dir=dir, sample=sample, service=service)
    if not _ATEXIT_REGISTERED:
        _ATEXIT_REGISTERED = True
        atexit.register(flush)
    return _TRACER


def active() -> Optional[Tracer]:
    return _TRACER


def span(name: str, node: Optional[str] = None, root: bool = True, **args):
    """Child span of the current context (or, with ``root=True``, a new
    sampled root); NOOP_SPAN when tracing is off, and also when
    ``root=False`` with no active context."""
    t = _TRACER
    if t is None:
        return NOOP_SPAN
    return t.span(name, node=node, root=root, **args)


def root_span(name: str, node: Optional[str] = None, **args):
    """Always a NEW trace (one per causal unit); NOOP_SPAN when off."""
    t = _TRACER
    if t is None:
        return NOOP_SPAN
    return t.root_span(name, node=node, **args)


def event(name: str, **args) -> None:
    """Instant event inside the current trace; dropped when tracing is off
    or no trace is active (event volume stays tied to sampled traces)."""
    t = _TRACER
    if t is None:
        return
    ctx = current()
    if ctx is None:
        return
    t._emit_instant(name, ctx, current_node() or t.service, args)


def event_in(ctx: Optional[TraceContext], name: str,
             node: Optional[str] = None, **args) -> None:
    """Instant event inside an EXPLICIT context — for callbacks that run
    after the owning thread moved on (e.g. a late quorum reply settling
    on a gRPC thread).  Capture `current()` where the context is live and
    pass it here; no-op when off or ctx is None."""
    t = _TRACER
    if t is None or ctx is None:
        return
    t._emit_instant(name, ctx, node or t.service, args)


def flush() -> Optional[str]:
    t = _TRACER
    return t.flush() if t is not None else None


# -- cross-process propagation ------------------------------------------------


def inject(ctx: TraceContext) -> Tuple[Tuple[str, str], ...]:
    """TraceContext -> gRPC invocation-metadata pairs."""
    return ((METADATA_KEY, f"{ctx.trace_id}-{ctx.span_id}"),)


def extract(metadata) -> Optional[TraceContext]:
    """gRPC invocation metadata -> the SENDER's TraceContext (used as the
    parent of the server-side span), or None when untraced."""
    if not metadata:
        return None
    for key, value in metadata:
        if key == METADATA_KEY:
            trace_id, sep, span_id = value.rpartition("-")
            if not sep or not trace_id or not span_id:
                # malformed header: leave the call untraced rather than
                # fabricate a parentless context (it would render as a
                # spurious second root in the merged timeline)
                return None
            return TraceContext(trace_id, span_id, "")
    return None
