"""Flight recorder: a bounded ring of recent structured events per
process, dumped to JSON post-mortem (docs/OBSERVABILITY.md).

Tracing (trace/__init__.py) answers "show me round N end to end" — but it
is sampled and default-off, and the runs that die are rarely the runs
someone thought to trace.  The flight recorder is the always-on black
box: every notable control-plane event (quorum degradation, hedges,
breaker trips, chaos injections, evictions, EF rollbacks) is appended to
a bounded ``deque`` — a single GIL-atomic append, no locks on the record
path — and the most recent ``capacity`` events are written to a JSON file
when something goes wrong:

- ``SIGUSR2`` (install_signal_handler; `kill -USR2 <pid>` on a live run),
- worker eviction (core/master.py unregister_worker(evicted=True)),
- below-quorum degradation of a sync window (core/master.py fit_sync),
- an uncaught exception in an engine loop (worker async loop, serving
  batcher, main.py role runner).

Events carry BOTH a monotonic timestamp (ordering across events survives
wall-clock jumps) and a wall timestamp (correlation with logs).  Dumps
overwrite per-(service, pid, reason) paths, so a repeating fault leaves a
bounded number of files.  ``DSGD_FLIGHT_RECORDER`` sets the capacity
(default 512; 0 disables recording entirely).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from collections import deque
from typing import List, Optional

log = logging.getLogger("dsgd.flight")

DEFAULT_CAPACITY = 512
# where un-configured recorders dump: DSGD_TRACE_DIR when the environment
# names one (so subprocess children — test workers, bench fits — inherit
# the redirect without running any configure() of their own), else next to
# the process, the classic black-box location.  Also overridable
# process-wide (tests/conftest.py does both) so harnesses keep evidence
# out of their CWD.
DEFAULT_DIR = os.environ.get("DSGD_TRACE_DIR") or "."


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 service: Optional[str] = None, dir: Optional[str] = None):
        self.capacity = max(0, int(capacity))
        self.service = service or f"proc-{os.getpid()}"
        self.dir = dir or DEFAULT_DIR
        # deque.append with maxlen is a single GIL-atomic operation: the
        # record path takes no lock (the lock below only serializes dumps)
        self._buf: deque = deque(maxlen=self.capacity or 1)
        self._dump_lock = threading.Lock()
        self._last_dump: dict = {}  # reason -> monotonic time, for throttling

    def record(self, kind: str, **fields) -> None:
        if self.capacity <= 0:
            return
        fields["t_mono"] = time.monotonic()
        fields["t_wall"] = time.time()
        fields["kind"] = kind
        self._buf.append(fields)

    def snapshot(self) -> List[dict]:
        return list(self._buf)

    def ring_len(self) -> int:
        """Events currently held (lock-free; len() of a deque is
        GIL-atomic).  The resource probe's flight-ring pressure gauge."""
        return len(self._buf)

    def dump(self, reason: str,
             min_interval_s: float = 0.0) -> Optional[str]:
        """Write the ring's current contents; returns the path (None when
        disabled or throttled).  `min_interval_s` rate-limits repeated
        dumps of the SAME reason — a caller in a hot loop (e.g. every
        below-quorum window of a long partition) keeps fresh evidence at
        a bounded I/O cost.  Never raises — a post-mortem writer that
        throws would mask the original failure."""
        if self.capacity <= 0:
            return None
        if min_interval_s > 0.0:
            with self._dump_lock:
                last = self._last_dump.get(reason, -float("inf"))
                if time.monotonic() - last < min_interval_s:
                    return None
                self._last_dump[reason] = time.monotonic()
        path = os.path.join(
            self.dir, f"flight-{self.service}-{os.getpid()}-{reason}.json")
        payload = {
            "service": self.service,
            "pid": os.getpid(),
            "reason": reason,
            "dumped_at_mono": time.monotonic(),
            "dumped_at_wall": time.time(),
            "capacity": self.capacity,
            "resources": self._resources(),
            "events": self.snapshot(),
        }
        try:
            with self._dump_lock:
                os.makedirs(self.dir, exist_ok=True)
                tmp = f"{path}.tmp"
                with open(tmp, "w") as f:
                    json.dump(payload, f, default=str)
                os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001 - never mask the original failure
            log.warning("flight-recorder dump (%s) failed: %s", reason, e)
            return None
        log.warning("flight recorder dumped %d event(s) -> %s",
                    len(payload["events"]), path)
        return path

    @staticmethod
    def _resources() -> Optional[dict]:
        """Resource snapshot for the dump payload: every quorum/eviction/
        crash dump carries RSS/fd/thread context for free (ISSUE 20).
        Lazy import (dump is the cold path; record must stay import-free)
        and guarded — a sampling failure must not break a post-mortem."""
        try:
            from distributed_sgd_tpu.telemetry import resources
            return resources.sample_resources()
        except Exception:  # noqa: BLE001 - never mask the original failure
            return None


_RECORDER: Optional[FlightRecorder] = None
_LOCK = threading.Lock()


def get() -> FlightRecorder:
    """The process recorder (default-on at DEFAULT_CAPACITY: a dead run
    leaves evidence even when nobody configured anything)."""
    global _RECORDER
    r = _RECORDER
    if r is None:
        with _LOCK:
            r = _RECORDER
            if r is None:
                r = _RECORDER = FlightRecorder()
    return r


def configure(capacity: int = DEFAULT_CAPACITY, service: Optional[str] = None,
              dir: Optional[str] = None) -> FlightRecorder:
    """Replace the process recorder (DSGD_FLIGHT_RECORDER wiring; 0
    disables recording)."""
    global _RECORDER
    with _LOCK:
        _RECORDER = FlightRecorder(capacity=capacity, service=service, dir=dir)
        return _RECORDER


def record(kind: str, **fields) -> None:
    get().record(kind, **fields)


def dump(reason: str, min_interval_s: float = 0.0) -> Optional[str]:
    return get().dump(reason, min_interval_s=min_interval_s)


def install_signal_handler(signum: int = signal.SIGUSR2) -> bool:
    """SIGUSR2 -> dump('sigusr2').  Returns False (and stays silent) when
    handlers cannot be installed here (non-main thread, platforms without
    the signal).

    The handler defers the dump to a short-lived thread: CPython runs
    signal handlers on the main thread between bytecodes, so dumping
    inline would deadlock on the non-reentrant ``_dump_lock`` (or the
    logging lock) whenever the signal lands while the main thread itself
    is inside ``dump()`` — e.g. the below-quorum dump of a long chaos
    partition."""

    def _handler(_signum, _frame):
        threading.Thread(target=dump, args=("sigusr2",),
                         name="flight-sigusr2-dump", daemon=True).start()

    try:
        signal.signal(signum, _handler)
        return True
    except (ValueError, AttributeError, OSError):
        return False
