"""RCV1 dataset loading, packing, and statistics.

TPU-native re-design of the reference loader (utils/Dataset.scala:13-59)
and the dimSparsity pass (Main.scala:54-65):

- text parsing goes through the native C++ chunked parser
  (data/_native/parser.cpp) with a pure-numpy fallback, instead of Scala
  parallel collections over boxed maps;
- rows land in flat CSR, then are packed once into fixed-shape
  ``int32[N, P]`` / ``f32[N, P]`` padded arrays — the representation the
  TPU kernels (ops/sparse.py) consume; P defaults to the dataset's max nnz
  (lossless), or can be capped (rows are then truncated by largest |value|);
- feature ids are converted to 0-based at parse time.  The reference keeps
  the file's 1-based ids (Dataset.scala:24-33) while building dimSparsity
  0-based (Main.scala:63 ``buff(idx - 1)``) — we index consistently instead
  (see models/linear.py docstring for the parity note);
- label binarization reproduces the reference exactly, including the
  last-topic-wins quirk: ``readLabels(...).toMap`` (Dataset.scala:36-45,53)
  keeps only the LAST qrels line per doc id, so a doc in CCAT *and* any
  later-sorted topic (E*/G*/M*) binarizes to -1;
- the 80/20 split is contiguous ``splitAt(0.8 * n)`` (Main.scala:52).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from distributed_sgd_tpu.data import _native

log = logging.getLogger("dsgd.data")

N_FEATURES = 47236  # Dataset.scala:16


@dataclass
class Dataset:
    """A packed sparse dataset: fixed-shape host arrays ready for device."""

    indices: np.ndarray  # int32[N, P], 0-based feature ids, 0-padded
    values: np.ndarray  # f32[N, P], 0.0-padded
    labels: np.ndarray  # int32[N], +/-1 (or float for regression)
    n_features: int

    def __post_init__(self):
        # a zero-width index array IS the dense-layout discriminator
        # (batches carry no n_features, so width 0 must imply dense
        # everywhere); sparse sets always pad to width >= 1 (pack_csr)
        if self.indices.shape[1] == 0 and self.values.shape[1] != self.n_features:
            raise ValueError(
                "zero-width indices mean dense layout: values must span all "
                f"{self.n_features} features, got width {self.values.shape[1]}"
            )

    def __len__(self) -> int:
        return self.values.shape[0]

    @property
    def pad_width(self) -> int:
        return self.values.shape[1]

    @property
    def is_dense(self) -> bool:
        """Dense layout: no index array (zero-width), values hold every
        feature.  Engines route these rows through plain-matmul kernels
        (models/linear.py dense fast path) instead of gather/scatter, and
        the int32 index array — which would double the footprint — is never
        materialized."""
        return self.indices.shape[1] == 0 and self.values.shape[1] == self.n_features

    @classmethod
    def dense(cls, values: np.ndarray, labels: np.ndarray) -> "Dataset":
        """Build a dense-layout dataset from values[N, D] + labels[N]."""
        values = np.ascontiguousarray(values, dtype=np.float32)
        return cls(
            indices=np.empty((values.shape[0], 0), dtype=np.int32),
            values=values,
            labels=np.asarray(labels),
            n_features=values.shape[1],
        )

    def slice(self, sel) -> "Dataset":
        return Dataset(self.indices[sel], self.values[sel], self.labels[sel], self.n_features)


def parse_svm_file_py(path: str, index_offset: int = -1):
    """Pure-python fallback parser -> (doc_ids, row_ptr, col_idx, values).

    Same format handling as the reference (Dataset.scala:19-34): first token
    is the doc id, remaining `f:v` tokens are features (the reference's
    `drop(2)` skips the empty token from the double space after the id;
    we split on arbitrary whitespace instead).  Streams line by line: a
    GIL-bound thread pool buys nothing for pure-python parsing, so the
    reference's chunk parallelism (.grouped(4096).par, Dataset.scala:21-22)
    lives in the native parser's threads and load_rcv1's per-file pool
    fan-out instead.
    """
    doc_ids: List[int] = []
    row_nnz: List[int] = []
    cols: List[int] = []
    vals: List[float] = []
    with open(path, "r") as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            doc_ids.append(int(parts[0]))
            n = 0
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                k, v = tok.split(":", 1)
                cols.append(int(k) + index_offset)
                vals.append(float(v))
                n += 1
            row_nnz.append(n)
    row_ptr = np.zeros(len(doc_ids) + 1, dtype=np.int64)
    np.cumsum(row_nnz, out=row_ptr[1:])
    return (
        np.asarray(doc_ids, dtype=np.int32),
        row_ptr,
        np.asarray(cols, dtype=np.int32),
        np.asarray(vals, dtype=np.float32),
    )


def parse_svm_file(path: str, index_offset: int = -1, n_threads: int = 0):
    """Native parser with python fallback."""
    out = _native.parse_svm_file(path, n_threads=n_threads, index_offset=index_offset)
    if out is None:
        out = parse_svm_file_py(path, index_offset=index_offset)
    return out


def read_labels(path: str) -> Dict[int, int]:
    """qrels 'topic docid 1' -> {docid: +/-1}, CCAT -> +1, last line wins.

    Reproduces Dataset.scala:36-45,53 including the Iterator.toMap
    overwrite semantics (see module docstring).
    """
    labels: Dict[int, int] = {}
    with open(path, "r") as f:
        for line in f:
            parts = line.split()
            if len(parts) < 2:
                continue
            labels[int(parts[1])] = 1 if parts[0] == "CCAT" else -1
    return labels


def pack_csr(
    row_ptr: np.ndarray,
    col_idx: np.ndarray,
    values: np.ndarray,
    pad_width: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """CSR -> padded [N, P] arrays.

    P defaults to max row nnz (lossless).  If a smaller P is forced, the
    affected rows keep their P largest-|value| features.  Uses the native
    row-loop pack (data/_native parser.cpp dsgd_pack_csr) when the library
    is available — the numpy scatter below was the slowest stage of
    full-scale loading — with identical output, truncation ties included.
    """
    nnz = np.diff(row_ptr).astype(np.int64)
    n = len(nnz)
    max_nnz = int(nnz.max()) if n else 0
    # width >= 1 always: a zero-width index array is the dense-layout
    # discriminator (Dataset.is_dense), so an all-empty-rows sparse set
    # pads to width 1 instead
    p = int(pad_width) if pad_width else max(max_nnz, 1)

    native = _native.pack_csr(row_ptr, col_idx, values, p)
    if native is not None:
        out_idx, out_val, truncated = native
    else:
        out_idx = np.zeros((n, p), dtype=np.int32)
        out_val = np.zeros((n, p), dtype=np.float32)
        pos_in_row = np.arange(len(col_idx), dtype=np.int64) - np.repeat(row_ptr[:-1], nnz)
        row_of = np.repeat(np.arange(n, dtype=np.int64), nnz)
        if max_nnz <= p:
            out_idx[row_of, pos_in_row] = col_idx
            out_val[row_of, pos_in_row] = values
            return out_idx, out_val
        over = np.nonzero(nnz > p)[0]
        keep = pos_in_row < p
        over_mask = np.isin(row_of, over)
        fast = keep & ~over_mask
        out_idx[row_of[fast], pos_in_row[fast]] = col_idx[fast]
        out_val[row_of[fast], pos_in_row[fast]] = values[fast]
        for r in over:  # rare rows: keep heaviest features, index-sorted
            s, e = row_ptr[r], row_ptr[r + 1]
            ci, cv = col_idx[s:e], values[s:e]
            sel = np.argsort(-np.abs(cv), kind="stable")[:p]  # ties: earliest wins
            sel.sort()
            out_idx[r, :p] = ci[sel]
            out_val[r, :p] = cv[sel]
        truncated = len(over)
    if truncated:
        log.warning("pad_width=%d truncated %d/%d rows (max nnz %d)", p, truncated, n, max_nnz)
    return out_idx, out_val


def merge_parts(parts) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Concatenate per-file (doc_ids, row_ptr, col_idx, values) CSR parts
    into one CSR with a rebuilt global row_ptr."""
    doc_ids = np.concatenate([p[0] for p in parts])
    col_idx = np.concatenate([p[2] for p in parts])
    values = np.concatenate([p[3] for p in parts])
    row_ptr = np.zeros(len(doc_ids) + 1, dtype=np.int64)
    np.cumsum(np.concatenate([np.diff(p[1]) for p in parts]), out=row_ptr[1:])
    return doc_ids, row_ptr, col_idx, values


def dim_sparsity(train: "Dataset") -> np.ndarray:
    """Inverse-document-frequency vector: 1/(count_i + 1) where feature i
    appears in the train split, else 0 (Main.scala:54-65)."""
    if train.is_dense:
        counts = (train.values != 0).sum(axis=0)
    else:
        idx = train.indices[train.values != 0]
        counts = np.bincount(idx.ravel(), minlength=train.n_features)
    out = np.zeros(train.n_features, dtype=np.float32)
    nz = counts > 0
    out[nz] = 1.0 / (counts[nz] + 1.0)
    return out


def train_test_split(data: "Dataset") -> Tuple["Dataset", "Dataset"]:
    """Contiguous 80/20 split (Main.scala:52)."""
    cut = int(len(data) * 0.8)
    return data.slice(slice(0, cut)), data.slice(slice(cut, None))


def load_rcv1(
    folder: str,
    full: bool = False,
    n_features: int = N_FEATURES,
    pad_width: Optional[int] = None,
    n_threads: int = 0,
) -> "Dataset":
    """Load RCV1 from `folder` (same file set as Dataset.scala:47-50)."""
    files = [os.path.join(folder, "lyrl2004_vectors_train.dat")]
    if full:
        files += [os.path.join(folder, f"lyrl2004_vectors_test_pt{d}.dat") for d in range(4)]
    labels_map = read_labels(os.path.join(folder, "rcv1-v2.topics.qrels"))

    # With auto threading (n_threads=0) and several files, fan out one parse
    # per file on the shared pool — the native parser releases the GIL
    # inside the ctypes call, so files parse concurrently (the reference's
    # .par chunk parallelism, one level up) — and split the core budget so
    # concurrent parses don't oversubscribe.  An EXPLICIT n_threads is a
    # per-parse budget: honor it with sequential parses.
    cores = os.cpu_count() or 1
    if n_threads == 0 and len(files) > 1 and cores >= 2 * len(files):
        from distributed_sgd_tpu.utils.pool import global_pool

        per_file = cores // len(files)
        parts = global_pool().map(
            lambda f: parse_svm_file(f, n_threads=per_file), files
        )
    else:
        parts = [parse_svm_file(f, n_threads=n_threads) for f in files]
    doc_ids, row_ptr, col_idx, values = merge_parts(parts)

    idx, val = pack_csr(row_ptr, col_idx, values, pad_width=pad_width)
    y = np.asarray([labels_map[int(d)] for d in doc_ids], dtype=np.int32)
    return Dataset(indices=idx, values=val, labels=y, n_features=n_features)
