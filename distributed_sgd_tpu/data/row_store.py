"""mmap-backed binary row store: file-backed RowReader for the real corpus.

The host-local loading discipline (data/host_shard.py, docs/HIERARCHY.md)
needs a ``RowReader`` — ``read_rows(start, stop) -> Dataset`` over global
row ids — but until now the only readers were in-memory
(``dataset_reader``), so the no-egress CLI worker role still had to parse
and MATERIALIZE the whole corpus before slicing it (ROADMAP item 1c).
This module closes that gap with a packed binary row store:

- **built once** from the native/python parser output (``build_row_store``
  packs a parsed ``Dataset``; ``build_from_corpus`` runs the parser first
  — the same ``load_rcv1`` path benches/real_rcv1.py gates), every row a
  FIXED-STRIDE record ``idx int32[P] | val f32[P] | label`` (dense
  layout: ``val f32[D] | label``) — the exact padded representation the
  engines consume, so reading is reshaping, not parsing;
- **offsets sidecar** ``<store>.meta.json`` records the layout (row
  stride, payload offset, shapes, dtypes: row i lives at
  ``payload_offset + i * row_stride_bytes``), so any process can map the
  store without touching the parser; an optional ``<store>.ds.npy``
  sidecar carries the train split's dim-sparsity vector so a worker can
  build its model without scanning the corpus;
- **read_rows = one seek + one contiguous read**: the store is mmap'd and
  a row range is one contiguous record slice — the OS pages in exactly
  the requested extent, nothing else.  Per-store ``rows_read`` /
  ``bytes_read`` counters make the O(delta) reload claims assertable
  (tests/test_row_store.py, ``bench.py --spinup``).

A worker role with ``DSGD_ROW_STORE=<store>`` (and optionally
``DSGD_HOST_INDEX=i``) spins up by mapping the store and loading ONLY its
host slice through ``RowStore.reader`` — the real-RCV1 no-egress worker
finally loads host-locally instead of materializing 800k rows.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from distributed_sgd_tpu.data.rcv1 import Dataset
from distributed_sgd_tpu.utils.fsio import atomic_write_json

MAGIC = "dsgd-row-store"
VERSION = 1


def meta_path(path: str) -> str:
    """The offsets-sidecar path for a store at `path` — the ONE place the
    naming convention lives (consumers check existence through this)."""
    return path + ".meta.json"


_meta_path = meta_path  # internal alias


def _ds_path(path: str) -> str:
    return path + ".ds.npy"


def _record_dtype(pad_width: int, n_features: int,
                  labels_dtype: str) -> np.dtype:
    """The fixed-stride per-row record.  pad_width == 0 is the dense-layout
    discriminator (data/rcv1.py): no index array, values span every
    feature."""
    lab = np.dtype(labels_dtype)
    if pad_width == 0:
        return np.dtype([("val", "<f4", (n_features,)), ("lab", lab)])
    return np.dtype([("idx", "<i4", (pad_width,)),
                     ("val", "<f4", (pad_width,)), ("lab", lab)])


def build_row_store(data: Dataset, path: str,
                    train_rows: Optional[int] = None,
                    dim_sparsity: Optional[np.ndarray] = None) -> dict:
    """Pack `data` into the store at `path` (+ its meta sidecar); returns
    the written metadata.  `train_rows` records the corpus's contiguous
    train-split cut (Main.scala:52's 0.8 * n) so host slices can be
    computed over the TRAIN rows without re-deriving the split; the
    optional `dim_sparsity` vector lands in the `.ds.npy` sidecar."""
    lab_dtype = np.dtype(data.labels.dtype)
    if lab_dtype not in (np.dtype(np.int32), np.dtype(np.float32)):
        raise ValueError(
            f"labels dtype {lab_dtype} not storable (int32/float32 only)")
    pad_width = 0 if data.is_dense else data.pad_width
    rec = _record_dtype(pad_width, data.n_features, lab_dtype.name)
    arr = np.zeros(len(data), dtype=rec)
    if pad_width:
        arr["idx"] = data.indices
    arr["val"] = data.values
    arr["lab"] = data.labels
    # pid-unique tmp names: concurrent builders (several CLI workers
    # finding the store missing on a shared volume at the same moment)
    # each write their own complete file and the atomic os.replace makes
    # last-writer-wins safe — the build is deterministic from the corpus,
    # so every winner installs identical bytes.  A FIXED tmp name would
    # let the second open() truncate the first writer's partial file and
    # keep writing through the inode the first os.replace installs.
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        arr.tofile(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    meta = {
        "magic": MAGIC,
        "version": VERSION,
        "n_rows": int(len(data)),
        "n_features": int(data.n_features),
        "pad_width": int(pad_width),
        "labels_dtype": lab_dtype.name,
        "row_stride_bytes": int(rec.itemsize),
        "payload_offset": 0,
        # row i's record: payload_offset + i * row_stride_bytes
        "train_rows": int(train_rows if train_rows is not None
                          else len(data)),
    }
    atomic_write_json(_meta_path(path), meta)
    if dim_sparsity is not None:
        # same atomic discipline as the payload/meta: a reader that saw
        # the meta sidecar land must never np.load a half-written vector
        ds_tmp = f"{_ds_path(path)}.tmp.{os.getpid()}.npy"
        np.save(ds_tmp, np.asarray(dim_sparsity, np.float32))
        os.replace(ds_tmp, _ds_path(path))
    return meta


def build_from_corpus(folder: str, path: str, full: bool = False,
                      pad_width: Optional[int] = None,
                      n_threads: int = 0) -> dict:
    """Parse the corpus in `folder` (native parser with python fallback —
    data/rcv1.py load_rcv1) and build the store from it, recording the
    80/20 train cut and the train split's dim-sparsity vector.  This is
    the ONE parse the store's consumers amortize."""
    from distributed_sgd_tpu.data.rcv1 import (
        dim_sparsity,
        load_rcv1,
        train_test_split,
    )

    data = load_rcv1(folder, full=full, pad_width=pad_width,
                     n_threads=n_threads)
    train, _ = train_test_split(data)
    return build_row_store(data, path, train_rows=len(train),
                           dim_sparsity=dim_sparsity(train))


class RowStore:
    """Read side: an mmap over the packed records.

    ``read_rows(start, stop)`` returns a zero-copy ``Dataset`` view over
    the record slice — one seek + one contiguous read's worth of pages.
    The instance counts ``rows_read``/``bytes_read``/``calls`` so callers
    (tests, ``bench.py --spinup``) can assert exactly how much of the
    corpus a spin-up or reload touched."""

    def __init__(self, path: str):
        if not os.path.exists(_meta_path(path)):
            raise FileNotFoundError(
                f"row store sidecar missing: {_meta_path(path)} (build one "
                f"with data.row_store.build_from_corpus)")
        with open(_meta_path(path)) as f:
            meta = json.load(f)
        if meta.get("magic") != MAGIC or meta.get("version") != VERSION:
            raise ValueError(
                f"not a v{VERSION} {MAGIC} sidecar: {_meta_path(path)}")
        self.path = path
        self.meta = meta
        self.n_rows = int(meta["n_rows"])
        self.n_features = int(meta["n_features"])
        self.pad_width = int(meta["pad_width"])
        self.train_rows = int(meta["train_rows"])
        self.labels_dtype = np.dtype(meta["labels_dtype"])
        self._rec = _record_dtype(self.pad_width, self.n_features,
                                  meta["labels_dtype"])
        if int(meta["row_stride_bytes"]) != self._rec.itemsize:
            raise ValueError(
                f"row stride {meta['row_stride_bytes']} != record size "
                f"{self._rec.itemsize}: sidecar/payload layout mismatch")
        expect = meta["payload_offset"] + self.n_rows * self._rec.itemsize
        actual = os.path.getsize(path)
        if actual < expect:
            raise ValueError(
                f"row store truncated: {actual} bytes < {expect} expected")
        self._mm = np.memmap(path, dtype=self._rec, mode="r",
                             offset=int(meta["payload_offset"]),
                             shape=(self.n_rows,))
        self.rows_read = 0
        self.bytes_read = 0
        self.calls = 0

    def __len__(self) -> int:
        return self.n_rows

    def read_rows(self, start: int, stop: int) -> Dataset:
        """Rows [start, stop) as a Dataset view over the mmap (zero copy:
        consumers that keep the rows copy them into their own buffers,
        e.g. load_host_shard)."""
        if not 0 <= start <= stop <= self.n_rows:
            raise ValueError(
                f"row range [{start}, {stop}) outside [0, {self.n_rows}]")
        view = self._mm[start:stop]
        self.calls += 1
        self.rows_read += stop - start
        self.bytes_read += (stop - start) * self._rec.itemsize
        if self.pad_width == 0:
            idx = np.empty((stop - start, 0), dtype=np.int32)
        else:
            idx = view["idx"]
        return Dataset(indices=idx, values=view["val"], labels=view["lab"],
                       n_features=self.n_features)

    @property
    def reader(self):
        """This store as a data/host_shard.py ``RowReader``."""
        return self.read_rows

    def dim_sparsity(self) -> Optional[np.ndarray]:
        """The train split's dim-sparsity sidecar, or None if the store
        was built without one."""
        if not os.path.exists(_ds_path(self.path)):
            return None
        return np.load(_ds_path(self.path))
