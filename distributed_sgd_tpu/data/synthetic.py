"""Synthetic datasets for tests and benchmarks.

`rcv1_like` generates a packed sparse classification set with RCV1-shaped
statistics (cosine-normalized rows, ~76 nnz per row over 47,236 features by
default) from a planted linear separator with label noise — used wherever
the real RCV1 files are unavailable (no network egress) and by BASELINE.md
config 5's dense least-squares problem via `dense_regression`.
"""

from __future__ import annotations

import numpy as np

from distributed_sgd_tpu.data.rcv1 import Dataset


def rcv1_like(
    n_samples: int,
    n_features: int = 47236,
    nnz: int = 76,
    noise: float = 0.05,
    seed: int = 0,
    idf_values: bool = False,
) -> Dataset:
    """Planted-separator sparse classification data, packed [N, P].

    `idf_values=True` weights each entry by its feature's inverse document
    frequency (log(N/df)) before the cosine normalization — the ltc
    (log-TF x IDF, cosine) scheme the REAL RCV1-v2 vectors use (LYRL2004).
    Without it, head (Zipf-popular) features carry the same magnitude
    distribution as tail ones, which real term weighting never allows —
    the difference that decides whether the reference's lr=0.5 converges
    smoothly (see BASELINE.md, Zipf-oscillation study).
    """
    rng = np.random.default_rng(seed)
    # Zipf-ish feature popularity like term frequencies
    pop = 1.0 / np.arange(1, n_features + 1, dtype=np.float64)
    pop /= pop.sum()
    idx = rng.choice(n_features, size=(n_samples, nnz), p=pop).astype(np.int32)
    idx.sort(axis=1)
    val = np.abs(rng.normal(size=(n_samples, nnz))).astype(np.float32)
    # real RCV1 rows (and the reference's Map-backed vectors) cannot hold
    # duplicate feature ids: zero out repeat draws, leaving inert pad slots
    dup = np.zeros_like(idx, dtype=bool)
    dup[:, 1:] = idx[:, 1:] == idx[:, :-1]
    if idf_values:
        # DOCUMENT frequency: each feature counts once per row (dedup via
        # the sorted-duplicate mask), so df <= n_samples and idf >= 0 —
        # collection frequency would exceed n_samples for mid-head Zipf
        # features and log(N/df) would go negative, zeroing terms real
        # ltc/IDF (LYRL2004) only down-weights
        df = np.bincount(idx[~dup], minlength=n_features)
        idf = np.log(n_samples / np.maximum(df, 1.0)).astype(np.float32)
        val *= idf[idx]
    val[dup] = 0.0
    val /= np.maximum(np.linalg.norm(val, axis=1, keepdims=True), 1e-12)  # cosine norm

    w_true = rng.normal(size=n_features).astype(np.float32)
    margins = np.einsum("np,np->n", val, w_true[idx])
    y = np.where(margins > np.median(margins), 1, -1).astype(np.int32)
    flip = rng.random(n_samples) < noise
    y[flip] = -y[flip]
    return Dataset(indices=idx, values=val, labels=y, n_features=n_features)


def dense_regression(
    n_samples: int,
    n_features: int = 1024,
    noise: float = 0.01,
    seed: int = 0,
) -> Dataset:
    """Dense least-squares data in the dense layout (BASELINE.md config 5).

    Uses `Dataset.dense`: values[N, D] only, no index array — engines route
    it through the plain-matmul kernels (models/linear.py dense fast path)
    and the int32 indices that would double the footprint never exist.
    """
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n_samples, n_features)).astype(np.float32)
    w_true = rng.normal(size=n_features).astype(np.float32)
    y = x @ w_true + noise * rng.normal(size=n_samples).astype(np.float32)
    return Dataset.dense(x, y.astype(np.float32))
