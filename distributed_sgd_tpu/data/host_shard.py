"""Host-local shard loading: no host materializes the global corpus.

The engines' resident-dataset layout pads the corpus to
``parallel/sync.py padded_layout(n, n_devices, eval_chunk)`` rows and
shards it equally over the device mesh, so each host owns one contiguous
padded row range (``parallel/multihost.py host_shard_bounds``).  This
module turns that bound into a first-class loader: a host hands in a
``RowReader`` — any callable ``read_rows(start, stop) -> Dataset`` over
GLOBAL row ids — and gets back exactly its padded extent, with the real
rows read in ONE clipped call and every padding row (index >= n_samples)
materialized as an all-zero row with label 0 (the engines' validity
mask).  Peak rows touched per host == the host's ``host_shard_bounds``
extent, asserted by tests/test_host_shard.py.

Consumers:

- the multi-host mesh path: ``SyncEngine.bind`` routes its per-host
  padding through ``load_host_shard`` (full-dataset reader), and
  ``SyncEngine.bind_host_local`` / ``parallel/multihost.py
  host_local_sharded`` build ``ShardedData`` straight from a reader so
  the global arrays never exist on any single host
  (tests/test_multihost_4proc.py);
- the hierarchical RPC topology (docs/HIERARCHY.md): ``host_slice`` maps
  a worker's position in the master's host-granular contiguous split to
  the rows it must load, and ``WorkerNode(data_offset=...)`` maps the
  master's global sample ids back into the slice.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Tuple

import numpy as np

from distributed_sgd_tpu.data.rcv1 import Dataset

# read_rows(start, stop) -> Dataset holding global rows [start, stop);
# start/stop are pre-clipped to [0, n_samples]
RowReader = Callable[[int, int], Dataset]


def dataset_reader(data: Dataset) -> RowReader:
    """A RowReader over an in-memory dataset (tests, in-process dev
    clusters — the memory win is a no-op there by construction)."""
    return lambda start, stop: data.slice(slice(start, stop))


def _read_into(reader: RowReader, r0: int, r1: int, idx, val, lab,
               dst: slice, pad_width: int, n_features: int) -> None:
    """ONE validated reader call copied into the output buffers at `dst`:
    the single place reader results are checked — row count, packed
    shape, and a lossless labels cast — shared by the initial loader and
    the incremental reload path."""
    part = reader(r0, r1)
    if len(part) != r1 - r0:
        raise ValueError(
            f"reader returned {len(part)} rows for [{r0}, {r1})")
    if (part.indices.shape[1] != pad_width
            or part.n_features != n_features):
        raise ValueError(
            f"reader shape ({part.indices.shape[1]}, "
            f"{part.n_features}) != expected "
            f"({pad_width}, {n_features})")
    if not np.can_cast(part.labels.dtype, lab.dtype, casting="same_kind"):
        # float regression targets into an int buffer would truncate
        # silently — the caller must pass the corpus's labels_dtype
        # (every host the same: the global array needs one dtype)
        raise ValueError(
            f"reader labels are {part.labels.dtype} but the shard "
            f"buffer is {lab.dtype}: pass labels_dtype="
            f"{part.labels.dtype}")
    idx[dst] = part.indices
    val[dst] = part.values
    lab[dst] = part.labels


def load_host_shard(
    reader: RowReader,
    n_samples: int,
    n_features: int,
    pad_width: int,
    start: int,
    end: int,
    labels_dtype=np.int32,
) -> Dataset:
    """Materialize padded rows [start, end) of the engine's padded row
    space: real rows come from ONE ``reader`` call clipped to the corpus,
    padding rows are all-zero with label 0 (a zero row contributes zero
    gradient in every model and the label-0 mask excludes it from eval).

    The returned dataset holds exactly ``end - start`` rows — the host's
    full resident footprint.  Nothing outside [start, min(end, n)) is
    ever requested from the reader.
    """
    if not 0 <= start <= end:
        raise ValueError(f"bad shard bounds [{start}, {end})")
    extent = end - start
    real_start = min(start, n_samples)
    real_stop = min(end, n_samples)
    # pad_width == 0 is the dense-layout discriminator (data/rcv1.py):
    # zero-width indices, values spanning every feature
    val_width = n_features if pad_width == 0 else pad_width
    idx = np.zeros((extent, pad_width), dtype=np.int32)
    val = np.zeros((extent, val_width), dtype=np.float32)
    lab = np.zeros((extent,), dtype=labels_dtype)
    if real_stop > real_start:
        _read_into(reader, real_start, real_stop, idx, val, lab,
                   slice(0, real_stop - real_start), pad_width, n_features)
    return Dataset(indices=idx, values=val, labels=lab,
                   n_features=n_features)


def host_slice(n_samples: int, host_index: int, n_hosts: int,
               weights: Optional[List[int]] = None) -> Tuple[int, int]:
    """[start, end) of host `host_index`'s rows under the master's
    host-granular contiguous split (docs/HIERARCHY.md).

    Mirrors core/split.py exactly: the unweighted form is vanilla_split's
    ``grouped(ceil(n/k))`` bounds; with per-host device `weights` it is
    weighted_split's largest-remainder layout.  A worker that loads only
    this range (``load_host_shard`` + ``WorkerNode(data_offset=start)``)
    serves every sample id the master can ever draw for it — as long as
    membership matches the planned topology (a resplit after a host loss
    redraws partitions the survivors' slices cannot cover; host-local
    deployments pair with on_worker_death='fail' or full reloads).
    """
    if not 0 <= host_index < n_hosts:
        raise ValueError(f"host_index {host_index} outside [0, {n_hosts})")
    # derive bounds from the ACTUAL split functions the master runs —
    # re-implementing their arithmetic here would let the worker's
    # resident slice drift from the master's partitions the moment either
    # changes, and every mismatched sample id is a worker eviction
    from distributed_sgd_tpu.core.split import vanilla_split, weighted_split

    parts = (vanilla_split(n_samples, n_hosts) if weights is None
             else weighted_split(n_samples, weights))
    part = parts[host_index]
    if len(part) == 0:
        at = sum(len(p) for p in parts[:host_index])
        return at, at
    return int(part[0]), int(part[-1]) + 1


def overprovision_margin(span: int, overprovision: float) -> int:
    """Rows of neighbor range loaded beyond each end of a nominal span of
    `span` rows: ceil(f * span), 0 when the knob is off."""
    if overprovision <= 0 or span <= 0:
        return 0
    return int(math.ceil(float(overprovision) * span))


def overprovisioned_slice(
    n_samples: int, host_index: int, n_hosts: int,
    overprovision: float = 0.0,
    weights: Optional[List[int]] = None,
) -> Tuple[int, int, int, int]:
    """(load_start, load_end, start, end): the host's nominal ``host_slice``
    bounds [start, end) widened by ``ceil(f * span)`` rows of NEIGHBOR
    range on each side, clipped to the corpus (DSGD_HOST_OVERPROVISION,
    docs/HIERARCHY.md "Elastic composition").

    The over-provisioned rows are the elastic slack: a membership change
    of up to ``f * n / n_hosts`` rows per boundary (one host joining or
    leaving an H-host split moves each boundary by at most n/H — so
    f >= 1/(H-1) covers a single leave, f >= 1/(H+1) a single join)
    re-splits WITHIN the already-resident range and costs the worker zero
    reload; a bigger shift re-loads only the uncovered delta through the
    worker's RowReader (``reload_slice``)."""
    start, end = host_slice(n_samples, host_index, n_hosts, weights=weights)
    margin = overprovision_margin(end - start, overprovision)
    return (max(0, start - margin), min(n_samples, end + margin),
            start, end)


def reload_slice(
    current: Dataset,
    current_start: int,
    reader: RowReader,
    n_samples: int,
    n_features: int,
    pad_width: int,
    new_start: int,
    new_end: int,
    labels_dtype=None,
) -> Tuple[Dataset, int]:
    """Incremental re-shard: materialize rows [new_start, new_end) reusing
    every overlapping row of `current` (resident rows
    [current_start, current_start + len(current))) and reading ONLY the
    uncovered delta ranges through `reader` — at most two clipped calls
    (left gap, right gap), O(delta) rows total, asserted by
    tests/test_host_shard.py and gated by ``bench.py --spinup``.

    Returns (new resident dataset, rows_read).  Rows at index >=
    n_samples are padding (all-zero, label 0), exactly like
    ``load_host_shard``.
    """
    if not 0 <= new_start <= new_end:
        raise ValueError(f"bad shard bounds [{new_start}, {new_end})")
    if labels_dtype is None:
        labels_dtype = current.labels.dtype
    extent = new_end - new_start
    val_width = n_features if pad_width == 0 else pad_width
    idx = np.zeros((extent, pad_width), dtype=np.int32)
    val = np.zeros((extent, val_width), dtype=np.float32)
    lab = np.zeros((extent,), dtype=labels_dtype)
    cur_end = current_start + len(current)
    # overlap with the resident slice: a pure host-memory copy
    lo = max(new_start, current_start)
    hi = min(new_end, cur_end)
    if lo < hi:
        src = slice(lo - current_start, hi - current_start)
        dst = slice(lo - new_start, hi - new_start)
        if pad_width:
            idx[dst] = current.indices[src]
        val[dst] = current.values[src]
        lab[dst] = current.labels[src]
    rows_read = 0
    # uncovered deltas, clipped to the real corpus (everything past
    # n_samples is padding and costs nothing)
    gaps = []
    if lo >= hi:  # disjoint: the whole new range is one gap
        gaps.append((new_start, new_end))
    else:
        if new_start < lo:
            gaps.append((new_start, lo))
        if hi < new_end:
            gaps.append((hi, new_end))
    for g0, g1 in gaps:
        r0, r1 = min(g0, n_samples), min(g1, n_samples)
        if r0 >= r1:
            continue
        _read_into(reader, r0, r1, idx, val, lab,
                   slice(r0 - new_start, r1 - new_start), pad_width,
                   n_features)
        rows_read += r1 - r0
    return (Dataset(indices=idx, values=val, labels=lab,
                    n_features=n_features), rows_read)
