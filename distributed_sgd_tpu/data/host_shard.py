"""Host-local shard loading: no host materializes the global corpus.

The engines' resident-dataset layout pads the corpus to
``parallel/sync.py padded_layout(n, n_devices, eval_chunk)`` rows and
shards it equally over the device mesh, so each host owns one contiguous
padded row range (``parallel/multihost.py host_shard_bounds``).  This
module turns that bound into a first-class loader: a host hands in a
``RowReader`` — any callable ``read_rows(start, stop) -> Dataset`` over
GLOBAL row ids — and gets back exactly its padded extent, with the real
rows read in ONE clipped call and every padding row (index >= n_samples)
materialized as an all-zero row with label 0 (the engines' validity
mask).  Peak rows touched per host == the host's ``host_shard_bounds``
extent, asserted by tests/test_host_shard.py.

Consumers:

- the multi-host mesh path: ``SyncEngine.bind`` routes its per-host
  padding through ``load_host_shard`` (full-dataset reader), and
  ``SyncEngine.bind_host_local`` / ``parallel/multihost.py
  host_local_sharded`` build ``ShardedData`` straight from a reader so
  the global arrays never exist on any single host
  (tests/test_multihost_4proc.py);
- the hierarchical RPC topology (docs/HIERARCHY.md): ``host_slice`` maps
  a worker's position in the master's host-granular contiguous split to
  the rows it must load, and ``WorkerNode(data_offset=...)`` maps the
  master's global sample ids back into the slice.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from distributed_sgd_tpu.data.rcv1 import Dataset

# read_rows(start, stop) -> Dataset holding global rows [start, stop);
# start/stop are pre-clipped to [0, n_samples]
RowReader = Callable[[int, int], Dataset]


def dataset_reader(data: Dataset) -> RowReader:
    """A RowReader over an in-memory dataset (tests, in-process dev
    clusters — the memory win is a no-op there by construction)."""
    return lambda start, stop: data.slice(slice(start, stop))


def load_host_shard(
    reader: RowReader,
    n_samples: int,
    n_features: int,
    pad_width: int,
    start: int,
    end: int,
    labels_dtype=np.int32,
) -> Dataset:
    """Materialize padded rows [start, end) of the engine's padded row
    space: real rows come from ONE ``reader`` call clipped to the corpus,
    padding rows are all-zero with label 0 (a zero row contributes zero
    gradient in every model and the label-0 mask excludes it from eval).

    The returned dataset holds exactly ``end - start`` rows — the host's
    full resident footprint.  Nothing outside [start, min(end, n)) is
    ever requested from the reader.
    """
    if not 0 <= start <= end:
        raise ValueError(f"bad shard bounds [{start}, {end})")
    extent = end - start
    real_start = min(start, n_samples)
    real_stop = min(end, n_samples)
    # pad_width == 0 is the dense-layout discriminator (data/rcv1.py):
    # zero-width indices, values spanning every feature
    val_width = n_features if pad_width == 0 else pad_width
    idx = np.zeros((extent, pad_width), dtype=np.int32)
    val = np.zeros((extent, val_width), dtype=np.float32)
    lab = np.zeros((extent,), dtype=labels_dtype)
    if real_stop > real_start:
        real = reader(real_start, real_stop)
        n_real = real_stop - real_start
        if len(real) != n_real:
            raise ValueError(
                f"reader returned {len(real)} rows for "
                f"[{real_start}, {real_stop})")
        if (real.indices.shape[1] != pad_width
                or real.n_features != n_features):
            raise ValueError(
                f"reader shape ({real.indices.shape[1]}, "
                f"{real.n_features}) != expected "
                f"({pad_width}, {n_features})")
        if not np.can_cast(real.labels.dtype, lab.dtype,
                           casting="same_kind"):
            # float regression targets into an int buffer would truncate
            # silently — the caller must pass the corpus's labels_dtype
            # (every host the same: the global array needs one dtype)
            raise ValueError(
                f"reader labels are {real.labels.dtype} but the shard "
                f"buffer is {lab.dtype}: pass labels_dtype="
                f"{real.labels.dtype}")
        idx[:n_real] = real.indices
        val[:n_real] = real.values
        lab[:n_real] = real.labels
    return Dataset(indices=idx, values=val, labels=lab,
                   n_features=n_features)


def host_slice(n_samples: int, host_index: int, n_hosts: int,
               weights: Optional[List[int]] = None) -> Tuple[int, int]:
    """[start, end) of host `host_index`'s rows under the master's
    host-granular contiguous split (docs/HIERARCHY.md).

    Mirrors core/split.py exactly: the unweighted form is vanilla_split's
    ``grouped(ceil(n/k))`` bounds; with per-host device `weights` it is
    weighted_split's largest-remainder layout.  A worker that loads only
    this range (``load_host_shard`` + ``WorkerNode(data_offset=start)``)
    serves every sample id the master can ever draw for it — as long as
    membership matches the planned topology (a resplit after a host loss
    redraws partitions the survivors' slices cannot cover; host-local
    deployments pair with on_worker_death='fail' or full reloads).
    """
    if not 0 <= host_index < n_hosts:
        raise ValueError(f"host_index {host_index} outside [0, {n_hosts})")
    # derive bounds from the ACTUAL split functions the master runs —
    # re-implementing their arithmetic here would let the worker's
    # resident slice drift from the master's partitions the moment either
    # changes, and every mismatched sample id is a worker eviction
    from distributed_sgd_tpu.core.split import vanilla_split, weighted_split

    parts = (vanilla_split(n_samples, n_hosts) if weights is None
             else weighted_split(n_samples, weights))
    part = parts[host_index]
    if len(part) == 0:
        at = sum(len(p) for p in parts[:host_index])
        return at, at
    return int(part[0]), int(part[-1]) + 1
