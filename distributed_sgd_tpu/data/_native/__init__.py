"""ctypes loader for the native CSR parser, with build-on-first-use.

pybind11 is not available in this image, so the native parser exposes a C
ABI (parser.cpp) loaded via ctypes.  The shared library is compiled with
g++ on first use and cached next to the source, keyed by source mtime.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger("dsgd.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "parser.cpp")
_LIB = os.path.join(_DIR, "_libdsgd_parser.so")
_LOCK = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
# must match parser.cpp dsgd_abi_version(): the CsrResult struct layout
# (and any function signature) is pinned by this number
_ABI_VERSION = 2


class _CsrResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("nnz", ctypes.c_int64),
        ("doc_ids", ctypes.POINTER(ctypes.c_int32)),
        ("row_ptr", ctypes.POINTER(ctypes.c_int64)),
        ("col_idx", ctypes.POINTER(ctypes.c_int32)),
        ("values", ctypes.POINTER(ctypes.c_float)),
        ("skipped_lines", ctypes.c_int64),
    ]


def _abi_version(lib: ctypes.CDLL) -> int:
    """Library's reported ABI version; 0 if it predates the export."""
    try:
        fn = lib.dsgd_abi_version
    except AttributeError:
        return 0
    fn.restype = ctypes.c_int32
    fn.argtypes = []
    return int(fn())


def _build() -> None:
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-pthread", _SRC, "-o", _LIB,
    ]
    log.info("building native parser: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True)


def load() -> Optional[ctypes.CDLL]:
    """Build (if stale) and load the native library; None if unavailable."""
    global _lib
    with _LOCK:
        if _lib is not None:
            return _lib
        try:
            if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
                _build()
            lib = ctypes.CDLL(_LIB)
            if _abi_version(lib) != _ABI_VERSION:
                # stale prebuilt .so whose mtime survived COPY/rsync/tar:
                # an mtime check cannot see it, but reading the grown
                # CsrResult through the old layout would be out-of-bounds
                log.info("native parser ABI mismatch; rebuilding")
                _build()
                lib = ctypes.CDLL(_LIB)
                if _abi_version(lib) != _ABI_VERSION:
                    raise RuntimeError(
                        f"rebuilt native parser still reports ABI "
                        f"{_abi_version(lib)}, expected {_ABI_VERSION}")
            lib.dsgd_parse_svm.restype = ctypes.POINTER(_CsrResult)
            lib.dsgd_parse_svm.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int32]
            lib.dsgd_free_csr.argtypes = [ctypes.POINTER(_CsrResult)]
            lib.dsgd_free_csr.restype = None
            lib.dsgd_pack_csr.restype = ctypes.c_int64
            lib.dsgd_pack_csr.argtypes = [
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float),
            ]
            _lib = lib
        except Exception as e:  # missing toolchain etc. -> python fallback
            log.warning("native parser unavailable (%s); using python fallback", e)
            _lib = None
        return _lib


def parse_svm_file(
    path: str, n_threads: int = 0, index_offset: int = -1
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Parse with the native library. Returns (doc_ids, row_ptr, col_idx,
    values) as owned numpy arrays, or None if the native path is unavailable.
    """
    lib = load()
    if lib is None:
        return None
    res = lib.dsgd_parse_svm(path.encode(), n_threads, index_offset)
    if not res:
        raise IOError(f"native parser failed to open {path!r}")
    try:
        r = res.contents
        n, nnz = r.n_rows, r.nnz
        if r.skipped_lines:
            # The python fallback (and the reference, Dataset.scala:24) raise
            # on a non-numeric doc id; the native scanner drops such lines.
            # Surface the count so the divergence is observable.
            log.warning(
                "native parser skipped %d malformed line(s) in %s (python "
                "fallback would raise on these)", r.skipped_lines, path)
        doc_ids = np.ctypeslib.as_array(r.doc_ids, shape=(n,)).copy()
        row_ptr = np.ctypeslib.as_array(r.row_ptr, shape=(n + 1,)).copy()
        col_idx = np.ctypeslib.as_array(r.col_idx, shape=(max(nnz, 1),))[:nnz].copy()
        values = np.ctypeslib.as_array(r.values, shape=(max(nnz, 1),))[:nnz].copy()
        return doc_ids, row_ptr, col_idx, values
    finally:
        lib.dsgd_free_csr(res)


def pack_csr(
    row_ptr: np.ndarray, col_idx: np.ndarray, values: np.ndarray, p: int
) -> Optional[Tuple[np.ndarray, np.ndarray, int]]:
    """Native CSR -> padded [N, p] pack; None if the library is unavailable.

    Returns (indices[N, p] int32, values[N, p] f32, n_truncated).  Rows
    wider than p keep their p largest-|value| features (same policy as the
    numpy fallback in data/rcv1.py).  ctypes releases the GIL for the call.
    """
    lib = load()
    if lib is None:
        return None
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float32)
    n = len(row_ptr) - 1
    out_idx = np.zeros((n, p), dtype=np.int32)
    out_val = np.zeros((n, p), dtype=np.float32)
    truncated = lib.dsgd_pack_csr(
        n,
        row_ptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        col_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        p,
        out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_val.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out_idx, out_val, int(truncated)
