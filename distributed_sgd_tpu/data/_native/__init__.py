"""ctypes loader for the native CSR parser, with build-on-first-use.

pybind11 is not available in this image, so the native parser exposes a C
ABI (parser.cpp) loaded via ctypes.  The shared library is compiled with
g++ on first use and cached next to the source at a path KEYED by the
toolchain fingerprint (compiler version + the arch `-march=native`
resolves to on this machine + flags + ABI — see _lib_path), staleness
checked by source mtime: machine classes sharing a volume each keep
their own artifact, and a machine without g++ refuses to load a binary
it cannot verify (the pure-python fallback takes over).
"""

from __future__ import annotations

import ctypes
import hashlib
import json
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger("dsgd.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "parser.cpp")
# legacy unkeyed artifact path (pre-fingerprint builds); new builds land
# at the toolchain-keyed path — see _lib_path
_LIB = os.path.join(_DIR, "_libdsgd_parser.so")
_LOCK = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
# must match parser.cpp dsgd_abi_version(): the CsrResult struct layout
# (and any function signature) is pinned by this number
_ABI_VERSION = 2
_CXXFLAGS = ["-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
             "-pthread"]


class _CsrResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("nnz", ctypes.c_int64),
        ("doc_ids", ctypes.POINTER(ctypes.c_int32)),
        ("row_ptr", ctypes.POINTER(ctypes.c_int64)),
        ("col_idx", ctypes.POINTER(ctypes.c_int32)),
        ("values", ctypes.POINTER(ctypes.c_float)),
        ("skipped_lines", ctypes.c_int64),
    ]


def _abi_version(lib: ctypes.CDLL) -> int:
    """Library's reported ABI version; 0 if it predates the export."""
    try:
        fn = lib.dsgd_abi_version
    except AttributeError:
        return 0
    fn.restype = ctypes.c_int32
    fn.argtypes = []
    return int(fn())


def _toolchain_sig() -> Optional[dict]:
    """Fingerprint of what a build HERE would produce: compiler version,
    the arch `-march=native` actually resolves to on THIS machine, the
    flag list, and the ABI pin.  None when g++ is unavailable.

    The resolved march matters because the .so can outlive its build host
    (a shared cache volume, a container image layered on a heterogeneous
    fleet): `-march=native` on an AVX-512 builder emits instructions that
    SIGILL on an older serving node, and neither the mtime check nor the
    ABI export can see that — the ISA is invisible until the crash."""
    try:
        ver = subprocess.run(
            ["g++", "--version"], check=True, capture_output=True,
            text=True).stdout.splitlines()[0].strip()
        target = subprocess.run(
            ["g++", "-march=native", "-Q", "--help=target"], check=True,
            capture_output=True, text=True).stdout
    except (OSError, subprocess.CalledProcessError, IndexError):
        return None
    march = ""
    for line in target.splitlines():
        parts = line.split()
        if len(parts) >= 2 and parts[0] in ("-march=", "-mtune="):
            march += f"{parts[0]}{parts[1]} "
    key = f"{ver}|{march.strip()}|{' '.join(_CXXFLAGS)}|abi={_ABI_VERSION}"
    return {
        "sig": hashlib.sha256(key.encode()).hexdigest(),
        "compiler": ver,
        "march": march.strip(),
        "flags": _CXXFLAGS,
        "abi": _ABI_VERSION,
    }


def _lib_path(sig: Optional[dict]) -> str:
    """The build artifact is KEYED by the toolchain fingerprint: every
    (compiler, resolved -march=native, flags, ABI) combination gets its
    own `.so`, so a shared volume serving a heterogeneous fleet holds one
    artifact per machine class — no cross-arch SIGILL, no rebuild
    ping-pong where two arches endlessly overwrite one shared path.
    Without a fingerprint (no g++) only the legacy unkeyed path could
    exist, and load() refuses it as unverifiable."""
    if sig is None:
        return _LIB
    return os.path.join(_DIR, f"_libdsgd_parser.{sig['sig'][:12]}.so")


def _build(sig: Optional[dict]) -> str:
    """Compile to the sig-keyed path via a pid-unique tmp + atomic
    replace (concurrent same-arch builders each install a complete,
    identical artifact) and record the fingerprint provenance sidecar;
    returns the installed path."""
    lib_path = _lib_path(sig)
    tmp = f"{lib_path}.tmp.{os.getpid()}"
    cmd = ["g++", *_CXXFLAGS, _SRC, "-o", tmp]
    log.info("building native parser: %s", " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, lib_path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    if sig is not None:
        from distributed_sgd_tpu.utils.fsio import atomic_write_json

        atomic_write_json(f"{lib_path}.build.json", sig)
    return lib_path


def load() -> Optional[ctypes.CDLL]:
    """Build (if stale) and load the native library; None if unavailable."""
    global _lib
    with _LOCK:
        if _lib is not None:
            return _lib
        try:
            # fingerprint THIS machine's toolchain (two g++ subprocesses,
            # once per process at the first parse) and address the
            # artifact it keys — see _lib_path
            sig = _toolchain_sig()
            if sig is None and os.path.exists(_LIB):
                # no g++ to fingerprint with: a (possibly foreign
                # -march=native) legacy .so would SIGILL uncatchably at
                # the first parse, so refuse to load an UNVERIFIABLE
                # binary — the raise lands in the except below and the
                # pure-python parser takes over (slower, never fatal)
                raise RuntimeError(
                    "cached native parser cannot be verified on "
                    "this machine (no g++ to resolve -march=native)")
            lib_path = _lib_path(sig)
            if (not os.path.exists(lib_path)
                    or os.path.getmtime(lib_path) < os.path.getmtime(_SRC)):
                lib_path = _build(sig)
            lib = ctypes.CDLL(lib_path)
            if _abi_version(lib) != _ABI_VERSION:
                # stale prebuilt .so whose mtime survived COPY/rsync/tar:
                # an mtime check cannot see it, but reading the grown
                # CsrResult through the old layout would be out-of-bounds
                log.info("native parser ABI mismatch; rebuilding")
                lib_path = _build(sig)
                lib = ctypes.CDLL(lib_path)
                if _abi_version(lib) != _ABI_VERSION:
                    raise RuntimeError(
                        f"rebuilt native parser still reports ABI "
                        f"{_abi_version(lib)}, expected {_ABI_VERSION}")
            lib.dsgd_parse_svm.restype = ctypes.POINTER(_CsrResult)
            lib.dsgd_parse_svm.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int32]
            lib.dsgd_free_csr.argtypes = [ctypes.POINTER(_CsrResult)]
            lib.dsgd_free_csr.restype = None
            lib.dsgd_pack_csr.restype = ctypes.c_int64
            lib.dsgd_pack_csr.argtypes = [
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float),
            ]
            _lib = lib
        except Exception as e:  # missing toolchain etc. -> python fallback
            log.warning("native parser unavailable (%s); using python fallback", e)
            _lib = None
        return _lib


def parse_svm_file(
    path: str, n_threads: int = 0, index_offset: int = -1
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Parse with the native library. Returns (doc_ids, row_ptr, col_idx,
    values) as owned numpy arrays, or None if the native path is unavailable.
    """
    lib = load()
    if lib is None:
        return None
    res = lib.dsgd_parse_svm(path.encode(), n_threads, index_offset)
    if not res:
        raise IOError(f"native parser failed to open {path!r}")
    try:
        r = res.contents
        n, nnz = r.n_rows, r.nnz
        if r.skipped_lines:
            # The python fallback (and the reference, Dataset.scala:24) raise
            # on a non-numeric doc id; the native scanner drops such lines.
            # Surface the count so the divergence is observable.
            log.warning(
                "native parser skipped %d malformed line(s) in %s (python "
                "fallback would raise on these)", r.skipped_lines, path)
        doc_ids = np.ctypeslib.as_array(r.doc_ids, shape=(n,)).copy()
        row_ptr = np.ctypeslib.as_array(r.row_ptr, shape=(n + 1,)).copy()
        col_idx = np.ctypeslib.as_array(r.col_idx, shape=(max(nnz, 1),))[:nnz].copy()
        values = np.ctypeslib.as_array(r.values, shape=(max(nnz, 1),))[:nnz].copy()
        return doc_ids, row_ptr, col_idx, values
    finally:
        lib.dsgd_free_csr(res)


def pack_csr(
    row_ptr: np.ndarray, col_idx: np.ndarray, values: np.ndarray, p: int
) -> Optional[Tuple[np.ndarray, np.ndarray, int]]:
    """Native CSR -> padded [N, p] pack; None if the library is unavailable.

    Returns (indices[N, p] int32, values[N, p] f32, n_truncated).  Rows
    wider than p keep their p largest-|value| features (same policy as the
    numpy fallback in data/rcv1.py).  ctypes releases the GIL for the call.
    """
    lib = load()
    if lib is None:
        return None
    row_ptr = np.ascontiguousarray(row_ptr, dtype=np.int64)
    col_idx = np.ascontiguousarray(col_idx, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float32)
    n = len(row_ptr) - 1
    out_idx = np.zeros((n, p), dtype=np.int32)
    out_val = np.zeros((n, p), dtype=np.float32)
    truncated = lib.dsgd_pack_csr(
        n,
        row_ptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        col_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        p,
        out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_val.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out_idx, out_val, int(truncated)
