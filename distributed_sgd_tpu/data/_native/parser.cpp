// Native RCV1/LIBSVM-style parser: text rows -> CSR arrays.
//
// TPU-native replacement for the reference's startup-dominating data path
// (utils/Dataset.scala:19-34): the reference parses "docid  f:v f:v ..."
// lines into boxed Map[Int, spire.math.Number] with Scala parallel
// collections; we parse straight into flat CSR buffers (int32 col ids,
// f32 values, int64 row offsets) with a chunked multi-threaded scan, which
// is both what the host can do fastest and exactly the layout the packing
// step (data/rcv1.py) needs to build device tensors.
//
// C ABI only (loaded via ctypes; no pybind11 in this image).

#include <algorithm>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// Floating-point std::from_chars needs libstdc++ >= 11 (__cpp_lib_to_chars);
// older toolchains (this image ships GCC 10) only have the integer
// overloads.  The shim below reproduces the from_chars contract the parser
// relies on — single token, NO leading whitespace, NO leading '+', ptr
// advanced past exactly the consumed characters, ptr == first on failure —
// on top of strtof (bounded copy; number tokens in these files are short).
#if defined(__cpp_lib_to_chars)
inline const char* parse_float(const char* first, const char* last, float& v) {
  auto r = std::from_chars(first, last, v, std::chars_format::general);
  return r.ptr;
}
#else
#include <locale.h>
inline const char* parse_float(const char* first, const char* last, float& v) {
  if (first >= last) return first;
  // from_chars parity: strtof would skip whitespace and accept a leading
  // '+'/"inf"/"nan"/hex — reject everything a LIBSVM value can't start with
  const char c = *first;
  if (!((c >= '0' && c <= '9') || c == '-' || c == '.')) return first;
  char buf[64];
  size_t n = static_cast<size_t>(last - first);
  if (n > sizeof(buf) - 1) n = sizeof(buf) - 1;
  memcpy(buf, first, n);
  buf[n] = '\0';
  // from_chars parity, continued: strtof reads "0x10" as hex (from_chars
  // general format stops after the "0") — truncate at the 'x' so both
  // build paths advance identically
  size_t digit0 = (buf[0] == '-') ? 1 : 0;
  if (buf[digit0] == '0' && (buf[digit0 + 1] == 'x' || buf[digit0 + 1] == 'X'))
    buf[digit0 + 1] = '\0';
  // strtof is locale-dependent (a de_DE LC_NUMERIC expects ',' and would
  // truncate "3.14" to 3.0); parse under an explicit "C" locale so an
  // embedding process's setlocale() cannot corrupt the data path
  static locale_t c_loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  char* endp = nullptr;
  float out = strtof_l(buf, &endp, c_loc);
  if (endp == buf) return first;
  v = out;
  return first + (endp - buf);
}
#endif

struct ChunkOut {
  std::vector<int32_t> doc_ids;
  std::vector<int64_t> row_nnz;
  std::vector<int32_t> col_idx;
  std::vector<float> values;
  int64_t skipped_lines = 0;
};

// Parse [begin, end) which is aligned to line boundaries.
//
// Number parsing uses std::from_chars (single-pass, locale-free) — on this
// toolchain it is several times faster than strtol/strtof, and the float
// overload accepts both fixed and scientific forms (chars_format::general).
// Unlike strtol/strtof, from_chars accepts neither leading whitespace nor a
// leading '+', so both are skipped explicitly where the old functions
// tolerated them (line start and after ':').
void parse_chunk(const char* begin, const char* end, int32_t index_offset,
                 ChunkOut* out) {
  const char* p = begin;
  while (p < end) {
    // skip blank lines and leading whitespace
    while (p < end && (*p == '\n' || *p == '\r' || *p == ' ' || *p == '\t')) ++p;
    if (p >= end) break;
    // doc id
    if (*p == '+') ++p;
    long doc = 0;
    auto rd = std::from_chars(p, end, doc);
    if (rd.ptr == p) {  // not a number: skip the malformed line entirely.
      // Counted so callers can observe the divergence from the python
      // fallback / reference (Dataset.scala:24), which raise here instead.
      ++out->skipped_lines;
      while (p < end && *p != '\n') ++p;
      continue;
    }
    p = rd.ptr;
    out->doc_ids.push_back(static_cast<int32_t>(doc));
    int64_t nnz = 0;
    // feature:value pairs until end of line
    while (p < end && *p != '\n') {
      while (p < end && *p == ' ') ++p;
      if (p >= end || *p == '\n' || *p == '\r') break;
      long feat = 0;
      auto rf = std::from_chars(p, end, feat);
      if (rf.ptr == p) {  // malformed token; skip to next space/newline
        while (p < end && *p != ' ' && *p != '\n') ++p;
        continue;
      }
      p = rf.ptr;
      if (p < end && *p == ':') {
        ++p;
        if (p < end && *p == '+') ++p;
        float v = 0.0f;
        const char* rv = parse_float(p, end, v);
        if (rv == p) {  // malformed value; drop token
          while (p < end && *p != ' ' && *p != '\n') ++p;
          continue;
        }
        p = rv;
        out->col_idx.push_back(static_cast<int32_t>(feat) + index_offset);
        out->values.push_back(v);
        ++nnz;
      }
      // token without ':' (e.g. the reference's dropped parts(1)) is skipped
    }
    out->row_nnz.push_back(nnz);
    while (p < end && *p != '\n') ++p;  // consume rest of line
  }
}

}  // namespace

extern "C" {

// Bumped on every CsrResult/function-signature change.  The ctypes loader
// refuses (and rebuilds) a library reporting a different version — an
// mtime staleness check alone cannot catch a stale prebuilt .so whose
// timestamp was normalized by COPY/rsync/tar.
int32_t dsgd_abi_version() { return 2; }

struct CsrResult {
  int64_t n_rows;
  int64_t nnz;
  int32_t* doc_ids;  // [n_rows]
  int64_t* row_ptr;  // [n_rows + 1]
  int32_t* col_idx;  // [nnz]
  float* values;     // [nnz]
  int64_t skipped_lines;  // malformed (non-numeric doc id) lines dropped
};

// Parse a whole file. index_offset is added to every feature id (use -1 to
// convert the file's 1-based ids to 0-based). Returns nullptr on I/O error.
CsrResult* dsgd_parse_svm(const char* path, int n_threads,
                          int32_t index_offset) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(size));
  if (size > 0 && fread(buf.data(), 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    fclose(f);
    return nullptr;
  }
  fclose(f);

  if (n_threads < 1) {
    unsigned hw = std::thread::hardware_concurrency();
    n_threads = hw ? static_cast<int>(hw) : 1;
  }
  // chunk boundaries aligned to newlines
  std::vector<const char*> bounds;
  const char* base = buf.data();
  const char* fend = base + size;
  bounds.push_back(base);
  for (int t = 1; t < n_threads; ++t) {
    const char* guess = base + size * t / n_threads;
    while (guess < fend && *guess != '\n') ++guess;
    if (guess < fend) ++guess;
    bounds.push_back(guess);
  }
  bounds.push_back(fend);

  std::vector<ChunkOut> outs(bounds.size() - 1);
  std::vector<std::thread> threads;
  for (size_t t = 0; t + 1 < bounds.size(); ++t) {
    if (bounds[t] >= bounds[t + 1]) continue;
    threads.emplace_back(parse_chunk, bounds[t], bounds[t + 1], index_offset,
                         &outs[t]);
  }
  for (auto& th : threads) th.join();

  auto* res = static_cast<CsrResult*>(malloc(sizeof(CsrResult)));
  int64_t n_rows = 0, nnz = 0, skipped = 0;
  for (auto& o : outs) {
    n_rows += static_cast<int64_t>(o.doc_ids.size());
    nnz += static_cast<int64_t>(o.values.size());
    skipped += o.skipped_lines;
  }
  res->n_rows = n_rows;
  res->nnz = nnz;
  res->skipped_lines = skipped;
  res->doc_ids = static_cast<int32_t*>(malloc(sizeof(int32_t) * n_rows));
  res->row_ptr = static_cast<int64_t*>(malloc(sizeof(int64_t) * (n_rows + 1)));
  res->col_idx = static_cast<int32_t*>(malloc(sizeof(int32_t) * (nnz ? nnz : 1)));
  res->values = static_cast<float*>(malloc(sizeof(float) * (nnz ? nnz : 1)));

  int64_t row_at = 0, nz_at = 0;
  res->row_ptr[0] = 0;
  for (auto& o : outs) {
    if (!o.doc_ids.empty()) {
      memcpy(res->doc_ids + row_at, o.doc_ids.data(),
             sizeof(int32_t) * o.doc_ids.size());
    }
    for (size_t i = 0; i < o.row_nnz.size(); ++i) {
      res->row_ptr[row_at + 1] = res->row_ptr[row_at] + o.row_nnz[i];
      ++row_at;
    }
    if (!o.values.empty()) {
      memcpy(res->col_idx + nz_at, o.col_idx.data(),
             sizeof(int32_t) * o.col_idx.size());
      memcpy(res->values + nz_at, o.values.data(),
             sizeof(float) * o.values.size());
      nz_at += static_cast<int64_t>(o.values.size());
    }
  }
  return res;
}

void dsgd_free_csr(CsrResult* r) {
  if (!r) return;
  free(r->doc_ids);
  free(r->row_ptr);
  free(r->col_idx);
  free(r->values);
  free(r);
}

// CSR -> padded [n_rows, p] pack (the layout ops/sparse.py kernels consume).
// out_idx / out_val must be zero-initialized by the caller.  Rows with
// nnz <= p are straight memcpys; wider rows keep their p largest-|value|
// features in ascending-column order (matching the numpy fallback in
// data/rcv1.py pack_csr).  Returns the number of truncated rows.
//
// This replaces the numpy scatter pack, whose np.repeat index expansion was
// the slowest stage of full-scale loading (~17 s for 804k rows); here the
// same pack is a ~0.3 s row loop.
int64_t dsgd_pack_csr(int64_t n_rows, const int64_t* row_ptr,
                      const int32_t* col_idx, const float* values, int64_t p,
                      int32_t* out_idx, float* out_val) {
  int64_t truncated = 0;
  std::vector<int32_t> order;  // scratch for truncation rows only
  for (int64_t r = 0; r < n_rows; ++r) {
    const int64_t s = row_ptr[r], e = row_ptr[r + 1];
    const int64_t nnz = e - s;
    int32_t* oi = out_idx + r * p;
    float* ov = out_val + r * p;
    if (nnz <= p) {
      if (nnz > 0) {
        memcpy(oi, col_idx + s, sizeof(int32_t) * nnz);
        memcpy(ov, values + s, sizeof(float) * nnz);
      }
      continue;
    }
    ++truncated;
    order.resize(nnz);
    for (int64_t i = 0; i < nnz; ++i) order[i] = static_cast<int32_t>(i);
    std::nth_element(order.begin(), order.begin() + p, order.end(),
                     [&](int32_t a, int32_t b) {
                       // NaN maps below every real |value| (abs >= 0) to keep
                       // the ordering strict-weak (raw NaN comparisons would
                       // make NaN "equivalent" to everything — UB for
                       // nth_element) and to match numpy argsort's NaN-last
                       float av = std::abs(values[s + a]);
                       float bv = std::abs(values[s + b]);
                       if (av != av) av = -1.0f;
                       if (bv != bv) bv = -1.0f;
                       // |value| ties keep the earlier position — same rule
                       // as the numpy fallback's stable argsort
                       return av != bv ? av > bv : a < b;
                     });
    std::sort(order.begin(), order.begin() + p);  // ascending column order
    for (int64_t i = 0; i < p; ++i) {
      oi[i] = col_idx[s + order[i]];
      ov[i] = values[s + order[i]];
    }
  }
  return truncated;
}

}  // extern "C"
