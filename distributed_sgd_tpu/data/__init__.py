from distributed_sgd_tpu.data.rcv1 import (  # noqa: F401
    Dataset,
    dim_sparsity,
    load_rcv1,
    pack_csr,
    read_labels,
    train_test_split,
)
from distributed_sgd_tpu.data.synthetic import rcv1_like, dense_regression  # noqa: F401
