"""Full-scale corpus writer in the reference's exact RCV1 text format.

The reference *gates* its loader on the real dataset: parse all 804,414
rows in < 40 s (src/test/scala/epfl/distributed/utils/DatasetTests.scala:11-23).
The real files cannot be fetched here (no egress), so this writer produces
a corpus with the same file layout (Dataset.scala:47-50: one train file +
four test parts), the same row format (Dataset.scala:19-34: ``docid␣␣f:v
f:v ...`` — double space after the id, 1-based feature ids), and the same
qrels label format (Dataset.scala:36-45: ``TOPIC docid 1``, CCAT → +1,
last line per doc wins) at the same row count and nnz density, so the
parser can be held to the reference's gate at the scale it exists for.

Speed: formatting ~61M ``f:v`` tokens in python would dominate the test,
so a pool of ``n_template`` fully random row bodies is formatted once and
tiled across the corpus with unique sequential doc ids.  The parser sees
the same byte volume, token count, and per-line work as a fully unique
corpus; only the value *strings* repeat every ``n_template`` rows.
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple, Union

import numpy as np

# real RCV1 layout: 23,149 train docs + 781,265 test docs = 804,414
N_ROWS_FULL = 804414
N_TRAIN_ROWS = 23149
FIRST_DOC_ID = 2286  # real RCV1 ids start here


def _template_bodies(
    n_template: int, nnz_mean: int, n_features: int, rng: np.random.Generator,
    return_debug: bool = False,
) -> Union[Tuple[List[str], np.ndarray],
           Tuple[List[str], np.ndarray, Dict[str, np.ndarray]]]:
    """Format `n_template` random row bodies ("f:v f:v ...", 1-based ids).

    Returns (bodies, labels): labels come from a planted linear separator
    over the row features (like data/synthetic.rcv1_like), so a corpus
    written from these templates is LEARNABLE — training on the parsed
    files converges, closing the text->parse->train loop end to end.

    `return_debug=True` additionally returns {"w_true", "margins"} so the
    regression tests (tests/test_data_scale.py) can verify the two
    ADVICE.md rounding invariants from the OUTSIDE: every emitted token
    formats nonzero, and each planted margin equals the dot product of
    the PARSED (file-precision) values with w_true — i.e. the label a
    reader derives from the file bytes is the label we planted.
    """
    nnz = np.clip(rng.poisson(nnz_mean, size=n_template), 1, None)
    max_nnz = int(nnz.max())
    # Zipf-ish feature popularity like term frequencies (matches synthetic.py)
    pop = 1.0 / np.arange(1, n_features + 1, dtype=np.float64)
    pop /= pop.sum()
    idx = rng.choice(n_features, size=(n_template, max_nnz), p=pop).astype(np.int32)
    val = rng.uniform(0.001, 1.0, size=(n_template, max_nnz))
    w_true = rng.normal(size=n_features).astype(np.float64)
    rows: List[Tuple[np.ndarray, np.ndarray]] = []
    for r in range(n_template):
        # slice to this row's draws FIRST, then sort: sorting the full
        # max_nnz row and truncating would leave short rows holding the
        # sorted prefix (systematically low feature ids), skewing the
        # corpus's popularity profile beyond the intended Zipf draw
        row_idx = np.sort(idx[r, : nnz[r]])
        # file rows cannot repeat a feature id (they decode into a map in
        # the reference, Dataset.scala:24-33): drop duplicate draws
        keep = np.ones(len(row_idx), dtype=bool)
        keep[1:] = row_idx[1:] != row_idx[:-1]
        rows.append((row_idx[keep], val[r, : nnz[r]][keep]))

    # ltc term weighting, like the REAL RCV1-v2 vectors (LYRL2004): weight
    # each entry by its feature's inverse DOCUMENT frequency over the
    # template pool (once per row, so df <= n_template and idf >= 0), then
    # cosine-normalize the row.  Without it Zipf-head features carry
    # unattenuated values no real term weighting produces and the
    # reference's lr=0.5 oscillates (BASELINE.md, Zipf-oscillation study).
    # The small floor keeps a ubiquitous feature's token nonzero in the
    # text (a 0-valued f:v entry would decode into the reference's map)
    df = np.zeros(n_features, dtype=np.int64)
    for row_idx, _ in rows:
        df[row_idx] += 1
    idf = np.maximum(np.log(n_template / np.maximum(df, 1.0)), 0.01)

    bodies: List[str] = []
    margins = np.zeros(n_template)
    for r, (row_idx, row_val) in enumerate(rows):
        row_val = row_val * idf[row_idx]
        row_val /= max(float(np.linalg.norm(row_val)), 1e-12)
        # drop entries the %.6f text format would round to 0.000000 (a
        # floored ubiquitous feature over a large row norm): real RCV1
        # files carry no zero-valued tokens, and the floor must sit at a
        # value %.6f keeps nonzero — 5e-7 itself formats as 0.000000
        # (round-half-even), so the floor and the degenerate fallback are
        # both 1e-6, the smallest value the format preserves
        keep = row_val >= 1e-6
        row_idx, row_val = row_idx[keep], row_val[keep]
        if len(row_idx) == 0:  # degenerate all-dropped row: keep one token
            row_idx, row_val = np.array([1], np.int32), np.array([1e-6], np.float64)
        # the planted margin must see exactly the values the parser will
        # read back: round to the %.6f wire precision BEFORE the dot, or a
        # margin near the median could flip its label relative to the file
        # contents even at noise=0
        row_val = np.round(row_val, 6)
        margins[r] = float(np.dot(row_val, w_true[row_idx]))
        bodies.append(
            " ".join(f"{c + 1}:{v:.6f}" for c, v in zip(row_idx, row_val))
        )
    labels = np.where(margins > np.median(margins), 1, -1).astype(np.int32)
    if return_debug:
        return bodies, labels, {"w_true": w_true, "margins": margins}
    return bodies, labels


def write_rcv1_corpus(
    folder: str,
    n_rows: int = N_ROWS_FULL,
    n_train: int = N_TRAIN_ROWS,
    n_template: int = 16384,
    # Zipf-popularity draws collide and are deduped, so the DRAW mean must
    # exceed the target ~76 distinct features/row (real RCV1 density);
    # 115 draws land at ~76 distinct, reported as `nnz_per_row` in metadata
    nnz_mean: int = 115,
    n_features: int = 47236,
    label_noise: float = 0.05,
    seed: int = 0,
    chunk: int = 65536,
) -> Dict[str, object]:
    """Write train + 4 test parts + qrels into `folder`; returns metadata.

    Labels follow the templates' planted separator (CCAT = +1 side) with
    `label_noise` random flips, so the corpus is learnable after parsing.
    """
    rng = np.random.default_rng(seed)
    bodies, tmpl_labels = _template_bodies(
        min(n_template, n_rows), nnz_mean, n_features, rng)
    n_template = len(bodies)
    tokens_per_row = sum(b.count(":") for b in bodies) / n_template

    os.makedirs(folder, exist_ok=True)
    n_test = n_rows - n_train
    part_sizes = [(n_test + i) // 4 for i in range(4)]  # reference's 4 test parts
    plan = [("lyrl2004_vectors_train.dat", n_train)] + [
        (f"lyrl2004_vectors_test_pt{d}.dat", part_sizes[d]) for d in range(4)
    ]

    doc = FIRST_DOC_ID
    total_bytes = 0
    for fname, rows in plan:
        path = os.path.join(folder, fname)
        with open(path, "w") as f:
            written = 0
            while written < rows:
                n = min(chunk, rows - written)
                lines = [
                    f"{doc + i}  {bodies[(doc + i) % n_template]}\n" for i in range(n)
                ]
                f.write("".join(lines))
                doc += n
                written += n
        total_bytes += os.path.getsize(path)

    # qrels: one line per doc (+ an extra preceding topic line for every
    # 50th doc so the last-line-wins overwrite path runs at scale too).
    # doc i reuses template (FIRST_DOC_ID + i) % n_template — same mapping
    # as the row bodies above — so its label matches its features
    doc_labels = tmpl_labels[(FIRST_DOC_ID + np.arange(n_rows)) % n_template]
    flip = rng.random(n_rows) < label_noise
    is_ccat = np.where(flip, -doc_labels, doc_labels) == 1
    other = rng.choice(["ECAT", "GCAT", "MCAT"], size=n_rows)
    qrels = os.path.join(folder, "rcv1-v2.topics.qrels")
    with open(qrels, "w") as f:
        for start in range(0, n_rows, chunk):
            n = min(chunk, n_rows - start)
            lines: List[str] = []
            for i in range(start, start + n):
                d = FIRST_DOC_ID + i
                if i % 50 == 0:
                    lines.append(f"C15 {d} 1\n")
                lines.append(f"{'CCAT' if is_ccat[i] else other[i]} {d} 1\n")
            f.write("".join(lines))

    return {
        "n_rows": n_rows,
        "files": [name for name, _ in plan] + ["rcv1-v2.topics.qrels"],
        "bytes": total_bytes,
        "n_ccat": int(is_ccat.sum()),
        "nnz_per_row": tokens_per_row,
    }
