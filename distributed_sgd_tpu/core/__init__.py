from distributed_sgd_tpu.core.early_stopping import no_improvement, target  # noqa: F401
from distributed_sgd_tpu.core.grad_state import GradState  # noqa: F401
from distributed_sgd_tpu.core.split import vanilla_split  # noqa: F401
