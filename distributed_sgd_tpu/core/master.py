"""Master node: cluster membership, readiness barrier, distributed fits.

TPU-native re-design of the reference master (core/Master.scala,
core/MasterSync.scala, core/MasterAsync.scala).  The control plane is
preserved structurally — registration with full-mesh peer introduction
(Master.scala:222-243), readiness barrier gating all work
(Master.scala:34-59), unregister broadcast (Master.scala:245-253), the
sync per-batch fan-out/barrier/mean loop (Master.scala:120-218), the async
StartAsync fan-out + update counting + loss checker (MasterAsync.scala) —
while all local evaluation runs compiled on the master's device and worker
gradient computation runs compiled on theirs.

This RPC mode exists for reference-parity cluster topology and cross-host
deployments WITHOUT a shared jax mesh; when all devices live in one
process/slice, parallel/sync.py's in-mesh engine is the fast path (no
weight serialization at all).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

import grpc
import jax
import jax.numpy as jnp
import numpy as np

from distributed_sgd_tpu.checkpoint import (
    restore_fit_state,
    restore_sync_fit,
    save_fit_state,
    save_sync_fit,
    save_sync_fit_final,
)
from distributed_sgd_tpu.core.early_stopping import Criterion
from distributed_sgd_tpu.core.grad_state import GradState
from distributed_sgd_tpu.core.loss_check import LossChecker, async_fit_result
from distributed_sgd_tpu.core.split import vanilla_split, weighted_split
from distributed_sgd_tpu.core.trainer import FitResult, record_epoch
from distributed_sgd_tpu.data.rcv1 import Dataset
from distributed_sgd_tpu.models.linear import LinearModel
from distributed_sgd_tpu.parallel.mesh import make_mesh
from distributed_sgd_tpu.parallel.sync import SyncEngine
from distributed_sgd_tpu.rpc import codec, dsgd_pb2 as pb
from distributed_sgd_tpu.rpc.service import (
    RpcPolicy,
    WorkerStub,
    add_master_servicer,
    new_channel,
    new_server,
)
from distributed_sgd_tpu import trace as trace_mod
from distributed_sgd_tpu.telemetry import resources
from distributed_sgd_tpu.trace import flight
from distributed_sgd_tpu.utils import metrics as metrics_mod
from distributed_sgd_tpu.utils.log import node_logger

SplitFn = Callable[[int, int], List[np.ndarray]]


class _FailureTracker:
    """Consecutive-failure counter with an eviction threshold.

    Shared policy for every fan-out that classifies worker failures
    (heartbeat probes, Gradient barriers, Forward eval): a success resets
    the worker's count; `record_failure` returns True once the worker has
    failed `threshold` consecutive times and should be declared dead.
    """

    def __init__(self, threshold: int):
        self.threshold = max(1, int(threshold))
        self._counts: Dict[Tuple[str, int], int] = {}

    def record_ok(self, key: Tuple[str, int]) -> None:
        self._counts.pop(key, None)

    def record_failure(self, key: Tuple[str, int]) -> Tuple[int, bool]:
        n = self._counts.get(key, 0) + 1
        if n >= self.threshold:
            self._counts.pop(key, None)
            return n, True
        self._counts[key] = n
        return n, False


def _await_futures(futs, bytes_counter=None):
    """Barrier with failure classification over [(key, future-or-None)].

    Returns (ok, failed): ok = [(key, reply)] in input order, failed =
    [(key, status-or-error)].  A None future stands for a channel that
    closed under us at call time.  `bytes_counter` (optional) accounts
    every reply that ARRIVED, right here in result handling — so a window
    later discarded because a sibling failed (fit_sync's retry) still
    counts its received bytes, which the old post-barrier sum missed."""
    ok, failed = [], []
    for key, fut in futs:
        try:
            if fut is None:
                raise ValueError("channel closed")
            reply = fut.result()
            if bytes_counter is not None:
                bytes_counter.increment(reply.ByteSize())
            ok.append((key, reply))
        except (grpc.RpcError, ValueError) as e:
            failed.append((key, e.code() if isinstance(e, grpc.RpcError) else e))
    return ok, failed


class _ArrivalDecoder:
    """Send-ordered decode-on-arrival for the sync fan-in (ROADMAP item 2),
    optionally SHARDED into K decoder lanes (DSGD_FANIN_LANES,
    docs/SCALING.md).

    The full-barrier fan-in used to decode every Gradient reply AFTER the
    barrier closed — N dim-sized scatter-decodes serialized on the
    critical path while N-1 of them could have run during the wait.  This
    moves each reply's decode into the reply's own arrival callback,
    constrained to SEND ORDER (the decode cursor only advances over the
    contiguous settled prefix), so float accumulation order — and
    therefore the resulting weights — stays bit-identical to the
    post-barrier loop.  With in-order arrivals every decode but the
    slowest reply's overlaps the wait; out-of-order arrivals decode as
    soon as their prefix completes.

    ``lanes=K >= 1`` shards the DECODE: workers map to lanes by a fixed
    send-index assignment (``i % K``), each lane guards its own slot map
    with its own lock, and — the point — the expensive half of the decode
    (`codec.parse_grad`: repeated-field -> ndarray materialization, qint8
    dequantization) runs in the arrival callback BEFORE any lock is
    taken, so K callbacks parse concurrently instead of queueing on one
    decoder lock.  Only the cheap float ACCUMULATION (`codec.add_parsed`)
    is serialized, under the accumulator lock, walking the contiguous
    settled prefix in send order.  Keeping the accumulation a single
    send-ordered f32 chain is what makes the lanes BIT-EXACT against the
    single-accumulator path: a per-lane partial-sum + K-way reduce would
    regroup the float additions ((r0+r1)+(r2+r3) instead of
    ((r0+r1)+r2)+r3) and drift in the last ulp — asserted impossible by
    tests/test_fanin_lanes.py, which pins lanes-on weights byte-identical
    to lanes-off across sync, quorum, retry, and compressed rounds.

    ``defer=True`` (the quorum barrier's mode) parses arrivals into a
    side table but never accumulates: the contributor set (hedge wins,
    late originals) is only known at round close, when the caller replays
    it in canonical order through ``add_into`` — pre-parsed replies cost
    O(dim) adds only, unparsed ones (hedge replies arrive on unary
    futures nobody watches) parse on the spot.

    Lock discipline: parse outside every lock; lane locks guard only
    their slot maps (set-once per index, so a callback racing `finish()`
    can never decode a reply twice); the accumulator lock serializes the
    cursor walk and is never held while a lane lock is awaited in the
    other direction.  A failed or stale reply marks the window dirty and
    freezes the cursor — the caller retries the window and the
    accumulator is re-zeroed on the next attempt, so partially-decoded
    state never leaks into an applied update.  ``lanes=0`` (default)
    keeps the pre-shard single-lock path byte-for-byte."""

    def __init__(self, acc: np.ndarray, lanes: int = 0, defer: bool = False):
        self.acc = acc
        self.lanes = max(0, int(lanes))
        self.defer = bool(defer)
        self._lock = threading.Lock()
        self._results: Dict[int, object] = {}
        self._cursor = 0
        self.dirty = False
        self.decoded = 0
        self.parsed = 0
        if self.lanes:
            k = self.lanes
            self._lane_locks = [threading.Lock() for _ in range(k)]
            # per-lane slot maps: index -> (reply | None, parsed | None)
            self._lane_slots: List[Dict[int, tuple]] = [dict() for _ in range(k)]
            # defer mode's side table: id(reply) -> (reply, parsed); the
            # reply reference keeps the id stable until the round closes
            self._parsed_by_reply: Dict[int, tuple] = {}

    # -- shared entry points ------------------------------------------------

    def watch(self, i: int, fut) -> None:
        if not self.lanes:
            if fut is None:
                with self._lock:
                    self._results.setdefault(i, None)
                    self._advance()
                return
            fut.add_done_callback(lambda f, i=i: self._on_done(i, f))
            return
        if fut is None:
            self._settle_lane(i, None)
            return
        fut.add_done_callback(lambda f, i=i: self._on_done_lane(i, f))

    def finish(self, futs) -> bool:
        """Drain any settled tail the callbacks have not reached yet (the
        barrier already awaited every future, but gRPC's callback threads
        may lag the main thread's own `result()`); returns clean?"""
        if not self.lanes:
            with self._lock:
                for i, (_key, fut) in enumerate(futs):
                    if i not in self._results:
                        try:
                            self._results[i] = (fut.result()
                                                if fut is not None else None)
                        except Exception:  # noqa: BLE001
                            self._results[i] = None
                self._advance()
                return not self.dirty
        for i, (_key, fut) in enumerate(futs):
            lane = self._lane_locks[i % self.lanes]
            with lane:
                seen = i in self._lane_slots[i % self.lanes]
            if not seen:
                try:
                    reply = fut.result() if fut is not None else None
                except Exception:  # noqa: BLE001
                    reply = None
                self._settle_lane(i, reply)
        self._advance_lanes()
        return not self.dirty

    # -- legacy single-lock path (lanes=0) ----------------------------------

    def _on_done(self, i: int, fut) -> None:
        try:
            reply = fut.result()
        except Exception:  # noqa: BLE001 - classification is the barrier's job
            reply = None
        with self._lock:
            self._results.setdefault(i, reply)
            self._advance()

    def _advance(self) -> None:
        while not self.dirty and self._cursor in self._results:
            r = self._results[self._cursor]
            if r is None or r.stale_version:
                # the window will retry: stop decoding (the work would be
                # discarded) and let the caller's classification decide
                self.dirty = True
                return
            codec.decode_grad_into(r, self.acc)
            self.decoded += 1
            self._cursor += 1

    # -- sharded lanes (lanes=K) --------------------------------------------

    def _on_done_lane(self, i: int, fut) -> None:
        try:
            reply = fut.result()
        except Exception:  # noqa: BLE001 - classification is the barrier's job
            reply = None
        self._settle_lane(i, reply)

    def _settle_lane(self, i: int, reply) -> None:
        # parse BEFORE any lock: this is the concurrency the lanes buy
        parsed = None
        if reply is not None and not reply.stale_version:
            parsed = codec.parse_grad(reply)
        lane = i % self.lanes
        with self._lane_locks[lane]:
            slots = self._lane_slots[lane]
            if i in slots:  # set-once: a lagging callback must not re-enter
                return
            slots[i] = (reply, parsed)
        if parsed is not None:
            with self._lock:  # exact count; defer's side table reads here too
                self.parsed += 1
                if self.defer:
                    self._parsed_by_reply[id(reply)] = (reply, parsed)
        if not self.defer:
            self._advance_lanes()

    def _advance_lanes(self) -> None:
        if self.defer:
            return
        with self._lock:  # the accumulator lock: one ordered f32 chain
            while not self.dirty:
                lane = self._cursor % self.lanes
                with self._lane_locks[lane]:
                    item = self._lane_slots[lane].get(self._cursor)
                if item is None:
                    return
                reply, parsed = item
                if reply is None or reply.stale_version:
                    self.dirty = True
                    return
                codec.add_parsed(parsed, self.acc)
                self.decoded += 1
                self._cursor += 1

    def add_into(self, reply, out: np.ndarray) -> None:
        """Defer mode's round-close accumulate: reuse the arrival
        callback's parse when one landed for this reply object, parse on
        the spot otherwise (hedge replies, late settles) — the float adds
        are `decode_grad_into`'s exactly, in the caller's order."""
        item = None
        if self.lanes and self.defer:
            with self._lock:
                item = self._parsed_by_reply.get(id(reply))
        if item is not None and item[0] is reply:
            codec.add_parsed(item[1], out)
        else:
            codec.decode_grad_into(reply, out)


class _LatencyEwma:
    """Per-worker Gradient reply-latency EWMA (mean + mean absolute
    deviation) feeding the quorum barrier's adaptive soft deadline
    (docs/FAULT_TOLERANCE.md).

    `soft_deadline_s(keys, quorum)` answers "how long should the `quorum`
    fastest workers need?": per worker a p95 proxy (mean + 3 * deviation),
    then the quorum-th SMALLEST of those, with slack.  Taking a low order
    statistic (not the max) is the point — a straggler's own tail must
    not stretch the deadline that is supposed to cut it off.  Returns
    None until at least `quorum` workers have history (the first windows
    include compile latency and must run as full barriers)."""

    SLACK = 1.5
    FLOOR_S = 0.05

    def __init__(self, alpha: float = 0.2):
        self.alpha = float(alpha)
        self._mean: Dict[Tuple[str, int], float] = {}
        self._dev: Dict[Tuple[str, int], float] = {}
        self._lock = threading.Lock()

    def record(self, key: Tuple[str, int], seconds: float) -> None:
        with self._lock:
            m = self._mean.get(key)
            if m is None:
                self._mean[key] = seconds
                self._dev[key] = 0.0
                return
            err = seconds - m
            self._mean[key] = m + self.alpha * err
            self._dev[key] = ((1 - self.alpha) * self._dev[key]
                              + self.alpha * abs(err))

    def p95_s(self, key: Tuple[str, int]) -> Optional[float]:
        with self._lock:
            m = self._mean.get(key)
            if m is None:
                return None
            return m + 3.0 * self._dev[key]

    def soft_deadline_s(self, keys, quorum: int) -> Optional[float]:
        ests = sorted(e for e in (self.p95_s(k) for k in keys) if e is not None)
        if len(ests) < max(1, quorum):
            return None
        return max(self.FLOOR_S, self.SLACK * ests[max(1, quorum) - 1])


def _reply_weight(reply) -> int:
    """Quorum mass of one barrier reply (docs/AGGREGATION.md §Quorum).

    Under DSGD_AGG_TREE the master's fan-in mixes three reply shapes: a
    subtree sum carries its exact contributor set (weight = |set|), an
    armless forwarded ack carries NOTHING — its gradient went up-tree —
    so it must not satisfy the quorum count blindly (weight 0), and a
    flat reply (a hedge, or a worker outside the plan) carries exactly
    one worker's gradient (weight 1).  Flat fits and Forward replies
    have neither field and weigh 1, so knobs-off counting is unchanged.
    """
    if getattr(reply, "agg_contributors", None):
        return len(reply.agg_contributors)
    if getattr(reply, "agg_forwarded", False):
        return 0
    return 1


def _await_quorum(futs, quorum: int, soft_deadline: float,
                  bytes_counter=None, latency: Optional[_LatencyEwma] = None):
    """Quorum barrier over [(key, future-or-None)] (docs/FAULT_TOLERANCE.md).

    Waits until every future settles, or until `soft_deadline` (absolute
    time.monotonic) passes with at least `quorum` worth of successful
    reply WEIGHT in hand — weight per _reply_weight, so a subtree sum
    counts its whole contributor set and a forwarded ack counts nothing
    (plain replies weigh 1, keeping the knobs-off count unchanged).
    Returns (ok, failed, pending): ok/failed as _await_futures,
    pending = [(key, future)] still in flight — the caller decides
    whether to hedge their slices, keep waiting, or discard them (late
    settles are idempotent: nobody reads an abandoned future).  Reply
    bytes and per-worker latencies are accounted as replies ARRIVE, so
    discarded stragglers still feed the EWMA that adapts the deadline."""
    cv = threading.Condition()

    def _notify(_):
        with cv:
            cv.notify()

    t_sent = time.monotonic()
    ok, failed, pending = [], [], []
    ok_weight = 0
    for key, fut in futs:
        if fut is None:
            failed.append((key, ValueError("channel closed")))
        else:
            pending.append((key, fut))
            fut.add_done_callback(_notify)
    while pending:
        still = []
        for key, fut in pending:
            if not fut.done():
                still.append((key, fut))
                continue
            try:
                reply = fut.result()
                if bytes_counter is not None:
                    bytes_counter.increment(reply.ByteSize())
                if latency is not None:
                    latency.record(key, time.monotonic() - t_sent)
                ok.append((key, reply))
                ok_weight += _reply_weight(reply)
            except grpc.RpcError as e:
                failed.append((key, e.code()))
        pending = still
        if not pending:
            break
        now = time.monotonic()
        remaining = soft_deadline - now
        if remaining <= 0 and ok_weight >= quorum:
            break
        with cv:
            # past the soft deadline but below quorum: keep waiting (the
            # per-call gRPC deadline is the hard bound), waking on settles
            cv.wait(timeout=0.25 if remaining <= 0
                    else max(0.005, min(0.25, remaining)))
    return ok, failed, pending


def _draw_ids(rng: np.random.Generator, part: np.ndarray, start: int,
              size: int) -> np.ndarray:
    """Uniform without-replacement draw of up to `size` sample ids from one
    worker's partition, clipped by the epoch cursor exactly like the
    reference's slice of a fresh permutation.

    The reference (Master.scala:184) re-permutes the ENTIRE partition
    every batch window and slices [start : start+size] — a fresh
    permutation per window makes that slice nothing more than a uniform
    without-replacement draw of min(size, len(part)-start) ids, at
    O(|part|) host work per window.  Generator.choice(replace=False) draws
    the same distribution at O(size): ~16 us vs ~6 ms on a 200k-sample
    partition.  The stream stays keyed by (seed, epoch) in the caller, so
    checkpoint resume replays identical draws."""
    take = min(int(size), max(0, len(part) - start))
    if take <= 0:
        return np.empty(0, dtype=np.int64)
    return np.asarray(part)[rng.choice(len(part), size=take, replace=False)]


class _DispatchStager:
    """Pooled round-(t+1) dispatch staging (DSGD_STAGE_POOL,
    docs/SCALING.md).

    The serialized master draws every worker's sample ids ON the dispatch
    critical path, one worker after another, each round.  With staging
    on, round t+1's draws run on the stage pool DURING round t's barrier
    (the main thread is blocked in gRPC with the GIL released, so the
    staging thread genuinely overlaps) — dispatch then starts from a
    ready ids-by-worker map.

    Determinism is the whole contract.  The sample stream is one
    epoch-keyed np.random.Generator consumed in (round, worker) order;
    a resumed fit replays it from a snapshotted bit-generator state.  So:

    - the pre-draw consumes the SAME values, in the SAME order, the
      serial path's next round would have consumed (one staging task
      draws all workers sequentially — never one task per worker);
    - the pre-draw snapshots the generator state first, and ANY
      discard — a retry re-dispatching the same cursor, a resplit
      changing membership/partitions, an epoch ending — RESTORES it, so
      the serial path's draw at that point reads the exact values it
      would have read had staging never run;
    - `rng_state()` exposes the state a SERIAL run would hold right now
      (the pre-draw base while a stage is pending), which is what the
      crash-safe fit-state snapshot must persist — persisting the
      post-pre-draw state would make a resumed fit skip a round's draws.

    The same pool is handed to `_BroadcastState` so per-worker request
    builds (weight-arm attach + frame construction) fan out across it at
    encode time; `hits`/`discards` feed master.sync.stage.* counters."""

    def __init__(self, pool_size: int):
        from concurrent.futures import ThreadPoolExecutor

        self.pool = ThreadPoolExecutor(
            max_workers=max(1, int(pool_size)), thread_name_prefix="stage-pool")
        self._fut = None
        self._base_state = None
        self._tag: Optional[Tuple[int, int]] = None
        self._keys: List[Tuple[str, int]] = []
        self.hits = 0
        self.discards = 0

    def stage(self, rng, keys, parts, epoch: int, cursor: int,
              span: int) -> None:
        """Arm one pre-draw for (epoch, cursor); call only with no stage
        pending (take/discard every round)."""
        assert self._fut is None, "a staged draw is already pending"
        self._base_state = rng.bit_generator.state
        self._tag = (int(epoch), int(cursor))
        self._keys = list(keys)
        parts = list(parts)

        def _draw_all():
            # sequential, in fan-out order: the exact consumption pattern
            # of the serial dispatch loop
            return [_draw_ids(rng, part, cursor, span) for part in parts]

        self._fut = self.pool.submit(_draw_all)

    def take(self, rng, keys, epoch: int, cursor: int):
        """The staged ids-by-worker map when the staging assumptions still
        hold (same epoch, same window cursor, same membership); None
        otherwise — the generator state is restored and the caller draws
        serially, reading the values a never-staged run would read."""
        if self._fut is None:
            return None
        draws = self._fut.result()  # join: surfaces staging exceptions
        self._fut = None
        if self._tag != (int(epoch), int(cursor)) or list(keys) != self._keys:
            rng.bit_generator.state = self._base_state
            self._base_state = None
            self.discards += 1
            return None
        self._base_state = None
        self.hits += 1
        return dict(zip(self._keys, draws))

    def discard(self, rng) -> None:
        """Membership moved under the stage (resplit): drop the pre-drawn
        ids and restore the generator."""
        if self._fut is None:
            return
        self._fut.result()
        self._fut = None
        rng.bit_generator.state = self._base_state
        self._base_state = None
        self.discards += 1

    def rng_state(self, rng):
        """The bit-generator state a SERIAL run would hold right now — the
        pre-draw base while a stage is pending, the live state otherwise.
        Crash-safe fit-state snapshots persist THIS, never the raw state."""
        return (self._base_state if self._fut is not None
                else rng.bit_generator.state)

    def close(self) -> None:
        self.pool.shutdown(wait=False)


class _BroadcastState:
    """Versioned master->worker weight broadcast for fit_sync
    (docs/SYNC_PIPELINE.md).

    Tracks the master's weight version, each worker's last-acknowledged
    replica version, and encodes — at most once per version — the wire
    forms a window can need: the full tensor, the sparse WeightDelta vs
    the previous version (absolute new values at the changed coordinates),
    or nothing at all (header-only, when the worker's replica is already
    current — retry windows re-serialize zero bytes).  With
    `delta_broadcast` off it degrades to the pre-pipeline wire — every
    request carries the full dense tensor and no version fields, byte-
    identical to the seed — while still re-encoding only when the weights
    actually changed.

    The sparse form is used only while it is cheaper than the tensor
    (8 bytes/changed coordinate vs 4 bytes/element dense: break-even at
    50% density); denser updates fall back to a full broadcast, as do a
    (re)joined worker, a worker more than one version behind, and any
    stale_version reply.
    """

    SPARSE_BREAK_EVEN = 0.5  # changed fraction above which dense is smaller

    def __init__(self, delta_broadcast: bool, metrics, versioned: bool = False,
                 encode_ahead: bool = True, stage_pool=None):
        self.delta_broadcast = delta_broadcast
        self.metrics = metrics
        # pooled dispatch (DSGD_STAGE_POOL, docs/SCALING.md): when a stage
        # pool executor is handed in, _build_staged fans the per-worker
        # request builds (weight-arm attach included) across it instead of
        # building N requests serially on the one encoder thread — and
        # staging is armed for UNARY fits too (raw GradientRequests
        # instead of stream Frames), so the serialized per-worker build
        # leaves the dispatch critical path on both transports
        self._stage_exec = stage_pool
        # encode-ahead (ROADMAP item 2): `advance()` hands the new
        # version's wire forms (full tensor bytes + the np.nonzero sparse
        # delta) to a single background encoder thread, overlapping the
        # encode with the window's host-side bookkeeping (fit-state
        # snapshot, membership check, sample draws) and — under quorum —
        # with straggler replies still in flight.  `populate` joins the
        # pending encode before reading, so the wire forms are
        # byte-identical to the synchronous path; with encode_ahead off
        # (or before the first advance) encoding stays lazy in populate.
        self.encode_ahead = bool(encode_ahead)
        self._enc_pool = None
        self._enc_future = None
        # `versioned` without delta_broadcast (the quorum barrier's mode):
        # every request still carries the full dense tensor, but stamped
        # with step_version — the workers' EF guard and the quorum
        # contribution mask (GradientRequest.ef_rollback_version) both key
        # on the version, so quorum + compression is correct on the
        # otherwise-unpipelined wire too
        self.versioned = bool(delta_broadcast or versioned)
        # versions start at 1: step_version=0 on the wire means "no version
        # tracking" (a pre-pipeline master), and the workers' EF retry
        # guard keys on the version alone whenever one is present — a
        # retried window may switch wire form (full -> header-only) while
        # keeping its version, so the version must never be ambiguous
        self.version = 1 if self.versioned else 0
        self._worker_ver: Dict[Tuple[str, int], int] = {}
        self._w_prev: Optional[np.ndarray] = None
        # the version's wire forms (full tensor / sparse delta), each
        # encoded lazily at most once — the shared versioned weight-send
        # plan (rpc/codec.py WeightSendPlan), the SAME path the serving
        # fleet's checkpoint push and the shard lanes ride
        self._send_plan: Optional[codec.WeightSendPlan] = None
        # pre-staged round dispatch (DSGD_STREAM, docs/SYNC_PIPELINE.md
        # "Streaming transport"): with staging armed (stage_for), the
        # encoder thread ALSO builds each worker's next request frame —
        # weight arm attached, version stamped — so when the window
        # barrier closes, dispatch is one sample draw + one stream write
        # per worker with zero weight re-serialization on the critical
        # path.  Entries carry the assumptions they were built under
        # (version, the worker's acknowledged version) and are discarded
        # when reality moved (stale fallback, resplit, retry window).
        self._stage_keys: list = []
        self._stage_ctx: Optional[Tuple[int, int, int, float]] = None
        self._stage_frames = True
        self._stage_lock = threading.Lock()
        self._staged: Dict[Tuple[str, int], tuple] = {}

    def stage_for(self, keys, fit_token: int, local_steps: int,
                  batch_size: int, learning_rate: float,
                  frames: bool = True) -> None:
        """Arm (or re-arm after a membership change) request staging for
        `keys`; takes effect from the next advance().  `frames=True`
        stages stream `pb.Frame`s (the DSGD_STREAM dispatch path);
        `frames=False` stages raw `pb.GradientRequest`s for the unary
        plane (DSGD_STAGE_POOL) — with neither knob on, nothing ever
        calls this and populate()'s call graph stays untouched."""
        self._stage_keys = list(keys)
        self._stage_ctx = (int(fit_token), int(local_steps),
                           int(batch_size), float(learning_rate))
        self._stage_frames = bool(frames)
        with self._stage_lock:
            self._staged = {}

    def advance(self, w_new: np.ndarray, w_old: np.ndarray) -> None:
        """Weights moved: bump the version, invalidate encoded forms, and
        (encode_ahead) start encoding the new version off-thread."""
        self.version += 1
        self._w_prev = w_old
        self._send_plan = None
        with self._stage_lock:
            self._staged = {}
        if not self.encode_ahead:
            return
        if self._enc_pool is None:
            import weakref
            from concurrent.futures import ThreadPoolExecutor

            self._enc_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="bcast-encode")
            # the broadcast state is fit-scoped: release the encoder
            # thread when the fit drops it (every exit path, exceptions
            # included) without threading a close() through fit_sync
            weakref.finalize(self, self._enc_pool.shutdown, wait=False)
        self._enc_future = self._enc_pool.submit(self._preencode, w_new)

    def _preencode(self, w: np.ndarray) -> None:
        """Encoder-thread body: build the forms `populate` will need —
        the resolved plan lands in the lazy slot, `_join_encode` gives
        the happens-before edge — then stage per-worker request frames
        when staging is armed (the slot is set by then, so _attach_arm
        never joins from the encoder thread itself)."""
        plan = self._new_plan(w)
        plan.full()
        if self.delta_broadcast:
            plan.delta()  # "use the full form" is itself a computed result
        self._send_plan = plan
        if self._stage_keys and self._stage_ctx is not None:
            self._build_staged(w)

    def _build_staged(self, w: np.ndarray) -> None:
        """Encoder-thread tail: one ready-to-send Frame (stream) or
        GradientRequest (unary, stage-pool fits) per staged worker for the
        NEXT window, fanned across the stage pool when one was handed in
        (per-worker weight-arm attach is the O(N x dim) serial wall this
        removes).  Wire accounting stays at dispatch time
        (take_staged_frame / take_staged_request), so counters equal the
        populate() path's."""
        token, k, bs, lr = self._stage_ctx
        version = self.version
        frames = self._stage_frames

        def _build(key):
            if frames:
                frame = pb.Frame()
                req = frame.request
                msg = frame
            else:
                req = pb.GradientRequest()
                msg = req
            req.fit_token = token
            if k > 1:
                req.local_steps = k
                req.batch_size = bs
                req.learning_rate = lr
            assumed = self._worker_ver.get(key)
            form, nbytes = self._attach_arm(req, key, w)
            return key, (msg, form, nbytes, assumed, version)

        keys = list(self._stage_keys)
        if self._stage_exec is not None and len(keys) > 1:
            staged = dict(self._stage_exec.map(_build, keys))
        else:
            staged = dict(_build(key) for key in keys)
        with self._stage_lock:
            self._staged = staged

    def _take_staged(self, key, frames: bool):
        """The pre-staged message for `key` if its staging assumptions
        still hold (same broadcast version, same acknowledged worker
        version, same transport); None otherwise — the caller builds and
        populates a fresh one.  Joins the encoder first, exactly like
        populate()'s lazy reads, and accounts the send here so metrics
        match the unstaged path."""
        self._join_encode()
        with self._stage_lock:
            if self._stage_frames != frames:
                return None
            item = self._staged.pop(key, None)
        if item is None:
            return None
        msg, form, nbytes, assumed, version = item
        if version != self.version or self._worker_ver.get(key) != assumed:
            return None  # stale fallback / resplit moved under the stage
        metrics_mod.record_broadcast(self.metrics, form, nbytes)
        return msg

    def take_staged_frame(self, key):
        """Stream dispatch's staged `pb.Frame`, or None (build fresh)."""
        return self._take_staged(key, frames=True)

    def take_staged_request(self, key):
        """Unary dispatch's staged `pb.GradientRequest`, or None."""
        return self._take_staged(key, frames=False)

    def _join_encode(self) -> None:
        f = self._enc_future
        if f is not None:
            f.result()  # surfaces encoder exceptions on the fit thread
            self._enc_future = None

    def note_ok(self, key) -> None:
        self._worker_ver[key] = self.version

    def note_stale(self, key) -> None:
        self._worker_ver.pop(key, None)

    def forget_missing(self, keys) -> None:
        """Membership changed: drop version claims for departed workers so
        a same-endpoint rejoin starts from a full broadcast."""
        live = set(keys)
        for k in [k for k in self._worker_ver if k not in live]:
            self._worker_ver.pop(k, None)

    def populate(self, req, key, w: np.ndarray) -> None:
        """Attach the cheapest valid weight arm for worker `key` to `req`
        and account it (utils/metrics.py master.sync.bcast.*)."""
        form, nbytes = self._attach_arm(req, key, w)
        metrics_mod.record_broadcast(self.metrics, form, nbytes)

    def _attach_arm(self, req, key, w: np.ndarray):
        """Choose + attach the weight arm for `key`; returns the
        (form, bytes) pair the caller accounts.  Shared by populate()
        (dispatch thread, joins the encoder through the lazy slot reads)
        and _build_staged (encoder thread, slots already set)."""
        if not self.delta_broadcast:
            full = self._plan_for(w).full()
            req.weights.CopyFrom(full)
            if self.versioned:
                req.step_version = self.version
            return "full", full.ByteSize()
        req.step_version = self.version
        plan = self._plan_for(w)
        arm = plan.choose_arm(self._worker_ver.get(key), self.version)
        if arm == "cached":
            return "cached", 0
        if arm == "delta":
            delta = plan.delta()
            req.delta.CopyFrom(delta)
            return "delta", delta.ByteSize()
        full = plan.full()
        req.weights.CopyFrom(full)
        return "full", full.ByteSize()

    def _new_plan(self, w: np.ndarray) -> "codec.WeightSendPlan":
        """This version's shared weight-send plan (rpc/codec.py): the
        delta-vs-full choice and both lazy encodes live in the ONE
        helper the checkpoint pusher and the shard lanes also walk.
        Without delta_broadcast the sparse form is disabled outright
        (w_prev=None), so the plan degrades to a lazy encode_tensor."""
        return codec.plan_weight_send(
            w, self._w_prev if self.delta_broadcast else None,
            base_version=self.version - 1,
            break_even=self.SPARSE_BREAK_EVEN)

    def _plan_for(self, w: np.ndarray) -> "codec.WeightSendPlan":
        # slot first, join only on a miss: a set slot IS the encoder's
        # finished result (assigned last, forms already resolved), and
        # checking first lets the encoder thread itself resolve forms
        # while staging frames without deadlocking on its own future
        if self._send_plan is None:
            self._join_encode()
        if self._send_plan is None:
            self._send_plan = self._new_plan(w)
        return self._send_plan


class MasterNode:
    def __init__(
        self,
        host: str,
        port: int,
        train: Dataset,
        test: Dataset,
        model: LinearModel,
        expected_workers: int,
        seed: int = 0,
        metrics: Optional[metrics_mod.Metrics] = None,
        rpc_policy: Optional[RpcPolicy] = None,
    ):
        self.host, self.port = host, port
        self.log = node_logger(host, port, master=True)
        self.metrics = metrics or metrics_mod.global_metrics()
        # unified control-plane RPC policy (deadline / backoff / breaker)
        # replacing the scattered hardcoded timeout=5.0 calls
        self.rpc_policy = rpc_policy or RpcPolicy(seed=seed,
                                                  metrics=self.metrics)
        # per-worker reply latency EWMAs: feed the quorum barriers'
        # adaptive soft deadlines (fit_sync / predict quorum params).
        # Gradient and Forward latencies differ by an order of magnitude,
        # so each fan-out keeps its own tracker
        self._latency = _LatencyEwma()
        self._fwd_latency = _LatencyEwma()
        self.model = model
        self.train = train
        self.test = test
        self.expected_workers = expected_workers
        self.seed = seed
        # O(N) master plane defaults (docs/SCALING.md): fit_sync resolves
        # its fanin_lanes / stage_pool parameters against these when the
        # caller passes None (main.py passes the DSGD_* config values
        # explicitly; tests and embedders may pin the attributes instead)
        self.fanin_lanes = 0
        self.stage_pool = 0
        # aggregation-tree plane default (DSGD_AGG_TREE, docs/AGGREGATION.md):
        # "" = flat fan-in; "fanout:F" elects sub-aggregator reduce nodes
        self.agg_tree = ""
        # feature-sharded master plane default (DSGD_MASTER_SHARDS,
        # docs/MASTER_SHARDING.md): 0 = the flat single-master wire;
        # M >= 1 range-partitions the weight vector across M shard lanes
        self.master_shards = 0
        # the in-flight fit's shard coordinator (set/cleared by fit_sync);
        # kill_shard() routes the bench chaos hook through it
        self._shard_coord = None
        # last sharded fit's per-lane wire ledger, [(index, bcast_bytes,
        # grad_bytes)] — the bench's bytes-per-process gate reads it after
        # the fit returns (the coordinator itself is fit-scoped)
        self._last_shard_bytes = None

        self._workers: Dict[Tuple[str, int], WorkerStub] = {}
        self._channels: Dict[Tuple[str, int], grpc.Channel] = {}
        self._order: List[Tuple[str, int]] = []  # registration order
        # persistent per-worker gradient streams (DSGD_STREAM,
        # docs/SYNC_PIPELINE.md "Streaming transport"): opened lazily by
        # the first streamed dispatch of a fit, closed at fit end /
        # unregister / stop.  Empty forever when no fit runs with
        # stream=True — the knobs-off call graph never touches FitStream
        # (asserted by tests/test_stream.py).
        self._streams: Dict[Tuple[str, int], object] = {}
        self._streams_lock = threading.Lock()
        # peers whose binary answered UNIMPLEMENTED to FitStream: skew is
        # per PROCESS, not per fit — the set outlives the fit-scoped
        # clients above (harvested in _close_streams) so a later fit never
        # re-probes a known-old binary.  Cleared per peer on unregister: a
        # worker restarting on the same endpoint may run a NEW binary.
        self._stream_unsupported: set = set()
        # host shapes (docs/HIERARCHY.md): local device count each worker
        # reported at registration (Node.devices; 0/absent = flat single-
        # device worker).  Feeds the host-granular weighted split below.
        self._worker_devices: Dict[Tuple[str, int], int] = {}
        self._members_lock = threading.Lock()
        self.cluster_ready = threading.Event()  # Master.scala:34-35

        # master-local eval (Master.localLoss/localAccuracy) on this device
        engine = SyncEngine(model, make_mesh(1), batch_size=1, learning_rate=0.0)
        self._eval_train = engine.bind(train)
        self._eval_test = engine.bind(test)

        # async state (AsyncMasterGrpcImpl)
        self._async_lock = threading.Lock()
        self._w_async: Optional[jax.Array] = None
        self._updates = 0
        self._max_steps = 0
        self._async_running = threading.Event()
        # inverse of _async_running for interruptible sleeps: CLEAR while a
        # fit runs (so wait(backoff) really sleeps), SET on budget/stop (so
        # the check loop wakes immediately instead of a full backoff later)
        self._async_done = threading.Event()
        self._apply = jax.jit(lambda w, d: w - d)
        # batch-drain inbox (docs/ELASTICITY.md; ROADMAP item 4): with
        # fit_async(batch_drain=True) incoming UpdateGrads buffer here and
        # a drain thread applies ONE summed update per drain — deltas
        # commute (parallel/hogwild.py _drain_inbox), so the per-message
        # jitted apply under _async_lock stops being the scaling wall.
        # Off (default) the servicer applies per message, byte-identical
        # to the pre-drain engine.
        self._inbox: List[Tuple[np.ndarray, int]] = []
        self._inbox_cv = threading.Condition()
        self._drain_on = False
        # long-horizon resource plane (telemetry/resources.py, ISSUE 20):
        # publish the drain-inbox depth as a pressure source — a slowly
        # filling inbox is the classic async-plane death.  The weakref
        # closure returns None once this master is collected, which
        # self-unregisters the source; registration is a dict insert, so
        # knobs-off runs (no probe thread) never call it.
        inbox_ref = weakref.ref(self)
        self._inbox_pressure_token = resources.register_pressure(
            metrics_mod.PROC_PRESSURE_DRAIN_INBOX,
            lambda: (len(m._inbox) if (m := inbox_ref()) is not None
                     else None))
        # endpoints that RE-registered while already members (a worker
        # process restarted on the same host:port before any eviction —
        # the new process idles with no assignment, heartbeats succeed,
        # and membership is unchanged, so neither the elastic resplit nor
        # the stall watchdog would ever re-issue its slice); the async
        # fit loop kicks these with their current assignment each tick
        self._rereg_pending: set = set()

        # cluster telemetry plane (telemetry/, DSGD_TELEMETRY,
        # docs/OBSERVABILITY.md): enable_telemetry() installs the scrape
        # aggregator (+ optional cluster /metrics endpoint); None (default)
        # means no Metrics RPC is ever issued — knobs-off call graph and
        # wire stay byte-identical
        self.telemetry = None
        self.telemetry_exporter = None

        self.server = new_server(port, host="0.0.0.0")
        self.port = self.port or self.server.bound_port
        add_master_servicer(self.server, _MasterServicer(self), node="master")

        # heartbeat failure detection (superset; SURVEY.md §5.3: the
        # reference has none and a dead worker hangs the sync barrier)
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        # fit-session counter: each fit_sync stamps its GradientRequests
        # with a fresh token so long-lived workers reset their sync-reply
        # EF residuals between fits (GradientRequest.fit_token).  The base
        # is a per-incarnation nonce: a RESTARTED master must not reuse a
        # token its long-lived workers already saw, or the worker would
        # skip the reset and leak the dead master's residual into the new
        # fit (48-bit nonce + 15-bit sequence stays inside int64)
        import random as _random

        self._fit_token_base = _random.getrandbits(48) << 15
        self._fit_seq = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self, heartbeat_s: Optional[float] = None,
              heartbeat_max_misses: int = 3) -> "MasterNode":
        """`heartbeat_max_misses` (DSGD_HEARTBEAT_MAX_MISSES) is the
        consecutive-miss eviction threshold — 3 keeps the historical
        hardcoded default."""
        self.server.start()
        self.log.info("master started on %s:%d, expecting %d workers",
                      self.host, self.port, self.expected_workers)
        if heartbeat_s:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                args=(heartbeat_s, max(1, int(heartbeat_max_misses))),
                daemon=True, name="heartbeat",
            )
            self._hb_thread.start()
        return self

    def enable_telemetry(self, port: Optional[int] = None,
                         scrape_min_age_s: float = 0.5):
        """Install the cluster telemetry plane (telemetry/aggregate.py):
        the master scrapes every registered worker's instrument registry
        over the Metrics RPC — piggybacked on the heartbeat cadence when
        the heartbeat runs, and refreshed on demand (throttled by
        `scrape_min_age_s`) whenever the cluster endpoint is pulled — and
        re-exports the merged series on one `/metrics` endpoint bound to
        `port` (0 = OS-assigned; None = aggregator only, no endpoint).
        Returns the ClusterTelemetry so embedders can render directly."""
        from distributed_sgd_tpu.telemetry.aggregate import (
            ClusterExporter,
            ClusterTelemetry,
        )

        self.telemetry = ClusterTelemetry(self.metrics, node="master",
                                          role="master")
        if port is not None:
            self.telemetry_exporter = ClusterExporter(
                self.telemetry.prometheus_text, port,
                refresh=lambda: self.scrape_telemetry(
                    min_age_s=scrape_min_age_s),
            ).start()
            self.log.info("cluster telemetry endpoint on :%d",
                          self.telemetry_exporter.port)
        return self.telemetry

    def scrape_telemetry(self, min_age_s: float = 0.0) -> int:
        """One (throttled) Metrics-RPC scrape over the current members;
        returns snapshots merged.  Safe from any thread; never raises."""
        if self.telemetry is None:
            return 0
        return self.telemetry.scrape(self._members(), self.rpc_policy,
                                     min_age_s=min_age_s)

    # bounded liveness-probe pool (docs/SCALING.md): at most this many
    # Ping futures in flight at once — at O(N) workers a thundering-herd
    # sweep would hold N channels' worth of pending probes while the
    # per-probe deadline bounds each one anyway.  Probes past the cap
    # defer one wheel quantum; liveness latency stays per-worker.
    HB_PROBE_POOL = 16

    def _heartbeat_loop(self, interval_s: float, max_failures: int = 3) -> None:
        """O(1)-latency liveness (docs/SCALING.md): per-worker probes on a
        shared deadline wheel instead of the old all-members sweep.

        The sweep awaited EVERY probe before the next cycle — one wedged
        peer stretched every worker's liveness cadence by the probe
        deadline, so eviction latency grew with the slowest member.  Here
        each worker owns a wheel entry: its probe fires at its own due
        time, settles on its own deadline, and re-arms itself `interval_s`
        after completion — a slow peer delays only itself.  Initial due
        times stagger across one interval so N probes never land as one
        herd.  Eviction decisions (the PR 6 semantics: `max_failures`
        consecutive misses, success resets, unregister_worker(evicted=
        True)) run on THIS thread — gRPC callbacks only enqueue
        completions — and the telemetry piggyback keeps its cadence on a
        sidecar thread so a slow scrape never delays a probe."""
        from distributed_sgd_tpu.rpc.stream import Wheel

        tracker = _FailureTracker(max_failures)
        # probe deadline: the interval, capped by the policy deadline so a
        # long interval doesn't grant a wedged peer a long blocking probe
        probe_timeout = min(interval_s, self.rpc_policy.deadline_s)
        # telemetry piggyback (docs/OBSERVABILITY.md): the scrape rides
        # the liveness cadence — concurrent futures bounded by the probe
        # deadline, breaker-consulting, failures degrade to counters — on
        # its own sidecar thread, so a degraded scrape can delay the VIEW
        # but never the eviction probes.  Armed lazily each tick because
        # enable_telemetry() typically runs AFTER start().
        scrape_armed = False

        def _scrape_loop():
            while not self._hb_stop.wait(interval_s):
                # leak-slope gauges first (docs/OBSERVABILITY.md): the
                # sidecar is the process's hours-horizon cadence, so RSS /
                # open-fd samples land in the same exposition the scrape
                # refreshes — what the flywheel bench's slope assert reads
                metrics_mod.sample_process_gauges(self.metrics)
                self.telemetry.scrape(self._members(), self.rpc_policy,
                                      deadline_s=probe_timeout)

        wheel = Wheel(name="heartbeat-wheel")
        due_ready: "collections.deque" = collections.deque()  # keys due now
        completions: "collections.deque" = collections.deque()  # (key, ok)
        wake = threading.Event()
        scheduled: set = set()   # keys with a wheel entry or probe in flight
        in_flight: set = set()
        deferred: List[Tuple[str, int]] = []  # due past the probe-pool cap

        def _fire(key):
            due_ready.append(key)
            wake.set()

        def _probe(key, stub):
            in_flight.add(key)
            try:
                fut = stub.Ping.future(pb.Empty(), timeout=probe_timeout)
            except ValueError:  # channel closed under us (unregister/stop)
                completions.append((key, False))
                wake.set()
                return

            def _done(f, key=key):
                try:
                    f.result()
                    completions.append((key, True))
                except Exception:  # noqa: BLE001 - any failure is a miss
                    completions.append((key, False))
                wake.set()

            fut.add_done_callback(_done)

        while not self._hb_stop.is_set():
            if self.telemetry is not None and not scrape_armed:
                scrape_armed = True
                threading.Thread(target=_scrape_loop, daemon=True,
                                 name="telemetry-scrape").start()
            now = time.monotonic()
            members = self._members()
            stub_by_key = dict(members)
            # new members join the wheel with phases staggered across one
            # interval; departed members' entries die on fire (no stub)
            fresh = [k for k, _ in members if k not in scheduled]
            for i, key in enumerate(fresh):
                scheduled.add(key)
                wheel.watch(now + interval_s * (i + 1) / (len(fresh) + 1),
                            lambda key=key: _fire(key))
            # completions first: decide liveness on THIS thread
            while completions:
                key, ok = completions.popleft()
                in_flight.discard(key)
                with self._members_lock:
                    still_member = key in self._workers
                if not still_member:
                    scheduled.discard(key)
                    tracker.record_ok(key)  # drop any stale miss count
                    continue
                if ok:
                    tracker.record_ok(key)
                else:
                    n, evict = tracker.record_failure(key)
                    self.log.warning("heartbeat miss %d/%d for %s:%d",
                                     n, max_failures, *key)
                    if evict:
                        self.log.warning("worker %s:%d declared dead", *key)
                        self.unregister_worker(*key, evicted=True)
                        scheduled.discard(key)
                        continue
                wheel.watch(time.monotonic() + interval_s,
                            lambda key=key: _fire(key))
            # fire due probes, bounded by the probe pool
            pending = deferred + [due_ready.popleft()
                                  for _ in range(len(due_ready))]
            deferred = []
            for key in pending:
                stub = stub_by_key.get(key)
                if stub is None or key not in scheduled:
                    scheduled.discard(key)
                    # drop any stale miss count: a re-registration on the
                    # same host:port must not inherit the departed
                    # incarnation's consecutive-miss tally
                    tracker.record_ok(key)
                    continue
                if len(in_flight) >= self.HB_PROBE_POOL:
                    deferred.append(key)  # next wake re-offers it
                    continue
                _probe(key, stub)
            wake.wait(timeout=min(interval_s, 0.5) if deferred
                      else interval_s)
            wake.clear()

    def stop(self) -> None:
        self._hb_stop.set()
        self._async_running.clear()
        self._async_done.set()
        self._close_streams()
        resources.unregister_pressure(
            metrics_mod.PROC_PRESSURE_DRAIN_INBOX, self._inbox_pressure_token)
        if self.telemetry_exporter is not None:
            self.telemetry_exporter.stop()
        self.server.stop(grace=1.0)
        for ch in self._channels.values():
            ch.close()
        self.log.info("master stopped")

    def await_ready(self, timeout: Optional[float] = None) -> bool:
        return self.cluster_ready.wait(timeout)

    # -- membership (Master.scala:222-253) ---------------------------------

    def register_worker(self, host: str, port: int, devices: int = 0) -> None:
        """Join-cap semantics: at most `expected_workers` members at any
        instant (the reference `require`s the same cap, Master.scala:224),
        but the cap is on CURRENT membership, not lifetime joins — an
        eviction (heartbeat, Gradient/Forward failure, graceful leave)
        frees a slot, so a restarted worker re-registers and a running
        fit_sync absorbs it at its next batch via the live-membership
        re-split (elastic grow-back up to the configured cluster size;
        tests/test_fault_tolerance.py::test_worker_rejoins_mid_fit)."""
        key = (host, port)
        rereg_stub = None
        with self._members_lock:
            # host shape (docs/HIERARCHY.md): recorded for members and
            # re-registrations alike (a restarted process may change its
            # device count); 0/absent = flat
            if devices > 0:
                self._worker_devices[key] = int(devices)
            else:
                self._worker_devices.pop(key, None)
            if key in self._workers:
                # already a member: either a redundant registration retry
                # (first attempt landed but its reply was lost) or a worker
                # process RESTARTED on the same endpoint — during an async
                # fit both are safe to answer with a fresh StartAsync kick
                # (the worker side replaces a running loop idempotently),
                # and the restarted-process case REQUIRES it: the idle new
                # process passes heartbeats, so nothing else would ever
                # re-issue its slice
                if self._async_running.is_set():
                    self._rereg_pending.add(key)
                rereg_stub = self._workers[key]
                rereg_others = [k for k in self._workers if k != key]
            elif len(self._workers) >= self.expected_workers:
                raise ValueError("cluster already at expected node count")
            else:
                others = list(self._workers.keys())
                ch = new_channel(host, port, origin=(self.host, self.port))
                stub = WorkerStub(ch)
                self._workers[key] = stub
                self._channels[key] = ch
                self._order.append(key)
                count = len(self._workers)
        if rereg_stub is not None:
            # re-introduce the peer set to the (possibly fresh) process: a
            # restarted worker starts with an EMPTY peer map, and without
            # this its gossip out-edges would stay gone for the rest of the
            # fit (it would send deltas only to the master).  add_peer is
            # idempotent on the worker side, so a redundant registration
            # retry from a live worker is a no-op fan-out.
            for oh, op in rereg_others:
                try:
                    self.rpc_policy.call_with_retry(
                        rereg_stub.RegisterSlave, pb.Node(host=oh, port=op),
                        peer=key, retries=1)
                except grpc.RpcError as e:
                    self.log.warning(
                        "peer re-introduction failed for %s:%d (%s)",
                        oh, op, e.code())
            return
        self.log.info("worker registered: %s:%d (%d/%d)",
                      host, port, count, self.expected_workers)
        # full-mesh introduction, both directions (Master.scala:229-233)
        new_node = pb.Node(host=host, port=port)
        for oh, op in others:
            try:
                # full policy (deadline + one jittered retry + breaker): a
                # transient blip must not silently cost the mesh an edge
                self.rpc_policy.call_with_retry(
                    self._workers[(oh, op)].RegisterSlave, new_node,
                    peer=(oh, op), retries=1)
                self.rpc_policy.call_with_retry(
                    stub.RegisterSlave, pb.Node(host=oh, port=op),
                    peer=key, retries=1)
            except grpc.RpcError as e:
                self.log.warning("peer introduction failed for %s:%d (%s)", oh, op, e.code())
        if count >= self.expected_workers:
            self.cluster_ready.set()  # Master.scala:235-241

    def unregister_worker(self, host: str, port: int,
                          evicted: bool = False) -> None:
        """`evicted=True` marks an involuntary removal (heartbeat miss,
        Gradient/Forward failure threshold, async watchdog) — those dump
        the flight recorder so a dead worker leaves post-mortem evidence
        even with tracing off; a graceful leave does not."""
        key = (host, port)
        if evicted:
            self.metrics.counter(metrics_mod.MASTER_EVICTIONS).increment()
            flight.record("worker.evicted", worker=f"{host}:{port}")
            flight.dump("eviction")
        # the departed worker's gradient stream dies with its membership
        # (its channel closes below; a half-open stream would otherwise
        # pin pending futures until their frame deadlines), and its skew
        # marker clears — a same-endpoint rejoin may be a newer binary
        with self._streams_lock:
            stream = self._streams.pop(key, None)
            self._stream_unsupported.discard(key)
        if stream is not None:
            stream.close()
        if self.telemetry is not None:
            # a departed worker's series leave the cluster exposition with
            # its membership (its final snapshot would otherwise pin stale
            # gauges forever)
            self.telemetry.drop(key)
        with self._members_lock:
            self._workers.pop(key, None)
            ch = self._channels.pop(key, None)
            self._worker_devices.pop(key, None)
            if key in self._order:
                self._order.remove(key)
            remaining = list(self._workers.values())
        if ch is not None:
            ch.close()
        node = pb.Node(host=host, port=port)
        for stub in remaining:  # broadcast (Master.scala:245-253)
            try:
                stub.UnregisterSlave(node, timeout=self.rpc_policy.deadline_s)
            except (grpc.RpcError, ValueError):
                # ValueError: the recipient's own channel closed under us —
                # at O(N) churn two departures can overlap, and the second
                # leaver's broadcast must not blow up the servicer thread
                pass
        self.log.info("worker unregistered: %s:%d", host, port)

    def _members(self) -> List[Tuple[Tuple[str, int], WorkerStub]]:
        with self._members_lock:
            return [(k, self._workers[k]) for k in self._order]

    def _split_parts(self, split: SplitFn, members) -> List[np.ndarray]:
        """Host-granular sample assignment (docs/HIERARCHY.md).

        When every member is a flat single-device worker — or the host
        shapes are all EQUAL, where proportional and even splits coincide
        — this delegates to `split` untouched, so the knobs-off call
        graph and partitions stay byte-identical to the pre-hierarchy
        engine.  Heterogeneous host shapes weight the contiguous split by
        each host's device count (core/split.py weighted_split) so every
        device across the cluster owns the same expected row count.
        Custom split strategies keep their own semantics: weighting only
        ever replaces the default `vanilla_split`."""
        with self._members_lock:
            devs = [max(1, self._worker_devices.get(k, 1))
                    for k, _ in members]
        if (split is not vanilla_split or not devs
                or len(set(devs)) == 1):
            return split(len(self.train), len(members))
        self.log.info(
            "host-granular split: weighting partitions by device count %s",
            devs)
        return weighted_split(len(self.train), devs)

    def _stubs(self) -> List[WorkerStub]:
        return [stub for _, stub in self._members()]

    # -- streaming fan-out (DSGD_STREAM; docs/SYNC_PIPELINE.md) ------------

    def _grad_stream(self, key, stub):
        """The live FitStream client for `key`, (re)opened lazily.

        The hot path is one lock-free dict read + three flag reads; the
        slow path returns None — sending goes unary — when the peer is
        marked unsupported (an older binary answered UNIMPLEMENTED: skew
        does not heal mid-process, so the marker survives the fit-scoped
        client in `_stream_unsupported` until the peer re-registers),
        when its breaker is suppressing (every stream teardown fed it one
        failure, so a flapping peer degrades to unary until the breaker's
        half-open probe heals it), or when the channel is gone
        (unregistered under us)."""
        s = self._streams.get(key)
        if s is not None and s.usable:
            return s
        from distributed_sgd_tpu.rpc.stream import FitStreamClient

        with self._streams_lock:
            if key in self._stream_unsupported:
                return None
            s = self._streams.get(key)
            if s is not None:
                if s.usable:
                    return s
                if s.unsupported:
                    self._stream_unsupported.add(key)
                    return None
                self._streams.pop(key, None)  # broken: replace below
            if self.rpc_policy.breaker(key).suppressed():
                return None
            with self._members_lock:
                if key not in self._workers:
                    return None
            breaker = self.rpc_policy.breaker(key)
            try:
                s = FitStreamClient(
                    stub.FitStream, peer=f"{key[0]}:{key[1]}",
                    metrics=self.metrics, log=self.log,
                    on_break=breaker.record_failure)
            except Exception:  # noqa: BLE001 - channel closed under us
                return None  # this window goes unary; the barrier classifies
            self._streams[key] = s
            return s

    def _close_streams(self) -> None:
        with self._streams_lock:
            streams, self._streams = dict(self._streams), {}
            # skew outlives the fit-scoped clients: a later fit must not
            # re-probe a peer whose binary already answered UNIMPLEMENTED
            for k, s in streams.items():
                if s.unsupported:
                    self._stream_unsupported.add(k)
        for s in streams.values():
            s.close()

    def _dispatch_gradient(self, key, stub, frame, req, timeout_s: float,
                           use_stream: bool):
        """One window's Gradient send for one worker: a frame write down
        the persistent stream (wrapped so a stream teardown transparently
        replays the request over unary with the remaining deadline), or
        the classic unary future.  Returns a future-alike or None (the
        channel closed under us — the barrier classifies it)."""
        if use_stream and frame is not None:
            s = self._grad_stream(key, stub)
            if s is not None:
                fut = s.send(frame, timeout_s,
                             unary_call=stub.Gradient, request=req)
                if fut is not None:
                    return fut
        try:
            return stub.Gradient.future(req, timeout=timeout_s)
        except ValueError:  # channel closed under us
            return None

    # -- distributed eval (Master.scala:61-98) -----------------------------

    def predict(
        self,
        weights: np.ndarray,
        split: SplitFn = vanilla_split,
        timeout_s: float = 60.0,
        retries: int = 1,
        return_margins: bool = False,
        quorum: Optional[int] = None,
        straggler_soft_s: Optional[float] = None,
    ):
        """Fan ForwardRequests out to every worker; gather predictions
        (and, with `return_margins`, the raw x.w margins — exact input for
        margin-based losses like logistic).

        Same fault policy as fit_sync: per-call deadlines, `retries`
        consecutive failures evict the worker, and the fan-out is retried
        across the survivors with a fresh split.  Raises RuntimeError if
        every worker is lost.

        With `quorum` set the barrier grows straggler hedging
        (docs/FAULT_TOLERANCE.md): once Q replies are in hand and the soft
        deadline (`straggler_soft_s`, or adaptive from the Forward
        latency EWMA) fires, each missing worker's sample slice is
        re-issued to the fastest responders.  Unlike fit_sync's quorum,
        evaluation NEVER drops a slice — predictions for every sample are
        required — so quorum here only bounds how long a straggler can
        hold the fan-out hostage before its slice is recomputed elsewhere;
        an uncoverable slice falls back to the classic retry/evict loop.
        """
        self._require_ready()
        wmsg = codec.encode_tensor(weights)
        tracker = _FailureTracker(retries + 1)
        while True:
            members = self._members()
            if not members:
                raise RuntimeError("all workers lost during predict")
            parts = self._split_parts(split, members)
            part_by_key = {key: ids for (key, _), ids in zip(members, parts)}
            # one trace per eval fan-out attempt (trace/): Forward calls
            # and their hedges become child spans, same as fit_sync windows
            with trace_mod.root_span(trace_mod.SPAN_EVAL_FORWARD,
                                     node="master", workers=len(members)):
                futs = []
                for (key, stub), ids in zip(members, parts):
                    try:
                        fut = stub.Forward.future(
                            pb.ForwardRequest(
                                samples=ids.astype(np.int32), weights=wmsg,
                                want_margins=return_margins,
                            ),
                            timeout=timeout_s,
                        )
                    except ValueError:
                        fut = None
                    futs.append((key, fut))
                if quorum is None:
                    ok, failed = _await_futures(futs)
                else:
                    ok, failed = self._forward_quorum(
                        futs, members, part_by_key, quorum, straggler_soft_s,
                        timeout_s, wmsg, return_margins)
            if not failed:
                out = np.zeros(len(self.train), dtype=np.float32)
                margins = np.zeros(len(self.train), dtype=np.float32)
                for key, reply in ok:
                    ids = part_by_key[key]
                    out[ids] = np.fromiter(reply.predictions, dtype=np.float32)
                    if return_margins:
                        if len(reply.margins) != len(ids):
                            # version-skew tolerance: an older worker that
                            # predates the margins field replies without it
                            margins = None
                        elif margins is not None:
                            margins[ids] = np.fromiter(reply.margins, dtype=np.float32)
                return (out, margins) if return_margins else out
            for key, _ in ok:
                tracker.record_ok(key)
            for key, code in failed:
                n, evict = tracker.record_failure(key)
                if evict:
                    self.log.warning("worker %s:%d failed Forward %d times (%s); "
                                     "declaring dead", key[0], key[1], n, code)
                    self.unregister_worker(*key, evicted=True)
                else:
                    self.log.warning("worker %s:%d failed Forward (%s); retry %d/%d",
                                     key[0], key[1], code, n, retries)

    def _forward_quorum(self, futs, members, part_by_key, quorum,
                        straggler_soft_s, timeout_s, wmsg, want_margins):
        """Quorum-gated Forward barrier with straggler hedging (see
        predict).  Returns (ok, failed) with every entry keyed by the
        SLICE's worker key — a winning hedge reply is recorded under the
        straggler's key, so the caller's slice-addressed assembly and the
        failure tracker both stay oblivious to who actually computed it."""
        quorum_n = min(quorum, len(members))
        soft_s = straggler_soft_s
        if soft_s is None:
            soft_s = self._fwd_latency.soft_deadline_s(
                part_by_key.keys(), quorum_n)
        soft_s = min(soft_s, timeout_s) if soft_s else timeout_s
        ok, failed, pending = _await_quorum(
            futs, quorum_n, time.monotonic() + soft_s,
            latency=self._fwd_latency)
        uncovered = [k for k, _ in pending] + [k for k, _ in failed]
        if uncovered and len(ok) >= quorum_n:
            stub_by_key = dict(members)
            donors = sorted(
                (k for k, _ in ok),
                key=lambda k: self._fwd_latency.p95_s(k) or float("inf"))
            hedges = []
            for i, skey in enumerate(uncovered):
                donor = donors[i % len(donors)]
                try:
                    hfut = stub_by_key[donor].Forward.future(
                        pb.ForwardRequest(
                            samples=part_by_key[skey].astype(np.int32),
                            weights=wmsg, want_margins=want_margins),
                        timeout=min(timeout_s, 2.0 * soft_s))
                except ValueError:
                    continue
                hedges.append((skey, hfut))
                self.metrics.counter(metrics_mod.QUORUM_HEDGES).increment()
                trace_mod.event(trace_mod.EVENT_QUORUM_HEDGE,
                                straggler=f"{skey[0]}:{skey[1]}",
                                donor=f"{donor[0]}:{donor[1]}")
                self.log.info("hedging Forward slice of straggler %s:%d "
                              "on %s:%d", *skey, *donor)
            h_ok, _h_failed = _await_futures(hedges)
            still = []
            for key, fut in pending:  # late originals are preferred
                if not fut.done():
                    still.append((key, fut))
                    continue
                try:
                    ok.append((key, fut.result()))
                except grpc.RpcError as e:
                    failed.append((key, e.code()))
            pending = still
            covered = {k for k, _ in ok}
            for skey, reply in h_ok:
                if skey not in covered:
                    ok.append((skey, reply))
                    covered.add(skey)
                    self.metrics.counter(
                        metrics_mod.QUORUM_HEDGE_WINS).increment()
        elif pending:
            # below quorum: wait the hard deadline out, classic barrier
            ok2, failed2, _ = _await_quorum(
                pending, len(pending) + 1,
                time.monotonic() + timeout_s + 5.0,
                latency=self._fwd_latency)
            ok.extend(ok2)
            failed.extend(failed2)
            pending = []
        covered = {k for k, _ in ok}
        # an uncoverable slice (straggler past soft + hedge deadlines, or
        # its hedge failed too) joins the classic retry/evict path
        failed = [(k, c) for k, c in failed if k not in covered]
        for key, fut in pending:
            if key not in covered:
                failed.append((key, grpc.StatusCode.DEADLINE_EXCEEDED))
        return ok, failed

    def distributed_loss(self, weights: np.ndarray) -> float:
        """Objective from the Forward fan-out (Master.scala:77-98).

        Computes per-sample losses from the workers' MARGINS (requested via
        ForwardRequest.want_margins) — exact for every model:
        prediction-based losses (the reference's hinge) are unchanged
        because losses_from_margins defaults to sample_loss(predict(m)),
        and margin-based losses (logistic) no longer need the mesh path.
        If an older worker replies without margins (version skew), falls
        back to the reference's prediction-based reconstruction — still
        exact for hinge; raises for margin-only losses.
        """
        preds, margins = self.predict(weights, return_margins=True)
        y = self.train.labels
        reg = self.model.lam * float(np.dot(weights, weights))
        if margins is not None:
            sample = np.asarray(
                self.model.losses_from_margins(jnp.asarray(margins), jnp.asarray(y))
            )
        else:
            self.log.warning(
                "a worker replied without margins (older binary?); "
                "reconstructing loss from predictions (Master.scala:77-98)"
            )
            sample = np.asarray(
                self.model.sample_loss(jnp.asarray(preds), jnp.asarray(y))
            )
        return reg + float(sample.mean())

    def distributed_accuracy(self, weights: np.ndarray) -> float:
        preds = self.predict(weights)
        return float((preds == self.train.labels).mean())

    def local_loss(self, weights, test: bool = False) -> Tuple[float, float]:
        bound = self._eval_test if test else self._eval_train
        return bound.evaluate(jnp.asarray(weights, dtype=jnp.float32))

    # -- aggregation tree (aggtree/, docs/AGGREGATION.md) --------------------

    def _build_tree_plan(self, keys, fanout: int):
        """Deterministic reduce tree over the current member list
        (aggtree/plan.py — pure, so every rebuild at the same membership
        lands on the byte-identical plan).  Called only with
        DSGD_AGG_TREE set; registers the tree gauges, which is why the
        knobs-off path must never reach here (tests/test_aggtree.py)."""
        from distributed_sgd_tpu.aggtree import build_plan

        plan = build_plan(keys, fanout, seed=self.seed)
        self.metrics.gauge(metrics_mod.TREE_DEPTH).set(plan.depth)
        self.metrics.gauge(metrics_mod.TREE_EDGES).set(plan.n_edges)
        flight.record("tree.plan", members=len(keys), fanout=int(fanout),
                      depth=plan.depth, edges=plan.n_edges,
                      aggregators=len(plan.aggregators()),
                      digest=plan.digest()[:12])
        self.log.info("aggregation tree: %r", plan)
        return plan

    def kill_shard(self, index: int) -> None:
        """Chaos hook (benches/bench_scale.py --scale chaos row): declare
        master shard `index` of the in-flight sharded fit dead.  The next
        window degrades to ONE flat single-master round, then the shard
        plan rebuilds over the survivors — live workers are never evicted
        for a master-side death (docs/MASTER_SHARDING.md failure
        matrix).  Raises when no sharded fit is in flight."""
        coord = self._shard_coord
        if coord is None:
            raise RuntimeError(
                "kill_shard: no sharded fit in flight "
                "(DSGD_MASTER_SHARDS, docs/MASTER_SHARDING.md)")
        coord.kill(int(index))

    @staticmethod
    def _annotate_tree(req, key, plan, agg_round: int,
                       grad_timeout_s: float) -> None:
        """Stamp one worker's GradientRequest with its tree role.  A
        worker that is a root child with no children gets NO stamp at
        all — its request (and reply) is byte-identical to the flat
        wire, which is also why a trivial plan annotates nothing."""
        parent = plan.parent.get(key)
        kids = plan.children.get(key, ())
        if parent is None and not kids:
            return
        if parent is not None:
            req.agg_parent = f"{parent[0]}:{parent[1]}"
        req.agg_round = int(agg_round)
        if kids:
            del req.agg_children[:]
            req.agg_children.extend(f"{c[0]}:{c[1]}" for c in kids)
            # child-wait budget scaled by subtree height: the deepest
            # nodes time out first, so partial sums cascade bottom-up
            # inside ~60% of the master's round deadline instead of
            # every level burning the full budget serially
            slice_s = 0.6 * float(grad_timeout_s) / max(1, plan.depth)
            req.agg_wait_ms = max(1, int(
                1000.0 * plan.height.get(key, 1) * slice_s))

    # -- sync fit (Master.scala:120-218) -----------------------------------

    def fit_sync(
        self,
        max_epochs: int,
        batch_size: int,
        learning_rate: float,
        criterion: Optional[Criterion] = None,
        split: SplitFn = vanilla_split,
        initial_weights: Optional[np.ndarray] = None,
        grad_timeout_s: float = 30.0,
        on_worker_death: str = "resplit",
        grad_retries: int = 1,
        checkpointer=None,
        checkpoint_every: int = 1,
        optimizer=None,
        momentum: float = 0.9,
        local_steps: int = 1,
        delta_broadcast: bool = False,
        quorum: Optional[int] = None,
        straggler_soft_s: Optional[float] = None,
        hedge: bool = True,
        fit_state_path: Optional[str] = None,
        fit_state_every: int = 0,
        health=None,
        stream: bool = False,
        fanin_lanes: Optional[int] = None,
        stage_pool: Optional[int] = None,
        agg_tree: Optional[str] = None,
        master_shards: Optional[int] = None,
    ) -> FitResult:
        """Fault-tolerant sync fit, with an optional pipelined wire path.

        The reference's barrier (`Future.sequence`, Master.scala:190) hangs
        forever if a worker dies mid-fit.  Here every Gradient call carries a
        deadline (`grad_timeout_s`), membership is re-read every batch, and a
        worker whose call fails `grad_retries + 1` consecutive times (grace
        for transient blips / first-call compile latency; a success resets
        the count) is declared dead.  What happens then is the caller's
        choice: `on_worker_death="resplit"` (default) unregisters it and
        retries the batch across the survivors with a fresh re-split;
        `on_worker_death="fail"` raises WITHOUT touching membership, so the
        caller can investigate the intact cluster.

        Checkpointing mirrors the mesh SyncTrainer (core/trainer.py):
        `checkpointer` saves weights + the newest-first test-loss history
        (+ optimizer kind/leaves) every `checkpoint_every` epochs and the
        fit resumes from the latest snapshot — same state keys, so the two
        sync engines' checkpoints are interchangeable for plain SGD.

        `optimizer` accepts the same surface as the mesh engine (None/'sgd'
        = the reference's plain update, Master.scala:197; 'momentum'/'adam'/
        an optax transformation): workers still return raw gradient sums
        (Slave.scala:153) and the transformation is applied master-side
        where the reference applies its update.

        Pipelined sync levers (docs/SYNC_PIPELINE.md), each default-off so
        the default wire stays byte-identical to the unpipelined path:

        - `delta_broadcast=True` (DSGD_DELTA_BROADCAST): versioned sparse
          weight broadcasts — workers cache the last applied weight vector
          and the master ships only the changed coordinates (or nothing on
          retry windows), falling back to a full tensor on worker (re)join,
          version mismatch (GradUpdate.stale_version), resplit, or a
          denser-than-break-even update.
        - `local_steps=K > 1` (DSGD_LOCAL_STEPS): each worker runs K
          device-side SGD steps per round over K batches drawn from its
          partition and returns the summed weight-space decrement; the
          master averages the decrements and applies the result as a
          pseudo-gradient (mean_delta / learning_rate) through the same
          optimizer surface — K x fewer barriers and broadcasts per epoch,
          local-SGD semantics (Stich, 2018) between them.
        - `stream=True` (DSGD_STREAM, "Streaming transport"): every
          window's GradientRequest rides ONE persistent bidirectional
          FitStream per worker instead of a fresh unary call, with the
          encode-ahead thread pre-staging each worker's next request
          frame — dispatch becomes one sample draw + one stream write per
          worker, amortizing per-call HTTP/2 setup/teardown, metadata,
          and future allocation over the whole fit.  The math is
          bit-identical to the unary plane (same messages, same
          send-ordered decode; the rpc bench gates drift 0.0), a broken
          stream transparently replays its window over unary and feeds
          the same per-peer breaker, UNIMPLEMENTED peers (older binaries)
          stay unary permanently, and hedges are ALWAYS unary — they
          target a different worker than the stream's owner, and every
          quorum fire re-proves the interop path.  Off (default): no
          Frame is ever constructed, call graph byte-identical.
        - `fanin_lanes=K` (DSGD_FANIN_LANES, docs/SCALING.md): shard the
          fan-in DECODE into K lanes — each reply's wire->ndarray parse
          runs in its own arrival callback without queueing on one
          decoder lock, while the float accumulation stays ONE
          send-ordered f32 chain, so the weights are byte-identical to
          the unsharded path (asserted by tests/test_fanin_lanes.py).
          Quorum rounds parse on arrival too and accumulate at round
          close, once the contributor set is known.  The lane count is
          pinned for the fit: changing the master's `fanin_lanes`
          attribute mid-fit (when this parameter was None) raises.
          None (default): resolve from `self.fanin_lanes` (0 = the
          pre-shard single-lock decoder, byte-identical).
        - `stage_pool=P` (DSGD_STAGE_POOL, docs/SCALING.md): stage round
          t+1's dispatch during round t's barrier on a P-thread pool —
          every worker's sample draw (determinism-safe: one staging task
          consumes the epoch generator in serial order and any
          retry/resplit restores its state, see _DispatchStager) and
          every worker's request build (weight arm attached by the
          encode-ahead thread, fanned across the pool), for stream AND
          unary fits — dispatch becomes a take + samples-append + send
          per worker.  Staged sends account the same master.sync.bcast.*
          counters populate() would.  None (default): resolve from
          `self.stage_pool` (0 = draws and builds on the dispatch path,
          byte-identical).

        - `master_shards=M` (DSGD_MASTER_SHARDS, docs/MASTER_SHARDING.md):
          range-partition the weight vector across M master shard lanes —
          each lane broadcasts only its contiguous feature slice (through
          the same delta/codec path), workers rendezvous the M slices,
          compute ONCE, and reply per-slice, and each lane applies its
          slice independently; range-disjoint hinge-loss SGD commutes, so
          the step is bit-identical to the flat plane while broadcast AND
          fan-in bytes per master process scale down ~1/M.  Composes with
          delta_broadcast (per-lane versions) and agg_tree (one
          shard-colored tree per lane); refuses stream / quorum /
          local_steps>1 / fanin_lanes / stage_pool.  A killed shard
          (kill_shard) costs ONE flat fallback round, then the plan
          rebuilds over the survivors.  0/None (default): no coordinator,
          no shard instrument, wire byte-identical.

        Quorum barrier (DSGD_QUORUM, docs/FAULT_TOLERANCE.md; Chen et al.
        2016's N+b backup-replica shape): with `quorum=Q` the window
        barrier returns once all replies land OR once a soft deadline
        (`straggler_soft_s`, or p95-adaptive from each worker's reply
        latency EWMA when unset) fires with >= Q worth of CONTRIBUTOR
        weight in hand — under DSGD_AGG_TREE a subtree sum counts its
        whole contributor set and a forwarded ack counts zero
        (_reply_weight), so acks from leaves whose gradients sit inside
        a straggling aggregator never satisfy the count blindly; flat
        replies weigh one, keeping the tree-off count unchanged.
        The master then hedges each missing worker's data slice to the
        fastest responders (`hedge=True`), prefers a straggler's own reply
        if it lands during the hedge window, averages over the actual
        contributors (unbiased 1/|ok| scaling), discards late replies
        idempotently via the (fit_token, step_version) window keys, and
        tells each non-contributing worker to roll back its error-feedback
        residual drain (GradientRequest.ef_rollback_version).  Below
        quorum the window degrades to today's full barrier + retry, and a
        quorum-satisfied round never counts toward eviction — a straggler
        is slow, not dead (run the heartbeat for liveness).  Default
        `quorum=None` keeps the barrier, wire, and call graph identical
        to the pre-quorum engine.

        Crash-safe fit state (`fit_state_path` + `fit_state_every=R`,
        DSGD_FIT_CKPT_EVERY, docs/ELASTICITY.md): every R successful
        windows the FULL loop state — weights, optimizer leaves, epoch +
        window cursor, sample-draw RNG state, early-stopping history,
        broadcast version, fit_token lineage — is written atomically to
        `fit_state_path`.  A restarted master (kill -9 mid-fit) that
        finds the snapshot waits for worker re-registration (the
        workers' jittered-backoff loop is storm-safe), issues a NEW
        fit_token from its fresh incarnation nonce (long-lived workers
        reset stale per-fit state; the old token joins the lineage),
        restores the cursor + RNG, and replays from the last completed
        snapshot — bit-identical to an uninterrupted run at the same
        step count (tests/test_elastic.py).  `fit_state_every=0`
        (default) disables snapshots; snapshotting is pure observation
        (enabled-but-uninterrupted runs land on bit-identical weights).

        Training-health monitor (`health`, a telemetry.HealthMonitor;
        DSGD_HEALTH_ACTION, docs/OBSERVABILITY.md): per-round gradient-
        norm/staleness gauges plus a loss-trend watchdog.  A non-finite
        fan-in gradient trips BEFORE the poisoned update is applied; an
        EWMA loss divergence trips at the epoch eval.  On trip the
        monitor dumps the flight recorder, and per its action the fit
        additionally writes a resumable fit-state snapshot to
        `fit_state_path` ('snapshot') and/or stops ('halt') — a dying
        fit leaves evidence and a checkpoint instead of a flat loss
        curve.  None (default) runs no health observation at all.
        """
        if on_worker_death not in ("resplit", "fail"):
            raise ValueError(f"on_worker_death must be resplit|fail, got {on_worker_death!r}")
        if quorum is not None and int(quorum) < 1:
            raise ValueError(f"quorum must be >= 1, got {quorum}")
        quorum = int(quorum) if quorum is not None else None
        if straggler_soft_s is not None and straggler_soft_s <= 0:
            raise ValueError(
                f"straggler_soft_s must be > 0, got {straggler_soft_s}")
        local_steps = max(1, int(local_steps))
        # O(N) master plane (docs/SCALING.md): both knobs resolve against
        # the node attributes when the parameters are None, and the lane
        # count is PINNED for the fit — per-window decoders must all shard
        # identically or a retry window's re-zeroed accumulator would walk
        # a different cursor layout than the attempt it replaces
        lanes = (self.fanin_lanes if fanin_lanes is None
                 else int(fanin_lanes))
        lanes = max(0, int(lanes))
        pool_n = (self.stage_pool if stage_pool is None else int(stage_pool))
        stager = _DispatchStager(pool_n) if pool_n and pool_n > 0 else None
        # aggregation-tree plane (DSGD_AGG_TREE, docs/AGGREGATION.md): the
        # fanout resolves against the node attribute like the knobs above;
        # 0/"" = flat fan-in — no plan is ever built, no tree instrument
        # registered, the wire byte-identical (tests/test_aggtree.py)
        tree_spec = (self.agg_tree if agg_tree is None else agg_tree) or ""
        tree_fanout = 0
        if tree_spec:
            from distributed_sgd_tpu.aggtree import parse_agg_tree

            tree_fanout = parse_agg_tree(tree_spec)
        tree_plan = None
        # feature-sharded master plane (DSGD_MASTER_SHARDS,
        # docs/MASTER_SHARDING.md): 0/None = the flat single-master wire —
        # no coordinator, no shard instrument, byte-identical
        # (tests/test_shardedps.py).  M >= 1 range-partitions every
        # round's broadcast AND fan-in across M shard lanes; the
        # restrictions below mirror Config.__post_init__ for embedders
        # that call fit_sync directly.
        from distributed_sgd_tpu.shardedps import parse_master_shards

        n_shards = parse_master_shards(
            self.master_shards if master_shards is None else master_shards)
        if n_shards:
            for bad, knob in ((stream, "DSGD_STREAM"),
                              (quorum is not None, "DSGD_QUORUM"),
                              (local_steps > 1, "DSGD_LOCAL_STEPS"),
                              (lanes > 0, "DSGD_FANIN_LANES"),
                              (stager is not None, "DSGD_STAGE_POOL")):
                if bad:
                    raise ValueError(
                        f"DSGD_MASTER_SHARDS does not compose with {knob} "
                        f"(docs/MASTER_SHARDING.md composition table)")
        self._require_ready()
        members = self._members()
        keys = [k for k, _ in members]
        if tree_fanout and not n_shards:
            tree_plan = self._build_tree_plan(keys, tree_fanout)
        shard_coord = None
        if n_shards:
            from distributed_sgd_tpu.shardedps.coordinator import (
                ShardedCoordinator,
            )

            # with DSGD_AGG_TREE the coordinator builds ONE shard-colored
            # tree per lane instead of the flat plan above
            shard_coord = ShardedCoordinator(
                self, n_shards, self.model.n_features, keys,
                delta_broadcast, tree_fanout, grad_timeout_s)
            self._shard_coord = shard_coord
        parts = self._split_parts(split, members)
        max_samples = max(len(p) for p in parts)
        w = (
            np.zeros(self.model.n_features, dtype=np.float32)
            if initial_weights is None
            else np.asarray(initial_weights, dtype=np.float32)
        )
        result = FitResult(state=GradState(weights=w))
        test_newest_first: List[float] = []
        tracker = _FailureTracker(grad_retries + 1)
        self._fit_seq += 1
        fit_token = self._fit_token_base + self._fit_seq
        # quorum forces version stamping even on the plain full-tensor
        # wire: the EF rollback mask keys on step_version
        bcast = _BroadcastState(delta_broadcast, self.metrics,
                                versioned=quorum is not None,
                                stage_pool=stager.pool if stager else None)
        use_stream = bool(stream)
        if use_stream or stager is not None:
            # pre-staged round dispatch: from the first advance() on, the
            # encoder thread (fanned across the stage pool when one is
            # armed) builds each worker's next request — stream Frames or
            # unary GradientRequests — while the current window's replies
            # are still in flight
            bcast.stage_for(keys, fit_token, local_steps, batch_size,
                            learning_rate, frames=use_stream)
        # allocation-free fan-in: one dim-sized accumulator reused by every
        # window instead of a (workers x dim) dense stack per barrier
        grad_acc = np.zeros(self.model.n_features, dtype=np.float32)
        grad_bytes = self.metrics.counter(metrics_mod.SYNC_GRAD_BYTES)
        rounds = self.metrics.counter(metrics_mod.SYNC_ROUNDS)
        window_span = batch_size * local_steps
        # scatter-formulation attribution (ROADMAP item 2 follow-up: the
        # DSGD_SCATTER=auto rematch outcome was only ever logged): a gauge
        # on this fit's registry — scraped onto the cluster /metrics
        # endpoint under telemetry — plus a flight record, and a trace
        # event inside the first window's span below, so a bench run or a
        # post-mortem can attribute which formulation the fit actually ran
        from distributed_sgd_tpu.ops import mxu

        scatter_form = mxu.active_scatter_formulation()
        self.metrics.gauge(metrics_mod.SCATTER_FORMULATION).set(
            mxu.SCATTER_FORMULATIONS.index(scatter_form))
        flight.record("scatter.formulation", formulation=scatter_form)
        scatter_evented = False
        # quorum bookkeeping (all inert when quorum is None):
        # ef_rollback[worker] = broadcast version whose reply the quorum
        # barrier discarded — the NEXT request to that worker carries it so
        # the worker rolls back its EF residual drain for the skipped round
        ef_rollback: Dict[Tuple[str, int], int] = {}
        # per-ATTEMPT tree round (DSGD_AGG_TREE): bumped on every fan-out,
        # retries included, so a stale child push from an abandoned attempt
        # keys a round its parent will never collect — it ages out of the
        # aggregator's bounded buffer instead of double-counting
        agg_round_seq = 0
        stalled = self.metrics.counter(metrics_mod.SYNC_STALLED)
        # training-health monitor (telemetry/health.py): inert when None
        if (health is not None and health.action != "warn"
                and not fit_state_path):
            self.log.warning(
                "health action %r has no fit-state path (set "
                "DSGD_CHECKPOINT_DIR): a trip will leave flight evidence "
                "but no resumable snapshot", health.action)
        halted = False

        from distributed_sgd_tpu.checkpoint import opt_kind_tag
        from distributed_sgd_tpu.parallel.sync import resolve_optimizer

        opt = resolve_optimizer(optimizer, learning_rate, momentum)
        opt_kind = opt_kind_tag(optimizer)
        opt_state = opt.init(jnp.asarray(w)) if opt is not None else None
        if opt is not None:
            import optax

            @jax.jit
            def _opt_step(w_, opt_state_, g_):
                updates, opt_state_ = opt.update(g_, opt_state_, w_)
                return optax.apply_updates(w_, updates), opt_state_

        start_epoch = 0
        expected = jax.tree_util.tree_leaves(opt_state) if opt is not None else []
        restored = restore_sync_fit(checkpointer, opt_kind, expected)
        if restored is not None:
            start_epoch, w_np, test_newest_first, opt_leaves = restored
            w = np.asarray(w_np, dtype=np.float32)
            if opt is not None and opt_leaves:
                opt_state = jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(opt_state),
                    [jnp.asarray(x) for x in opt_leaves],
                )
            self.log.info("resumed sync fit from checkpoint at epoch %d", start_epoch)

        # crash-safe fit state (docs/ELASTICITY.md): a window-cadence
        # snapshot outranks the epoch-cadence one — it is strictly newer
        # state (mid-epoch cursor + RNG) written by the same fit
        resume_batch = 0
        resume_rng_state = None
        fit_tokens = [fit_token]
        fit_state_every = max(0, int(fit_state_every))
        fs = (restore_fit_state(fit_state_path, opt_kind, expected)
              if fit_state_path else None)
        if fs is not None and fs.epoch < start_epoch:
            # the epoch-cadence checkpoint is strictly newer — possible
            # when fit_state_every exceeds the windows in an epoch:
            # resuming from the older window snapshot would re-train
            # completed, already-checkpointed epochs
            self.log.info(
                "fit-state snapshot at epoch %d is older than the epoch "
                "checkpoint at %d: ignoring it", fs.epoch, start_epoch)
            fs = None
        if fs is not None:
            start_epoch = fs.epoch
            resume_batch = fs.batch
            resume_rng_state = fs.rng_state
            w = np.asarray(fs.weights, dtype=np.float32)
            test_newest_first = list(fs.test_losses_nf)
            if opt is not None and fs.opt_leaves:
                opt_state = jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(opt_state),
                    [jnp.asarray(x) for x in fs.opt_leaves],
                )
            if bcast.versioned and fs.bcast_version > 0:
                # continue the version stream: workers key EF retry guards
                # on step_version, and a restarted master must never reuse
                # a version its long-lived workers already acknowledged
                bcast.version = int(fs.bcast_version)
            fit_tokens = fs.fit_tokens + [fit_token]
            self.log.info(
                "resumed crash-safe fit state at epoch %d window cursor %d "
                "(fit lineage: %d token(s))",
                start_epoch, resume_batch, len(fit_tokens))

        if start_epoch >= max_epochs or (fs is not None and fs.finished):
            # nothing to run: the budget is exhausted, OR the snapshot is
            # the TERMINAL one of a fit that already finished (possibly
            # early via the convergence criterion at epoch < max_epochs —
            # resuming such a fit would train PAST convergence and mutate
            # a finished run's weights)
            loss, acc = self.local_loss(w)
            self.log.info(
                "fit state already %s at epoch %d (max_epochs %d): nothing "
                "to run (loss=%.6f acc=%.4f)",
                "finished" if (fs is not None and fs.finished) else "complete",
                start_epoch, max_epochs, loss, acc)
            result.epochs_run = start_epoch
            result.state = GradState(weights=w, loss=loss).finish()
            return result

        def _health_snapshot(epoch_, batch_, rng_state_, w_):
            """Resumable fit-state snapshot at the exact loop state a
            health trip interrupted (actions 'snapshot'/'halt'); no-op
            without a fit_state_path (warned above)."""
            if not fit_state_path:
                return
            save_fit_state(
                fit_state_path, weights=w_, epoch=epoch_, batch=batch_,
                rng_state=rng_state_, test_losses_nf=test_newest_first,
                opt_kind=opt_kind,
                opt_leaves=jax.tree_util.tree_leaves(opt_state)
                if opt_state is not None else [],
                bcast_version=bcast.version, fit_tokens=fit_tokens)
            self.log.warning(
                "health watchdog wrote a resumable fit-state snapshot to "
                "%s", fit_state_path)

        rounds_since_save = 0
        stopped_early = False
        # streams are fit-scoped: whatever path exits the epoch loop
        # (completion, convergence, health halt, all-workers-lost,
        # any exception), the persistent per-worker gradient streams
        # close with the fit
        try:
            for epoch in range(start_epoch, max_epochs):
                t0 = time.perf_counter()
                batch = 0
                # keyed by absolute epoch: a resumed run draws the same per-epoch
                # sample stream a fresh run would (mirrors SyncTrainer's
                # fold_in(base_key, epoch))
                rng = np.random.default_rng((self.seed, epoch))
                if resume_rng_state is not None:
                    # crash-safe resume lands MID-epoch: restore the generator
                    # to its snapshotted state and continue from the window
                    # cursor — the remaining windows draw the identical sample
                    # stream the uninterrupted run would have drawn
                    rng.bit_generator.state = resume_rng_state
                    batch = resume_batch
                    resume_rng_state = None
                while batch < max_samples:
                    # lane pin: the sharded decoder's cursor layout must be
                    # identical across every attempt of a window — an
                    # attribute flip mid-fit is refused, not absorbed
                    live_lanes = (self.fanin_lanes if fanin_lanes is None
                                  else fanin_lanes)
                    if max(0, int(live_lanes)) != lanes:
                        raise RuntimeError(
                            f"fan-in lane count changed mid-fit "
                            f"({lanes} -> {live_lanes}): the lane layout is "
                            f"pinned at fit start (docs/SCALING.md)")
                    # live membership: heartbeat-driven unregister_worker (or a
                    # graceful leave) reaches the loop here, not at fit start
                    current = self._members()
                    if [k for k, _ in current] != keys:
                        if not current:
                            raise RuntimeError("all workers lost mid-fit")
                        if stager is not None:
                            # pre-drawn samples were drawn for the OLD
                            # partitions: drop them and rewind the
                            # generator so the fresh serial draw below
                            # reads what a never-staged run would
                            stager.discard(rng)
                        members, keys = current, [k for k, _ in current]
                        parts = self._split_parts(split, members)
                        max_samples = max(len(p) for p in parts)
                        if tree_fanout and shard_coord is None:
                            # the reduce tree is a pure function of the
                            # member list: rebuild it on the SAME hook the
                            # resplit fires, so plan and split always
                            # describe the same membership snapshot
                            tree_plan = self._build_tree_plan(
                                keys, tree_fanout)
                            self.metrics.counter(
                                metrics_mod.TREE_REBUILDS).increment()
                            flight.record("tree.rebuild",
                                          members=len(keys),
                                          depth=tree_plan.depth)
                        if shard_coord is not None:
                            # the shard plan keys on (dim, M), not the
                            # member list — but the per-lane trees and
                            # per-lane version claims do: rebuild them on
                            # the SAME membership hook as the resplit
                            shard_coord.on_membership(keys)
                        bcast.forget_missing(keys)  # rejoins start from full
                        if use_stream or stager is not None:
                            # re-arm staging for the new membership; departed
                            # workers' streams were closed by unregister, and
                            # a (re)joined worker's stream re-opens lazily on
                            # its first dispatch below
                            bcast.stage_for(keys, fit_token, local_steps,
                                            batch_size, learning_rate,
                                            frames=use_stream)
                        # host-local workers absorb the new partition bounds
                        # themselves: ids outside a resident slice trigger the
                        # worker-side incremental reload (O(delta) rows through
                        # its RowReader) or the classified foreign-id refusal
                        self.metrics.counter(metrics_mod.SYNC_RESPLITS).increment()
                        flight.record("sync.resplit", members=len(members))
                        self.log.warning("membership changed; re-split across %d workers",
                                         len(members))
                        if batch >= max_samples:
                            break
                    t_batch = time.perf_counter()
                    # one trace per fan-out window (trace/; NOOP when tracing
                    # is off or this round is not head-sampled): worker
                    # Gradient calls — hedges and retries included — become
                    # client/server child spans of this root via the stub and
                    # servicer hooks in rpc/service.py, and quorum/chaos
                    # events attach inside it (docs/OBSERVABILITY.md)
                    wspan = trace_mod.root_span(
                        trace_mod.SPAN_SYNC_WINDOW, node="master", epoch=epoch,
                        batch=int(batch), version=bcast.version)
                    with wspan:
                        if not scatter_evented:
                            trace_mod.event(trace_mod.EVENT_SCATTER_SELECTED,
                                            formulation=scatter_form)
                            scatter_evented = True
                        futs = []
                        agg_round_seq += 1  # fresh tree round per attempt
                        ids_by_key: Dict[Tuple[str, int], np.ndarray] = {}
                        rb_sent: Dict[Tuple[str, int], int] = {}
                        # overlapped fan-in (full barrier only): zero the
                        # accumulator BEFORE the fan-out so each reply's
                        # scatter-decode runs in its arrival callback,
                        # send-ordered — only the slowest reply's decode stays
                        # on the critical path.  The quorum barrier keeps its
                        # post-barrier decode: its contributor set (hedge wins,
                        # late originals) is only known once the round closes.
                        decoder = None
                        if shard_coord is not None:
                            # per-lane slice replies decode in
                            # ShardedCoordinator.accumulate — an arrival
                            # decoder would scatter slice-local coordinates
                            # into the full accumulator at the wrong offsets
                            pass
                        elif quorum is None:
                            grad_acc.fill(0.0)
                            decoder = _ArrivalDecoder(grad_acc, lanes=lanes)
                        elif lanes:
                            # quorum + lanes: parse-on-arrival only — the
                            # contributor set (hedge wins, late originals)
                            # is resolved at round close, where add_into
                            # replays it in canonical order
                            decoder = _ArrivalDecoder(grad_acc, lanes=lanes,
                                                      defer=True)
                        # pooled dispatch: round t's barrier already drew
                        # these ids on the stage pool; a retry/resplit that
                        # falsified the staging assumptions restored the
                        # generator, and the serial draw below reads the
                        # exact values a never-staged run would
                        staged_ids = (stager.take(rng, keys, epoch, batch)
                                      if stager is not None else None)
                        if shard_coord is not None:
                            # sharded fan-out: the serial sample draw below
                            # is the flat loop's exactly (the bit-identity
                            # contract keys on identical draws); the
                            # per-lane request build, byte accounting, and
                            # shard-colored tree stamps are the
                            # coordinator's (shardedps/coordinator.py)
                            for (key, stub), part in zip(members, parts):
                                ids_by_key[key] = _draw_ids(
                                    rng, part, batch, window_span)
                            agg_round_seq = shard_coord.dispatch(
                                members, ids_by_key, w, fit_token,
                                grad_timeout_s, agg_round_seq)
                        else:
                            for (key, stub), part in zip(members, parts):
                                ids = (staged_ids[key]
                                       if staged_ids is not None
                                       else _draw_ids(rng, part, batch,
                                                      window_span))
                                ids_by_key[key] = ids
                                frame = None
                                req = None
                                if use_stream:
                                    # pre-staged dispatch: the encoder
                                    # thread already built this worker's
                                    # frame (weight arm attached) during
                                    # the previous barrier — dispatch adds
                                    # the sample draw and writes
                                    frame = bcast.take_staged_frame(key)
                                    if frame is not None:
                                        req = frame.request
                                elif stager is not None:
                                    req = bcast.take_staged_request(key)
                                if req is not None:
                                    req.samples.extend(ids.astype(np.int32))
                                else:
                                    if use_stream:
                                        frame = pb.Frame()
                                        req = frame.request
                                        req.samples.extend(
                                            ids.astype(np.int32))
                                        req.fit_token = fit_token
                                    else:
                                        req = pb.GradientRequest(
                                            samples=ids.astype(np.int32),
                                            fit_token=fit_token)
                                    if local_steps > 1:
                                        req.local_steps = local_steps
                                        req.batch_size = batch_size
                                        req.learning_rate = learning_rate
                                    bcast.populate(req, key, w)
                                if (tree_plan is not None
                                        and not tree_plan.trivial):
                                    # stamp this worker's tree role
                                    # (parent / children / wait budget)
                                    # from the plan — staged requests and
                                    # stream frames are mutated in place,
                                    # so the annotation rides every
                                    # transport; a trivial plan (N <= F)
                                    # stamps nothing, the wire stays flat
                                    self._annotate_tree(req, key, tree_plan,
                                                        agg_round_seq,
                                                        grad_timeout_s)
                                rb = ef_rollback.pop(key, None)
                                if rb is not None:
                                    req.ef_rollback_version = rb
                                    # re-armed if this request fails
                                    rb_sent[key] = rb
                                fut = self._dispatch_gradient(
                                    key, stub, frame, req, grad_timeout_s,
                                    use_stream)
                                futs.append((key, fut))
                                if decoder is not None:
                                    decoder.watch(len(futs) - 1, fut)
                        if (stager is not None
                                and batch + window_span < max_samples):
                            # overlap window: round t+1's draws run on the
                            # stage pool while this round's replies are in
                            # flight (epoch-final rounds stage nothing —
                            # the next epoch re-keys the generator)
                            stager.stage(rng, keys, parts, epoch,
                                         batch + window_span, window_span)
                        if shard_coord is not None:
                            # M x N barrier with per-worker collapse: any
                            # stale/failed leg degrades the worker exactly
                            # once (shardedps/coordinator.py collect)
                            replies = None
                            good, stale, failed = shard_coord.collect(
                                grad_bytes)
                            satisfied = False
                            if (straggler_soft_s is not None
                                    and time.perf_counter() - t_batch
                                    > straggler_soft_s):
                                stalled.increment()
                        elif quorum is None:
                            # barrier, with deadlines; receive-side wire accounting
                            # happens per arriving reply inside _await_futures (send-
                            # side comms.* counters live in the workers' compressors),
                            # so discarded/retried windows are accounted too
                            ok, failed = _await_futures(futs, bytes_counter=grad_bytes)
                            decoder.finish(futs)
                            good, stale = [], []
                            for key, reply in ok:
                                (stale if reply.stale_version else good).append((key, reply))
                            replies = [r for _, r in good]
                            satisfied = False
                            # pure observation when a soft deadline is configured
                            # without quorum: how often would the quorum barrier
                            # have had to intervene?  (bench_chaos.py's baseline)
                            if (straggler_soft_s is not None
                                    and time.perf_counter() - t_batch > straggler_soft_s):
                                stalled.increment()
                        else:
                            replies, good, stale, failed, satisfied = (
                                self._quorum_barrier(
                                    futs, members, ids_by_key, quorum,
                                    straggler_soft_s, grad_timeout_s, fit_token,
                                    local_steps, batch_size, learning_rate, bcast,
                                    w, hedge, ef_rollback, grad_bytes, rb_sent))
                            if not satisfied:
                                # below-quorum degradation: the barrier fell back
                                # to the classic full barrier — dump the flight
                                # ring so the window leaves evidence even when
                                # the fit later recovers (docs/OBSERVABILITY.md)
                                flight.record(
                                    "quorum.below", epoch=epoch, batch=int(batch),
                                    version=bcast.version,
                                    got=sum(_reply_weight(r)
                                            for _, r in good),
                                    quorum=min(quorum, len(members)))
                                # throttled: a minutes-long partition degrades
                                # EVERY window — keep evidence fresh without
                                # blocking the barrier loop on disk each round
                                flight.dump("below_quorum", min_interval_s=10.0)
                        rounds.increment()
                        for key, _ in good:
                            tracker.record_ok(key)
                            bcast.note_ok(key)
                        for key, _ in stale:
                            # a stale reply is still a LIVE worker: reset its
                            # failure count (the pre-quorum code treated every ok
                            # reply as liveness evidence)
                            tracker.record_ok(key)
                            # replica mismatch (restart, missed window): full
                            # broadcast on the retry — the correctness fallback
                            bcast.note_stale(key)
                            self.metrics.counter(metrics_mod.SYNC_STALE).increment()
                            trace_mod.event(trace_mod.EVENT_BCAST_STALE,
                                            worker=f"{key[0]}:{key[1]}")
                            self.log.warning(
                                "worker %s:%d replica stale at v%d; falling back to "
                                "full broadcast", key[0], key[1], bcast.version)
                        if not satisfied:
                            if failed:
                                for key, code in failed:
                                    n, evict = tracker.record_failure(key)
                                    if not evict:
                                        self.log.warning(
                                            "worker %s:%d failed Gradient (%s); retry %d/%d",
                                            key[0], key[1], code, n, grad_retries)
                                        continue
                                    if on_worker_death == "fail":
                                        # abort WITHOUT mutating membership: the caller
                                        # chose to investigate, not to continue degraded
                                        raise RuntimeError(
                                            f"worker {key[0]}:{key[1]} died mid-fit "
                                            f"({n} consecutive Gradient failures: {code})")
                                    self.log.warning(
                                        "worker %s:%d failed Gradient %d times (%s); declaring dead",
                                        key[0], key[1], n, code)
                                    self.unregister_worker(*key, evicted=True)
                            if failed or stale:
                                wspan.set(retry=True)
                                continue  # retry this window (survivors or re-split)
                        # allocation-free fan-in: scatter/add every reply into the
                        # preallocated accumulator, then scale once — replaces the
                        # per-window [decode_grad(r) for r in ok] dense stack +
                        # np.mean (Vec.mean, Master.scala:194).  The full barrier
                        # already decoded per arrival (send-ordered, so the sums
                        # are bit-identical — see _ArrivalDecoder); the quorum
                        # path decodes here, once the contributor set is known:
                        # under a satisfied quorum `replies` holds the actual
                        # contributors (own + hedge replies) and the mean over
                        # |contributors| is the unbiased 1/|ok| scaling of Chen
                        # et al. 2016's backup-worker rule.
                        if shard_coord is not None:
                            # range-disjoint slice fan-in: each lane
                            # decodes its replies into its OWN view of the
                            # accumulator and applies its own divisor —
                            # per coordinate, the flat barrier's exact
                            # float chain (docs/MASTER_SHARDING.md)
                            shard_coord.accumulate(grad_acc)
                        elif decoder is not None and decoder.defer:
                            # quorum + lanes: the contributor set is known
                            # only now — accumulate it in canonical order,
                            # reusing each reply's arrival-callback parse
                            # (hedge replies parse here; the float adds
                            # are decode_grad_into's exactly)
                            grad_acc.fill(0.0)
                            for reply in replies:
                                decoder.add_into(reply, grad_acc)
                        elif decoder is None or decoder.decoded != len(replies):
                            grad_acc.fill(0.0)
                            for reply in replies:
                                codec.decode_grad_into(reply, grad_acc)
                        if shard_coord is not None:
                            pass  # per-lane divisors applied above
                        elif tree_plan is not None and not tree_plan.trivial:
                            # tree fan-in: each reply is either a subtree
                            # sum tagged with its exact contributor set, a
                            # flat-fallback payload (dead parent), or an
                            # armless agg_forwarded ack (decodes as zero,
                            # contributes nothing) — the mean divides by
                            # the TOTAL contributors, so a partial round
                            # (missed child push) still averages honestly
                            n_contrib = 0
                            for r in replies:
                                if r.agg_contributors:
                                    n_contrib += len(r.agg_contributors)
                                elif not r.agg_forwarded:
                                    # flat reply inside a tree round (e.g.
                                    # a quorum hedge, or a worker absent
                                    # from the plan): one contributor
                                    n_contrib += 1
                                if r.agg_partial:
                                    self.metrics.counter(
                                        metrics_mod.TREE_PARTIAL).increment()
                                if r.agg_flat:
                                    self.metrics.counter(
                                        metrics_mod.TREE_FLAT_FALLBACK
                                    ).increment()
                            grad_acc /= max(1, n_contrib)
                        else:
                            grad_acc /= len(replies)  # true divide, bit-matching np.mean
                        if health is not None:
                            # NaN/Inf sentinel: a non-finite fan-in NEVER
                            # reaches the weights, whatever the action — the
                            # snapshot carries the last GOOD state, cursor
                            # pointing at this window
                            if health.observe_round(
                                    float(np.linalg.norm(grad_acc)),
                                    staleness_s=time.perf_counter() - t_batch):
                                wspan.set(health_tripped=True)
                                if health.action in ("snapshot", "halt"):
                                    # the stager may have pre-drawn the next
                                    # round: persist the SERIAL state, or a
                                    # resume would skip a round's draws
                                    _health_snapshot(
                                        epoch, batch,
                                        stager.rng_state(rng)
                                        if stager is not None
                                        else rng.bit_generator.state, w)
                                if health.action == "halt":
                                    halted = True
                                    break
                                # warn/snapshot: drop the poisoned round and
                                # continue on the last finite weights (the
                                # verdict is NOT latched — every later
                                # non-finite round is dropped too)
                                self.log.error(
                                    "dropping non-finite fan-in at epoch %d "
                                    "window %d (health action %s)",
                                    epoch, int(batch), health.action)
                                batch += window_span
                                continue
                        w_old = w
                        if local_steps > 1:
                            # replies are summed weight-space decrements; apply the
                            # mean as a pseudo-gradient through the same optimizer
                            # surface (error-feedback discipline of local SGD)
                            if opt is None:
                                w = w - grad_acc
                            else:
                                w_j, opt_state = _opt_step(
                                    jnp.asarray(w), opt_state,
                                    jnp.asarray(grad_acc) / learning_rate)
                                w = np.asarray(w_j)
                        elif opt is None:
                            w = w - learning_rate * grad_acc  # Master.scala:197
                        else:
                            w_j, opt_state = _opt_step(
                                jnp.asarray(w), opt_state, jnp.asarray(grad_acc))
                            w = np.asarray(w_j)
                        if shard_coord is not None:
                            # per-lane versions advance over slices; a
                            # just-absorbed shard kill rebuilds the plan
                            # here, before the next window dispatches
                            shard_coord.advance(w, w_old)
                        else:
                            bcast.advance(w, w_old)
                        self.metrics.histogram("master.sync.batch.duration").record(
                            time.perf_counter() - t_batch)
                        batch += window_span
                        rounds_since_save += 1
                        if (fit_state_path and fit_state_every
                                and rounds_since_save >= fit_state_every):
                            # window-cadence crash snapshot: the cursor points
                            # PAST the just-applied window, and the RNG state is
                            # exactly what the next window will draw from — the
                            # stager's serial-equivalent view when a pre-draw
                            # is pending, so a resumed fit replays identically
                            save_fit_state(
                                fit_state_path, weights=w, epoch=epoch,
                                batch=batch,
                                rng_state=stager.rng_state(rng)
                                if stager is not None
                                else rng.bit_generator.state,
                                test_losses_nf=test_newest_first,
                                opt_kind=opt_kind,
                                opt_leaves=jax.tree_util.tree_leaves(opt_state)
                                if opt_state is not None else [],
                                bcast_version=bcast.version,
                                fit_tokens=fit_tokens)
                            rounds_since_save = 0
                if halted:
                    self.log.error(
                        "fit halted by the training-health watchdog (%s) at "
                        "epoch %d window %d", health.trip_reason, epoch,
                        int(batch))
                    break
                epoch_s = time.perf_counter() - t0

                loss, acc = self.local_loss(w)
                test_loss, test_acc = self.local_loss(w, test=True)
                record_epoch(result, test_newest_first, epoch,
                             loss, acc, test_loss, test_acc, epoch_s)
                self.metrics.histogram("master.sync.loss").record(loss)
                self.metrics.histogram("master.sync.acc").record(100 * acc)
                self.metrics.histogram("master.sync.epoch.seconds").record(epoch_s)
                self.log.info(
                    "epoch %d: loss=%.6f acc=%.4f test_loss=%.6f test_acc=%.4f (%.2fs)",
                    epoch, loss, acc, test_loss, test_acc, epoch_s,
                )
                if health is not None and health.observe_loss(loss):
                    # loss-trend watchdog (EWMA divergence / non-finite loss):
                    # the monitor already dumped the flight ring; snapshot at
                    # the epoch boundary (next epoch's cursor, fresh per-epoch
                    # stream — the same shape as the terminal snapshot below)
                    if health.action in ("snapshot", "halt"):
                        _health_snapshot(
                            epoch + 1, 0,
                            np.random.default_rng(
                                (self.seed, epoch + 1)).bit_generator.state, w)
                    if health.action == "halt":
                        self.log.error(
                            "fit halted by the training-health watchdog (%s) "
                            "after epoch %d", health.trip_reason, epoch)
                        halted = True
                        break
                if checkpointer is not None and (epoch + 1) % checkpoint_every == 0:
                    save_sync_fit(
                        checkpointer, epoch + 1, w, test_newest_first, opt_kind,
                        jax.tree_util.tree_leaves(opt_state)
                        if opt_state is not None else [])
                if criterion is not None and criterion(test_newest_first):
                    self.log.info("Converged to target: stopping computation")
                    stopped_early = True
                    break
        finally:
            # the shard coordinator is fit-scoped: kill_shard must never
            # reach a coordinator whose fit already returned.  Its wire
            # ledger outlives it for the bench's bytes-per-process gate.
            if self._shard_coord is not None:
                self._last_shard_bytes = self._shard_coord.bytes_by_lane()
            self._shard_coord = None
            if use_stream:
                self._close_streams()
            if stager is not None:
                # a pending pre-draw dies with the fit (the epoch generator
                # it would restore into is gone too); hit/discard tallies
                # land once per fit
                stager.close()
                self.metrics.counter(
                    metrics_mod.STAGE_HITS).increment(stager.hits)
                self.metrics.counter(
                    metrics_mod.STAGE_DISCARDS).increment(stager.discards)

        save_sync_fit_final(
            checkpointer, result.epochs_run, start_epoch, checkpoint_every,
            w, test_newest_first, opt_kind,
            jax.tree_util.tree_leaves(opt_state) if opt_state is not None else [])
        if fit_state_path and (fit_state_every or health is not None) \
                and not halted:
            # terminal snapshot (skipped on a health halt: the watchdog's
            # own snapshot carries the exact interrupted cursor, which a
            # coarser end-of-fit write would roll back).  A health-enabled
            # run writes it even with fit_state_every=0, so a COMPLETED
            # resume overwrites the stale trip snapshot instead of leaving
            # it to be re-restored by every later run.  finished marks a
            # CONVERGED fit (criterion
            # break at epochs_run < max_epochs) so a restart takes the
            # nothing-to-run path instead of training past convergence —
            # the epoch cursor alone cannot say this.  Budget exhaustion
            # is NOT marked: there the cursor carries the same fact
            # (start_epoch >= max_epochs), and leaving it unmarked lets a
            # re-run with a RAISED max_epochs resume training, matching
            # the epoch-checkpoint workflow next door
            save_fit_state(
                fit_state_path, weights=w, epoch=result.epochs_run, batch=0,
                rng_state=np.random.default_rng(
                    (self.seed, result.epochs_run)).bit_generator.state,
                test_losses_nf=test_newest_first, opt_kind=opt_kind,
                opt_leaves=jax.tree_util.tree_leaves(opt_state)
                if opt_state is not None else [],
                bcast_version=bcast.version, fit_tokens=fit_tokens,
                finished=stopped_early)

        result.state = GradState(
            weights=w, loss=result.losses[-1] if result.losses else float("nan")
        ).finish()
        return result

    def _quorum_barrier(self, futs, members, ids_by_key, quorum,
                        straggler_soft_s, grad_timeout_s, fit_token,
                        local_steps, batch_size, learning_rate, bcast, w,
                        hedge, ef_rollback, grad_bytes, rb_sent):
        """One window's quorum barrier + straggler hedging
        (docs/FAULT_TOLERANCE.md).

        Returns (replies, good, stale, failed, satisfied):

        - satisfied=True — the round closes NOW with `replies` (>= quorum
          worth of CONTRIBUTOR weight — _reply_weight: a subtree sum
          counts its whole contributor set, a forwarded ack counts zero,
          a flat or hedge reply counts one — so under DSGD_AGG_TREE the
          quorum measures gradients actually in hand, not acks).  `good`
          lists the workers whose OWN reply was
          used (liveness + broadcast-version bookkeeping); stragglers'
          discarded windows are marked in `ef_rollback` and their late
          replies are counted (idempotently dropped — nobody reads an
          abandoned future).  No failure is recorded for a missing
          straggler: slow is not dead (heartbeat owns liveness).
        - satisfied=False — quorum could not be met at the soft deadline:
          everything was awaited to the hard (per-call) deadline and the
          caller runs the classic full-barrier failure/stale/retry path
          over (good, stale, failed) unchanged.
        """
        quorum_n = min(quorum, len(members))
        soft_s = straggler_soft_s
        if soft_s is None:
            # p95-adaptive from the per-worker reply-latency EWMA; until
            # it warms (>= quorum workers with history) the window runs as
            # a full barrier, which is what seeds the EWMA
            soft_s = self._latency.soft_deadline_s(ids_by_key.keys(), quorum_n)
        soft_s = min(soft_s, grad_timeout_s) if soft_s else grad_timeout_s
        t0 = time.monotonic()
        ok, failed, pending = _await_quorum(
            futs, quorum_n, t0 + soft_s,
            bytes_counter=grad_bytes, latency=self._latency)
        # a stalled round is one the quorum could NOT relieve: the barrier
        # physically overran the soft deadline because fewer than Q usable
        # replies were in hand when it fired (a quorum-relieved round exits
        # within a poll quantum of the deadline).  bench_chaos.py's >= 3x
        # headline counts exactly these.
        if time.monotonic() - t0 > soft_s + max(0.05, 0.25 * soft_s):
            self.metrics.counter(metrics_mod.SYNC_STALLED).increment()
            trace_mod.event(trace_mod.EVENT_BARRIER_STALLED,
                            soft_s=round(soft_s, 4), got=len(ok))
            flight.record("barrier.stalled", soft_s=round(soft_s, 4),
                          got=len(ok), quorum=quorum_n)
        good, stale = [], []
        for key, reply in ok:
            (stale if reply.stale_version else good).append((key, reply))

        uncovered = ([k for k, _ in pending] + [k for k, _ in failed]
                     + [k for k, _ in stale])
        hedge_futs = []
        # quorum is counted in CONTRIBUTOR weight, not reply count: under
        # DSGD_AGG_TREE a subtree sum covers its whole contributor set
        # while a forwarded ack covers nobody (_reply_weight) — Q acks
        # from leaves whose gradients are still stuck inside a straggling
        # aggregator must not close the round
        good_weight = sum(_reply_weight(r) for _, r in good)
        if uncovered and good_weight >= quorum_n and hedge and good:
            # hedge each missing slice on the fastest responders: a
            # duplicate Gradient over the straggler's drawn ids, weights
            # populated for the donor (header-only under delta broadcast —
            # the donor just acknowledged this version)
            donors = sorted(
                (k for k, _ in good),
                key=lambda k: self._latency.p95_s(k) or float("inf"))
            stub_by_key = dict(members)
            hedge_deadline = min(grad_timeout_s, 2.0 * soft_s)
            for i, skey in enumerate(uncovered):
                donor = donors[i % len(donors)]
                hreq = pb.GradientRequest(
                    samples=ids_by_key[skey].astype(np.int32),
                    fit_token=fit_token, hedge=True)
                if local_steps > 1:
                    hreq.local_steps = local_steps
                    hreq.batch_size = batch_size
                    hreq.learning_rate = learning_rate
                bcast.note_ok(donor)  # its own reply proved this version
                bcast.populate(hreq, donor, w)
                try:
                    hfut = stub_by_key[donor].Gradient.future(
                        hreq, timeout=hedge_deadline)
                except ValueError:
                    continue
                hedge_futs.append((skey, hfut))
                self.metrics.counter(metrics_mod.QUORUM_HEDGES).increment()
                trace_mod.event(trace_mod.EVENT_QUORUM_HEDGE,
                                straggler=f"{skey[0]}:{skey[1]}",
                                donor=f"{donor[0]}:{donor[1]}")
                flight.record("quorum.hedge",
                              straggler=f"{skey[0]}:{skey[1]}",
                              donor=f"{donor[0]}:{donor[1]}")
                self.log.info(
                    "hedging slice of straggler %s:%d on %s:%d", *skey, *donor)
            h_ok, _h_failed = _await_futures(hedge_futs,
                                             bytes_counter=grad_bytes)
        else:
            h_ok = []

        # harvest originals that landed while the hedges ran — a
        # straggler's OWN reply is always preferred over its hedge (its
        # EF drain was real, and preferring it keeps the residual exact)
        still_pending = []
        for key, fut in pending:
            if not fut.done():
                still_pending.append((key, fut))
                continue
            try:
                reply = fut.result()
                grad_bytes.increment(reply.ByteSize())
                self._latency.record(key, soft_s)  # at least the soft window
                (stale if reply.stale_version else good).append((key, reply))
            except grpc.RpcError as e:
                failed.append((key, e.code()))

        own = {k for k, _ in good}
        # a slice covered by BOTH its own late original and its hedge
        # contributes exactly once — the original wins, the hedge is waste
        hedge_wins = [
            (skey, r) for skey, r in h_ok
            if skey not in own and not r.stale_version]
        # canonical slice order: float accumulation is order-sensitive, so
        # contributions are summed in fan-out order regardless of arrival
        # order — a quorum round with every reply in hand is bit-identical
        # to the plain barrier
        order = {key: i for i, key in enumerate(ids_by_key)}
        good.sort(key=lambda kr: order[kr[0]])
        replies = [r for _, r in
                   sorted(good + hedge_wins, key=lambda kr: order[kr[0]])]
        # satisfaction in contributor weight (see _reply_weight): the
        # harvested late originals above may have lifted the weight past
        # Q even if the soft-deadline snapshot was short, and vice versa
        # a pile of forwarded acks never lifts it at all
        reply_weight = sum(_reply_weight(r) for r in replies)
        if reply_weight >= quorum_n:
            if len(good) < len(ids_by_key):
                self.metrics.counter(metrics_mod.QUORUM_DEGRADED).increment()
                missing = [f"{k[0]}:{k[1]}" for k in ids_by_key
                           if k not in own]
                trace_mod.event(trace_mod.EVENT_QUORUM_DEGRADED,
                                contributors=reply_weight, missing=missing)
                flight.record("quorum.degraded", contributors=reply_weight,
                              missing=missing)
            for skey, _ in hedge_wins:
                self.metrics.counter(metrics_mod.QUORUM_HEDGE_WINS).increment()
                trace_mod.event(trace_mod.EVENT_QUORUM_HEDGE_WIN,
                                straggler=f"{skey[0]}:{skey[1]}")
            # contribution mask: every fanned-out worker whose own reply
            # was NOT used rolls its EF drain back on the next request
            # (exact-match on the broadcast version, so a worker that
            # never received this window simply ignores it).  A request
            # that failed outright may never have been processed — a
            # rollback marker it carried is still owed, so re-arm the OLD
            # marker for those (exact-match keeps either choice safe; this
            # picks the one a never-delivered request leaves true).
            late_counter = self.metrics.counter(metrics_mod.QUORUM_LATE)
            failed_keys = {k for k, _ in failed}
            for key in ids_by_key:
                if key not in own:
                    if key in failed_keys and key in rb_sent:
                        ef_rollback[key] = rb_sent[key]
                    else:
                        ef_rollback[key] = bcast.version
            # the late settle runs on a gRPC callback thread after this
            # window's span closed: capture the window context NOW so the
            # discard still lands in the round's timeline
            w_ctx = trace_mod.current()
            for key, fut in still_pending:
                def _count_late(f, _c=late_counter, _k=key):
                    if not f.cancelled():
                        _c.increment()
                        trace_mod.event_in(
                            w_ctx, trace_mod.EVENT_QUORUM_LATE,
                            node="master", worker=f"{_k[0]}:{_k[1]}")
                        flight.record("quorum.late", worker=f"{_k[0]}:{_k[1]}")
                fut.add_done_callback(_count_late)
            # stragglers are NOT failures: no tracker/eviction pressure
            # from a quorum-satisfied round
            return replies, good, stale, [], True

        # below quorum: classic full barrier — await the hard deadline,
        # then hand the classic failure/stale/retry path the full picture
        # (the stall, if any, was already counted by the overrun check)
        if still_pending:
            ok2, failed2, _ = _await_quorum(
                still_pending, len(still_pending) + 1,
                time.monotonic() + grad_timeout_s + 5.0,
                bytes_counter=grad_bytes, latency=self._latency)
            for key, reply in ok2:
                (stale if reply.stale_version else good).append((key, reply))
            failed.extend(failed2)
        # hedge replies are dropped below quorum: the classic retry path
        # averages over the member fan-out only (and hedges were only sent
        # if quorum had been met when the soft deadline fired).  Fan-out
        # order again, for bit-identity with the plain barrier.  Rollback
        # markers whose carrying request yielded no usable reply are
        # re-armed for the retry (a worker that DID process the request
        # consumed its marker, making the repeat an exact-match no-op).
        order = {key: i for i, key in enumerate(ids_by_key)}
        good.sort(key=lambda kr: order[kr[0]])
        own = {k for k, _ in good}
        for key, rb in rb_sent.items():
            if key not in own:
                ef_rollback.setdefault(key, rb)
        return [r for _, r in good], good, stale, failed, False

    def fit_async(
        self,
        max_epochs: int,
        batch_size: int,
        learning_rate: float,
        criterion: Optional[Criterion] = None,
        check_every: int = 100,
        leaky_loss: float = 0.9,
        backoff_s: float = 2.5,
        split: SplitFn = vanilla_split,
        initial_weights: Optional[np.ndarray] = None,
        checkpointer=None,
        optimizer: Optional[str] = None,
        momentum: float = 0.9,
        stall_checks: int = 4,
        max_stall_interventions: int = 3,
        stall_window_s: Optional[float] = None,
        startup_grace_s: Optional[float] = None,
        elastic: bool = False,
        batch_drain: bool = False,
    ) -> FitResult:
        """Async fit with a stall watchdog (superset; the reference counts
        updates blindly, MasterAsync.scala:164-177, and a dead worker means
        the budget never completes — the master spins forever re-evaluating
        frozen weights).  When no update arrives for the stall window, the
        watchdog probes every assigned worker: the dead are evicted
        (joining any heartbeat eviction that already happened) and their
        sample assignments re-issued to survivors via StartAsync with the
        current weights, so the lifetime budget completes on the
        survivors; with no survivors — or after `max_stall_interventions`
        interventions without any progress — the fit aborts cleanly with
        RuntimeError instead of spinning (the bar fit_sync already set,
        on_worker_death).

        Window sizing: `stall_window_s` defaults to
        max(stall_checks x backoff_s, 60) — a short backoff must not arm a
        sub-compile-time watchdog, because a worker's FIRST dispatch
        legitimately produces nothing while XLA compiles its k-step
        program (and a misfired kick replaces the loop and recompiles,
        making the stall worse).  Before the first update ever arrives the
        window is `startup_grace_s` (default max(stall_window, 180)) for
        the same reason.  Tests pass explicit small values.

        Elastic membership (`elastic=True`, DSGD_ELASTIC,
        docs/ELASTICITY.md): on ANY membership change — a worker evicted,
        a worker gracefully leaving, or a NEW worker registering mid-fit —
        the loop re-splits the sample assignment deterministically across
        the CURRENT members (the same core/split.py strategy the sync
        resplit path uses) and re-issues StartAsync (with the current
        weights) only to workers whose slice changed; the gossip plane
        absorbs the change through the existing full-mesh introduction /
        unregister broadcast, so a join or leave never stops the world.
        Off (default) the loop keeps the pre-elastic behavior: evicted
        workers' slices MERGE into survivors and mid-fit joins idle until
        the next fit.

        Batch drain (`batch_drain=True`, DSGD_ASYNC_DRAIN): buffer
        incoming UpdateGrads in an inbox and apply one summed update per
        drain (deltas commute; mirrors parallel/hogwild.py _drain_inbox),
        replacing the per-message jitted apply that serializes on
        _async_lock at high worker counts.  Off (default) keeps the
        per-message apply byte-identical."""
        if optimizer is not None and not isinstance(optimizer, str):
            raise ValueError(
                "the RPC topology ships the optimizer by NAME in "
                "StartAsyncRequest; pass 'sgd'/'momentum'/'adam' (an optax "
                "transform object cannot cross the wire)"
            )
        from distributed_sgd_tpu.parallel.sync import resolve_optimizer

        # dry-run the resolution so an unknown name fails HERE, before any
        # worker is started (a mid-fan-out failure would leave early
        # workers gossiping and _async_running permanently set)
        resolve_optimizer(optimizer, learning_rate, momentum)
        self._require_ready()
        if self._async_running.is_set():
            raise RuntimeError("a computation is already running")  # MasterAsync.scala:42
        members = self._members()
        parts = self._split_parts(split, members)
        # per-worker sample assignment, kept for watchdog reassignment
        assignments = {key: part for (key, _), part in zip(members, parts)}
        w0 = (
            np.zeros(self.model.n_features, dtype=np.float32)
            if initial_weights is None
            else np.asarray(initial_weights, dtype=np.float32)
        )
        # the checker restores any prior snapshot, including the lifetime
        # update count: maxSteps is a LIFETIME budget (MasterAsync.scala:83
        # counts updates across the whole computation), so a resumed fit
        # starts its counter at the restored count and spends only the
        # remainder
        checker = LossChecker(leaky_loss, criterion, checkpointer=checkpointer)
        t_start = time.time()
        with self._async_lock:
            self._w_async = jnp.asarray(w0)
            self._updates = checker.restored_updates
            self._max_steps = len(self.train) * max_epochs  # MasterAsync.scala:83
        if self._updates >= self._max_steps:
            self.log.info(
                "resumed past the %d-step budget (%d updates done): nothing to run",
                self._max_steps, self._updates)
            return async_fit_result(
                checker, w0, t_start, self._updates, batch_size, len(self.train))
        self._async_done.clear()
        self._async_running.set()

        last_step = self._updates - check_every  # first check runs immediately
        if stall_window_s is None:
            stall_window_s = max(max(1, stall_checks) * backoff_s, 60.0)
        if startup_grace_s is None:
            startup_grace_s = max(stall_window_s, 180.0)
        start_updates = self._updates
        last_progress = self._updates
        last_progress_t = time.monotonic()
        interventions = 0
        # every endpoint that EVER held an assignment gets the end-of-fit
        # StopAsync broadcast, even if evicted mid-fit: a falsely-evicted
        # but alive worker must not keep training (and gossiping into the
        # master) after the fit returns
        ever_assigned = set(assignments)
        with self._members_lock:
            self._rereg_pending.clear()  # stale kicks from a prior fit
        drain_thread = None
        if batch_drain:
            with self._inbox_cv:
                self._inbox.clear()  # never apply a prior fit's stragglers
                self._drain_on = True
            drain_thread = threading.Thread(
                target=self._drain_loop, daemon=True, name="async-drain")
            drain_thread.start()
        try:
            # fan-out INSIDE the try: a worker dying mid-fan-out must still
            # reach the finally (_end_async_endpoints), or _async_running
            # stays set forever and the started workers gossip with no stop
            for key, part in assignments.items():  # MasterAsync.scala:52-55
                self._start_async_worker(key, part, w0, batch_size,
                                         learning_rate, optimizer, momentum)
            self.log.info("waiting for slaves updates")
            while self._async_running.is_set():
                with self._async_lock:
                    updates = self._updates
                    w_now = self._w_async
                window = (startup_grace_s if updates == start_updates
                          else stall_window_s)
                # membership reaches the async fit HERE each tick: an
                # assigned worker that lost membership gets its samples
                # re-issued immediately (no full-stall wait), and under
                # `elastic` a JOIN triggers the same deterministic resplit
                with self._members_lock:
                    member_order = list(self._order)
                if elastic:
                    if set(member_order) != set(assignments):
                        self._elastic_resplit(
                            assignments, member_order, np.asarray(w_now),
                            batch_size, learning_rate, optimizer, momentum,
                            split, ever_assigned)
                else:
                    member_keys = set(member_order)
                    evicted = [k for k in assignments if k not in member_keys]
                    if evicted:
                        self.log.warning(
                            "async fit: %d assigned worker(s) no longer members; "
                            "reassigning", len(evicted))
                        self._reassign_async(assignments, evicted,
                                             np.asarray(w_now),
                                             batch_size, learning_rate,
                                             optimizer, momentum)
                # same-endpoint restarts: a worker that RE-registered while
                # still a member left no membership delta for the blocks
                # above to see — re-kick its current slice (idempotent on a
                # live worker; see register_worker)
                with self._members_lock:
                    rejoined = [k for k in self._rereg_pending
                                if k in assignments]
                    self._rereg_pending.clear()
                for key in rejoined:
                    self.log.warning(
                        "async fit: %s:%d re-registered while assigned; "
                        "re-issuing its StartAsync", key[0], key[1])
                    self._try_start_async_worker(
                        key, assignments[key], np.asarray(w_now),
                        batch_size, learning_rate, optimizer, momentum)
                if updates > last_progress:
                    last_progress, last_progress_t = updates, time.monotonic()
                    interventions = 0
                elif time.monotonic() - last_progress_t > window:
                    interventions += 1
                    if interventions > max_stall_interventions:
                        raise RuntimeError(
                            f"async fit stalled: no update progress after "
                            f"{interventions - 1} watchdog interventions "
                            f"(budget {updates}/{self._max_steps})")
                    self._async_watchdog(
                        assignments, np.asarray(w_now), batch_size,
                        learning_rate, optimizer, momentum)
                    last_progress_t = time.monotonic()
                if updates - last_step < check_every:
                    self._async_done.wait(backoff_s)
                    continue
                raw_loss, raw_acc = self.local_loss(w_now, test=True)
                stop = checker.check(raw_loss, raw_acc, w_now, step=updates)
                # counter keeps the reference's toLong truncation quirk
                # (MasterAsync.scala:126); the histogram carries the real value
                self.metrics.counter("master.async.loss").increment(int(checker.smoothed[0]))
                self.metrics.histogram("master.async.loss.value").record(checker.smoothed[0])
                self.log.info(
                    "loss computed at %d updates: test_loss=%.6f test_acc=%.4f",
                    updates, checker.smoothed[0], checker.smoothed_accs[0],
                )
                last_step = updates
                if stop:
                    self.log.info("converged to target: stopping computation")
                    break
        finally:
            self._end_async_endpoints(ever_assigned)
            if drain_thread is not None:
                # stop the drain AFTER StopAsync: in-flight gossip drains
                # into the weights instead of stranding in the inbox
                with self._inbox_cv:
                    self._drain_on = False
                    self._inbox_cv.notify()
                drain_thread.join(timeout=10.0)
        # BEST weights, not last (MasterAsync.scala:87-94)
        return async_fit_result(
            checker, w0, t_start, self._updates, batch_size, len(self.train))

    def _end_async_endpoints(self, endpoints) -> None:
        """StopAsync broadcast to every endpoint that ever held an
        assignment — members through their live stubs, evicted endpoints
        through a short-lived channel (best effort; a truly dead process
        just refuses the connection)."""
        self._async_running.clear()
        self._async_done.set()
        deadline = self.rpc_policy.deadline_s
        for key in endpoints:
            with self._members_lock:
                stub = self._workers.get(key)
            try:
                if stub is not None:
                    stub.StopAsync(pb.Empty(), timeout=deadline)
                else:
                    ch = new_channel(*key, origin=(self.host, self.port))
                    try:
                        WorkerStub(ch).StopAsync(pb.Empty(), timeout=deadline)
                    finally:
                        ch.close()
            except (grpc.RpcError, ValueError):
                pass

    def _start_async_worker(self, key, part, w, batch_size, learning_rate,
                            optimizer, momentum) -> None:
        with self._members_lock:
            stub = self._workers.get(key)
        if stub is None:
            raise RuntimeError(f"worker {key[0]}:{key[1]} vanished before StartAsync")
        # generous deadline: a RE-issued StartAsync first joins the
        # worker's running loop thread (worker.py start_async), which can
        # legitimately block for a full in-flight dispatch — a deadline
        # shorter than that would falsely evict a live survivor while the
        # handler goes on to start the new loop anyway (orphan training)
        stub.StartAsync(
            pb.StartAsyncRequest(
                weights=codec.encode_tensor(np.asarray(w)),
                samples=np.asarray(part).astype(np.int32),
                batch_size=batch_size,
                learning_rate=learning_rate,
                optimizer=optimizer or "",
                momentum=momentum,
            ),
            timeout=60.0,
        )

    def _async_watchdog(self, assignments, w_now, batch_size, learning_rate,
                        optimizer, momentum) -> None:
        """No update progress for the stall window: probe every assigned
        worker, evict the unresponsive, and re-issue their assignments.

        Dead workers fall in two classes: already evicted by the heartbeat
        loop (no longer members) and newly unresponsive to a Ping (evicted
        here).  Each dead worker's samples are merged into a survivor's
        assignment and re-issued via StartAsync with the CURRENT weights —
        the worker side replaces its running loop on a repeated StartAsync
        (worker.py start_async), so kicking a live-but-idle worker is safe
        too.  Raises RuntimeError when nobody is left to carry the budget.
        """
        with self._members_lock:
            member_keys = set(self._workers)
        dead = [k for k in assignments if k not in member_keys]
        for key in assignments:
            if key in dead:
                continue
            with self._members_lock:
                stub = self._workers.get(key)
            try:
                if stub is None:
                    raise ValueError("channel closed")
                stub.Ping(pb.Empty(), timeout=self.rpc_policy.deadline_s)
            except (grpc.RpcError, ValueError) as e:
                code = e.code() if isinstance(e, grpc.RpcError) else e
                self.log.warning(
                    "async watchdog: worker %s:%d unresponsive (%s); "
                    "declaring dead", key[0], key[1], code)
                self.unregister_worker(*key, evicted=True)
                dead.append(key)
        if not dead:
            survivors = list(assignments)
            if not survivors:
                raise RuntimeError("async fit: all workers lost mid-fit")
            # every worker answers pings yet nobody gossips: their async
            # loops are gone (e.g. a restarted process re-registered) —
            # re-issue every assignment with the current weights
            self.log.warning(
                "async watchdog: stalled with %d live workers; re-issuing "
                "all StartAsync assignments", len(survivors))
            for key in survivors:
                self._try_start_async_worker(key, assignments[key], w_now,
                                             batch_size, learning_rate,
                                             optimizer, momentum)
            return
        self._reassign_async(assignments, dead, w_now, batch_size,
                             learning_rate, optimizer, momentum)

    def _elastic_resplit(self, assignments, member_order, w_now, batch_size,
                         learning_rate, optimizer, momentum, split,
                         ever_assigned) -> None:
        """Elastic membership change (docs/ELASTICITY.md): re-split the
        corpus deterministically across the CURRENT members — the same
        core/split.py strategy the sync resplit path uses, over the same
        registration order, so any master looking at the same membership
        derives the same slices — and re-issue StartAsync (current
        weights) ONLY to workers whose slice changed.  Workers that kept
        their slice keep training untouched: a join or leave never stops
        the world.  Departed workers simply drop out of the assignment
        map; their peers swept the gossip state when the unregister
        broadcast landed (worker.remove_peer drops the EF residual, the
        RPC-sender window is closed)."""
        if not member_order:
            raise RuntimeError("async fit: all workers lost mid-fit")
        parts = self._split_parts(
            split, [(k, None) for k in member_order])
        new_assign = {key: part for key, part in zip(member_order, parts)}
        changed = [key for key in member_order
                   if key not in assignments
                   or not np.array_equal(assignments[key], new_assign[key])]
        joined = [key for key in member_order if key not in assignments]
        departed = [key for key in assignments if key not in new_assign]
        assignments.clear()
        assignments.update(new_assign)
        ever_assigned.update(member_order)
        self.metrics.counter(metrics_mod.ASYNC_RESPLITS).increment()
        flight.record("async.resplit", members=len(member_order),
                      joined=len(joined), departed=len(departed),
                      reissued=len(changed))
        self.log.warning(
            "elastic resplit across %d member(s): %d joined, %d departed, "
            "%d assignment(s) re-issued", len(member_order), len(joined),
            len(departed), len(changed))
        for key in changed:
            self._try_start_async_worker(key, assignments[key], w_now,
                                         batch_size, learning_rate,
                                         optimizer, momentum)

    def _reassign_async(self, assignments, dead, w_now, batch_size,
                        learning_rate, optimizer, momentum) -> None:
        """Merge each dead worker's samples into a survivor's assignment and
        re-issue StartAsync there with the current weights (the worker side
        replaces its running loop on a repeated StartAsync).  Raises
        RuntimeError when no survivor is left to carry the budget."""
        survivors = [k for k in assignments if k not in dead]
        if not survivors:
            raise RuntimeError("async fit: all workers lost mid-fit")
        targets = []
        for i, key in enumerate(dead):
            target = survivors[i % len(survivors)]
            part = assignments.pop(key)
            assignments[target] = np.concatenate([assignments[target], part])
            if target not in targets:
                targets.append(target)
            self.log.warning(
                "async fit: re-issuing %d samples of dead worker "
                "%s:%d to %s:%d", len(part), key[0], key[1], *target)
        for target in targets:
            self._try_start_async_worker(target, assignments[target], w_now,
                                         batch_size, learning_rate, optimizer,
                                         momentum)

    def _try_start_async_worker(self, key, part, w, batch_size, learning_rate,
                                optimizer, momentum) -> None:
        """Re-issue wrapper: a target that dies in the window between the
        probe and the StartAsync is evicted instead of aborting the fit —
        the loop's membership check reassigns its samples next tick."""
        try:
            self._start_async_worker(key, part, w, batch_size, learning_rate,
                                     optimizer, momentum)
        except (grpc.RpcError, RuntimeError) as e:
            code = e.code() if isinstance(e, grpc.RpcError) else e
            self.log.warning(
                "async fit: StartAsync re-issue to %s:%d failed (%s); "
                "evicting — samples reassign next tick", key[0], key[1], code)
            self.unregister_worker(*key, evicted=True)

    # -- batch-drain inbox (docs/ELASTICITY.md) ----------------------------

    # inbox bound, mirroring hogwild's max_inbox=1024: each entry holds a
    # DENSE dim-sized float32 delta, so an unbounded list would grow the
    # master's RSS without limit whenever sustained arrival outruns the
    # single drain thread (exactly the high-worker-count regime the drain
    # targets)
    ASYNC_INBOX_CAP = 1024

    def _inbox_put(self, delta: np.ndarray, n_steps: int) -> bool:
        """Buffer a delta iff the drain thread is accepting AND the inbox
        has room.  The check happens under the inbox lock — an
        unsynchronized `_drain_on` read followed by a put could land AFTER
        the drain thread observed shutdown and exited, stranding the delta
        in the inbox where the NEXT batch-drain fit would apply it to
        fresh weights.  Returns False when declined (caller applies
        per-message: on overflow that keeps every delta counted AND
        throttles arrival through the jitted apply under `_async_lock` —
        bounded work, so the gRPC server pool never starves the way a
        blocking put would)."""
        with self._inbox_cv:
            if not self._drain_on or len(self._inbox) >= self.ASYNC_INBOX_CAP:
                if self._drain_on:
                    self.metrics.counter(
                        metrics_mod.ASYNC_DRAIN_FALLBACK).increment()
                return False
            self._inbox.append((delta, n_steps))
            # health gauge (telemetry/health.py): inbox depth is the
            # arrival-vs-drain pressure signal the alert rules watch; a
            # GIL-atomic float set under the lock we already hold
            self.metrics.gauge(
                metrics_mod.HEALTH_DRAIN_BACKLOG).set(len(self._inbox))
            self._inbox_cv.notify()
            return True

    def _drain_loop(self) -> None:
        """Batch-drain thread: sum every buffered delta on the host and
        apply ONE jitted update per drain (deltas commute — the receiving
        merge sees exactly the per-message subtractions, summed; mirrors
        parallel/hogwild.py _drain_inbox).  Exits once the fit clears
        `_drain_on` AND the inbox is empty, so no delta is stranded."""
        drains = self.metrics.counter(metrics_mod.ASYNC_DRAINS)
        sizes = self.metrics.histogram(metrics_mod.ASYNC_DRAIN_SIZE)
        while True:
            with self._inbox_cv:
                while not self._inbox and self._drain_on:
                    self._inbox_cv.wait(timeout=0.25)
                batch, self._inbox = self._inbox, []
                self.metrics.gauge(metrics_mod.HEALTH_DRAIN_BACKLOG).set(0)
                if not batch and not self._drain_on:
                    return
            if not batch:
                continue
            acc = np.array(batch[0][0], dtype=np.float32, copy=True)
            total = int(batch[0][1])
            for delta, n in batch[1:]:
                acc += delta
                total += int(n)
            self._update_grad(acc, n_steps=total)
            drains.increment()
            sizes.record(len(batch))

    # master UpdateGrad RPC (MasterAsync.scala:164-177); one gossip message
    # may carry n_steps summed local steps (dispatch amortization) and
    # maxSteps counts local steps
    def _update_grad(self, delta: np.ndarray, n_steps: int = 1) -> None:
        with self._async_lock:
            if self._w_async is None:
                return
            self._w_async = self._apply(self._w_async, jnp.asarray(delta))
            stride = max(1, int(n_steps))
            self._updates += stride
            updates = self._updates
        if updates % 1000 < stride:  # crossing check: strides of k
            self.log.info("%d updates received", updates)
        if updates >= self._max_steps and self._async_running.is_set():
            self.log.info("max number of steps reached: stopping computation")
            self._async_running.clear()
            self._async_done.set()  # wake the check loop immediately

    def _require_ready(self) -> None:
        if not self.cluster_ready.is_set():  # withClusterReady barrier
            self.log.info("waiting for %d workers to join", self.expected_workers)
            self.cluster_ready.wait()


class _MasterServicer:
    """gRPC method bodies (AbstractMasterGrpc, Master.scala:220-253)."""

    def __init__(self, m: MasterNode):
        self.m = m

    def RegisterSlave(self, request, context):  # noqa: N802
        try:
            # Node.devices (docs/HIERARCHY.md): 0/absent from flat workers
            # and pre-hierarchy binaries — the split stays unweighted
            self.m.register_worker(request.host, request.port,
                                   devices=request.devices)
        except ValueError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return pb.Ack()

    def UnregisterSlave(self, request, context):  # noqa: N802
        self.m.unregister_worker(request.host, request.port)
        return pb.Ack()

    def UpdateGrad(self, request, context):  # noqa: N802
        # receive-side wire accounting for the gossip stream (send-side
        # comms.* counters live in the workers' compressors)
        self.m.metrics.counter("master.async.grad.bytes").increment(
            request.ByteSize())
        delta = codec.decode_grad(request)
        n_steps = request.n_steps or 1
        # batch-drain mode: decode on the servicer thread (parallel),
        # buffer for the drain thread's single summed apply; _inbox_put
        # declines atomically when draining is off (or just shut down)
        if not self.m._inbox_put(delta, n_steps):
            self.m._update_grad(delta, n_steps=n_steps)
        return pb.Ack()

    def Ping(self, request, context):  # noqa: N802
        # membership probe for the workers' re-registration watch
        # (docs/ELASTICITY.md): a caller this master does not know gets
        # NOT_FOUND — the one signal that survives a FAST restart (the
        # rebound port answers probes before the watch can accumulate
        # unreachability misses) and an eviction the worker missed
        if request.host:
            key = (request.host, request.port)
            with self.m._members_lock:
                known = key in self.m._workers
            if not known:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"{key[0]}:{key[1]} is not a member")
        return pb.Ack()
