"""Worker node: gRPC server + device-resident data + compiled kernels.

TPU-native re-design of the reference's Slave (core/Slave.scala): the
process boundary, registration retry, peer bookkeeping, and the async
gossip loop survive as host-side control plane, while every computation a
slave performs — per-sample forward (Slave.scala:129-140), batch gradient
sum + regularize (Slave.scala:142-157), and the Hogwild local step
(Slave.scala:79-111) — runs as a jitted XLA program on this worker's
device over a device-resident copy of the training data.

Variable-length RPC sample lists are padded to power-of-two buckets with
zeroed feature values (a zero row contributes zero gradient in every
model), so each bucket size compiles exactly once.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, NamedTuple, Optional, Tuple

import grpc
import jax
import jax.numpy as jnp
import numpy as np

from distributed_sgd_tpu.data.rcv1 import Dataset
from distributed_sgd_tpu.models.linear import LinearModel
from distributed_sgd_tpu.ops.sparse import SparseBatch
from distributed_sgd_tpu.rpc import codec, dsgd_pb2 as pb
from distributed_sgd_tpu.rpc.service import (
    GossipSender,
    MasterStub,
    RpcPolicy,
    WorkerStub,
    add_worker_servicer,
    new_channel,
    new_server,
)
from distributed_sgd_tpu import trace as trace_mod
from distributed_sgd_tpu.trace import flight
from distributed_sgd_tpu.utils import measure
from distributed_sgd_tpu.utils import metrics as metrics_mod
from distributed_sgd_tpu.utils.log import node_logger

# registration timing now lives in RpcPolicy (rpc/service.py): the policy
# defaults keep the reference's 5 s call deadline (Slave.scala:48) and 2 s
# initial retry delay (Slave.scala:56), growing with jittered exponential
# backoff to a ~30 s cap instead of a fixed 2 s sleep forever


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


class _Resident(NamedTuple):
    """One consistent snapshot of the worker's resident data slice.

    All five fields swap together (a single attribute assignment, atomic
    under the GIL) when an elastic reload re-shards the slice
    (``ensure_rows``), so a dispatch that grabbed the snapshot before the
    swap computes entirely on the OLD slice with the OLD offset — never a
    mix.  ``host`` keeps the host-side arrays only when a RowReader makes
    incremental reloads possible (the overlap rows a reload reuses)."""

    offset: Optional[int]  # global row id of local row 0 (None = full corpus)
    n: int  # resident rows
    idx: object  # device-resident indices / values / labels
    val: object
    y: object
    host: Optional[Dataset]  # host copy for reload overlap reuse (reader set)


class WorkerNode:
    def __init__(
        self,
        host: str,
        port: int,
        master_host: str,
        master_port: int,
        data: Dataset,
        model: LinearModel,
        device=None,
        seed: int = 0,
        metrics: Optional[metrics_mod.Metrics] = None,
        steps_per_dispatch: int = 1,
        max_inflight_gossip: int = 64,
        compress: str = "none",
        compress_k: float = 0.01,
        compress_ef: bool = True,
        rpc_policy: Optional[RpcPolicy] = None,
        profile_dir: Optional[str] = None,
        profile_steps: int = 16,
        gossip_topology: str = "all",
        master_watch_s: Optional[float] = None,
        master_watch_misses: int = 3,
        telemetry: bool = False,
        host_devices: int = 1,
        devices=None,
        data_offset: Optional[int] = None,
        row_reader=None,
        total_rows: Optional[int] = None,
        host_overprovision: float = 0.0,
    ):
        self.host, self.port = host, port
        self.log = node_logger(host, port, master=False)
        self.metrics = metrics or metrics_mod.global_metrics()
        # unified retry/backoff/breaker policy for every outgoing RPC
        # (registration backoff, gossip breaker suppression)
        self.rpc_policy = rpc_policy or RpcPolicy(
            seed=seed + port, metrics=self.metrics)
        self.model = model
        self.device = device if device is not None else jax.devices()[0]
        self.seed = seed
        # wire-path gradient compression (compress/, docs/COMPRESSION.md):
        # None for the default codec, keeping every send below byte-identical
        # to the uncompressed tree.  Residuals are per destination inside the
        # compressor, so sync replies ("sync:master") and each gossip peer
        # accumulate independently.
        from distributed_sgd_tpu.compress import make_compressor

        self._compressor = make_compressor(
            compress, k=compress_k, error_feedback=compress_ef,
            seed=seed + port, metrics=self.metrics)
        # sync-reply EF retry guard: (window key, residual snapshot) of
        # the last Gradient request, plus the fit-session token last seen —
        # see encode_sync_grad.  The key is the broadcast step_version
        # under the versioned wire (retries repeat it even when the wire
        # form changes), the raw weight bytes under the pre-pipeline wire.
        # The lock exists for the quorum barrier (DSGD_QUORUM): a straggler
        # can still be encoding window v when the master's request for v+1
        # (possibly carrying an ef_rollback_version) arrives on another
        # servicer thread — without quorum exactly one Gradient is ever in
        # flight per worker and the lock is uncontended
        self._sync_guard_lock = threading.Lock()
        self._sync_ef_guard: Tuple[Optional[object], Optional[np.ndarray]] = (
            None, None)
        self._sync_fit_token = 0
        # versioned weight-replica cache for the pipelined sync path
        # (docs/SYNC_PIPELINE.md): the last applied weight vector keyed by
        # (fit_token, step_version), so the master can broadcast sparse
        # WeightDeltas (or nothing at all on retry windows) instead of the
        # full dense tensor — see resolve_request_weights
        self._replica_lock = threading.Lock()
        self._replica: Optional[Tuple[int, int, np.ndarray]] = None
        # k local SGD steps per compiled dispatch; the summed delta is
        # gossiped every k steps (deltas commute — same amortization as
        # parallel/hogwild.py, GradUpdate.n_steps carries k on the wire).
        # k=1 is the reference's per-step gossip (Slave.scala:103-105)
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        # sparse gossip topology (DSGD_GOSSIP_TOPOLOGY, parallel/topology.py,
        # docs/ELASTICITY.md): which peers receive each dispatch's delta.
        # "all" (default) keeps the reference's full fan-out byte-identical;
        # ring/random:k select deterministically per (dispatch, worker) with
        # breaker-aware reselection around suppressed edges.  The master
        # ALWAYS receives the delta (budget counting) regardless of mode.
        from distributed_sgd_tpu.parallel.topology import parse_topology

        self._topo_mode, self._topo_k = parse_topology(gossip_topology)
        self._dispatch_no = 0
        # master-membership watch (docs/ELASTICITY.md): when set, a
        # registered worker probes Master.Ping with its own identity every
        # `master_watch_s`; after `master_watch_misses` consecutive misses
        # — or ONE NOT_FOUND from a reachable master that does not know us
        # (fast restart / missed eviction) — it clears _registered and
        # re-enters the jittered registration loop, the storm-safe path a
        # RESTARTED master's workers take back into membership.  None
        # (default) keeps the one-shot registration of the reference.
        self._master_watch_s = master_watch_s
        self._master_watch_misses = max(1, int(master_watch_misses))
        # cluster telemetry plane (telemetry/, DSGD_TELEMETRY,
        # docs/OBSERVABILITY.md): when on, each gradient dispatch publishes
        # the training-health gauges (gradient norm, dispatch staleness,
        # EF residual norm) that the master's Metrics-RPC scrape
        # re-exports per worker.  Off (default) the dispatch path runs no
        # extra host work at all; the Metrics RPC itself is always served
        # (pull-only — it costs nothing until somebody scrapes).
        self.telemetry = bool(telemetry)
        self._last_dispatch_t: Optional[float] = None

        # hierarchical in-host mesh (docs/HIERARCHY.md, DSGD_HOST_DEVICES):
        # host_devices > 1 binds the data slice to a local D-device mesh —
        # each Gradient / local-window dispatch shards the request batch
        # over the local devices and reduces with ONE in-host psum, so the
        # cross-host plane sees one reply per HOST instead of per device.
        # host_devices=1 (default) keeps the flat single-device worker
        # byte-identical to the pre-hierarchy engine.
        self._hier = None
        self.host_devices = max(1, int(host_devices))
        # incremental host-local re-sharding (data/host_shard.py,
        # docs/HIERARCHY.md "Elastic composition"): with a RowReader the
        # worker can RELOAD its resident slice when an elastic resplit
        # assigns rows outside it — reading only the uncovered delta —
        # instead of refusing the foreign ids.  `host_overprovision`
        # widens each reload by a neighbor-range margin so small boundary
        # shifts cost zero reloads.  The reader's domain is the TRAIN
        # corpus, so its row count must be explicit.
        self._row_reader = row_reader
        self._overprovision = max(0.0, float(host_overprovision))
        self._total_rows = total_rows
        self._reload_lock = threading.Lock()
        # resident-extent budget for reloads (see ensure_rows): seeded by
        # the constructed slice (nominal + over-provision), re-anchored by
        # each full-assignment reload (start_async).  Bounds both memory
        # and the per-reload device_put under drifting resplits.
        self._resident_budget = len(data)
        if row_reader is not None:
            if total_rows is None:
                raise ValueError(
                    "row_reader needs total_rows: the reload path must "
                    "know the reader's corpus extent to clip slices")
            if data_offset is None:
                raise ValueError(
                    "row_reader without data_offset: a full-corpus worker "
                    "has nothing to reload")
            if host_devices > 1:
                raise ValueError(
                    "row_reader is incompatible with host_devices > 1: "
                    "the in-host mesh replicates its slice at build time "
                    "(elastic reload would need a mesh rebind)")
        if self.host_devices > 1:
            from distributed_sgd_tpu.parallel.hier import HostMeshEngine

            devs = list(devices) if devices is not None else jax.local_devices()
            if len(devs) < self.host_devices:
                raise ValueError(
                    f"host_devices={self.host_devices} but only "
                    f"{len(devs)} local device(s) are available")
            self._hier = HostMeshEngine(model, devs[: self.host_devices], data)
            self.device = devs[0]
            # forward/async reuse the engine's mesh-replicated arrays
            # (ops on replicated arrays compute fine; the sync Gradient
            # plane is where the in-host reduction pays)
            res_idx, res_val, res_y = (
                self._hier.idx, self._hier.val, self._hier.y)
        else:
            # device-resident copy of the worker's data (the reference
            # slave also holds the full data and receives sample indices,
            # Main.scala:138)
            res_idx = jax.device_put(data.indices, self.device)
            res_val = jax.device_put(data.values, self.device)
            res_y = jax.device_put(data.labels, self.device)
        # host-local data slice (data/host_shard.py): `data` holds only
        # global rows [data_offset, data_offset + len(data)) and incoming
        # sample ids are mapped before any gather.  None (default) = the
        # full corpus is resident and ids pass through untouched.  The
        # whole resident state lives in ONE snapshot tuple so an elastic
        # reload swaps it atomically (see _Resident).
        self._resident = _Resident(
            data_offset, len(data), res_idx, res_val, res_y,
            data if row_reader is not None else None)
        # which scatter formulation this node's kernels run, as a
        # scrapeable gauge (ROADMAP item: the DSGD_SCATTER=auto pick was
        # only logged; the cluster /metrics endpoint now attributes it —
        # value indexes ops/mxu.SCATTER_FORMULATIONS)
        from distributed_sgd_tpu.ops import mxu as _mxu

        self.metrics.gauge(metrics_mod.SCATTER_FORMULATION).set(
            _mxu.SCATTER_FORMULATIONS.index(
                _mxu.active_scatter_formulation()))

        self._peers: Dict[Tuple[str, int], WorkerStub] = {}
        # bounded fire-and-forget gossip per peer (and to the master):
        # drop-oldest over max_inflight_gossip in-flight UpdateGrads, drops
        # counted under slave.async.grad.dropped (parity with the
        # in-process engine's bounded inbox, parallel/hogwild.py)
        self._gossip: Dict[Tuple[str, int], GossipSender] = {}
        self._max_inflight_gossip = int(max_inflight_gossip)
        self._peers_lock = threading.Lock()
        # server first: port 0 resolves to the bound port HERE, so the
        # outgoing channels below carry the worker's real endpoint as their
        # chaos edge origin
        self.server = new_server(port, host="0.0.0.0")
        self.port = self.port or self.server.bound_port
        self._master_channel = new_channel(master_host, master_port,
                                           origin=(host, self.port))
        self._master = MasterStub(self._master_channel)
        self._master_gossip = GossipSender(
            self._master.UpdateGrad, self.metrics, self._max_inflight_gossip,
            breaker=self.rpc_policy.breaker((master_host, master_port)),
            deadline_s=self.rpc_policy.deadline_s)

        # async (Hogwild) state — Slave.scala:23-34
        self._w_lock = threading.Lock()
        self._w: Optional[jax.Array] = None
        self._running_async = threading.Event()
        self._async_thread: Optional[threading.Thread] = None
        self._assignment: Optional[jax.Array] = None
        self._async_bs = 0
        self._async_lr = 0.0

        self._apply = jax.jit(lambda w, d: w - d)
        self._grad_cache: Dict[int, callable] = {}  # keyed by padded capacity

        # aggregation-tree reduce role (aggtree/reduce.py, DSGD_AGG_TREE):
        # constructed lazily by the FIRST agg-annotated request so a
        # knobs-off worker registers no aggtree instrument and allocates
        # nothing (tests/test_aggtree.py identity gate)
        self._agg = None
        self._shard_asm = None
        self._agg_lock = threading.Lock()

        # DSGD_PROFILE_DIR on the RPC worker role: a jax.profiler capture
        # of the FIRST `profile_steps` device dispatches (Gradient bodies
        # or async-loop steps) — this is where the distributed wall-clock
        # actually goes, which the trainer-only wiring never saw
        # (docs/OBSERVABILITY.md).  Thread-safe inside ProfileWindow:
        # dispatches arrive on gRPC servicer threads and the async loop
        # concurrently.
        self._profile = measure.ProfileWindow(profile_dir, profile_steps,
                                              logger=self.log)

        add_worker_servicer(self.server, _WorkerServicer(self),
                            node=self.node_label)
        self._registered = threading.Event()
        self._stopped = threading.Event()

    @property
    def node_label(self) -> str:
        """Stable identity for trace spans and flight events."""
        return f"{self.host}:{self.port}"

    def _ensure_reducer(self):
        """Lazily construct the aggregation-tree reduce role
        (aggtree/reduce.py) on the first agg-annotated request or child
        push — a knobs-off worker never calls this, so it registers no
        aggtree instrument (tests/test_aggtree.py identity gate)."""
        if self._agg is None:
            with self._agg_lock:
                if self._agg is None:
                    from distributed_sgd_tpu.aggtree.reduce import Reducer

                    self._agg = Reducer(self)
        return self._agg

    def _ensure_shard_assembler(self):
        """Lazily construct the shard rendezvous (shardedps/assemble.py)
        on the first shard-tagged Gradient request — the same default-off
        discipline as the reducer above: a knobs-off worker never calls
        this and registers no shard instrument (tests/test_shardedps.py
        identity gate)."""
        if self._shard_asm is None:
            with self._agg_lock:
                if self._shard_asm is None:
                    from distributed_sgd_tpu.shardedps.assemble import (
                        ShardAssembler,
                    )

                    self._shard_asm = ShardAssembler(metrics=self.metrics,
                                                     log=self.log)
        return self._shard_asm

    # resident-slice views (read-only; the canonical state is the atomic
    # _Resident snapshot — dispatch paths grab the snapshot ONCE and use
    # its fields, these properties serve telemetry/tests)
    @property
    def _idx(self):
        return self._resident.idx

    @property
    def _val(self):
        return self._resident.val

    @property
    def _y(self):
        return self._resident.y

    @property
    def _n(self) -> int:
        return self._resident.n

    @property
    def _data_offset(self) -> Optional[int]:
        return self._resident.offset

    # -- lifecycle (Slave.scala:40-77) -------------------------------------

    def start(self, wait_registered: bool = True) -> "WorkerNode":
        self.server.start()
        self.log.info("worker started on %s:%d", self.host, self.port)
        t = threading.Thread(target=self._register_loop, daemon=True, name="register")
        t.start()
        if wait_registered:
            self._registered.wait()
        return self

    def _register_loop(self) -> None:
        node = pb.Node(host=self.host, port=self.port)
        if self.host_devices > 1:
            # host shape rides the registration (docs/HIERARCHY.md): the
            # master weights its host-granular split by devices so a
            # bigger host gets a proportionally bigger partition.  Flat
            # workers leave the field unset — wire byte-identical to the
            # pre-hierarchy Node
            node.devices = self.host_devices
        while not self._stopped.is_set():
            attempt = 0
            while not self._stopped.is_set() and not self._registered.is_set():
                try:
                    self._master.RegisterSlave(
                        node, timeout=self.rpc_policy.deadline_s)
                    self._registered.set()
                    self.log.info("registered with master")
                except grpc.RpcError as e:
                    # jittered exponential backoff (policy default: 2 s first
                    # delay, the reference's fixed retry period,
                    # Slave.scala:56).  The jitter is what makes a whole
                    # fleet re-registering after a master restart storm-safe:
                    # N workers' retries spread over the backoff window
                    # instead of synchronizing (docs/ELASTICITY.md)
                    delay = self.rpc_policy.backoff_s(attempt)
                    attempt += 1
                    self.log.info("registration failed (%s); retry %d in %.1fs",
                                  e.code(), attempt, delay)
                    self._stopped.wait(delay)
            if self._master_watch_s is None or self._stopped.is_set():
                return
            # registered + watch enabled: probe the master WITH OUR OWN
            # identity.  Two distinct loss signals re-enter the
            # registration loop above: sustained unreachability (slow
            # restart / partition, counted in misses) and NOT_FOUND — a
            # reachable master that does not know us (a FAST restart
            # rebinds the port before misses can accumulate, and an
            # eviction we missed looks identical), which re-registers
            # immediately
            misses = 0
            while not self._stopped.wait(self._master_watch_s):
                try:
                    self._master.Ping(node,
                                      timeout=self.rpc_policy.deadline_s)
                    misses = 0
                except grpc.RpcError as e:
                    if e.code() == grpc.StatusCode.NOT_FOUND:
                        self.log.warning(
                            "master no longer knows us (restart or "
                            "eviction); re-registering")
                        flight.record("master.forgot", worker=self.node_label)
                        self._registered.clear()
                        break
                    misses += 1
                    if misses >= self._master_watch_misses:
                        self.log.warning(
                            "master unreachable for %d probes (%s); "
                            "re-registering", misses, e.code())
                        flight.record("master.lost", worker=self.node_label,
                                      misses=misses)
                        self._registered.clear()
                        break
            if self._registered.is_set():
                return  # stopped while the watch was healthy

    def stop(self) -> None:
        self._stopped.set()
        self._running_async.clear()
        if self._async_thread is not None:
            self._async_thread.join()
        self._profile.close()
        if self._registered.is_set():
            try:
                self._master.UnregisterSlave(
                    pb.Node(host=self.host, port=self.port), timeout=2.0
                )
            except grpc.RpcError:
                pass
        with self._peers_lock:
            senders = list(self._gossip.values())
        for sender in senders:
            sender.close()
        self._master_gossip.close()
        self.server.stop(grace=1.0)
        self._master_channel.close()
        self.log.info("worker stopped")

    def await_termination(self) -> None:
        self.server.wait_for_termination()

    # -- peer management ---------------------------------------------------

    def add_peer(self, host: str, port: int) -> None:
        key = (host, port)
        if key == (self.host, self.port):
            return
        with self._peers_lock:
            if key not in self._peers:
                stub = WorkerStub(new_channel(host, port,
                                              origin=(self.host, self.port)))
                self._peers[key] = stub
                # breaker-aware gossip: a partitioned peer costs one probe
                # per cooldown, not max_inflight in-flight cancels.  A
                # (re)introduction is evidence of liveness, so a breaker
                # tripped by the peer's previous incarnation re-closes
                breaker = self.rpc_policy.breaker(key)
                breaker.record_ok()
                self._gossip[key] = GossipSender(
                    stub.UpdateGrad, self.metrics, self._max_inflight_gossip,
                    breaker=breaker, deadline_s=self.rpc_policy.deadline_s)
                self.log.info("peer added: %s:%d", host, port)

    def remove_peer(self, host: str, port: int) -> None:
        with self._peers_lock:
            self._peers.pop((host, port), None)
            sender = self._gossip.pop((host, port), None)
            if self._compressor is not None:
                # a rejoining peer starts from a zero residual (the same
                # state as any destination joining mid-stream), and departed
                # peers must not pin dim-sized residual arrays forever.  An
                # async-loop compress in flight for this dest may re-create
                # the entry after this drop; the loop's post-fan-out sweep
                # (under this same lock) re-drops any dest that lost
                # membership mid-fan-out
                self._compressor.residual_drop(("peer", (host, port)))
        if sender is not None:
            sender.close()

    # -- compiled kernels --------------------------------------------------

    def _grad_fn(self, capacity: int):
        """Sync Gradient RPC body (sum + regularize), jitted per capacity.

        On a TPU-pinned worker the body runs on the lane-blocked MXU path
        (ops/mxu.py, the same kernels as the mesh engines); on CPU workers
        the scalar gather/scatter is faster than one-hot matmuls, so it
        stays.  The async step compiles its own mean-reduced variant
        (_async_loop).
        """
        model = self.model
        blocked = self._blocked_device()
        if capacity not in self._grad_cache:

            def fn(w, idx, val, y, ids, valid):
                rows_i = idx[ids]
                rows_v = val[ids] * valid[:, None]  # zero rows for pads
                batch = SparseBatch(rows_i, rows_v)
                by = y[ids] * valid.astype(y.dtype)
                return model.grad_regularized(w, batch, by, blocked=blocked)

            # donate the request's weight buffer (ROADMAP item 2): the
            # wrapper creates it from the wire/replica numpy array per
            # dispatch and nobody reads it afterwards, so XLA can write
            # the [D] gradient straight into its HBM instead of
            # allocating a fresh dim-sized output every window
            self._grad_cache[capacity] = jax.jit(fn, donate_argnums=(0,))
        return self._grad_cache[capacity]

    def _blocked_device(self) -> bool:
        """Blocked MXU kernels pay off on this worker's pinned device?"""
        from distributed_sgd_tpu.ops import mxu

        return mxu.blocked_pays_off(self.device)

    def _pad_ids(self, ids: np.ndarray) -> Tuple[jax.Array, jax.Array]:
        cap = _next_pow2(len(ids))
        padded = np.zeros(cap, dtype=np.int32)
        padded[: len(ids)] = ids
        valid = np.zeros(cap, dtype=np.float32)
        valid[: len(ids)] = 1.0
        return jnp.asarray(padded), jnp.asarray(valid)

    def warmup_thunks(self, batch_size: int, local_steps: int = 1):
        """Flagship compile thunks for the AOT warmup pass
        (compile_cache.py, DSGD_COMPILE_CACHE): the sync Gradient kernel
        at this worker's configured capacity bucket, the K-step local
        window when the pipelined engine is on, and their hierarchical
        (in-host psum) twins on a multi-device host.  Each thunk runs the
        REAL jitted callable once on inert inputs (zero weights, all-pad
        batches — zero rows contribute zero gradient in every model), so
        both the in-process dispatch cache and the persistent disk cache
        are populated before the first master request arrives."""
        d = self.model.n_features
        bs = max(1, int(batch_size))
        k = max(1, int(local_steps))
        if self._resident.n == 0:
            # an empty joining slice has no rows to gather from; kernels
            # compile lazily after the first reload assigns real rows
            return []
        if self._hier is not None:
            hier = self._hier
            thunks = [(f"hier.grad[b{bs}]", lambda: hier.grad(
                np.zeros(d, np.float32), np.zeros(bs, np.int64)))]
            if k > 1:
                thunks.append((f"hier.window[k{k},b{bs}]", lambda: (
                    hier.local_window(np.zeros(d, np.float32),
                                      np.zeros(k * bs, np.int64),
                                      k, bs, 0.0))))
            return thunks
        cap = _next_pow2(bs)

        def grad():
            res = self._resident
            np.asarray(self._grad_fn(cap)(
                jnp.zeros(d, jnp.float32), res.idx, res.val, res.y,
                jnp.zeros(cap, jnp.int32), jnp.zeros(cap, jnp.float32)))

        thunks = [(f"grad[cap{cap}]", grad)]
        if k > 1:

            def window():
                res = self._resident
                np.asarray(self._window_fn(k, bs)(
                    jnp.zeros(d, jnp.float32), res.idx, res.val, res.y,
                    jnp.zeros((k, bs), jnp.int32),
                    jnp.zeros((k, bs), jnp.float32), jnp.float32(0.0)))

            thunks.append((f"window[k{k},b{bs}]", window))
        return thunks

    def _local_ids(self, ids: np.ndarray) -> Tuple[np.ndarray, "_Resident"]:
        """Map global sample ids into this worker's resident rows; returns
        (local ids, the resident snapshot they are valid against) — the
        caller must compute on THAT snapshot's arrays, not re-read the
        attributes (an elastic reload may swap them mid-dispatch).

        With the full corpus resident (data_offset=None, the default) ids
        pass through untouched — zero cost on the flat path.  A host-local
        slice (data/host_shard.py) maps id -> id - offset; ids outside the
        slice trigger an incremental RELOAD through the worker's RowReader
        when one is configured (ensure_rows — the elastic resplit path,
        O(delta) rows read), and are REFUSED otherwise: silently wrapping
        them would compute a gradient over the wrong samples, and the
        failed RPC surfaces at the master as a classified worker failure
        (retry/evict), which is the honest signal that the split and the
        resident slices disagree.  The refusal also covers the reload swap
        window: a request racing the swap either maps cleanly against one
        snapshot or fails loudly and is retried."""
        res = self._resident
        if res.offset is None:
            return ids, res
        local = np.asarray(ids, dtype=np.int64) - res.offset
        if len(local) and (local.min() < 0 or local.max() >= res.n):
            if self._row_reader is not None:
                gmin = int(np.min(ids))
                gmax = int(np.max(ids)) + 1
                res = self.ensure_rows(gmin, gmax)
                local = np.asarray(ids, dtype=np.int64) - res.offset
                if not len(local) or (local.min() >= 0
                                      and local.max() < res.n):
                    return local, res
            raise ValueError(
                f"sample ids outside this host's resident slice "
                f"[{res.offset}, {res.offset + res.n}): "
                f"the master's split is not host-granular for this worker")
        return local, res

    def ensure_rows(self, lo: int, hi: int) -> "_Resident":
        """Grow/shift the resident slice to cover global rows [lo, hi)
        through the RowReader, reading ONLY the uncovered delta
        (data/host_shard.reload_slice) widened by the over-provision
        margin (DSGD_HOST_OVERPROVISION); returns the current snapshot.

        A range the slice already covers returns immediately (the
        membership-stable fast path costs one tuple read + two compares).
        An overlapping reload UNIONs with the resident range — repeated
        window-level triggers after one resplit each read only their gap,
        never re-read rows the previous trigger fetched — but the union
        is BOUNDED by the resident budget (the constructed slice extent,
        re-anchored by full-assignment reloads): when it would exceed the
        budget, rows on the side FARTHEST from the requested range are
        dropped, so drifting resplits slide a fixed-size window across
        the corpus instead of growing the resident set monotonically
        toward it (disk reads stay O(delta); host/device memory and the
        per-reload device_put stay O(budget)).  A disjoint jump drops
        the old rows entirely.  Swaps the _Resident snapshot atomically;
        in-flight dispatches keep computing on the snapshot they
        grabbed."""
        from distributed_sgd_tpu.data import host_shard

        with self._reload_lock:
            res = self._resident
            if (res.offset is None or self._row_reader is None
                    or (lo >= res.offset and hi <= res.offset + res.n)):
                return res
            total = self._total_rows
            margin = host_shard.overprovision_margin(
                hi - lo, self._overprovision)
            req_lo = max(0, lo - margin)
            req_hi = min(total, max(hi, lo + 1) + margin)
            want_lo, want_hi = req_lo, req_hi
            if want_lo < res.offset + res.n and res.offset < want_hi:
                # overlap: union so earlier rows stay warm
                want_lo = min(want_lo, res.offset)
                want_hi = max(want_hi, res.offset + res.n)
            budget = max(self._resident_budget, req_hi - req_lo)
            excess = (want_hi - want_lo) - budget
            if excess > 0:
                # trim old slack outside the requested range, biggest
                # side first — the kept window always covers [req_lo,
                # req_hi) and tracks the direction the split moved
                slack_lo = req_lo - want_lo
                slack_hi = want_hi - req_hi
                if slack_lo >= slack_hi:
                    cut = min(slack_lo, excess)
                    want_lo += cut
                    want_hi -= min(slack_hi, excess - cut)
                else:
                    cut = min(slack_hi, excess)
                    want_hi -= cut
                    want_lo += min(slack_lo, excess - cut)
            host = res.host
            new_data, rows_read = host_shard.reload_slice(
                host, res.offset, self._row_reader, total,
                host.n_features, host.pad_width if not host.is_dense else 0,
                want_lo, want_hi, labels_dtype=host.labels.dtype)
            new_res = _Resident(
                want_lo, len(new_data),
                jax.device_put(new_data.indices, self.device),
                jax.device_put(new_data.values, self.device),
                jax.device_put(new_data.labels, self.device),
                new_data)
            self._resident = new_res
            self.metrics.counter(metrics_mod.DATA_RELOADS).increment()
            self.metrics.counter(
                metrics_mod.DATA_RELOAD_ROWS).increment(rows_read)
            flight.record("data.reload", worker=self.node_label,
                          start=want_lo, end=want_hi, rows_read=rows_read)
            self.log.info(
                "resident slice re-sharded: [%d, %d) -> [%d, %d), "
                "%d row(s) read (delta only)", res.offset,
                res.offset + res.n, want_lo, want_hi, rows_read)
            return new_res

    def compute_gradient(self, w: np.ndarray, ids: np.ndarray) -> np.ndarray:
        """Sync Gradient RPC body: sum of backwards + regularize
        (Slave.scala:142-157).  On a hierarchical host the batch shards
        over the local mesh and reduces with one in-host psum
        (parallel/hier.py) — same reply, one RPC per host."""
        self._profile.tick()
        ids, res = self._local_ids(ids)
        if self._hier is not None:
            g = self._hier.grad(np.asarray(w, dtype=np.float32), ids)
            self.metrics.counter("slave.sync.backward").increment()
            return g
        pids, valid = self._pad_ids(ids)
        g = self._grad_fn(len(pids))(
            jnp.asarray(w), res.idx, res.val, res.y, pids, valid
        )
        self.metrics.counter("slave.sync.backward").increment()
        return np.asarray(g)

    def compute_gradient_hedged(self, w: np.ndarray,
                                ids: np.ndarray) -> np.ndarray:
        """Hedge-request compute (GradientRequest.hedge): same math as
        compute_gradient, but a FOREIGN slice — ids outside a host-local
        donor's resident window — is read through the donor's RowReader
        into a transient scratch batch instead of sliding the resident
        window via ensure_rows.  The donor's resident bounds, reload
        counters, and over-provision budget belong to ITS OWN slice; a
        backup duplicate of someone else's rows must not thrash them
        (docs/HIERARCHY.md — the caveat that used to ban hedge=True in
        bench_soak).  Ids inside the resident slice take the normal path
        unchanged, so a full-corpus worker never pays anything here."""
        res = self._resident
        if (res.offset is not None and self._row_reader is not None
                and len(ids)):
            local = np.asarray(ids, dtype=np.int64) - res.offset
            if local.min() < 0 or local.max() >= res.n:
                return self._scratch_gradient(w, ids, res)
        return self.compute_gradient(w, ids)

    def _scratch_gradient(self, w: np.ndarray, ids: np.ndarray,
                          res: "_Resident") -> np.ndarray:
        """Bounded scratch read + one gradient over it: materializes ONLY
        [min(ids), max(ids)+1) through the RowReader — the same clipped
        window ensure_rows would have requested, WITHOUT the
        over-provision margin, the resident-budget union, the _Resident
        swap, or the reload counters/flight record — computes on the
        transient arrays, and drops them."""
        from distributed_sgd_tpu.data import host_shard

        self._profile.tick()
        gmin = int(np.min(ids))
        gmax = int(np.max(ids)) + 1
        host = res.host
        scratch = host_shard.load_host_shard(
            self._row_reader, self._total_rows, host.n_features,
            host.pad_width if not host.is_dense else 0, gmin, gmax,
            labels_dtype=host.labels.dtype)
        self.metrics.counter(metrics_mod.HEDGE_SCRATCH).increment()
        pids, valid = self._pad_ids(np.asarray(ids, dtype=np.int64) - gmin)
        g = self._grad_fn(len(pids))(
            jnp.asarray(w), jnp.asarray(scratch.indices),
            jnp.asarray(scratch.values), jnp.asarray(scratch.labels),
            pids, valid)
        self.metrics.counter("slave.sync.backward").increment()
        return np.asarray(g)

    # -- pipelined sync engine (docs/SYNC_PIPELINE.md) ---------------------

    def resolve_request_weights(self, request):
        """Versioned weight resolution for the sync Gradient path.

        Returns (weights, stale).  A full broadcast (`weights` set)
        installs the replica at `step_version`; a WeightDelta assigns the
        master's ABSOLUTE new values at `delta.indices` on top of the
        cached replica when `base_version` matches; a header-only request
        (neither arm set, version tracking on) reuses the replica as-is.
        Any mismatch — empty cache after a (re)start, wrong base version,
        wrong fit session — returns stale=True WITHOUT computing anything:
        the master falls back to a full broadcast on the retry window.

        A request whose target version the replica already holds returns
        the cache directly regardless of arm, so a delta re-sent after a
        lost reply is never applied twice (the absolute-value encoding
        would make re-application harmless anyway; the version check makes
        it structural).  Pre-pipeline masters always send full weights
        with step_version=0, which lands in the install arm every window —
        identical behavior to the unversioned wire.
        """
        tok = request.fit_token
        version = request.step_version
        with self._replica_lock:
            if self._replica is not None and self._replica[0] != tok:
                self._replica = None  # new fit session: drop the old replica
            if request.HasField("weights"):
                w = codec.decode_tensor(request.weights)
                self._replica = (tok, version, w)
                return w, False
            if self._replica is None:
                return None, True
            _, cached_ver, cached = self._replica
            if cached_ver == version:
                return cached, False  # retry / already-applied: idempotent
            if request.HasField("delta") and cached_ver == request.delta.base_version:
                w = codec.apply_weight_delta(cached, request.delta)
                self._replica = (tok, version, w)
                return w, False
            return None, True

    def _window_fn(self, steps: int, capacity: int):
        """K-step local-SGD window (GradientRequest.local_steps), jitted per
        (steps, per-step capacity): a lax.scan of the same sum-reduced
        regularized gradient as _grad_fn, each step applying the
        reference's plain update w -= lr * g locally.  Returns the summed
        weight-space decrement w_start - w_end — at K=1 this is exactly
        lr * compute_gradient(w, ids), so the master recovers the same
        pseudo-gradient the one-batch window would have produced."""
        model = self.model
        blocked = self._blocked_device()
        key = ("window", steps, capacity)
        if key not in self._grad_cache:

            def fn(w, idx, val, y, ids, valid, lr):
                def body(w_t, inp):
                    ids_t, valid_t = inp
                    rows_i = idx[ids_t]
                    rows_v = val[ids_t] * valid_t[:, None]  # zero rows for pads
                    batch = SparseBatch(rows_i, rows_v)
                    by = y[ids_t] * valid_t.astype(y.dtype)
                    g = model.grad_regularized(w_t, batch, by, blocked=blocked)
                    return w_t - lr * g, None

                w_end, _ = jax.lax.scan(body, w, (ids, valid))
                return w - w_end

            # w is request-scoped here too (see _grad_fn): donating it
            # lets the K-step scan run in place and the summed decrement
            # reuse the buffer — no per-window HBM copy
            self._grad_cache[key] = jax.jit(fn, donate_argnums=(0,))
        return self._grad_cache[key]

    def compute_local_window(self, w: np.ndarray, ids: np.ndarray, k: int,
                             batch_size: int, learning_rate: float) -> np.ndarray:
        """Run up to `k` local SGD steps over `ids` split into
        `batch_size`-sized batches; returns the summed decrement delta.
        The final (or only) batch may be short — epoch tails send fewer
        than k*batch_size ids — and is masked out via zeroed rows, so each
        (steps, batch_size) shape compiles exactly once."""
        self._profile.tick()
        ids, res = self._local_ids(ids)
        bs = max(1, int(batch_size))
        n = len(ids)
        # step count derives from the ids actually sent, capped at k so an
        # oversized sample list cannot run more local steps than the wire
        # contract (GradientRequest.local_steps) allows
        steps = max(1, min(-(-n // bs), max(1, int(k))))
        if self._hier is not None:
            delta = self._hier.local_window(
                np.asarray(w, dtype=np.float32), ids, steps, bs,
                float(learning_rate))
            self.metrics.counter("slave.sync.backward").increment(steps)
            return delta
        n = min(n, steps * bs)  # excess ids beyond the k-step budget dropped
        padded = np.zeros(steps * bs, dtype=np.int32)
        padded[:n] = np.asarray(ids[:n], dtype=np.int32)
        valid = np.zeros(steps * bs, dtype=np.float32)
        valid[:n] = 1.0
        delta = self._window_fn(steps, bs)(
            jnp.asarray(w), res.idx, res.val, res.y,
            jnp.asarray(padded.reshape(steps, bs)),
            jnp.asarray(valid.reshape(steps, bs)),
            jnp.float32(learning_rate),
        )
        self.metrics.counter("slave.sync.backward").increment(steps)
        return np.asarray(delta)

    def encode_sync_grad(self, g: np.ndarray, window_key,
                         fit_token: int = 0):
        """Compressed Gradient reply with at-most-once residual drain.

        `compress` removes the shipped top-k mass from the EF residual at
        encode time, but the sync master DISCARDS every ok reply in a batch
        window when a sibling worker fails and retries the whole window
        (core/master.py fit_sync) — without compensation each retry would
        permanently lose this worker's largest-magnitude coordinates.  A
        retry is recognizable here by `window_key` — the broadcast
        step_version when the master versions its broadcasts (versions
        start at 1 and only advance after a fully-successful window, and
        a retry repeats the version even when the wire FORM changed, e.g.
        a full broadcast downgrading to header-only once this worker
        acknowledged it), the raw weight bytes otherwise (byte-identical
        weights = retry, the pre-pipeline rule).  On a repeated key the
        pre-drain residual is restored before re-encoding.  (Identical
        weights across *different* windows would need an exactly-zero
        update — in which case the restored and current residuals
        coincide and the rollback is a no-op.)

        `fit_token` scopes the residual to ONE fit: the master stamps each
        fit_sync's requests with a fresh token, and a token change drops
        the residual + guard here, so one fit's unsent mass (a gradient of
        the abandoned trajectory) never leaks into the next fit's first
        windows.  0 = an older master without session tracking: behave as
        before (residual carried, bounded by one window's unsent mass).
        """
        with self._sync_guard_lock:
            if fit_token and fit_token != self._sync_fit_token:
                self._sync_fit_token = fit_token
                self._compressor.residual_drop("sync:master")
                self._sync_ef_guard = (None, None)
            prev_key, prev_res = self._sync_ef_guard
            if prev_key is not None and prev_key == window_key:
                self._compressor.residual_restore("sync:master", prev_res)
            else:
                self._sync_ef_guard = (
                    window_key,
                    self._compressor.residual_snapshot("sync:master"),
                )
            return self._compressor.compress(g, dest="sync:master")

    def record_health(self, g: np.ndarray) -> None:
        """Per-dispatch training-health gauges (telemetry/health.py,
        DSGD_TELEMETRY): this node's gradient norm, the gap since its
        previous dispatch (update staleness as the worker sees it), and
        the error-feedback residual norm when compression is on.  Called
        only with ``self.telemetry`` set, so the knobs-off dispatch path
        pays nothing."""
        now = time.monotonic()
        prev, self._last_dispatch_t = self._last_dispatch_t, now
        m = self.metrics
        m.gauge(metrics_mod.HEALTH_GRAD_NORM).set(float(np.linalg.norm(g)))
        if prev is not None:
            m.gauge(metrics_mod.HEALTH_STALENESS).set(now - prev)
        if self._compressor is not None:
            # the residual destination depends on the engine: sync replies
            # drain "sync:master", the async gossip loop drains "master" —
            # report whichever this worker is actually accumulating
            res = self._compressor.residual_snapshot("sync:master")
            if res is None:
                res = self._compressor.residual_snapshot("master")
            if res is not None:
                m.gauge(metrics_mod.HEALTH_EF_RESIDUAL_NORM).set(
                    float(np.linalg.norm(res)))

    def rollback_sync_ef(self, version: int) -> None:
        """Quorum contribution mask (GradientRequest.ef_rollback_version):
        the master discarded this worker's reply for broadcast `version`
        (the quorum barrier proceeded without it), so the residual drain
        of that window must be rolled back — the round contributed
        nothing, and its unsent top-k mass must neither be lost (drain)
        nor ride a later message twice (the master never applied the
        shipped part, so restoring the PRE-drain snapshot is exact).

        Exact-match only: if the guard's window key is not `version` the
        worker never encoded that window (the request itself was lost
        before compute) and there is nothing to roll back — the
        instruction is idempotent and safe to repeat."""
        if self._compressor is None:
            return
        with self._sync_guard_lock:
            prev_key, prev_res = self._sync_ef_guard
            if prev_key is not None and prev_key == version:
                self._compressor.residual_restore("sync:master", prev_res)
                self._sync_ef_guard = (None, None)
                self.metrics.counter("slave.sync.ef.rollback").increment()
                trace_mod.event(trace_mod.EVENT_EF_ROLLBACK, version=version)
                flight.record("ef.rollback", worker=self.node_label,
                              version=version)

    def compute_forward(self, w: np.ndarray, ids: np.ndarray):
        """Forward RPC body (Slave.scala:129-140) -> (predictions, margins).

        Margins ride along so the master can compute margin-based losses
        (logistic) exactly — see ForwardReply in dsgd.proto."""
        ids, res = self._local_ids(ids)
        pids, _ = self._pad_ids(ids)
        wj = jnp.asarray(w)
        batch = SparseBatch(res.idx[pids], res.val[pids])
        margins = self.model.margins(wj, batch)
        preds = self.model.predict(margins)
        self.metrics.counter("slave.sync.forward").increment()
        return np.asarray(preds)[: len(ids)], np.asarray(margins)[: len(ids)]

    # -- async engine (Slave.scala:79-111,159-195) -------------------------

    def start_async(self, w0: np.ndarray, assignment: np.ndarray, batch_size: int,
                    learning_rate: float, optimizer: str = "",
                    momentum: float = 0.9) -> None:
        # a re-issued StartAsync (master watchdog reassignment after a peer
        # death, master.py _async_watchdog) REPLACES any running loop: stop
        # and join it first so two loops never race on the shared state
        if self._async_thread is not None and self._async_thread.is_alive():
            self.log.info("StartAsync re-issued: replacing the running async loop")
            self._running_async.clear()
            self._async_thread.join()
        if self._hier is not None:
            # the in-host reduction is a sync-plane lever; the async loop
            # runs on the mesh-replicated arrays (correct, but every local
            # device computes the same step — no speedup)
            self.log.warning(
                "host_devices=%d: the async loop runs replicated on the "
                "local mesh (the in-host psum accelerates the sync "
                "Gradient plane)", self.host_devices)
        res = self._resident
        if res.offset is not None:
            if self._row_reader is not None and len(assignment):
                # elastic resplit landing outside the resident slice:
                # re-shard incrementally (O(delta) rows through the
                # reader) BEFORE mapping, instead of refusing the fit.
                # The assignment is the FULL new slice, so it re-anchors
                # the resident budget (span + both margins) — later
                # window-level reloads trim to this size
                a_lo = int(np.min(assignment))
                a_hi = int(np.max(assignment)) + 1
                from distributed_sgd_tpu.data.host_shard import (
                    overprovision_margin,
                )

                self._resident_budget = (a_hi - a_lo) + 2 * \
                    overprovision_margin(a_hi - a_lo, self._overprovision)
                res = self.ensure_rows(a_lo, a_hi)
            assignment = np.asarray(assignment, dtype=np.int64) - res.offset
            if len(assignment) and (assignment.min() < 0
                                    or assignment.max() >= res.n):
                raise ValueError(
                    "StartAsync assignment outside this host's resident "
                    "slice (host-local loading needs a host-granular split)")
        if self._compressor is not None:
            # error-feedback residuals belong to the trajectory that
            # accumulated them: a StartAsync begins (or replaces) a session
            # from fresh weights, and shipping the abandoned trajectory's
            # unsent mass into it would inject stale gradients — same for
            # the sync-reply residual of any fit that ran before this one
            self._compressor.reset()
            self._sync_ef_guard = (None, None)
        with self._w_lock:
            self._w = jax.device_put(jnp.asarray(w0, dtype=jnp.float32), self.device)
        self._assignment = jax.device_put(
            jnp.asarray(assignment, dtype=jnp.int32), self.device
        )
        self._async_bs = int(batch_size)
        self._async_lr = float(learning_rate)
        # optimizer for the LOCAL steps (StartAsyncRequest.optimizer;
        # ""/sgd = the reference's plain update, Slave.scala:99-101) —
        # resolved HERE so an unknown name fails the StartAsync RPC
        # instead of killing the daemon loop thread
        from distributed_sgd_tpu.parallel.sync import resolve_optimizer

        # momentum passes through verbatim — an explicit 0.0 is honored
        # (the master always sets both proto fields; when optimizer is
        # absent/sgd the value is unused anyway)
        self._async_opt = resolve_optimizer(
            optimizer or None, float(learning_rate), float(momentum))
        self._running_async.set()
        self._async_thread = threading.Thread(
            target=self._async_loop, daemon=True, name=f"async-{self.port}"
        )
        self._async_thread.start()
        self.log.info("async started: %d samples, bs=%d lr=%g optimizer=%s",
                      len(assignment), batch_size, learning_rate, optimizer or "sgd")

    def stop_async(self) -> None:
        self._running_async.clear()

    def apply_delta(self, delta: np.ndarray) -> None:
        """Peer/master UpdateGrad: w <- w - delta (Slave.scala:177-185)."""
        with self._w_lock:
            if self._w is not None:
                self._w = self._apply(self._w, jnp.asarray(delta))
        self.metrics.counter("slave.async.grad.update").increment()

    def _async_loop(self) -> None:
        # the loop thread is a daemon: an uncaught exception here would
        # kill Hogwild training SILENTLY (the master's stall watchdog only
        # notices minutes later) — leave post-mortem evidence first
        try:
            self._async_loop_impl()
        except Exception as e:  # noqa: BLE001 - record, dump, then surface
            flight.record("async.loop.crash", worker=self.node_label,
                          error=repr(e))
            flight.dump("exception")
            self.log.exception("async loop crashed")
            raise

    def _async_loop_impl(self) -> None:
        bs, lr = self._async_bs, self._async_lr
        n_assigned = int(self._assignment.shape[0])
        model = self.model
        ksteps = self.steps_per_dispatch
        # one resident snapshot for the whole loop: the assignment was
        # mapped against it in start_async, and a replacement StartAsync
        # (the only path that re-shards mid-async) replaces this loop too
        res = self._resident

        blocked = self._blocked_device()
        opt = self._async_opt

        def kstep(w, opt_state, assignment, idx, val, y, key):
            # k local SGD steps in ONE compiled dispatch; returns the
            # SUMMED delta for gossip (commutative merge — peers applying
            # the sum see exactly the k individual w <- w - delta merges,
            # just k steps later; staleness bounded by k).  Optimizer
            # state is LOCAL and threads through the carry across
            # dispatches; the wire still carries weight-space deltas
            def body(carry, kk):
                w_t, opt_s, acc = carry
                ids = assignment[jax.random.randint(kk, (bs,), 0, n_assigned)]
                batch = SparseBatch(idx[ids], val[ids])
                # MEAN reduce (Slave.scala:93-98) + regularize (Slave:99)
                g = model.grad_regularized(
                    w_t, batch, y[ids], reduce="mean", blocked=blocked
                )
                from distributed_sgd_tpu.parallel.sync import local_update

                w_t, opt_s, delta = local_update(opt, lr, g, w_t, opt_s)
                return (w_t, opt_s, acc + delta), None

            keys = jax.random.split(key, ksteps)
            (_, opt_state, acc), _ = jax.lax.scan(
                body, (w, opt_state, jnp.zeros_like(w)), keys)
            return acc, opt_state

        # donate the local optimizer state (threaded carry, rebound every
        # dispatch; the weight SNAPSHOT must not be donated — a concurrent
        # UpdateGrad may still read the same buffer through self._w)
        kstep = jax.jit(kstep, donate_argnums=(1,))
        key = jax.random.PRNGKey(self.seed + self.port)
        opt_state = opt.init(self._w) if opt is not None else None
        while self._running_async.is_set():
            key, k = jax.random.split(key)
            self._profile.tick()
            snapshot = self._w  # stale read is the algorithm
            delta, opt_state = kstep(
                snapshot, opt_state, self._assignment, res.idx, res.val,
                res.y, k)
            with self._w_lock:
                self._w = self._apply(self._w, delta)
            self.metrics.counter("slave.async.batch").increment(ksteps)
            delta_np = np.asarray(delta)
            if self.telemetry:
                # async dispatches publish the same health gauges as sync
                # Gradient bodies: the delta IS this node's update signal
                self.record_health(delta_np)
            # gossip fan-out span (trace/, one local trace per dispatch,
            # head-sampled): encode + hand-off per destination — the sends
            # themselves are fire-and-forget futures
            with measure.span("slave.async.gossip", metrics=self.metrics,
                              node=self.node_label, k=ksteps):
                self._gossip_dispatch(delta_np, ksteps)

    def _select_gossip(self):
        """This dispatch's peer destinations under the configured topology
        (parallel/topology.py).  'all' returns the live sender map in
        insertion order — the exact pre-topology iteration, so the default
        wire is byte- and order-identical; ring/random:k select
        deterministically per (dispatch, worker) and re-route edges whose
        breaker is refusing sends (counted + traced)."""
        with self._peers_lock:
            senders = dict(self._gossip)
        if self._topo_mode == "all":
            return list(senders.items())
        from distributed_sgd_tpu.parallel import topology as topo

        def _suppressed(key):
            s = senders.get(key)
            return (s is not None and s.breaker is not None
                    and s.breaker.suppressed())

        keys, reselects = topo.select_gossip_peers(
            self._topo_mode, self._topo_k, list(senders),
            (self.host, self.port), self._dispatch_no, seed=self.seed,
            suppressed=_suppressed)
        if reselects:
            self.metrics.counter(
                metrics_mod.TOPOLOGY_RESELECT).increment(reselects)
            trace_mod.event(trace_mod.EVENT_TOPOLOGY_RESELECT,
                            node=self.node_label, edges=reselects)
            flight.record("topology.reselect", worker=self.node_label,
                          edges=reselects)
        return [(k, senders[k]) for k in keys]

    def _gossip_dispatch(self, delta_np: np.ndarray, ksteps: int) -> None:
        """One dispatch's delta fan-out to the topology-selected peers + the
        master (the master ALWAYS receives: it counts the budget)."""
        self._dispatch_no += 1
        if self._compressor is None:
            msg = codec.encode_grad(delta_np)
            msg.n_steps = ksteps
            for _key, sender in self._select_gossip():
                sender.send(msg)  # fire-and-forget (Slave.scala:103-105),
            self._master_gossip.send(msg)  # bounded in-flight, drop-oldest
            return
        # per-destination encode: each peer (and the master) has its
        # own error-feedback residual, so the k coordinates shipped
        # can differ by destination.  Every message stays a plain
        # weight-space delta, so the receiving merges keep the
        # summed-delta commutativity contract above — EF only defers
        # WHEN a coordinate's mass arrives, bounded by the residual.
        # Note on transport drops: like the uncompressed wire, a
        # gossip message the bounded sender cancels is simply lost
        # (fire-and-forget permits it) — EF retransmits only what
        # SELECTION dropped, never what the transport dropped; the
        # loss stays bounded by one message per cancel, exactly as
        # in the uncompressed mode (docs/COMPRESSION.md).
        # Compress OUTSIDE _peers_lock (the first call jit-compiles
        # the selection — holding the lock through that would stall
        # Register/UnregisterSlave servicers); the post-loop sweep
        # below closes the race where a concurrent remove_peer's
        # residual_drop interleaves with an in-flight compress and
        # the dropped entry gets silently re-created.
        senders_c = self._select_gossip()
        for peer_key, sender in senders_c:
            msg = self._compressor.compress(
                delta_np, dest=("peer", peer_key))
            msg.n_steps = ksteps
            sender.send(msg)
        msg = self._compressor.compress(delta_np, dest="master")
        msg.n_steps = ksteps
        self._master_gossip.send(msg)
        with self._peers_lock:
            for peer_key, _ in senders_c:
                if peer_key not in self._gossip:
                    self._compressor.residual_drop(("peer", peer_key))


class _WorkerServicer:
    """gRPC method bodies (SlaveImpl, Slave.scala:113-196)."""

    def __init__(self, w: WorkerNode):
        self.w = w

    def RegisterSlave(self, request, context):  # noqa: N802
        self.w.add_peer(request.host, request.port)
        return pb.Ack()

    def UnregisterSlave(self, request, context):  # noqa: N802
        self.w.remove_peer(request.host, request.port)
        return pb.Ack()

    def Ping(self, request, context):  # noqa: N802
        return pb.Ack()

    def Forward(self, request, context):  # noqa: N802
        w = codec.decode_tensor(request.weights)
        ids = np.fromiter(request.samples, dtype=np.int64)
        preds, margins = self.w.compute_forward(w, ids)
        if request.want_margins:
            return pb.ForwardReply(predictions=preds, margins=margins)
        return pb.ForwardReply(predictions=preds)

    def Gradient(self, request, context):  # noqa: N802
        return self._gradient_update(request)

    def _gradient_update(self, request):
        """One sync-window Gradient body, shared verbatim by the unary
        Gradient RPC and the FitStream servicer loop below — streaming
        changes the transport, never the math (the stream-vs-unary
        bit-identity the rpc bench gates on falls out of this sharing)."""
        # quorum contribution mask: the master marks the window whose
        # reply it discarded so the EF residual drain rolls back first
        if request.ef_rollback_version:
            self.w.rollback_sync_ef(request.ef_rollback_version)
        if request.shard_count:
            # feature-sharded master plane (DSGD_MASTER_SHARDS,
            # docs/MASTER_SHARDING.md): this request is one lane's leg of
            # an M-way round — rendezvous the slices, compute once, reply
            # the range slice.  Flat requests never set shard_count, so
            # the knobs-off path pays one falsy proto-field read.
            return self._sharded_update(request)
        w, stale = self.w.resolve_request_weights(request)
        if stale:
            # replica/version mismatch: no gradient to give — the master
            # falls back to a full broadcast on the retry window
            self.w.metrics.counter("slave.sync.stale").increment()
            return pb.GradUpdate(stale_version=True)
        ids = np.fromiter(request.samples, dtype=np.int64)
        k = request.local_steps
        # compute vs encode/EF attribution (docs/OBSERVABILITY.md): under
        # an active trace these become children of the Gradient server
        # span (root=False: on an unsampled round they stay no-op rather
        # than fabricating orphan traces); always they feed the span.*
        # histograms
        with measure.span("slave.grad.compute", metrics=self.w.metrics,
                          root=False,
                          samples=len(ids), local_steps=int(k or 1)):
            if k > 1:
                g = self.w.compute_local_window(
                    w, ids, k, request.batch_size, request.learning_rate)
            elif request.hedge:
                # foreign-slice hedges read through a bounded scratch so
                # the donor's resident window never slides for someone
                # else's rows (see compute_gradient_hedged)
                g = self.w.compute_gradient_hedged(w, ids)
            else:
                g = self.w.compute_gradient(w, ids)
        if request.hedge:
            # straggler hedge (another worker's data slice): reply
            # uncompressed and leave this worker's OWN sync EF residual
            # untouched — the residual for that slice belongs to the
            # straggler, and draining ours here would double-count mass
            # against the master's average.  The health gauges are
            # likewise NOT recorded: the gradient norm belongs to the
            # straggler's slice, and overwriting this node's per-worker
            # series with it would pollute the dashboards exactly when
            # the cluster is under straggler stress
            self.w.metrics.counter("slave.sync.hedge").increment()
            msg = codec.encode_grad(g)
            if k > 1:
                msg.n_steps = k
            return msg
        if self.w.telemetry:
            self.w.record_health(g)
        if request.agg_parent or request.agg_children:
            # aggregation tree (DSGD_AGG_TREE, docs/AGGREGATION.md): this
            # node is an elected reduce node and/or an interior child —
            # collect, reduce, and route the subtree sum instead of the
            # plain reply.  Flat requests never reach this branch, so the
            # knobs-off dispatch path pays one falsy proto-field read.
            return self._agg_gradient(request, g, k)
        return self._encode_reply(request, g, k)

    def _sharded_update(self, request):
        """One lane's leg of a sharded round (shardedps/assemble.py):
        resolve this shard's weight slice, rendezvous with the sibling
        legs, compute the full gradient ONCE per round, and reply only
        the ``[shard_lo, shard_hi)`` slice — through the SAME encode/tree
        tail as a flat reply, so per-shard trees and the wire codec need
        no sharded special case."""
        asm = self.w._ensure_shard_assembler()
        g = asm.gradient(request, self.w.compute_gradient)
        if g is None:
            # a slice failed to resolve (or the rendezvous timed out):
            # every leg of the round replies stale and the master's retry
            # re-sends full slices on every lane
            self.w.metrics.counter("slave.sync.stale").increment()
            return pb.GradUpdate(stale_version=True,
                                 shard_index=request.shard_index)
        if self.w.telemetry and request.shard_index == 0:
            # health gauges once per round, not once per lane — the
            # gradient is the round's single full-dimension fan-in
            self.w.record_health(g)
        g_slice = np.ascontiguousarray(
            g[request.shard_lo:request.shard_hi])
        k = request.local_steps
        if request.agg_parent or request.agg_children:
            msg = self._agg_gradient(request, g_slice, k)
        else:
            msg = self._encode_reply(request, g_slice, k)
        msg.shard_index = request.shard_index
        return msg

    def _encode_reply(self, request, g, k):
        """The sync-reply encode tail, shared by the flat path and the
        tree path (a subtree sum rides the SAME per-edge codec /
        compression / EF machinery as a flat reply — for an aggregator
        the error-feedback residual simply accumulates against its
        subtree sum instead of its own gradient)."""
        # sync fan-in reply: compressed when configured (EF residual keyed
        # to the one sync destination — this worker answers one master),
        # with the retry-rollback + fit-session guards of encode_sync_grad
        with measure.span("slave.grad.encode", metrics=self.w.metrics,
                          root=False):
            if self.w._compressor is not None:
                # retry-window key: the step_version when the master versions
                # its broadcasts (a retry repeats the version even if the wire
                # form changed, e.g. full -> header-only after a mid-window
                # fallback), the weight bytes otherwise (pre-pipeline wire:
                # byte-identical weights = retry)
                window_key = request.step_version or request.weights.data
                msg = self.w.encode_sync_grad(g, window_key, request.fit_token)
            else:
                msg = codec.encode_grad(g)
        if k > 1:
            msg.n_steps = k  # wire accounting: steps amortized per round
        return msg

    def _agg_gradient(self, request, g, k):
        """Tree-annotated Gradient body (docs/AGGREGATION.md): reduce the
        stamped children into this node's own gradient in CANONICAL
        (stamped) order, then either push the subtree sum to the stamped
        parent over AggregateGrad (reply = armless agg_forwarded ack) or
        reply it to the master directly (root child — and the flat
        fallback when the push fails, tagged agg_flat).  Either way the
        encode tail below runs EXACTLY once per round, so the per-edge
        error-feedback residual drains at most once per round too."""
        from distributed_sgd_tpu.aggtree import reduce as agg_reduce

        red = self.w._ensure_reducer()
        contributors = [self.w.node_label]
        partial = False
        if request.agg_children:
            children = list(request.agg_children)
            with measure.span("slave.agg.reduce", metrics=self.w.metrics,
                              root=False, children=len(children)):
                got = red.collect(request.fit_token, request.agg_round,
                                  children,
                                  agg_reduce.wait_budget_s(request))
                # canonical order: the stamped child tuple, misses skipped
                # (f32 addition is order-sensitive — two runs over the same
                # plan and reply set must chain identically)
                updates = [got[c] for c in children if c in got]
                g = red.reduce(np.asarray(g, dtype=np.float32), updates)
            for c in children:
                u = got.get(c)
                if u is None:
                    partial = True
                else:
                    contributors.extend(u.agg_contributors or [c])
        msg = self._encode_reply(request, g, k)
        msg.agg_contributors.extend(contributors)
        if partial:
            msg.agg_partial = True
            self.w.metrics.counter(metrics_mod.AGG_PARTIAL).increment()
        if request.agg_parent:
            if red.push_up(request.agg_parent, request.fit_token,
                           request.agg_round, msg):
                # the subtree sum is riding the tree — the master's
                # barrier still gets one reply per dispatched worker,
                # this armless ack (decodes as zero, see codec.parse_grad)
                return pb.GradUpdate(agg_forwarded=True)
            # dead/unreachable parent: this whole subtree degrades to a
            # direct-to-master send for THIS round (the tree loses
            # performance, never the round).  Counted HERE, not at the
            # master: a dead parent usually fails its own reply in the
            # same window, so the master retries and discards the very
            # replies that carried the fallback flag — the child is the
            # only node that reliably witnesses the degradation.
            self.w.metrics.counter(metrics_mod.AGG_FLAT).increment()
            msg.agg_flat = True
            flight.record("agg.flat_fallback", worker=self.w.node_label,
                          parent=request.agg_parent,
                          round=int(request.agg_round))
        return msg

    def AggregateGrad(self, request, context):  # noqa: N802
        """Tree child push intake (DSGD_AGG_TREE): buffer the child's
        encoded subtree sum for the in-flight (or imminent) Gradient
        body above — see aggtree/reduce.py for the buffer contract."""
        self.w._ensure_reducer().offer(request.fit_token, request.round,
                                       request.origin, request.update)
        return pb.Ack()

    def FitStream(self, request_iterator, context):  # noqa: N802
        """Streaming sync fan-out (DSGD_STREAM, docs/SYNC_PIPELINE.md):
        one persistent bidi stream per master carrying framed
        GradientRequests for the lifetime of a fit; each frame runs the
        EXACT unary Gradient body and answers on the stream under the
        request's seq.  Teardown — the master closing, a transport reset,
        or an exception out of the body (e.g. the foreign-id refusal) —
        ends the generator, which the master's stream client treats like
        a failed unary call: in-flight windows replay over unary, the
        re-register path is untouched, and an elastic resplit simply
        re-opens the stream (rpc/stream.py)."""
        m = self.w.metrics
        m.counter(metrics_mod.SLAVE_STREAM_OPENED).increment()
        self.w.log.info("FitStream opened by %s", context.peer())
        try:
            for frame in request_iterator:
                if frame.WhichOneof("payload") != "request":
                    continue  # future-proofing: unknown arms are skipped
                m.counter(metrics_mod.SLAVE_STREAM_FRAMES).increment()
                update = self._gradient_update(frame.request)
                yield pb.Frame(seq=frame.seq, fit_token=frame.fit_token,
                               update=update)
        except grpc.RpcError:
            # the CLIENT tore the stream down (master closed at fit end,
            # cancelled, or the connection reset) — there is nobody left
            # to answer; end quietly, this is the normal lifecycle
            self.w.log.info("FitStream closed by peer")
        except Exception as e:  # noqa: BLE001 - surface, then tear down
            # a per-frame failure has no error arm on the stream: tearing
            # the stream down IS the classified failure (the master falls
            # back to unary, where the same request fails loudly per-call)
            self.w.log.warning("FitStream servicer loop failed: %r", e)
            flight.record("stream.servicer.error", worker=self.w.node_label,
                          error=repr(e))
            raise
        finally:
            m.counter(metrics_mod.SLAVE_STREAM_CLOSED).increment()

    def StartAsync(self, request, context):  # noqa: N802
        self.w.start_async(
            codec.decode_tensor(request.weights),
            np.fromiter(request.samples, dtype=np.int64),
            request.batch_size,
            request.learning_rate,
            optimizer=request.optimizer,
            momentum=request.momentum,
        )
        return pb.Ack()

    def StopAsync(self, request, context):  # noqa: N802
        self.w.stop_async()
        return pb.Ack()

    def UpdateGrad(self, request, context):  # noqa: N802
        self.w.apply_delta(codec.decode_grad(request))
        return pb.Ack()

    def Metrics(self, request, context):  # noqa: N802
        # cluster telemetry scrape (telemetry/aggregate.py): pull-only —
        # serving the snapshot costs nothing until a master scrapes, so
        # the method needs no knob
        from distributed_sgd_tpu.telemetry.aggregate import snapshot_metrics

        return snapshot_metrics(self.w.metrics, role="worker",
                                node=self.w.node_label)
