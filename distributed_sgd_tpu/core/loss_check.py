"""Shared async loss-checker state: leaky smoothing + best-weights tracking.

Factors the reference's loss-checking loop state (MasterAsync.scala:96-162)
used by all three async drivers (gRPC master, in-process Hogwild, on-mesh
local SGD): smoothed_t = c * raw + (1 - c) * smoothed_{t-1} (first check
uses raw as prev), newest-first smoothed history for the stopping
criterion, and best-(loss, weights) snapshotting.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from distributed_sgd_tpu.core.early_stopping import Criterion


class LossChecker:
    def __init__(
        self,
        leaky_loss: float,
        criterion: Optional[Criterion] = None,
        checkpointer=None,
        save_every: int = 10,
    ):
        if not (0.0 <= leaky_loss <= 1.0):
            raise ValueError("leaking coefficient must be between 0 and 1")
        self.leaky = leaky_loss
        self.criterion = criterion
        # optional checkpoint.Checkpointer: each new best-weights snapshot
        # is persisted, so the reference's "return best" behavior
        # (MasterAsync.scala:87-94) survives a process restart.  Saves are
        # offset past the directory's latest step: a resumed run's fresh
        # step counter must not save below the previous run's snapshots
        # (restore_latest picks the max step)
        self.checkpointer = checkpointer
        # write cadence: every improvement, plus every `save_every`-th
        # non-improving check (bounds history lost to a crash without
        # paying a blocking orbax write per check on long plateaus)
        self.save_every = max(1, int(save_every))
        self._checks_since_save = 0
        self._step_base = 0
        self.smoothed: List[float] = []  # newest first
        self.smoothed_accs: List[float] = []  # newest first
        self.best_loss = float("inf")
        self.best_weights: Optional[np.ndarray] = None
        # lifetime update count at the last snapshot: async drivers seed
        # their update counter from this so a resumed fit spends only the
        # REMAINING budget (maxSteps counts lifetime updates,
        # MasterAsync.scala:83), not a fresh full one.  _updates_seen is
        # the monotone high-water mark persisted on save: a check() without
        # an explicit step must never regress the snapshot's count
        self.restored_updates = 0
        self._updates_seen = 0
        if checkpointer is not None:
            restored = checkpointer.restore_latest()
            if restored is not None:
                step, state = restored
                # saves land strictly past the prior run's steps (orbax
                # silently drops writes to an existing step)
                self._step_base = step + 1
                # seed best-(loss, weights) from the snapshot so a resumed
                # run's first (possibly worse) check cannot shadow the
                # prior run's true best at a higher step
                if "best_loss" in state:
                    self.best_loss = float(state["best_loss"])
                    self.best_weights = np.asarray(state["weights"])
                # continuity of the smoothing + stopping history: a resumed
                # run's leaky smoothing chains from the prior run's values
                # and its criterion sees the full newest-first series, not
                # a fresh patience window (same fix as SyncTrainer's
                # test_losses_nf for the sync path)
                if "smoothed_nf" in state:
                    self.smoothed = [float(x) for x in np.asarray(state["smoothed_nf"])]
                if "smoothed_accs_nf" in state:
                    self.smoothed_accs = [
                        float(x) for x in np.asarray(state["smoothed_accs_nf"])
                    ]
                if "updates" in state:
                    self.restored_updates = int(state["updates"])
                    self._updates_seen = self.restored_updates

    def check(self, raw_loss: float, raw_acc: float, weights, step: Optional[int] = None) -> bool:
        """Record one evaluation; returns True if training should stop.

        `step` (e.g. the update count) labels the persisted checkpoint; it
        defaults to the number of checks so far."""
        if step is not None:
            self._updates_seen = max(self._updates_seen, int(step))
        prev = self.smoothed[0] if self.smoothed else raw_loss
        loss = self.leaky * raw_loss + (1 - self.leaky) * prev
        prev_acc = self.smoothed_accs[0] if self.smoothed_accs else raw_acc
        acc = self.leaky * raw_acc + (1 - self.leaky) * prev_acc
        self.smoothed.insert(0, loss)
        self.smoothed_accs.insert(0, acc)
        improved = loss < self.best_loss  # MasterAsync.scala:130-139
        if improved:
            self.best_loss = loss
            self.best_weights = np.asarray(weights)
        self._checks_since_save += 1
        # cadence saves require a genuine best snapshot: before the first
        # finite-loss improvement, best_weights is None and saving would
        # persist the CURRENT (possibly divergent) weights as "best"
        # (ADVICE r2)
        if self.checkpointer is not None and self.best_weights is not None and (
            improved or self._checks_since_save >= self.save_every
        ):
            # the snapshot always carries the best-so-far weights — so
            # restore_latest returns the reference's "best"
            # (MasterAsync.scala:91) — plus the complete smoothing/stopping
            # history, so a resumed run's patience window does not restart
            # at the last improvement.  Non-improving checks persist at the
            # save_every cadence (a blocking orbax write per check would be
            # O(n^2) I/O over a long plateau)
            self.checkpointer.save(
                self._step_base + (step if step is not None else len(self.smoothed)),
                self.best_weights,
                extra={
                    "best_loss": self.best_loss,
                    "smoothed_nf": np.asarray(self.smoothed, np.float32),
                    "smoothed_accs_nf": np.asarray(self.smoothed_accs, np.float32),
                    # lifetime update count (callers pass their — already
                    # resume-seeded — update counter as `step`); the
                    # monotone high-water mark, so a step-less check can
                    # never regress a restored count back toward zero
                    "updates": self._updates_seen,
                },
            )
            self._checks_since_save = 0
        return self.criterion is not None and self.criterion(self.smoothed)

    def refresh(self, best_loss: Optional[float] = None,
                best_weights=None) -> None:
        """Rotate the checker's baseline onto a NEW evaluation set
        (ROADMAP 3c: canary probe-set refresh, docs/SERVING.md).

        The smoothing history and best-loss baseline are only meaningful
        against the rows they were measured on — after the caller swaps
        its held-out probe rows, the old numbers compare apples to
        oranges, so refresh CLEARS them and (optionally) re-anchors
        `best_loss`/`best_weights` from a measurement the caller already
        took on the new rows (the serving router re-evaluates its
        PROMOTED version there).  `best_loss=None` leaves the checker
        baseline-less: the next check() (or canary pass) seeds it, the
        same cold-start rule as a fresh checker.  Checkpointer state and
        the lifetime update count are untouched — only the loss view
        rotates, not the training lineage."""
        self.smoothed = []
        self.smoothed_accs = []
        self.best_loss = float("inf")
        self.best_weights = None
        if best_loss is not None and np.isfinite(best_loss):
            self.best_loss = float(best_loss)
            if best_weights is not None:
                self.best_weights = np.asarray(best_weights)

    @property
    def history(self) -> List[float]:
        """Chronological smoothed losses."""
        return list(reversed(self.smoothed))

    @property
    def acc_history(self) -> List[float]:
        return list(reversed(self.smoothed_accs))


def async_fit_result(checker: "LossChecker", w0, t_start: float,
                     updates: int, batch_size: int, n_samples: int):
    """Assemble an async fit's FitResult from the checker's state: the
    BEST weights, not the last (MasterAsync.scala:87-94), with inf -> nan
    loss normalization and the epochs_run back-computation.  Shared by
    every async driver's normal exit and resumed-past-budget
    short-circuit (gRPC fit_async, HogwildEngine, LocalSGDEngine)."""
    import jax.numpy as jnp

    from distributed_sgd_tpu.core.grad_state import GradState
    from distributed_sgd_tpu.core.trainer import FitResult

    best = checker.best_weights if checker.best_weights is not None else w0
    result = FitResult(state=GradState(
        weights=jnp.asarray(best),
        loss=checker.best_loss if checker.best_loss != float("inf") else float("nan"),
        start=t_start,
        updates=updates,
    ).finish())
    result.test_losses = checker.history
    result.test_accuracies = checker.acc_history
    result.epochs_run = updates * batch_size // max(n_samples, 1)
    return result
