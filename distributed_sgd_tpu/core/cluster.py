"""In-process development cluster over real loopback gRPC.

The reference's dev mode spawns 1 master + nodeCount slaves in one JVM on
consecutive localhost ports through the real gRPC stack
(Main.scala:143-158); this does the same in one Python process — real
sockets, real proto marshalling, real registration/introduction — with
each worker assigned a device round-robin (on the CPU test mesh every
worker gets its own virtual device).  Ports default to 0 (OS-assigned).
"""

from __future__ import annotations

import logging
from typing import List, Optional

import jax

from distributed_sgd_tpu.core.master import MasterNode
from distributed_sgd_tpu.core.worker import WorkerNode
from distributed_sgd_tpu.data.rcv1 import Dataset
from distributed_sgd_tpu.models.linear import LinearModel

log = logging.getLogger("dsgd.cluster")


class DevCluster:
    def __init__(
        self,
        model: LinearModel,
        train: Dataset,
        test: Dataset,
        n_workers: int,
        host: str = "127.0.0.1",
        base_port: int = 0,
        devices=None,
        seed: int = 0,
        heartbeat_s: Optional[float] = None,
        heartbeat_max_misses: int = 3,
        steps_per_dispatch: int = 1,
        compress: str = "none",
        compress_k: float = 0.01,
        compress_ef: bool = True,
        chaos: Optional[str] = None,
        gossip_topology: str = "all",
        master_watch_s: Optional[float] = None,
        telemetry_port: Optional[int] = None,
        host_devices: int = 1,
        host_local: bool = False,
        host_overprovision: float = 0.0,
    ):
        """`host_devices > 1` builds a HIERARCHICAL cluster
        (docs/HIERARCHY.md): each worker is a multi-device host — a
        contiguous group of `host_devices` devices backing one in-host
        psum mesh (parallel/hier.py) — so the cluster needs
        n_workers x host_devices devices.  `host_local=True` additionally
        gives each worker ONLY its contiguous slice of the corpus
        (data/host_shard.py host_slice + WorkerNode data_offset), the
        no-host-materializes-the-corpus loading discipline; it requires
        the master's default vanilla split (which DevCluster uses).  With
        the flat topology (host_devices=1) each host-local worker also
        carries a RowReader over the corpus, so an elastic resplit
        re-shards its slice INCREMENTALLY (O(delta) rows re-read) instead
        of refusing the new sample ids; ``host_overprovision=f``
        additionally widens each slice by ceil(f * slice) neighbor rows
        per side so small boundary shifts cost zero reloads
        (docs/HIERARCHY.md "Elastic composition").  Hierarchical workers
        (host_devices > 1) keep the membership-stable contract: their
        in-host mesh binds the slice at build time."""
        # fault injection (chaos/, DSGD_CHAOS): the plan must be installed
        # BEFORE any node opens a channel so every stub is wrapped — but it
        # stays un-armed through cluster formation (registration and peer
        # introduction run on clear weather) and the fault clock starts at
        # the await_ready barrier below, which makes partition windows
        # (@30s) deterministic relative to the start of training
        self._chaos_installed = False
        if chaos:
            from distributed_sgd_tpu import chaos as chaos_mod
            from distributed_sgd_tpu.utils import metrics as metrics_mod

            chaos_mod.install(chaos, metrics=metrics_mod.global_metrics(),
                              armed=False)
            self._chaos_installed = True
        devs = list(devices if devices is not None else jax.devices())
        # kept for add_worker (elastic churn: join a fresh worker mid-fit)
        self._host, self._devs, self._seed = host, devs, seed
        self._train, self._model = train, model
        # cluster telemetry (telemetry/, DSGD_TELEMETRY): per-NODE metric
        # registries instead of the shared process-global one — in one
        # process a shared registry would make every worker's Metrics
        # reply identical and the cluster sum triple-count — plus the
        # master-side aggregator + endpoint on `telemetry_port`
        self._telemetry = telemetry_port is not None
        from distributed_sgd_tpu.utils import metrics as metrics_mod

        def node_metrics():
            return metrics_mod.Metrics() if self._telemetry else None

        self._node_metrics = node_metrics
        self._worker_kwargs = dict(
            steps_per_dispatch=steps_per_dispatch, compress=compress,
            compress_k=compress_k, compress_ef=compress_ef,
            gossip_topology=gossip_topology, master_watch_s=master_watch_s,
            telemetry=self._telemetry,
        )
        self.master = MasterNode(
            host, base_port, train, test, model,
            expected_workers=n_workers, seed=seed, metrics=node_metrics(),
        ).start(heartbeat_s=heartbeat_s,
                heartbeat_max_misses=heartbeat_max_misses)
        if self._telemetry:
            self.master.enable_telemetry(telemetry_port)
        if self._chaos_installed:
            from distributed_sgd_tpu import chaos as chaos_mod

            chaos_mod.name_endpoint(host, self.master.port, "master")
        # hierarchical topology (docs/HIERARCHY.md): contiguous device
        # groups + optional host-local data slices per worker
        self._host_devices = max(1, int(host_devices))
        groups = None
        if self._host_devices > 1:
            from distributed_sgd_tpu.parallel.mesh import local_device_groups

            groups = local_device_groups(devs, n_workers, self._host_devices)
        self._host_local = bool(host_local)
        self._overprovision = max(0.0, float(host_overprovision))
        self.workers: List[WorkerNode] = []
        for i in range(n_workers):
            port = 0 if base_port == 0 else base_port + 1 + i
            wdata, offset, reader, total = train, None, None, None
            if host_local:
                from distributed_sgd_tpu.data.host_shard import (
                    dataset_reader,
                    overprovisioned_slice,
                )

                lo, hi, _s, _e = overprovisioned_slice(
                    len(train), i, n_workers,
                    overprovision=self._overprovision)
                wdata, offset = train.slice(slice(lo, hi)), lo
                if self._host_devices == 1:
                    # flat host-local workers can re-shard incrementally
                    # (the reader is in-memory here — the discipline and
                    # the O(delta) accounting are what dev mode proves)
                    reader, total = dataset_reader(train), len(train)
            w = WorkerNode(
                host, port, host, self.master.port, wdata, model,
                device=devs[i % len(devs)], seed=seed + i,
                metrics=node_metrics(),
                steps_per_dispatch=steps_per_dispatch,
                compress=compress, compress_k=compress_k,
                compress_ef=compress_ef,
                gossip_topology=gossip_topology,
                master_watch_s=master_watch_s,
                telemetry=self._telemetry,
                host_devices=self._host_devices,
                devices=groups[i] if groups is not None else None,
                data_offset=offset,
                row_reader=reader, total_rows=total,
                host_overprovision=self._overprovision,
            )
            self.workers.append(w)
            if self._chaos_installed:
                from distributed_sgd_tpu import chaos as chaos_mod

                chaos_mod.name_endpoint(host, w.port, f"w{i}")
        for w in self.workers:
            w.start(wait_registered=True)
        self.master.await_ready()
        if self._chaos_installed:
            from distributed_sgd_tpu import chaos as chaos_mod

            chaos_mod.arm()
            log.warning("chaos plan armed: %s", chaos)
        log.info("dev cluster ready: master :%d + %d workers", self.master.port, n_workers)

    def add_worker(self, seed: Optional[int] = None,
                   wait_registered: bool = True,
                   host_local: Optional[bool] = None) -> WorkerNode:
        """Join a NEW worker to the running cluster (elastic churn /
        grow-back tests, docs/ELASTICITY.md): same data + model, an
        OS-assigned port, registered through the real control plane.  The
        master must have a free membership slot (an eviction or graceful
        leave frees one); an elastic fit absorbs the join at its next
        membership tick.

        ``host_local`` (default: the cluster's setting) joins the worker
        with an EMPTY resident slice and a RowReader: its first
        assignment loads exactly its new slice (+ the over-provision
        margin) through ``ensure_rows`` — the O(slice) spin-up path the
        spin-up bench measures, instead of materializing the corpus."""
        i = len(self.workers)
        host_local = (self._host_local and self._host_devices == 1
                      if host_local is None else host_local)
        wdata, extra = self._train, {}
        if host_local:
            from distributed_sgd_tpu.data.host_shard import dataset_reader

            wdata = self._train.slice(slice(0, 0))
            extra = dict(data_offset=0,
                         row_reader=dataset_reader(self._train),
                         total_rows=len(self._train),
                         host_overprovision=self._overprovision)
        w = WorkerNode(
            self._host, 0, self._host, self.master.port,
            wdata, self._model,
            device=self._devs[i % len(self._devs)],
            seed=self._seed + i if seed is None else seed,
            metrics=self._node_metrics(),
            **self._worker_kwargs, **extra,
        )
        self.workers.append(w)
        if self._chaos_installed:
            from distributed_sgd_tpu import chaos as chaos_mod

            chaos_mod.name_endpoint(self._host, w.port, f"w{i}")
        w.start(wait_registered=wait_registered)
        return w

    def leave_worker(self, i: int) -> WorkerNode:
        """GRACEFUL leave of worker `i` (autoscale churn drills,
        docs/SCALING.md soak methodology): the worker unregisters itself
        through the real control plane and its server/channels close —
        the counterpart of a scale-down, not a crash (no eviction, no
        heartbeat misses).  The master's next membership read resplits;
        the freed slot lets `add_worker` model the scale-up half.  The
        node is removed from `self.workers` so cluster teardown does not
        stop it twice."""
        w = self.workers.pop(i)
        w.stop()
        return w

    def stop(self) -> None:
        for w in self.workers:
            w.stop()
        self.master.stop()
        if self._chaos_installed:
            from distributed_sgd_tpu import chaos as chaos_mod

            chaos_mod.uninstall()

    def __enter__(self) -> "DevCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
