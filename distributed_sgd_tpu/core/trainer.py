"""Host-side training drivers: epoch loop, evaluation, early stopping.

The TPU-native counterpart of the reference master's fit orchestration
(core/Master.scala:120-218): run one compiled epoch (parallel/sync.py),
evaluate train+test objective/accuracy on device, feed the *test* loss
history (newest first) to the stopping criterion — exactly the reference's
loop structure (early stop on test losses, Master.scala:166; epoch-end
eval of all four series, Master.scala:201-211) with the per-batch gRPC
fan-out replaced by `lax.scan` + `psum`.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sgd_tpu.checkpoint import (
    restore_sync_fit,
    save_sync_fit,
    save_sync_fit_final,
)
from distributed_sgd_tpu.core.early_stopping import Criterion
from distributed_sgd_tpu.core.grad_state import GradState
from distributed_sgd_tpu.data.rcv1 import Dataset
from distributed_sgd_tpu.models.linear import LinearModel
from distributed_sgd_tpu.parallel.sync import BoundSync, SyncEngine
from distributed_sgd_tpu.utils import measure
from distributed_sgd_tpu.utils import metrics as metrics_mod

log = logging.getLogger("dsgd.trainer")


@dataclass
class FitResult:
    state: GradState
    losses: List[float] = field(default_factory=list)  # chronological
    accuracies: List[float] = field(default_factory=list)
    test_losses: List[float] = field(default_factory=list)
    test_accuracies: List[float] = field(default_factory=list)
    epochs_run: int = 0
    epoch_seconds: List[float] = field(default_factory=list)

    @property
    def weights(self):
        return self.state.weights


def record_epoch(result: FitResult, test_newest_first: List[float], epoch: int,
                 loss: float, acc: float, test_loss: float, test_acc: float,
                 epoch_s: float) -> None:
    """Epoch-end bookkeeping shared by every sync fit loop (mesh trainer,
    RPC fit_sync, feature-sharded fit): the four series + wall clock,
    epochs_run, and the NEWEST-FIRST test-loss history the stopping
    criterion consumes (the reference reads newest first,
    EarlyStopping.scala:18-46)."""
    result.losses.append(loss)
    result.accuracies.append(acc)
    result.test_losses.append(test_loss)
    result.test_accuracies.append(test_acc)
    result.epoch_seconds.append(epoch_s)
    result.epochs_run = epoch + 1
    test_newest_first.insert(0, test_loss)


class SyncTrainer:
    """Bulk-synchronous data-parallel trainer over a device mesh."""

    def __init__(
        self,
        model: LinearModel,
        mesh,
        batch_size: int,
        learning_rate: float,
        sampling: str = "fresh",
        metrics: Optional[metrics_mod.Metrics] = None,
        seed: int = 0,
        profile_dir: Optional[str] = None,
        checkpointer=None,
        checkpoint_every: int = 1,
        kernel: str = "mxu",
        virtual_workers: int = 1,
        optimizer=None,
        momentum: float = 0.9,
    ):
        self.engine = SyncEngine(
            model, mesh, batch_size, learning_rate, sampling=sampling,
            kernel=kernel, virtual_workers=virtual_workers,
            optimizer=optimizer, momentum=momentum,
        )
        from distributed_sgd_tpu.checkpoint import opt_kind_tag

        self._opt_kind = opt_kind_tag(optimizer)
        self.model = model
        self.metrics = metrics or metrics_mod.global_metrics()
        self.seed = seed
        self.profile_dir = profile_dir  # jax.profiler trace of epoch 1
        self.checkpointer = checkpointer  # checkpoint.Checkpointer or None
        self.checkpoint_every = checkpoint_every

    def fit(
        self,
        train: Dataset,
        test: Dataset,
        max_epochs: int,
        criterion: Optional[Criterion] = None,
        initial_weights: Optional[jax.Array] = None,
    ) -> FitResult:
        bound_train = self.engine.bind(train)
        bound_test = self.engine.bind(test)
        w = (
            jnp.zeros((self.model.n_features,), dtype=jnp.float32)
            if initial_weights is None
            else jnp.asarray(initial_weights, dtype=jnp.float32)
        )
        base_key = jax.random.PRNGKey(self.seed)
        result = FitResult(state=GradState(weights=w))
        test_losses_newest_first: List[float] = []

        start_epoch = 0
        restored = restore_sync_fit(
            self.checkpointer, self._opt_kind, bound_train.opt_state_leaves())
        if restored is not None:
            # early-stopping continuity: the criterion sees the full
            # newest-first test-loss history; optimizer continuity:
            # momentum/adam buffers resume where they left off (a zeroed
            # adam state on converged weights would bias-correct into a
            # large first step).  Kind/shape mismatches raise (shared
            # contract, checkpoint.decode_sync_fit_state)
            start_epoch, w_np, test_losses_newest_first, opt_leaves = restored
            w = jnp.asarray(w_np)
            if opt_leaves:
                bound_train.load_opt_state_leaves(opt_leaves)
            log.info("resumed from checkpoint at epoch %d", start_epoch)

        if start_epoch >= max_epochs:
            # a resumed run that is already done must not report epochs_run=0
            # with a NaN loss (ADVICE r2): evaluate the restored weights
            loss, acc = bound_train.evaluate(w)
            log.info(
                "checkpoint already at epoch %d >= max_epochs %d: nothing to "
                "run (loss=%.6f acc=%.4f)", start_epoch, max_epochs, loss, acc)
            result.epochs_run = start_epoch
            result.state = GradState(weights=w, loss=loss).finish()
            return result

        # prefer the second epoch (steady-state, compile excluded) but fall
        # back to the only epoch when the fit runs just one
        profile_epoch = start_epoch + 1 if max_epochs > start_epoch + 1 else start_epoch
        profiled = False
        for epoch in range(start_epoch, max_epochs):
            profiling = self.profile_dir is not None and epoch == profile_epoch
            if profiling:
                jax.profiler.start_trace(self.profile_dir)
                profiled = True
            t0 = time.perf_counter()
            # keyed by absolute epoch index: a resumed run continues the same
            # batch-sampling stream instead of replaying epochs 0..N-1's keys
            ek = jax.random.fold_in(base_key, epoch)
            # measure.span feeds BOTH the histogram exporters and (when
            # DSGD_TRACE is on) a trace span per epoch — the mesh engine
            # has no per-window RPC spans, so the epoch is its trace unit
            with measure.span("trainer.epoch", metrics=self.metrics,
                              node="trainer", epoch=epoch), \
                    self.metrics.timer("master.sync.batch.duration"):
                w = bound_train.epoch(w, ek)
                jax.block_until_ready(w)
            epoch_s = time.perf_counter() - t0
            if profiling:
                jax.profiler.stop_trace()
                log.info("profiler trace written to %s", self.profile_dir)

            loss, acc = bound_train.evaluate(w)
            test_loss, test_acc = bound_test.evaluate(w)
            record_epoch(result, test_losses_newest_first, epoch,
                         loss, acc, test_loss, test_acc, epoch_s)

            self.metrics.histogram("master.sync.loss").record(loss)
            self.metrics.histogram("master.sync.acc").record(100 * acc)
            self.metrics.histogram("master.sync.epoch.seconds").record(epoch_s)
            log.info(
                "epoch %d: loss=%.6f acc=%.4f test_loss=%.6f test_acc=%.4f (%.2fs)",
                epoch, loss, acc, test_loss, test_acc, epoch_s,
            )

            if self.checkpointer is not None and (epoch + 1) % self.checkpoint_every == 0:
                save_sync_fit(self.checkpointer, epoch + 1, w,
                              test_losses_newest_first, self._opt_kind,
                              bound_train.opt_state_leaves())

            if criterion is not None and criterion(test_losses_newest_first):
                log.info("Converged to target: stopping computation")
                break
        else:
            if max_epochs > 0:
                log.info("Reached max number of epochs: stopping computation")
        save_sync_fit_final(
            self.checkpointer, result.epochs_run, start_epoch,
            self.checkpoint_every, w, test_losses_newest_first,
            self._opt_kind, bound_train.opt_state_leaves())
        if self.profile_dir is not None and not profiled:
            log.warning(
                "no profiler trace captured: the fit stopped before epoch %d",
                profile_epoch,
            )

        result.state = GradState(
            weights=w, loss=result.losses[-1] if result.losses else float("nan")
        ).finish()
        return result

    def predict(self, weights: jax.Array, data: Dataset):
        """Predictions over a split (Master.predict, Master.scala:61-75)."""
        bound = self.engine.bind(data)
        return bound.predict(weights)

    def evaluate(self, weights: jax.Array, data: Dataset):
        """(objective, accuracy) — Master.distributedLoss/Accuracy."""
        return self.engine.bind(data).evaluate(weights)
