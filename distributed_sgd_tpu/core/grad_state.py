"""Immutable training-state record.

TPU-native equivalent of the reference's ``GradState``
(core/ml/GradState.scala:6-24): weights + running loss + wall-clock
start/end + update count.  ``update`` applies a delta ``w <- w - d`` and
bumps the counter (GradState.scala:8); ``finish`` stamps the end time
(GradState.scala:12).  Weights may be a numpy array or a jax Array.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass
from typing import Any, Optional


@dataclass(frozen=True, eq=False)  # eq=False: array-valued weights break generated __eq__
class GradState:
    weights: Any
    loss: float = float("nan")
    start: float = dataclasses.field(default_factory=time.time)
    updates: int = 0
    end: Optional[float] = None

    def update(self, delta: Any) -> "GradState":
        return dataclasses.replace(self, weights=self.weights - delta, updates=self.updates + 1)

    def replace_weights(self, weights: Any, loss: Optional[float] = None) -> "GradState":
        kw = {"weights": weights, "updates": self.updates + 1}
        if loss is not None:
            kw["loss"] = loss
        return dataclasses.replace(self, **kw)

    def finish(self) -> "GradState":
        return dataclasses.replace(self, end=time.time())

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start
