"""Sample-index split strategies for data parallelism.

Equivalent of the reference's ``SplitStrategy``
(core/ml/SplitStrategy.scala:5-16): a strategy maps (n_samples, n_workers)
to a list of per-worker sample-index sequences.  The reference ships only
``vanilla`` — contiguous chunks of size ceil(n/n_workers)
(SplitStrategy.scala:13-14); we add ``strided`` and ``shuffled`` as
documented supersets (useful when label order is not i.i.d., as in RCV1's
chronological row order).
"""

from __future__ import annotations

import math
from typing import List

import numpy as np


def vanilla_split(n_samples: int, n_workers: int) -> List[np.ndarray]:
    """Contiguous `grouped(ceil(n/k))` split, SplitStrategy.scala:13-14.

    Note the reference quirk: with ceil-sized groups the final group may be
    short, and for k not dividing pathological n the number of groups can be
    < n_workers; we reproduce sizes exactly but always return n_workers
    entries (trailing entries may be empty), which the trainer requires.
    """
    idx = np.arange(n_samples, dtype=np.int64)
    size = max(1, math.ceil(n_samples / n_workers))
    groups = [idx[i : i + size] for i in range(0, n_samples, size)]
    while len(groups) < n_workers:
        groups.append(np.empty(0, dtype=np.int64))
    return groups[:n_workers]


def strided_split(n_samples: int, n_workers: int) -> List[np.ndarray]:
    """Round-robin split: worker i gets samples i, i+k, i+2k, ..."""
    idx = np.arange(n_samples, dtype=np.int64)
    return [idx[i::n_workers] for i in range(n_workers)]


def shuffled_split(n_samples: int, n_workers: int, seed: int = 0) -> List[np.ndarray]:
    """Uniform random permutation then contiguous chunks."""
    rng = np.random.default_rng(seed)
    idx = rng.permutation(n_samples).astype(np.int64)
    size = max(1, math.ceil(n_samples / n_workers))
    groups = [idx[i : i + size] for i in range(0, n_samples, size)]
    while len(groups) < n_workers:
        groups.append(np.empty(0, dtype=np.int64))
    return groups[:n_workers]


STRATEGIES = {
    "vanilla": vanilla_split,
    "strided": strided_split,
    "shuffled": shuffled_split,
}
