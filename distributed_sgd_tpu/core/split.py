"""Sample-index split strategies for data parallelism.

Equivalent of the reference's ``SplitStrategy``
(core/ml/SplitStrategy.scala:5-16): a strategy maps (n_samples, n_workers)
to a list of per-worker sample-index sequences.  The reference ships only
``vanilla`` — contiguous chunks of size ceil(n/n_workers)
(SplitStrategy.scala:13-14); we add ``strided`` and ``shuffled`` as
documented supersets (useful when label order is not i.i.d., as in RCV1's
chronological row order).
"""

from __future__ import annotations

import math
from typing import List

import numpy as np


def vanilla_split(n_samples: int, n_workers: int) -> List[np.ndarray]:
    """Contiguous `grouped(ceil(n/k))` split, SplitStrategy.scala:13-14.

    Note the reference quirk: with ceil-sized groups the final group may be
    short, and for k not dividing pathological n the number of groups can be
    < n_workers; we reproduce sizes exactly but always return n_workers
    entries (trailing entries may be empty), which the trainer requires.

    Sampling-bias bound (VERDICT item 7): the sync fan-in averages
    per-WORKER gradients with equal weight 1/k, and each worker draws its
    window uniformly from its OWN partition — so a sample's effective
    per-window inclusion weight is proportional to 1/|partition|.  When
    k does not divide n the trailing group is short and its samples are
    over-weighted by exactly `ceil(n/k) / trailing_size`, the value
    `sampling_bias_bound` computes (1.0 when k | n; it grows without
    bound as the trailing group degenerates toward one sample —
    n = (k-1) * ceil(n/k) + 1 is the adversarial shape).  The same ratio
    bounds the virtual-worker wrap bias in parallel/sync.py, whose
    modulo wrap maps out-of-range draws into the short trailing
    sub-shard.  Asserted in tests/test_virtual_workers.py.
    """
    idx = np.arange(n_samples, dtype=np.int64)
    size = max(1, math.ceil(n_samples / n_workers))
    groups = [idx[i : i + size] for i in range(0, n_samples, size)]
    while len(groups) < n_workers:
        groups.append(np.empty(0, dtype=np.int64))
    return groups[:n_workers]


def sampling_bias_bound(n_samples: int, n_workers: int) -> float:
    """Max per-sample over-weighting ratio under vanilla_split + equal
    per-worker averaging (see vanilla_split's docstring): the largest
    partition size over the smallest NON-EMPTY partition size.  1.0 when
    the split is even; == ceil(n/k) / trailing_size otherwise.  Empty
    trailing partitions are excluded — they hold no samples to bias."""
    if n_samples <= 0 or n_workers <= 0:
        return 1.0
    sizes = [len(p) for p in vanilla_split(n_samples, n_workers) if len(p)]
    return max(sizes) / min(sizes)


def weighted_split(n_samples: int, weights: List[int]) -> List[np.ndarray]:
    """Contiguous partitions with sizes proportional to `weights` — the
    host-granular assignment of the hierarchical topology
    (docs/HIERARCHY.md): a host with D devices gets a D-weighted share of
    the corpus, so every device across the cluster owns the same expected
    row count regardless of how devices are packed into hosts.

    Sizes are largest-remainder rounded (deterministic, ties broken by
    position), so they sum to exactly `n_samples` and differ from the
    exact proportional share by < 1 row.  With equal weights this
    degenerates to an even contiguous split — same coverage as
    `vanilla_split` up to the ceil-vs-even tail (the master only takes
    this path when host shapes actually differ)."""
    if not weights or min(weights) < 1:
        raise ValueError(f"weights must be positive, got {weights}")
    total = float(sum(weights))
    exact = [n_samples * w / total for w in weights]
    sizes = [int(e) for e in exact]
    # largest remainder: hand the leftover rows to the biggest fractions
    leftover = n_samples - sum(sizes)
    order = sorted(range(len(weights)), key=lambda i: exact[i] - sizes[i],
                   reverse=True)
    for i in order[:leftover]:
        sizes[i] += 1
    idx = np.arange(n_samples, dtype=np.int64)
    out, at = [], 0
    for s in sizes:
        out.append(idx[at: at + s])
        at += s
    return out


def strided_split(n_samples: int, n_workers: int) -> List[np.ndarray]:
    """Round-robin split: worker i gets samples i, i+k, i+2k, ..."""
    idx = np.arange(n_samples, dtype=np.int64)
    return [idx[i::n_workers] for i in range(n_workers)]


def shuffled_split(n_samples: int, n_workers: int, seed: int = 0) -> List[np.ndarray]:
    """Uniform random permutation then contiguous chunks."""
    rng = np.random.default_rng(seed)
    idx = rng.permutation(n_samples).astype(np.int64)
    size = max(1, math.ceil(n_samples / n_workers))
    groups = [idx[i : i + size] for i in range(0, n_samples, size)]
    while len(groups) < n_workers:
        groups.append(np.empty(0, dtype=np.int64))
    return groups[:n_workers]


STRATEGIES = {
    "vanilla": vanilla_split,
    "strided": strided_split,
    "shuffled": shuffled_split,
}
