"""Early-stopping criteria over newest-first loss sequences.

Faithful re-implementation of the reference's ``EarlyStopping``
(core/ml/EarlyStopping.scala:7-46).  A criterion is a callable
``losses -> bool`` where ``losses[0]`` is the NEWEST loss.

The ``no_improvement`` tolerance scan reproduces the reference's quirk
exactly (EarlyStopping.scala:18-28): the fold accepts any value within
``min_delta`` of the running minimum as the new minimum, so a *later*
near-tie wins the min index — this makes the criterion more patient with
plateaus than a strict argmin would be.  Training stops when the winning
index is >= ``patience`` (i.e. the effective min is at least `patience`
evaluations old).
"""

from __future__ import annotations

import sys
from typing import Callable, Optional, Sequence

Criterion = Callable[[Sequence[float]], bool]


def target(target_loss: float) -> Criterion:
    """Stop once the newest loss is <= target. EarlyStopping.scala:11."""

    def criterion(losses: Sequence[float]) -> bool:
        return len(losses) > 0 and losses[0] <= target_loss

    return criterion


def no_improvement(
    patience: int = 5,
    min_delta: float = 1e-3,
    min_steps: Optional[int] = None,
) -> Criterion:
    """Stop when the (tolerance-scanned) min loss is >= `patience` old.

    EarlyStopping.scala:13-46, including the fold-based findMin quirk.
    """
    abs_min_delta = abs(min_delta)

    def find_min_index(losses: Sequence[float]) -> int:
        cur_min, idx_min = sys.float_info.max, -1
        for i, v in enumerate(losses):
            if (v - cur_min) <= abs_min_delta:  # accepts later near-ties
                cur_min, idx_min = v, i
        return idx_min

    def criterion(losses: Sequence[float]) -> bool:
        if not losses:
            return False
        # minSteps semantics reproduced verbatim from EarlyStopping.scala:45
        # (`if (steps < losses.size) false else check`): the check only runs
        # while the history is no longer than min_steps, and is permanently
        # disabled once it grows past it.  This looks inverted from the
        # intent, but the reference always passes minSteps=None
        # (Main.scala:88-107), so the quirk is latent; we keep it for parity.
        if min_steps is not None and min_steps < len(losses):
            return False
        idx_min = find_min_index(losses)
        if idx_min == 0:  # newest is the min -> still improving
            return False
        return idx_min >= patience

    return criterion
