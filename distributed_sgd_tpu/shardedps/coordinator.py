"""Master-side shard lanes for the feature-sharded master plane
(DSGD_MASTER_SHARDS, docs/MASTER_SHARDING.md).

The flat sync engine is O(dim x N) on ONE master endpoint in both
directions: every round broadcasts the full weight vector to N workers
and decodes N full-dimension gradients.  ``ShardedCoordinator``
range-partitions that traffic across M shard lanes (shardedps/plan.py —
contiguous ranges, pure function of ``(dim, M)``): each lane owns its own
versioned broadcast state over its slice (the SAME ``_BroadcastState``
delta/codec machinery the flat plane runs, so delta broadcasts compose
per lane), its own per-shard aggregation tree when DSGD_AGG_TREE is on
(shard-colored: the tree seed is offset by the shard index, so different
lanes elect different aggregators and the reduce fan-in load spreads),
and its own byte ledger — the per-process wire cost ``bench.py --scale``
gates on is the MAX over lanes, not the sum.

Correctness is commutativity, not consensus: hinge-loss SGD applies
``w -= lr * mean(grads)`` coordinate-wise, so range-disjoint slices
applied independently land on the bit-identical weight vector the flat
engine produces — asserted per round by the bench sweep.  A worker is
good for a round only if EVERY lane's leg succeeded; any stale or failed
leg degrades the worker exactly as the flat plane would (one failure per
round per worker — M failed legs are ONE liveness strike, never M).

Failure plane (docs/MASTER_SHARDING.md "failure matrix"): ``kill(i)``
(the bench chaos hook, ``MasterNode.kill_shard``) marks lane *i* dead.
The next window dispatches ONE flat single-master fallback round —
untagged full-weight requests, classic barrier, zero special-casing on
the workers — then the plan rebuilds over the surviving lanes before the
following window, so exactly the affected rounds degrade and no live
worker is ever evicted for a master-side death.  All lanes dead leaves
the fit in permanent flat fallback: the fit completes, the perf win is
gone.

Constructed only by ``MasterNode.fit_sync`` when the knob is on; the
knobs-off fit never imports this module, registers no shard instrument,
and keeps the wire byte-identical (tests/test_shardedps.py).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import grpc
import numpy as np

from distributed_sgd_tpu.core.master import _BroadcastState
from distributed_sgd_tpu.rpc import codec, dsgd_pb2 as pb
from distributed_sgd_tpu.shardedps.plan import build_shard_plan
from distributed_sgd_tpu.trace import flight
from distributed_sgd_tpu.utils import metrics as metrics_mod


class ShardLane:
    """One master shard: a contiguous feature range, its versioned
    broadcast state over the slice wire, its shard-colored reduce tree,
    and its byte ledger."""

    def __init__(self, index: int, lo: int, hi: int, delta_broadcast: bool,
                 metrics):
        self.index = int(index)
        self.lo = int(lo)
        self.hi = int(hi)
        # encode_ahead off: M lanes spawning M encoder threads buys
        # nothing for dim/M-sized slices — the per-lane encode is already
        # off the O(dim) critical path by construction
        self.bcast = _BroadcastState(delta_broadcast, metrics,
                                     encode_ahead=False)
        self.tree_plan = None
        self.bcast_bytes = 0
        self.grad_bytes = 0
        self.killed = False


class ShardedCoordinator:
    """Per-fit shard fan-out/fan-in driver, called from fit_sync's hook
    points (plan build, membership rebuild, dispatch, collect,
    accumulate, advance) — the registration/liveness/resplit surface
    stays the flat master's."""

    def __init__(self, master, shards: int, dim: int, keys,
                 delta_broadcast: bool, tree_fanout: int,
                 grad_timeout_s: float):
        self.master = master
        self.metrics = master.metrics
        self.log = master.log
        self.dim = int(dim)
        self.delta_broadcast = bool(delta_broadcast)
        self.tree_fanout = int(tree_fanout or 0)
        self.grad_timeout_s = float(grad_timeout_s)
        self.plan = build_shard_plan(self.dim, shards)
        self._keys = list(keys)
        self._lanes: List[ShardLane] = []
        # chaos plane: kill() may land from any thread mid-round; the
        # fit thread absorbs it at the next dispatch boundary
        self._kill_lock = threading.Lock()
        self._killed: set = set()
        self._permanent_flat = False
        self._flat_round = False
        # in-flight round: ("sharded" | "flat", [(key, lane|None, fut)])
        self._round: Optional[Tuple[str, list]] = None
        self._collected: List[tuple] = []
        self._bcast_total = self.metrics.counter(
            metrics_mod.SHARD_BCAST_BYTES)
        self._grad_total = self.metrics.counter(metrics_mod.SHARD_GRAD_BYTES)
        self._build_lanes()
        flight.record("shard.plan", shards=self.plan.shards, dim=self.dim,
                      digest=self.plan.digest()[:12])
        self.log.info("sharded master plane: %r", self.plan)

    # -- plan / lane lifecycle ----------------------------------------------

    def _build_lanes(self) -> None:
        self._lanes = [
            ShardLane(i, lo, hi, self.delta_broadcast, self.metrics)
            for i, (lo, hi) in enumerate(self.plan.ranges)
        ]
        self.metrics.gauge(metrics_mod.SHARD_COUNT).set(len(self._lanes))
        if self.tree_fanout:
            self._build_lane_trees()

    def _build_lane_trees(self) -> None:
        """One reduce tree PER LANE, shard-colored: the plan seed is
        offset by the shard index, so per-host rotation elects different
        aggregators lane to lane and no single worker carries every
        shard's reduce fan-in (aggtree/plan.py build_plan)."""
        from distributed_sgd_tpu.aggtree import build_plan

        for lane in self._lanes:
            lane.tree_plan = build_plan(self._keys, self.tree_fanout,
                                        seed=self.master.seed + lane.index)

    def on_membership(self, keys) -> None:
        """Rides fit_sync's membership-rebuild block — the same hook the
        resplit and the flat tree rebuild fire on, so shard trees and
        version claims always describe the same membership snapshot."""
        self._keys = list(keys)
        if self.tree_fanout:
            self._build_lane_trees()
        for lane in self._lanes:
            lane.bcast.forget_missing(keys)

    def kill(self, index: int) -> None:
        """Chaos hook: mark shard `index` dead.  Takes effect at the next
        dispatch boundary — one flat fallback round, then a plan rebuild
        over the survivors (benches/bench_scale.py chaos row)."""
        with self._kill_lock:
            lanes = {lane.index for lane in self._lanes}
            if index not in lanes:
                raise ValueError(
                    f"no live master shard {index} (live: {sorted(lanes)})")
            self._killed.add(int(index))
        for lane in self._lanes:
            if lane.index == index:
                lane.killed = True
        self.log.warning("master shard %d killed; next window falls back "
                         "to the flat single-master plane", index)
        flight.record("shard.kill", shard=int(index))

    def bytes_by_lane(self) -> List[Tuple[int, int, int]]:
        """[(shard_index, broadcast_bytes, gradient_bytes)] for the LIVE
        lanes — the per-process wire ledger bench.py --scale's gate reads
        (max over lanes vs the flat plane's single-process total)."""
        return [(lane.index, lane.bcast_bytes, lane.grad_bytes)
                for lane in self._lanes]

    # -- per-round hooks ----------------------------------------------------

    def dispatch(self, members, ids_by_key, w: np.ndarray, fit_token: int,
                 grad_timeout_s: float, agg_round_seq: int) -> int:
        """Fan this window out: M tagged slice requests per worker (one
        per lane), or ONE untagged flat request per worker when a shard
        kill is being absorbed.  Returns the new agg_round_seq — sharded
        rounds consume one tree round PER LANE so a stale child push from
        an abandoned attempt can never alias another lane's round."""
        with self._kill_lock:
            fallback = bool(self._killed) or self._permanent_flat
        if fallback:
            return self._dispatch_flat(members, ids_by_key, w, fit_token,
                                       grad_timeout_s, agg_round_seq)
        self._flat_round = False
        shard_round = int(agg_round_seq)
        futs = []
        for lane in self._lanes:
            w_slice = w[lane.lo:lane.hi]
            for key, stub in members:
                ids = ids_by_key[key]
                req = pb.GradientRequest(samples=ids.astype(np.int32),
                                         fit_token=fit_token)
                req.shard_index = lane.index
                req.shard_count = len(self._lanes)
                req.shard_lo = lane.lo
                req.shard_hi = lane.hi
                req.shard_round = shard_round
                form, nbytes = lane.bcast._attach_arm(req, key, w_slice)
                metrics_mod.record_broadcast(self.metrics, form, nbytes)
                self._bcast_total.increment(nbytes)
                lane.bcast_bytes += nbytes
                if lane.tree_plan is not None and not lane.tree_plan.trivial:
                    self.master._annotate_tree(
                        req, key, lane.tree_plan,
                        agg_round_seq + lane.index, grad_timeout_s)
                fut = self.master._dispatch_gradient(
                    key, stub, None, req, grad_timeout_s, False)
                futs.append((key, lane, fut))
        self._round = ("sharded", futs)
        return agg_round_seq + len(self._lanes) - 1

    def _dispatch_flat(self, members, ids_by_key, w, fit_token,
                       grad_timeout_s, agg_round_seq: int) -> int:
        """The degraded round: classic untagged full-weight requests —
        the workers run their flat path, no shard state involved, so a
        master-shard death costs performance for exactly this round and
        never a worker eviction."""
        self._flat_round = True
        self.metrics.counter(metrics_mod.SHARD_FALLBACK_ROUNDS).increment()
        flight.record("shard.fallback", killed=sorted(self._killed),
                      permanent=self._permanent_flat)
        # evidence before recovery, throttled like the quorum dump: a
        # permanent-flat fit degrades EVERY window
        flight.dump("shard-kill", min_interval_s=10.0)
        send = codec.plan_weight_send(w)  # full-only plan, encoded once
        futs = []
        for key, stub in members:
            ids = ids_by_key[key]
            req = pb.GradientRequest(samples=ids.astype(np.int32),
                                     fit_token=fit_token)
            full = send.full()
            req.weights.CopyFrom(full)
            metrics_mod.record_broadcast(self.metrics, "full",
                                         full.ByteSize())
            fut = self.master._dispatch_gradient(
                key, stub, None, req, grad_timeout_s, False)
            futs.append((key, None, fut))
        self._round = ("flat", futs)
        return agg_round_seq

    def collect(self, grad_bytes):
        """Barrier over this round's M x N (or flat N) legs with
        per-WORKER collapse: good iff every leg arrived non-stale; any
        stale leg -> stale (every lane drops its claim, full slices on
        the retry); failures DEDUPED per worker so M dead legs are one
        liveness strike.  Returns (good, stale, failed) shaped like the
        flat barrier's lists."""
        kind, futs = self._round
        self._collected = []
        failed: Dict[tuple, object] = {}
        stale_keys: List[tuple] = []
        arrived: Dict[tuple, int] = {}
        for key, lane, fut in futs:
            try:
                if fut is None:
                    raise ValueError("channel closed")
                reply = fut.result()
                nbytes = reply.ByteSize()
                grad_bytes.increment(nbytes)
                if lane is not None:
                    self._grad_total.increment(nbytes)
                    lane.grad_bytes += nbytes
                if reply.stale_version:
                    if key not in stale_keys:
                        stale_keys.append(key)
                else:
                    arrived[key] = arrived.get(key, 0) + 1
                    self._collected.append((key, lane, reply))
            except (grpc.RpcError, ValueError) as e:
                failed.setdefault(
                    key, e.code() if isinstance(e, grpc.RpcError) else e)
        expect = len(self._lanes) if kind == "sharded" else 1
        good, stale = [], []
        seen = set()
        for key, lane, fut in futs:
            if key in seen:
                continue
            seen.add(key)
            if key in failed:
                continue
            if key in stale_keys or arrived.get(key, 0) != expect:
                # a short-counted worker (some legs stale-dropped by the
                # assembler's rendezvous, others fine) is stale, not
                # dead: full slices on the retry re-sync every lane
                if key not in stale_keys:
                    stale_keys.append(key)
                stale.append((key, None))
                for ln in self._lanes:
                    ln.bcast.note_stale(key)
                continue
            good.append((key, None))
            for ln in self._lanes:
                ln.bcast.note_ok(key)
        return good, stale, [(k, c) for k, c in failed.items()]

    def accumulate(self, grad_acc: np.ndarray) -> None:
        """Range-disjoint fan-in: each lane decodes its replies into its
        OWN view of the accumulator in canonical (dispatch) worker order
        and scales by its own contributor count — per coordinate this is
        the flat barrier's exact float chain (same worker order, same
        single true-divide), which is what makes the sharded step
        bit-identical to the unsharded one."""
        grad_acc.fill(0.0)
        kind, _ = self._round
        if kind == "flat":
            replies = [r for _, _, r in self._collected]
            for r in replies:
                codec.decode_grad_into(r, grad_acc)
            grad_acc /= len(replies)
            return
        for lane in self._lanes:
            view = grad_acc[lane.lo:lane.hi]
            lane_replies = [r for _, ln, r in self._collected if ln is lane]
            treed = lane.tree_plan is not None and not lane.tree_plan.trivial
            n_contrib = 0
            for r in lane_replies:
                codec.decode_grad_into(r, view)
                if r.agg_contributors:
                    n_contrib += len(r.agg_contributors)
                elif not r.agg_forwarded:
                    n_contrib += 1
                if r.agg_partial:
                    self.metrics.counter(
                        metrics_mod.TREE_PARTIAL).increment()
                if r.agg_flat:
                    self.metrics.counter(
                        metrics_mod.TREE_FLAT_FALLBACK).increment()
            if treed:
                view /= max(1, n_contrib)
            else:
                view /= len(lane_replies)
        self.metrics.counter(metrics_mod.SHARD_ROUNDS).increment()

    def advance(self, w_new: np.ndarray, w_old: np.ndarray) -> None:
        """Post-apply hook: advance every lane's broadcast version over
        its slice — or, after a fallback round, absorb the kill by
        rebuilding the plan over the surviving shard count (fresh lanes,
        full broadcasts next round; the workers' assemblers reset on the
        geometry change)."""
        if self._flat_round:
            self._flat_round = False
            with self._kill_lock:
                killed = set(self._killed)
                self._killed.clear()
            if not killed:
                return  # permanent flat: nothing left to rebuild
            survivors = len(self._lanes) - len(killed)
            if survivors < 1:
                self._permanent_flat = True
                self.metrics.gauge(metrics_mod.SHARD_COUNT).set(0)
                self.log.error("every master shard is dead: continuing in "
                               "permanent flat fallback")
                return
            self.plan = build_shard_plan(self.dim, survivors)
            self._build_lanes()
            self.metrics.counter(metrics_mod.SHARD_REBUILDS).increment()
            flight.record("shard.rebuild", shards=survivors,
                          digest=self.plan.digest()[:12])
            self.log.warning("shard plan rebuilt over %d surviving "
                             "shard(s): %r", survivors, self.plan)
            return
        for lane in self._lanes:
            lane.bcast.advance(w_new[lane.lo:lane.hi],
                               w_old[lane.lo:lane.hi])
