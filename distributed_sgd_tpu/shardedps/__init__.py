"""Feature-sharded master plane (DSGD_MASTER_SHARDS,
docs/MASTER_SHARDING.md): range-partition the weight vector across M
master shard lanes so per-round broadcast AND fan-in bytes scale as
dim/M per process instead of dim through one.

- plan.py: the pure (dim, M) -> contiguous range partition, sha256
  digest-stable across processes.
- coordinator.py: the master-side shard lanes — one _BroadcastState +
  byte ledger (+ optional reduce tree) per range — and the flat
  single-master fallback + plan rebuild a shard loss degrades to.
- assemble.py: the worker-side slice rendezvous — M range-tagged
  requests assemble one full weight vector, the gradient is computed
  ONCE, and each lane's reply carries its range slice.

Everything is default-off: with the knob unset no plan is built, no
lane or assembler is constructed, no instrument registers, and the
wire is byte-identical to the flat master (proto3 unset shard fields
serialize to nothing — asserted by tests/test_shardedps.py).
"""

from distributed_sgd_tpu.shardedps.plan import (  # noqa: F401
    ShardPlan,
    build_shard_plan,
    parse_master_shards,
)
