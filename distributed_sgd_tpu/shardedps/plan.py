"""Deterministic feature-range shard plan for the sharded master plane
(docs/MASTER_SHARDING.md, DSGD_MASTER_SHARDS).

``build_shard_plan`` is a PURE function of ``(dim, shards)``: every
process that knows the model dimension and the shard count computes the
byte-identical range partition (asserted via ``ShardPlan.digest`` by
tests/test_shardedps.py), so the coordinator can rebuild it after a
shard loss without a coordination round — the same purity contract the
reduce-tree plan (aggtree/plan.py) and the split functions
(core/split.py) rely on.

Shape: the weight vector's ``dim`` coordinates are carved into
``shards`` contiguous near-even ``[lo, hi)`` ranges — the SAME carve
rule as the reduce tree's chunking and core/split.py's contiguous
splits (sizes differ by at most one, larger ranges first), so an
awkward ``dim % shards != 0`` still covers every coordinate exactly
once.  Contiguity is what makes the per-shard traffic cheap: a slice
of a dense f32 tensor is a memcpy, a sparse gradient's ids bucket by
one range comparison (the dp×tp mesh engine proves the same algebra in
parallel/feature_sharded.py), and a WeightDelta in shard frame is just
the master delta restricted to ``[lo, hi)`` and shifted.
"""

from __future__ import annotations

import hashlib
import json
from typing import List, Optional, Tuple

# one carve rule for the whole codebase: the reduce tree's near-even
# contiguous chunking is exactly the range partition a feature shard
# needs (larger chunks first, sizes differ by <= 1)
from distributed_sgd_tpu.aggtree.plan import _chunks


def parse_master_shards(value: Optional[object]) -> int:
    """DSGD_MASTER_SHARDS grammar -> shard count (0 = off).

    Accepts None/""/0 (off) or an integer M >= 1.  The strict grammar
    is the config-validation contract: config.py delegates here so a
    typo fails at startup, not mid-fit.  M=1 is legal — the degenerate
    single-shard plane exercises the full sharded wire (range-tagged
    requests, worker-side assembly) with one lane, which is what the
    bench's M=1 sweep row pins."""
    if value is None or value == "":
        return 0
    try:
        shards = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"DSGD_MASTER_SHARDS must be an integer >= 0, got {value!r}")
    if shards < 0:
        raise ValueError(
            f"DSGD_MASTER_SHARDS must be >= 0 (0 = off), got {shards}")
    return shards


class ShardPlan:
    """One immutable range partition of a ``dim``-long weight vector.

    ``ranges[i]`` is shard i's contiguous ``[lo, hi)`` feature range;
    ranges are ascending, disjoint, and cover ``[0, dim)`` exactly.
    The plan is a value object — rebuilding at the same ``(dim,
    shards)`` lands on the byte-identical plan, which ``digest()``
    witnesses across processes."""

    def __init__(self, dim: int, shards: int):
        dim = int(dim)
        shards = int(shards)
        if dim < 1:
            raise ValueError(f"shard plan needs dim >= 1, got {dim}")
        if shards < 1:
            raise ValueError(f"shard plan needs shards >= 1, got {shards}")
        self.dim = dim
        # more shards than coordinates degenerates to one shard per
        # coordinate (the _chunks clamp), never an empty range
        self.shards = min(shards, dim)
        self.ranges: Tuple[Tuple[int, int], ...] = tuple(
            _chunks(dim, self.shards))

    def range_of(self, index: int) -> Tuple[int, int]:
        return self.ranges[index]

    def digest(self) -> str:
        """sha256 over the canonical (dim, ranges) JSON — the
        cross-process byte-identity witness tests/test_shardedps.py
        pins (mirrors TreePlan.digest)."""
        blob = json.dumps(
            {"dim": self.dim, "ranges": [list(r) for r in self.ranges]},
            separators=(",", ":"), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()

    def __repr__(self):
        sizes = [hi - lo for lo, hi in self.ranges]
        return (f"ShardPlan(dim={self.dim}, shards={self.shards}, "
                f"range_sizes={min(sizes)}..{max(sizes)})")


def build_shard_plan(dim: int, shards: int) -> ShardPlan:
    """(model dimension, shard count) -> deterministic range partition.

    Pure: no RNG, no wall clock, no membership — the plan depends on
    nothing a restarted or remote process could disagree about."""
    return ShardPlan(dim, shards)


def slice_ranges(plan: ShardPlan) -> List[Tuple[int, int, int]]:
    """[(index, lo, hi)] convenience view for coordinator fan-out."""
    return [(i, lo, hi) for i, (lo, hi) in enumerate(plan.ranges)]
