"""Worker-side shard rendezvous for the feature-sharded master plane
(DSGD_MASTER_SHARDS, docs/MASTER_SHARDING.md).

A sharded round reaches each worker as M concurrent Gradient requests —
one per master shard lane, each carrying only its range slice of the
weight vector (full tensor, WeightDelta vs the lane's previous version,
or a header-only cached form).  The gradient, however, is a function of
the WHOLE weight vector: hinge-loss backprop reads every feature a
sample touches.  ``ShardAssembler`` is the meeting point:

- each request resolves ITS slice against the per-shard resident cache
  (the same install/cached/delta/stale ladder as the flat replica,
  core/worker.py ``resolve_request_weights``, keyed per shard index);
- the M requests of one round rendezvous on ``(fit_token, shard_round)``;
  the request that completes the set assembles the full vector from the
  range slices and computes the gradient ONCE;
- every request then slices the shared gradient by its own
  ``[shard_lo, shard_hi)`` and replies it up its own lane — so the
  per-worker compute cost is identical to a flat round while the wire
  cost scales down per shard.

Any slice that fails to resolve marks the whole round stale: all M
replies come back ``stale_version`` and the master's retry re-sends full
slices on every lane (each lane dropped its version claim), exactly the
flat plane's correctness fallback.  Abandoned rounds (master retried,
shard died mid-flight) age out of a bounded buffer, mirroring the
aggregation tree's reduce buffer discipline (aggtree/reduce.py).

Constructed lazily on the first shard-tagged request
(``WorkerNode._ensure_shard_assembler``): a knobs-off worker never
builds one and never registers a shard instrument
(tests/test_shardedps.py identity gate).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from distributed_sgd_tpu.rpc import codec
from distributed_sgd_tpu.utils import metrics as metrics_mod

# how long a request waits for its round's sibling slices before replying
# stale — generous vs the master's per-round deadline, because the wait
# covers only the skew between M sends of the SAME round (microseconds on
# a healthy wire), not a round-trip
ASSEMBLE_BUDGET_S = 5.0

# bounded rendezvous buffer: rounds the master abandoned (retry bumped
# shard_round, shard lane died mid-flight) must not leak — the oldest
# round is evicted, its waiters woken to reply stale for a round nobody
# will collect
MAX_PENDING_ROUNDS = 8


class _Round:
    """One shard round's rendezvous state (guarded by the assembler lock)."""

    __slots__ = ("slices", "stale", "grad", "done", "computing")

    def __init__(self):
        # shard_index -> (lo, hi, slice ndarray)
        self.slices = {}
        self.stale = False
        self.grad: Optional[np.ndarray] = None
        self.done = False
        self.computing = False


class ShardAssembler:
    def __init__(self, metrics=None, log=None):
        if metrics is None:
            metrics = metrics_mod.global_metrics()
        self.metrics = metrics
        self.log = log
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # geometry = (fit_token, shard_count): a new fit session or a
        # rebuilt shard plan (kill -> M-1 lanes, different ranges) resets
        # every per-shard resident — slices cached under the old ranges
        # have the wrong extents
        self._geometry = None
        # shard_index -> (version, slice ndarray)
        self._resident = {}
        # (fit_token, shard_round) -> _Round
        self._rounds: "OrderedDict[tuple, _Round]" = OrderedDict()

    # -- per-shard slice resolution (caller holds the lock) -----------------

    def _resolve_slice(self, request):
        """The flat replica ladder, per shard index: install / cached /
        delta / stale.  Returns (slice, stale)."""
        i = int(request.shard_index)
        version = request.step_version
        if request.HasField("weights"):
            sl = codec.decode_tensor(request.weights)
            self._resident[i] = (version, sl)
            return sl, False
        held = self._resident.get(i)
        if held is None:
            return None, True
        cached_ver, cached = held
        if cached_ver == version:
            return cached, False  # retry / already-applied: idempotent
        if request.HasField("delta") and cached_ver == request.delta.base_version:
            sl = codec.apply_weight_delta(cached, request.delta)
            self._resident[i] = (version, sl)
            return sl, False
        return None, True

    def _round_for(self, key) -> _Round:
        rd = self._rounds.get(key)
        if rd is None:
            rd = _Round()
            self._rounds[key] = rd
            while len(self._rounds) > MAX_PENDING_ROUNDS:
                _, old = self._rounds.popitem(last=False)
                # wake the abandoned round's waiters: they reply stale
                # for a round the master already moved past
                old.stale = True
                old.done = True
                self._cv.notify_all()
        return rd

    # -- the rendezvous -----------------------------------------------------

    def gradient(self, request,
                 compute: Callable[[np.ndarray, np.ndarray], np.ndarray]
                 ) -> Optional[np.ndarray]:
        """Resolve this request's slice, rendezvous with its round's
        siblings, and return the round's FULL-dimension gradient (shared,
        read-only — the caller slices its own range) or None (stale slice
        anywhere in the round, abandoned round, or rendezvous timeout)."""
        rkey = (request.fit_token, int(request.shard_round))
        count = int(request.shard_count)
        with self._cv:
            geometry = (request.fit_token, count)
            if self._geometry != geometry:
                self._resident.clear()
                self._geometry = geometry
            sl, stale = self._resolve_slice(request)
            rd = self._round_for(rkey)
            if stale or rd.stale:
                rd.stale = True
                rd.done = True
                self._cv.notify_all()
                return None
            rd.slices[int(request.shard_index)] = (
                int(request.shard_lo), int(request.shard_hi), sl)
            assemble = len(rd.slices) == count and not rd.computing
            if assemble:
                # claim the compute before dropping the lock: exactly one
                # thread per round assembles and runs the backward pass
                rd.computing = True
                pieces = dict(rd.slices)
        if assemble:
            dim = max(hi for _, hi, _ in pieces.values())
            w = np.empty(dim, dtype=np.float32)
            for lo, hi, piece in pieces.values():
                w[lo:hi] = piece
            ids = np.fromiter(request.samples, dtype=np.int64)
            g = np.asarray(compute(w, ids), dtype=np.float32)
            with self._cv:
                rd.grad = g
                rd.done = True
                self._cv.notify_all()
            self.metrics.counter(metrics_mod.SHARD_ASSEMBLED).increment()
            return g
        deadline = time.monotonic() + ASSEMBLE_BUDGET_S
        with self._cv:
            while not rd.done:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    rd.stale = True
                    rd.done = True
                    self._cv.notify_all()
                    self.metrics.counter(
                        metrics_mod.SHARD_ASM_TIMEOUTS).increment()
                    if self.log is not None:
                        self.log.warning(
                            "shard round %s timed out waiting for %d/%d "
                            "slices", rkey, len(rd.slices), count)
                    return None
                self._cv.wait(remaining)
            return rd.grad if not rd.stale else None
