"""Compressor implementations: policy + error-feedback state per codec.

The stateless wire pack/unpack lives in rpc/codec.py; these classes decide
WHAT ships (selection, quantization, residual bookkeeping) and account the
bytes (utils/metrics.py comms.* instruments).  One instance per sending
node — residuals are keyed by destination, so a worker gossiping to P peers
plus the master holds P+1 independent accumulators and a destination that
joins mid-stream simply starts from a zero residual (exactly as if it had
missed the earlier messages, which the fire-and-forget wire already
permits).
"""

from __future__ import annotations

import threading
from typing import Dict, Hashable, Optional, Protocol, runtime_checkable

import numpy as np

from distributed_sgd_tpu.rpc import codec, dsgd_pb2 as pb
from distributed_sgd_tpu.utils import metrics as metrics_mod


@runtime_checkable
class Compressor(Protocol):
    """One sending node's gradient->wire policy.

    `compress` returns a ready-to-send GradUpdate for `x` bound for `dest`
    (any hashable — peer address, "master", ...).  Implementations with
    error feedback mutate per-dest residual state on every call, so the
    caller must compress once per destination actually sent to.
    """

    name: str

    def compress(self, x: np.ndarray, dest: Hashable = None) -> pb.GradUpdate: ...

    def reset(self) -> None:
        """Drop all error-feedback state (e.g. between fits)."""


class _AccountingMixin:
    """Shared bytes/ratio/residual accounting (utils/metrics.py comms.*)."""

    def _account(self, msg: pb.GradUpdate, dim: int) -> None:
        metrics_mod.record_wire(self.metrics, msg.ByteSize(), 4 * dim)

    def _record_residual(self, residual: np.ndarray) -> None:
        self.metrics.histogram(metrics_mod.COMMS_RESIDUAL_NORM).record(
            float(np.linalg.norm(residual))
        )


class _ResidualStateMixin:
    """Snapshot/restore of one destination's EF residual.

    `compress` drains the shipped coordinates out of the residual at encode
    time, which assumes the message is delivered.  Callers whose transport
    can DISCARD an already-encoded reply (the sync master drops every ok
    reply in a batch window when a sibling worker fails, core/master.py)
    use these to roll the drain back before re-encoding for the retry —
    otherwise each retry permanently loses the largest-magnitude gradient
    coordinates.  Retries are recognized by a caller-chosen window key
    (core/worker.py encode_sync_grad: weights bytes + broadcast
    step_version under the pipelined sync engine, where retry windows may
    carry no weight payload at all — docs/SYNC_PIPELINE.md).

    The residual mechanics are payload-agnostic: K-step local-SGD windows
    (GradientRequest.local_steps) reply with lr-scaled weight-space
    decrements instead of raw gradient sums, and the same snapshot/
    restore/drop lifecycle applies unchanged — the residual simply
    accumulates unsent delta mass in the same (weight) space the wire
    ships.
    """

    def residual_snapshot(self, dest: Hashable):
        with self._lock:
            r = self._residuals.get(dest)
            return None if r is None else r.copy()

    def residual_restore(self, dest: Hashable, snapshot) -> None:
        with self._lock:
            if snapshot is None:
                self._residuals.pop(dest, None)
            else:
                self._residuals[dest] = snapshot

    def residual_drop(self, dest: Hashable) -> None:
        """Forget one destination's residual — for a departed peer: a peer
        that later rejoins must start from zero, as the joined-mid-stream
        contract promises, not from mass accumulated against its pre-crash
        trajectory (and departed peers must not pin dim-sized arrays)."""
        with self._lock:
            self._residuals.pop(dest, None)


class NoneCompressor(_AccountingMixin):
    """Identity codec: exactly today's dense-or-sparse auto switch.

    `make_compressor("none")` deliberately returns None instead of this
    class so production hot paths skip the wrapper entirely (byte-identical
    AND call-graph-identical to the pre-compression tree); the class exists
    so benches and tests can drive every codec through one interface —
    including the wire accounting, which the raw codec call doesn't do.
    """

    name = "none"

    def __init__(self, metrics: Optional[metrics_mod.Metrics] = None, **_):
        self.metrics = metrics or metrics_mod.global_metrics()

    def compress(self, x: np.ndarray, dest: Hashable = None) -> pb.GradUpdate:
        msg = codec.encode_grad(np.asarray(x, dtype=np.float32))
        self._account(msg, len(x))
        return msg

    def reset(self) -> None:
        pass

    # stateless: the snapshot surface exists for API uniformity only
    def residual_snapshot(self, dest: Hashable = None):
        return None

    def residual_restore(self, dest: Hashable, snapshot) -> None:
        pass

    def residual_drop(self, dest: Hashable) -> None:
        pass


class TopKCompressor(_AccountingMixin, _ResidualStateMixin):
    """Magnitude top-k sparsification with per-destination error feedback.

    Ships the k largest-|v| coordinates of v = x + residual[dest]; the
    unsent coordinates become the new residual.  With error_feedback=False
    the residual is never kept (plain sparsification — biased, kept for
    ablation; convergence needs EF at aggressive k, see
    tests/test_compress.py).
    """

    name = "topk"

    def __init__(
        self,
        k: float = 0.01,
        error_feedback: bool = True,
        metrics: Optional[metrics_mod.Metrics] = None,
        **_,
    ):
        from distributed_sgd_tpu.ops.topk import resolve_k  # validates k > 0

        if k <= 0:
            raise ValueError(f"compress_k must be > 0, got {k}")
        self._resolve_k = resolve_k
        self.k = k
        self.error_feedback = bool(error_feedback)
        self.metrics = metrics or metrics_mod.global_metrics()
        # gRPC servicer threads and the async loop both compress; the
        # residual read-modify-write must not interleave per destination
        self._lock = threading.Lock()
        self._residuals: Dict[Hashable, np.ndarray] = {}

    def compress(self, x: np.ndarray, dest: Hashable = None) -> pb.GradUpdate:
        from distributed_sgd_tpu.ops.topk import topk_magnitude

        x = np.asarray(x, dtype=np.float32)
        dim = len(x)
        k = self._resolve_k(self.k, dim)
        with self._lock:
            if self.error_feedback:
                r = self._residuals.get(dest)
                v = x + r if r is not None else x
            else:
                v = x
            idx, vals = topk_magnitude(v, k)
            if self.error_feedback:
                residual = v.copy()
                residual[idx] = 0.0
                self._residuals[dest] = residual
                self._record_residual(residual)
        msg = codec.encode_topk(idx, vals, dim)
        self._account(msg, dim)
        return msg

    def reset(self) -> None:
        with self._lock:
            self._residuals.clear()


class QInt8Compressor(_AccountingMixin, _ResidualStateMixin):
    """Stochastic int8 quantization with per-chunk scales (QSGD-style).

    Full support, ~4x payload reduction, unbiased codes (E[decode] = x).
    With error feedback the (already small) quantization error of the
    destination's previous message is folded into the next one.
    """

    name = "qint8"

    def __init__(
        self,
        chunk: int = codec.QINT8_CHUNK,
        error_feedback: bool = True,
        seed: int = 0,
        metrics: Optional[metrics_mod.Metrics] = None,
        **_,
    ):
        if chunk < 1:
            raise ValueError(f"qint8 chunk must be >= 1, got {chunk}")
        self.chunk = int(chunk)
        self.error_feedback = bool(error_feedback)
        self.metrics = metrics or metrics_mod.global_metrics()
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._residuals: Dict[Hashable, np.ndarray] = {}

    def compress(self, x: np.ndarray, dest: Hashable = None) -> pb.GradUpdate:
        x = np.asarray(x, dtype=np.float32)
        dim = len(x)
        with self._lock:
            if self.error_feedback:
                r = self._residuals.get(dest)
                v = x + r if r is not None else x
            else:
                v = x
            msg = codec.quantize_qint8(v, self._rng, self.chunk)
            if self.error_feedback:
                residual = v - codec.decode_compressed(msg.compressed)
                self._residuals[dest] = residual
                self._record_residual(residual)
        self._account(msg, dim)
        return msg

    def reset(self) -> None:
        with self._lock:
            self._residuals.clear()


def make_compressor(
    name: Optional[str],
    k: float = 0.01,
    error_feedback: bool = True,
    seed: int = 0,
    metrics: Optional[metrics_mod.Metrics] = None,
) -> Optional[Compressor]:
    """Config surface -> compressor instance, or None for the identity path.

    None keeps the callers' pre-compression fast paths literally unchanged
    (one encode shared across destinations, no accounting overhead) — the
    DSGD_COMPRESS=none byte-identity guarantee.
    """
    if name in (None, "", "none"):
        return None
    if name == "topk":
        return TopKCompressor(k=k, error_feedback=error_feedback, metrics=metrics)
    if name == "qint8":
        return QInt8Compressor(
            error_feedback=error_feedback, seed=seed, metrics=metrics)
    raise ValueError(f"unknown compressor {name!r} (none | topk | qint8)")
