"""Pluggable gradient compression (DSGD_COMPRESS; docs/COMPRESSION.md).

Every gradient that crosses the wire — sync fan-in replies
(core/worker.py Gradient), async delta gossip (core/worker.py _async_loop,
parallel/hogwild.py), and the master-bound update stream — goes through a
`Compressor`, which turns a dense f32 vector into a `GradUpdate` wire
message and keeps the per-destination error-feedback state that makes the
lossy codecs converge:

- ``none``   identity; `make_compressor` returns None so the hot paths keep
             today's `codec.encode_grad` calls byte-for-byte (the
             `NoneCompressor` class exists for API-uniform benches/tests);
- ``topk``   magnitude top-k sparsification (Deep Gradient Compression,
             Lin et al.): ship the k largest-|x| coordinates, accumulate
             the rest in a per-destination residual that rides a later
             message — selection jit-compiled in ops/topk.py;
- ``qint8``  stochastic int8 quantization with per-chunk scales (QSGD,
             Alistarh et al.): full support, 4x fewer payload bytes,
             unbiased codes; quantization error optionally fed back.

Residuals and the summed-delta contract: peers merge gossip by commutative
subtraction (core/worker.py _async_loop), and every message a compressor
emits is still a plain weight-space delta — error feedback only moves WHEN
a coordinate's mass ships, never what the receiving merge does with it, so
the commutativity the async engines rely on is untouched.
"""

from distributed_sgd_tpu.compress.codecs import (  # noqa: F401
    Compressor,
    NoneCompressor,
    QInt8Compressor,
    TopKCompressor,
    make_compressor,
)

COMPRESS_CHOICES = ("none", "topk", "qint8")
