from distributed_sgd_tpu.ops.sparse import (  # noqa: F401
    SparseBatch,
    matvec,
    pad_rows,
    scatter_add,
)
