from distributed_sgd_tpu.ops.sparse import (  # noqa: F401
    SparseBatch,
    matvec,
    pad_rows,
    scatter_add,
)

# Kernel families live in submodules (import explicitly; none are loaded
# eagerly so production imports stay lean and Pallas stays off the import
# path until an engine selects it):
# - ops.mxu           lane-blocked one-hot MXU kernels (default hot path)
# - ops.pallas_sparse fused Pallas worker-gradient kernel
# - ops.flat_sparse   flat CSR-style layout (SparseArrayVector parity)
# - ops.gradcheck     central-difference gradient checking (F parity)
