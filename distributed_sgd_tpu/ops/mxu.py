"""Blocked one-hot MXU kernels: sparse gather/scatter as matmuls.

XLA lowers a random scatter/gather over a 47k-float vector to a serialized
per-element loop on TPU (~13 ns/element measured — the whole hot path of
the reference's sync mode, SURVEY.md §3.5, is bound by it).  The TPU-native
answer is to reshape the weight vector into a lane-blocked matrix

    w2 = w padded to R*128, viewed as [R, 128]   (R = ceil(D/128), 8-aligned)

and express both sparse kernels as one-hot matmuls that run on the MXU
(systolic array) instead of the scalar path:

- gather:  w[idx[t]] = (onehot(idx[t]//128) @ w2)[t, idx[t]%128]
           -> M1 = OHR @ w2 on the MXU, then a lane-select against
           OHC = onehot(idx%128) on the VPU;
- scatter: sum_t v[t]*e_{idx[t]} = OHR^T @ (OHC * v[:,None])  — one MXU
           matmul producing the blocked gradient [R, 128] directly.

Per element this costs R*128 ≈ 48k MACs — and still beats the scalar
scatter ~13x on measured throughput (~1 ns vs ~13 ns per element), because
the MXU runs at tens of TFLOP/s while the scalar path runs at ~75M
elements/s.  The one-hot matrices are built in-registers by XLA (iota
compare) and fuse into the surrounding step, so a full SGD step (gather +
hinge + scatter + update) measures ~27 us vs ~110 us for the scalar path
at RCV1 shapes (B=100, P=76).

These kernels replace the reference's per-sample map arithmetic
(Sparse.scala:15-46, Slave.scala:147-153) on the training hot path; the
scalar-path kernels in ops/sparse.py remain the reference-shaped fallback
(`kernel='scalar'`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sgd_tpu.ops.sparse import SparseBatch

LANES = 128
_SUBLANE = 8


def blocked_pays_off(device=None) -> bool:
    """One shared policy for 'should this device use the blocked one-hot
    MXU kernels?': yes on TPU (where they beat scalar scatter ~10x), no on
    CPU (where the scalar gather/scatter wins).  Pass the pinned device
    when there is one; falls back to the process default backend."""
    platform = getattr(device, "platform", None)
    if platform is None:
        platform = jax.default_backend()
    return platform == "tpu"


def n_blocks(n_features: int) -> int:
    """Rows R of the blocked weight view: ceil(D/128), rounded up to a
    multiple of 8 so [R, 128] is exactly sublane x lane tiled."""
    r = -(-int(n_features) // LANES)
    return -(-r // _SUBLANE) * _SUBLANE


def to_blocked(w: jax.Array, n_features: int) -> jax.Array:
    """[D] -> [R, 128] (zero-padded).  Cheap: pad + reshape."""
    r = n_blocks(n_features)
    return jnp.pad(w, (0, r * LANES - n_features)).reshape(r, LANES)


def from_blocked(w2: jax.Array, n_features: int) -> jax.Array:
    """[R, 128] -> [D]."""
    return w2.reshape(-1)[:n_features]


def to_blocked_np(w: np.ndarray, n_features: int) -> np.ndarray:
    r = n_blocks(n_features)
    return np.pad(w, (0, r * LANES - n_features)).reshape(r, LANES)


class OneHotBatch:
    """The per-batch one-hot operands, built once and shared by the gather
    and scatter sides of a step.  All members are traced arrays; XLA fuses
    the iota-compare builds into the consuming matmuls."""

    def __init__(self, batch: SparseBatch, n_rows: int, dtype=jnp.float32):
        flat_idx = batch.indices.reshape(-1)
        self.values = batch.values.astype(jnp.float32).reshape(-1)  # [T]
        self.ohr = jax.nn.one_hot(flat_idx // LANES, n_rows, dtype=dtype)  # [T, R]
        self.ohc = jax.nn.one_hot(flat_idx % LANES, LANES, dtype=dtype)  # [T, L]
        self.batch_size = batch.batch_size
        self.pad_width = batch.pad_width

    def gathered_products(self, w2: jax.Array) -> jax.Array:
        """[T] of values[t] * w[idx[t]] — the gather, via MXU."""
        m1 = jax.lax.dot(
            self.ohr, w2.astype(self.ohr.dtype), preferred_element_type=jnp.float32
        )  # [T, L]
        return jnp.sum(m1 * self.ohc.astype(jnp.float32), axis=-1) * self.values

    def margins(self, w2: jax.Array) -> jax.Array:
        """Per-sample dots x_b . w  (ops.sparse.matvec equivalent)."""
        return self.gathered_products(w2).reshape(self.batch_size, self.pad_width).sum(-1)

    def scatter_add(self, coeff: jax.Array) -> jax.Array:
        """Blocked sum_b coeff[b] * x_b -> [R, 128] (scatter_add equivalent).

        Stays the single deep-contraction dot ON MEASUREMENT
        (benches/scatter_wide.py + BASELINE.md round 4, raw JSON in
        benches/results/scatter_{crossover,fused_ab}.json): splitting the
        contraction into S=4 batched shards (a [4, R, 128]-wide output
        footprint) runs the ISOLATED scatter 1.7-4.8x faster below the
        T ~ 32k crossover (4.8x at the flagship T=22,800) — but regresses
        the FUSED training step 8-15% in an interleaved same-chip A/B
        (0.845x for the scatter-only reshape, 0.92x for a shared
        [S, sub, R] one-hot layout feeding gather AND scatter), because
        the sharded layouts break the iota-compare one-hot fusion the
        single dot shares with the gather.  Measured rejection, not an
        estimate.
        """
        cv = (
            self.values.reshape(self.batch_size, self.pad_width)
            * coeff.astype(jnp.float32)[:, None]
        ).reshape(-1)
        contrib = self.ohc.astype(jnp.float32) * cv[:, None]  # [T, L]
        return jax.lax.dot(
            self.ohr.T, contrib.astype(self.ohr.dtype), preferred_element_type=jnp.float32
        )


def matvec(batch: SparseBatch, w2: jax.Array) -> jax.Array:
    """Standalone blocked matvec (margins) for eval-style uses."""
    return OneHotBatch(batch, w2.shape[0]).margins(w2)


def scatter_add(batch: SparseBatch, coeff: jax.Array, n_rows: int) -> jax.Array:
    """Standalone blocked scatter-add."""
    return OneHotBatch(batch, n_rows).scatter_add(coeff)
