"""Blocked one-hot MXU kernels: sparse gather/scatter as matmuls.

XLA lowers a random scatter/gather over a 47k-float vector to a serialized
per-element loop on TPU (~13 ns/element measured — the whole hot path of
the reference's sync mode, SURVEY.md §3.5, is bound by it).  The TPU-native
answer is to reshape the weight vector into a lane-blocked matrix

    w2 = w padded to R*128, viewed as [R, 128]   (R = ceil(D/128), 8-aligned)

and express both sparse kernels as one-hot matmuls that run on the MXU
(systolic array) instead of the scalar path:

- gather:  w[idx[t]] = (onehot(idx[t]//128) @ w2)[t, idx[t]%128]
           -> M1 = OHR @ w2 on the MXU, then a lane-select against
           OHC = onehot(idx%128) on the VPU;
- scatter: sum_t v[t]*e_{idx[t]} = OHR^T @ (OHC * v[:,None])  — one MXU
           matmul producing the blocked gradient [R, 128] directly.

Per element this costs R*128 ≈ 48k MACs — and still beats the scalar
scatter ~13x on measured throughput (~1 ns vs ~13 ns per element), because
the MXU runs at tens of TFLOP/s while the scalar path runs at ~75M
elements/s.  The one-hot matrices are built in-registers by XLA (iota
compare) and fuse into the surrounding step, so a full SGD step (gather +
hinge + scatter + update) measures ~27 us vs ~110 us for the scalar path
at RCV1 shapes (B=100, P=76).

These kernels replace the reference's per-sample map arithmetic
(Sparse.scala:15-46, Slave.scala:147-153) on the training hot path; the
scalar-path kernels in ops/sparse.py remain the reference-shaped fallback
(`kernel='scalar'`).
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sgd_tpu.ops.sparse import SparseBatch

log = logging.getLogger("dsgd.mxu")

LANES = 128
_SUBLANE = 8

# -- selectable scatter formulations (DSGD_SCATTER; ROADMAP item 2) --------
#
# The scatter side of the fused step is the measured MXU bottleneck
# (BASELINE.md roofline: single [R, 128] output block, 3 MXU tiles fed by a
# T-deep contraction).  Round 4 measured and rejected the wide-output
# reshape; this round ships a third sweep as a SELECTABLE backend so the
# fused A/B harness (benches/scatter_wide.py --fused-ab) can rematch the
# formulations on real hardware and `auto` can promote a measured winner
# at runtime:
#
# - 'onehot'   (default): the shipped single deep-contraction one-hot
#   matmul — knobs-off training is byte-identical to every prior round.
# - 'segment'  sort-by-index + jax.ops.segment_sum into the blocked rows:
#   contributions sorted by flat feature id, one sorted segment-sum into
#   the [R*128] flat view.  No one-hot operands at all on the scatter
#   side (the gather still builds OHR/OHC; XLA drops the unused scatter
#   operand).
# - 'twostage' per-lane spread, then block add: stage 1 forms the [T, 128]
#   per-lane contribution rows on the VPU (OHC * values — the same
#   operand the one-hot matmul contracts), stage 2 segment-reduces the
#   rows by block id (sorted) instead of paying the T-deep matmul.
# - 'bf16'     the one-hot contraction with bf16 ACCUMULATION: the
#   contraction is split into two shards, each accumulated in bf16
#   (preferred_element_type=bfloat16 — half the accumulator traffic of
#   the f32-accumulate pass), with the final cross-shard add in f32.
#   Numerics: ~3 decimal digits per partial sum — parity holds to a
#   tolerance bound, not bit-exactness (tests/test_kernel_edge_shapes.py
#   pins the bound).
#
# All formulations compute sum_b coeff[b] * x_b on the blocked [R, 128]
# view; 'onehot'/'segment'/'twostage' agree up to float summation order,
# 'bf16' to the documented tolerance.  The active formulation is a
# process-wide knob (config.py DSGD_SCATTER -> main.py -> engines) read at
# TRACE time: set it before building engines/jitted fns (main.py does),
# or scope it with `scatter_formulation(...)` around engine construction
# the way the benches and tests do.

SCATTER_FORMULATIONS = ("onehot", "segment", "twostage", "bf16")

_scatter_lock = threading.Lock()
_active_scatter = "onehot"


def set_scatter_formulation(name: str) -> None:
    """Select the process-wide scatter formulation (trace-time dispatch).

    Call before building engines / jitted functions: already-compiled
    programs keep the formulation they were traced with."""
    if name not in SCATTER_FORMULATIONS:
        raise ValueError(
            f"scatter formulation {name!r} must be one of "
            f"{SCATTER_FORMULATIONS} (or 'auto' via "
            f"resolve_scatter_formulation)")
    global _active_scatter
    with _scatter_lock:
        _active_scatter = name


def active_scatter_formulation() -> str:
    return _active_scatter


@contextlib.contextmanager
def scatter_formulation(name: str):
    """Scoped formulation override (benches/tests): build + trace engines
    inside the block; restores the previous selection on exit."""
    prev = _active_scatter
    set_scatter_formulation(name)
    try:
        yield
    finally:
        set_scatter_formulation(prev)


# 'auto' measurements, keyed by (backend, batch, nnz, n_features) — one
# runtime rematch per process per shape
_AUTO_CACHE: Dict[Tuple, str] = {}


def resolve_scatter_formulation(
    name: str,
    batch_size: int = 100,
    nnz: int = 76,
    n_features: int = 47_236,
    reps: int = 2,
) -> str:
    """'auto' -> the formulation measured fastest ON THIS DEVICE at the
    given step shape (chained-scan slope over the fused gather+scatter
    body, the harness methodology); anything else passes through.

    The rematch runs once per process per shape (~seconds) and its pick is
    logged; the default config never calls this — DSGD_SCATTER defaults to
    'onehot', so knobs-off behavior stays byte-identical."""
    if name != "auto":
        if name not in SCATTER_FORMULATIONS:
            raise ValueError(
                f"DSGD_SCATTER={name!r} must be 'auto' or one of "
                f"{SCATTER_FORMULATIONS}")
        return name
    key = (jax.default_backend(), int(batch_size), int(nnz), int(n_features))
    if key in _AUTO_CACHE:
        return _AUTO_CACHE[key]
    import time as _time

    r = n_blocks(n_features)
    rng = np.random.default_rng(0)
    idx = jnp.asarray(np.sort(
        rng.integers(0, n_features, (batch_size, nnz)).astype(np.int32), axis=1))
    val = jnp.asarray(np.abs(rng.normal(size=(batch_size, nnz))).astype(np.float32))
    batch = SparseBatch(idx, val)

    def _slope(form: str) -> float:
        with scatter_formulation(form):
            def body(c):
                oh = OneHotBatch(batch, r)
                coeff = oh.margins(jnp.zeros((r, LANES), jnp.float32)) + c[:batch_size, 0]
                g = oh.scatter_add(coeff)
                return c + 1e-30 * g[0, 0]

            def looped(iters):
                f = jax.jit(lambda c: jax.lax.scan(
                    lambda cc, _: (body(cc), None), c, None, length=iters)[0])
                jax.block_until_ready(f(val))
                best = float("inf")
                for _ in range(reps):
                    t0 = _time.perf_counter()
                    jax.block_until_ready(f(val))
                    best = min(best, _time.perf_counter() - t0)
                return best

            lo, hi = 8, 24
            return max(looped(hi) - looped(lo), 1e-12) / (hi - lo)

    times = {form: _slope(form) for form in SCATTER_FORMULATIONS}
    winner = min(times, key=times.get)
    log.info(
        "DSGD_SCATTER=auto rematch on %s (B=%d, nnz=%d, D=%d): %s -> %s",
        key[0], batch_size, nnz, n_features,
        {f: f"{t * 1e6:.1f}us" for f, t in times.items()}, winner)
    _AUTO_CACHE[key] = winner
    # surface the rematch OUTCOME beyond the log line (ROADMAP item 2
    # follow-up): a process-global gauge (value indexes
    # SCATTER_FORMULATIONS, scraped by /metrics exporters), a trace event
    # (no-op unless a trace is active), and a flight record so a
    # post-mortem dump attributes which formulation the process ran.
    # fit_sync and WorkerNode additionally stamp their OWN registries at
    # fit/build time — the per-fit attribution the bench gates read.
    from distributed_sgd_tpu import trace as _trace_mod
    from distributed_sgd_tpu.trace import flight as _flight
    from distributed_sgd_tpu.utils import metrics as _metrics_mod

    _metrics_mod.global_metrics().gauge(
        _metrics_mod.SCATTER_FORMULATION).set(
            SCATTER_FORMULATIONS.index(winner))
    _trace_mod.event(_trace_mod.EVENT_SCATTER_SELECTED, formulation=winner,
                     backend=key[0])
    _flight.record("scatter.rematch", formulation=winner, backend=key[0],
                   batch=int(batch_size), nnz=int(nnz),
                   n_features=int(n_features))
    return winner


def blocked_pays_off(device=None) -> bool:
    """One shared policy for 'should this device use the blocked one-hot
    MXU kernels?': yes on TPU (where they beat scalar scatter ~10x), no on
    CPU (where the scalar gather/scatter wins).  Pass the pinned device
    when there is one; falls back to the process default backend."""
    platform = getattr(device, "platform", None)
    if platform is None:
        platform = jax.default_backend()
    return platform == "tpu"


def n_blocks(n_features: int) -> int:
    """Rows R of the blocked weight view: ceil(D/128), rounded up to a
    multiple of 8 so [R, 128] is exactly sublane x lane tiled."""
    r = -(-int(n_features) // LANES)
    return -(-r // _SUBLANE) * _SUBLANE


def to_blocked(w: jax.Array, n_features: int) -> jax.Array:
    """[D] -> [R, 128] (zero-padded).  Cheap: pad + reshape."""
    r = n_blocks(n_features)
    return jnp.pad(w, (0, r * LANES - n_features)).reshape(r, LANES)


def from_blocked(w2: jax.Array, n_features: int) -> jax.Array:
    """[R, 128] -> [D]."""
    return w2.reshape(-1)[:n_features]


def to_blocked_np(w: np.ndarray, n_features: int) -> np.ndarray:
    r = n_blocks(n_features)
    return np.pad(w, (0, r * LANES - n_features)).reshape(r, LANES)


class OneHotBatch:
    """The per-batch one-hot operands, built once and shared by the gather
    and scatter sides of a step.  All members are traced arrays; XLA fuses
    the iota-compare builds into the consuming matmuls."""

    def __init__(self, batch: SparseBatch, n_rows: int, dtype=jnp.float32):
        flat_idx = batch.indices.reshape(-1)
        self.flat_idx = flat_idx  # [T] flat feature ids (segment formulations)
        self.n_rows = n_rows
        self.values = batch.values.astype(jnp.float32).reshape(-1)  # [T]
        self.ohr = jax.nn.one_hot(flat_idx // LANES, n_rows, dtype=dtype)  # [T, R]
        self.ohc = jax.nn.one_hot(flat_idx % LANES, LANES, dtype=dtype)  # [T, L]
        self.batch_size = batch.batch_size
        self.pad_width = batch.pad_width

    def gathered_products(self, w2: jax.Array) -> jax.Array:
        """[T] of values[t] * w[idx[t]] — the gather, via MXU."""
        m1 = jax.lax.dot(
            self.ohr, w2.astype(self.ohr.dtype), preferred_element_type=jnp.float32
        )  # [T, L]
        return jnp.sum(m1 * self.ohc.astype(jnp.float32), axis=-1) * self.values

    def margins(self, w2: jax.Array) -> jax.Array:
        """Per-sample dots x_b . w  (ops.sparse.matvec equivalent)."""
        return self.gathered_products(w2).reshape(self.batch_size, self.pad_width).sum(-1)

    def scatter_add(self, coeff: jax.Array) -> jax.Array:
        """Blocked sum_b coeff[b] * x_b -> [R, 128] (scatter_add equivalent).

        Dispatches on the process-wide scatter formulation (module
        docstring; DSGD_SCATTER).  The default, 'onehot', stays the single
        deep-contraction dot ON MEASUREMENT (benches/scatter_wide.py +
        BASELINE.md rounds 4/6, raw JSON in benches/results/scatter_*.json):
        splitting the contraction into S=4 batched shards (a [4, R, 128]
        wide output footprint) runs the ISOLATED scatter 1.7-4.8x faster
        below the T ~ 32k crossover — but regresses the FUSED training
        step 8-15% in an interleaved same-chip A/B, because the sharded
        layouts break the iota-compare one-hot fusion the single dot
        shares with the gather.  Measured rejections, not estimates; the
        round-6 formulations stay selectable for the next hardware
        rematch (`--fused-ab`).
        """
        cv = (
            self.values.reshape(self.batch_size, self.pad_width)
            * coeff.astype(jnp.float32)[:, None]
        ).reshape(-1)
        form = _active_scatter
        if form == "segment":
            return _scatter_segment(self.flat_idx, cv, self.n_rows)
        if form == "twostage":
            return _scatter_twostage(
                self.flat_idx, self.ohc.astype(jnp.float32), cv, self.n_rows)
        if form == "bf16":
            return _scatter_bf16(self.ohr, self.ohc, cv)
        contrib = self.ohc.astype(jnp.float32) * cv[:, None]  # [T, L]
        return jax.lax.dot(
            self.ohr.T, contrib.astype(self.ohr.dtype), preferred_element_type=jnp.float32
        )


def _scatter_segment(flat_idx: jax.Array, cv: jax.Array, n_rows: int) -> jax.Array:
    """'segment': sort-by-index + one sorted segment-sum into the flat
    [R*128] view.  Sorting first lets XLA lower the segment reduction over
    monotone ids instead of a random scatter; pads (index 0, value 0)
    contribute exactly 0 to feature 0 like every other formulation."""
    order = jnp.argsort(flat_idx)
    flat = jax.ops.segment_sum(
        cv[order], flat_idx[order],
        num_segments=n_rows * LANES, indices_are_sorted=True)
    return flat.reshape(n_rows, LANES)


def _scatter_twostage(flat_idx: jax.Array, ohc: jax.Array, cv: jax.Array,
                      n_rows: int) -> jax.Array:
    """'twostage': stage 1 spreads each contribution across its lane on
    the VPU (OHC * value — [T, 128] rows, the one-hot matmul's own right
    operand); stage 2 block-adds the rows by block id with a SORTED
    segment reduction, replacing the T-deep MXU contraction."""
    rows = flat_idx // LANES
    order = jnp.argsort(rows)
    contrib = ohc * cv[:, None]  # [T, L] stage 1
    return jax.ops.segment_sum(
        contrib[order], rows[order],
        num_segments=n_rows, indices_are_sorted=True)


def _scatter_bf16(ohr: jax.Array, ohc: jax.Array, cv: jax.Array) -> jax.Array:
    """'bf16': the one-hot contraction accumulated in bf16, f32 final add.

    The contraction is split into two halves, each accumulated in bf16
    (preferred_element_type=bfloat16 — half the accumulator traffic), and
    the halves are added in f32.  Parity holds to a tolerance bound, not
    bit-exactness (tests/test_kernel_edge_shapes.py)."""
    contrib = (ohc.astype(jnp.float32) * cv[:, None]).astype(jnp.bfloat16)
    ohr16 = ohr.astype(jnp.bfloat16)
    t, r = ohr.shape
    if t % 2:
        g = jax.lax.dot(ohr16.T, contrib,
                        preferred_element_type=jnp.bfloat16)
        return g.astype(jnp.float32)
    s, sub = 2, t // 2
    g = jax.lax.dot_general(
        ohr16.reshape(s, sub, r), contrib.reshape(s, sub, LANES),
        (((1,), (1,)), ((0,), (0,))), preferred_element_type=jnp.bfloat16)
    return jnp.sum(g.astype(jnp.float32), axis=0)


def matvec(batch: SparseBatch, w2: jax.Array) -> jax.Array:
    """Standalone blocked matvec (margins) for eval-style uses."""
    return OneHotBatch(batch, w2.shape[0]).margins(w2)


def scatter_add(batch: SparseBatch, coeff: jax.Array, n_rows: int) -> jax.Array:
    """Standalone blocked scatter-add."""
    return OneHotBatch(batch, n_rows).scatter_add(coeff)
