"""Numeric gradient checking — the reference's F utility, TPU-style.

The reference ships a central-difference numerical gradient over its dense
vector type (math/F.scala:10-23: ``(f(x+d) - f(x-d)) / 2d`` per coordinate,
with an arbitrary-precision delta of 1e-25 on spire.math.Number).  That file
is dead code in the reference (SURVEY.md §2.1) but represents a real
capability: validating analytic gradients.  Here it is a live, tested
utility: a vmapped central-difference over f32/f64 arrays with a
finite-precision-appropriate delta, used by the test suite to validate every
model's ``grad_coeff`` against its objective.

Unlike F.scala's per-coordinate Scala loop, the whole Jacobian row sweep is
one ``vmap`` over basis vectors — a single compiled batched evaluation.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def numeric_grad(
    f: Callable[[jax.Array], jax.Array],
    x: jax.Array,
    eps: float = 1e-3,
    coords: Optional[jax.Array] = None,
) -> jax.Array:
    """Central-difference gradient of scalar ``f`` at ``x`` (F.scala:10-18).

    coords: optional int array of coordinate ids to probe (returns a gradient
    of that length); default probes every coordinate.  eps defaults to 1e-3 —
    appropriate for f32, unlike the reference's 1e-25 which only makes sense
    for spire's arbitrary precision (F.scala:23).
    """
    x = jnp.asarray(x)
    if coords is None:
        coords = jnp.arange(x.shape[0])

    def probe(i):
        e = jnp.zeros_like(x).at[i].set(eps)
        return (f(x + e) - f(x - e)) / (2.0 * eps)

    return jax.vmap(probe)(jnp.asarray(coords))


def check_grad(
    f: Callable[[jax.Array], jax.Array],
    grad_f: Callable[[jax.Array], jax.Array],
    x: jax.Array,
    eps: float = 1e-3,
    atol: float = 1e-3,
    rtol: float = 1e-2,
    coords: Optional[jax.Array] = None,
) -> bool:
    """True iff the analytic gradient matches central differences.

    Probes `coords` (default: all) coordinates of ``grad_f(x)`` against
    ``numeric_grad``; mirrors how F.scala was meant to be used as a
    gradient-check oracle.
    """
    num = numeric_grad(f, x, eps=eps, coords=coords)
    ana = jnp.asarray(grad_f(x))
    if coords is not None:
        ana = ana[jnp.asarray(coords)]
    return bool(jnp.allclose(num, ana, atol=atol, rtol=rtol))
