"""Pallas TPU kernel: fused per-worker sparse-SVM-family gradient.

The XLA one-hot MXU path (ops/mxu.py) is already ~4x faster than scalar
scatter, but XLA materializes the [T, R] one-hot operand (~11 MB per step
at RCV1 shapes) through HBM for each of the two matmuls.  This kernel
fuses the whole worker gradient —

    margins  m_b = x_b . w                (gather via one-hot MXU matmul)
    coeff_b  = grad_coeff(m_b, y_b)       (hinge / logistic / lsq)
    grad     g = sum_b coeff_b * x_b      (scatter via one-hot MXU matmul)

— into one single-pass `pallas_call` per step: blocked weights [R, 128]
and the gradient accumulator live in VMEM for the whole kernel, and the
one-hot operands are built in VMEM once per tile and consumed by both
matmuls without ever touching HBM.  Grid dimension = virtual workers K, so
one launch produces every reference worker's Gradient reply
(Slave.scala:142-153) for the step.

Mosaic has no cross-lane reshapes, so the host passes entries FLAT —
idx/val [K, T, 1] with T = B*P — and all in-kernel per-sample plumbing is
done with matmuls against a sample-aggregation one-hot S[T_tile, 32]
(S[e, b] = 1 iff entry e belongs to sample b):

    per-sample margins   m = S^T @ gathered        (aggregate entries)
    per-entry coeff      c_e = S @ coeff           (broadcast back)

Each tile covers 32 whole samples (TT = 32*P entries), so margins complete
within the tile and coeff/scatter fuse into the same pass.

The coefficient rule is a static python function (margins, labels) ->
coeff traced into the kernel, so every LinearModel subclass
(models/linear.py) reuses the same kernel.  Labels are f32; padding rows
carry y=0, val=0 and are inert (val=0 zeroes the scatter side).

CPU/testing: pass interpret=True (tests/test_pallas_kernels.py) — the same
kernel runs under the Pallas interpreter on the CPU test mesh
(SURVEY.md §4 strategy).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
SAMPLE_TILE = 32  # samples per in-kernel tile; 32*P entries per matmul

_SUPPORTED: "bool | None" = None


def pallas_supported() -> bool:
    """Capability probe: can `worker_grads` run under THIS jax?

    The kernel targets a newer pallas surface (`jax.typeof` vma plumbing
    in out_shape) than some images ship; on those, every call raises at
    trace time.  The probe runs one tiny interpreter-mode `worker_grads`
    and caches the verdict — tests/test_pallas*.py skip on False (unless
    forced with DSGD_PALLAS=1), so tier-1 reflects the supported surface
    instead of failing 22 known-incompatible tests (ROADMAP item 2; the
    kernel itself is measured-rejected per BASELINE.md / config.py
    `_CHOICES['kernel']`, kept only for kernel work).
    """
    global _SUPPORTED
    if _SUPPORTED is None:
        try:
            w2 = jnp.zeros((2, LANES), jnp.float32)
            idx = jnp.zeros((1, 4, 2), jnp.int32)
            val = jnp.ones((1, 4, 2), jnp.float32)
            y = jnp.ones((1, 4), jnp.int32)
            worker_grads(w2, idx, val, y,
                         coeff_fn=lambda m, yy: yy.astype(jnp.float32),
                         interpret=True)
            _SUPPORTED = True
        except Exception:  # noqa: BLE001 - any trace-time failure = unsupported
            _SUPPORTED = False
    return _SUPPORTED


def _worker_grad_kernel(idx_ref, val_ref, y_ref, w2_ref, g2_ref, g2_acc, *, coeff_fn, p):
    """One grid step = one worker's fused gradient (see module docstring)."""
    r = w2_ref.shape[0]
    t_total = idx_ref.shape[1]
    tt = SAMPLE_TILE * p
    n_tiles = t_total // tt

    g2_acc[:] = jnp.zeros_like(g2_acc)
    for t in range(n_tiles):
        sl = pl.ds(t * tt, tt)
        idxt = idx_ref[0, sl, :]  # [TT, 1] i32
        valt = val_ref[0, sl, :]  # [TT, 1] f32
        rows = idxt // LANES
        cols = idxt % LANES
        ohr = (
            jax.lax.broadcasted_iota(jnp.int32, (tt, r), 1) == rows
        ).astype(jnp.float32)  # [TT, R]
        ohc = (
            jax.lax.broadcasted_iota(jnp.int32, (tt, LANES), 1) == cols
        ).astype(jnp.float32)  # [TT, 128]
        # sample-of-entry aggregation one-hot
        ent = jax.lax.broadcasted_iota(jnp.int32, (tt, 1), 0)
        sid = ent // p  # [TT, 1] in [0, 32)
        s_agg = (
            jax.lax.broadcasted_iota(jnp.int32, (tt, SAMPLE_TILE), 1) == sid
        ).astype(jnp.float32)  # [TT, 32]

        # gather: margins of this tile's 32 samples
        m1 = jax.lax.dot_general(
            ohr, w2_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [TT, 128]
        gathered = jnp.sum(m1 * ohc, axis=-1, keepdims=True) * valt  # [TT, 1]
        m_tile = jax.lax.dot_general(
            s_agg, gathered, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [32, 1]

        # coefficient rule + broadcast back to entries
        y_tile = y_ref[0, pl.ds(t * SAMPLE_TILE, SAMPLE_TILE), :]  # [32, 1]
        coeff = coeff_fn(m_tile, y_tile)  # [32, 1]
        coeff_e = jax.lax.dot_general(
            s_agg, coeff, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [TT, 1]

        # scatter: accumulate this tile's gradient contribution
        contrib = ohc * (coeff_e * valt)  # [TT, 128]
        g2_acc[:] += jax.lax.dot_general(
            ohr, contrib, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [R, 128]
    g2_ref[0, :, :] = g2_acc[:]


def pad_batch(idx: jax.Array, val: jax.Array, y: jax.Array):
    """Pad the per-worker batch dim to a SAMPLE_TILE multiple with inert
    rows (idx 0, val 0, y 0)."""
    k, b, p = idx.shape
    bp = -(-b // SAMPLE_TILE) * SAMPLE_TILE
    if bp == b:
        return idx, val, y
    pad = ((0, 0), (0, bp - b), (0, 0))
    return (
        jnp.pad(idx, pad),
        jnp.pad(val, pad),
        jnp.pad(y, ((0, 0), (0, bp - b))),
    )


@functools.partial(jax.jit, static_argnames=("coeff_fn", "interpret"))
def worker_grads(
    w2: jax.Array,
    idx: jax.Array,
    val: jax.Array,
    y: jax.Array,
    coeff_fn: Callable[[jax.Array, jax.Array], jax.Array],
    interpret: bool = False,
) -> jax.Array:
    """Fused gradients for K workers: [K, R, 128] from idx/val/y [K, B, P].

    coeff_fn(margins, labels) -> per-sample gradient coefficient, applied
    on [32, 1] tiles inside the kernel (e.g. SparseSVM.grad_coeff).
    """
    idx, val, y = pad_batch(idx, val.astype(jnp.float32), y.astype(jnp.float32))
    k, bp, p = idx.shape
    r, lanes = w2.shape
    assert lanes == LANES
    t_total = bp * p
    # flatten on the host side: Mosaic supports no cross-lane reshapes
    idx_f = idx.reshape(k, t_total, 1)
    val_f = val.reshape(k, t_total, 1)
    y3 = y.reshape(k, bp, 1)
    kernel = functools.partial(_worker_grad_kernel, coeff_fn=coeff_fn, p=p)
    return pl.pallas_call(
        kernel,
        grid=(k,),
        in_specs=[
            pl.BlockSpec((1, t_total, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t_total, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bp, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((r, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r, LANES), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        # under shard_map the output inherits the inputs' varying mesh axes
        out_shape=jax.ShapeDtypeStruct(
            (k, r, LANES),
            jnp.float32,
            vma=frozenset(jax.typeof(idx_f).vma) | frozenset(jax.typeof(w2).vma),
        ),
        scratch_shapes=[
            pltpu.VMEM((r, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(idx_f, val_f, y3, w2)
