"""Pallas TPU kernel: fused per-worker sparse-SVM-family gradient.

The XLA one-hot MXU path (ops/mxu.py) is already ~4x faster than scalar
scatter, but XLA materializes the [T, R] one-hot operand (~11 MB per step
at RCV1 shapes) through HBM for each of the two matmuls.  This kernel
fuses the whole worker gradient —

    margins  m_b = x_b . w                (gather via one-hot MXU matmul)
    coeff_b  = grad_coeff(m_b, y_b)       (hinge / logistic / lsq)
    grad     g = sum_b coeff_b * x_b      (scatter via one-hot MXU matmul)

— into one `pallas_call` per step: the blocked weights [R, 128] live in
VMEM for the whole kernel, one-hot tiles are built in registers/VMEM per
608-entry tile (8 samples x 76 nnz) and never touch HBM, and the gradient
accumulates in a VMEM scratch.  Grid dimension = virtual workers K, so one
launch produces every reference worker's Gradient reply
(Slave.scala:142-153) for the step.

The coefficient rule is passed as a static python function of
(margins, labels) -> coeff, so every LinearModel subclass (models/linear.py)
reuses the same kernel.  Labels are f32; padding rows carry y=0, val=0 and
are inert through both phases (coeff(0-margin, y=0) may be nonzero for the
hinge, but val=0 zeroes the scatter side).

CPU/testing: pass interpret=True (tests/test_pallas_kernels.py) — the same
kernel runs under the Pallas interpreter on the 8-device CPU mesh used by
the test suite (SURVEY.md §4 strategy).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
SAMPLE_TILE = 8  # samples per in-kernel tile (sublane-aligned)


def _worker_grad_kernel(
    idx_ref, val_ref, y_ref, w2_ref, g2_ref, g2_acc, m_scratch, *, coeff_fn
):
    """One grid step = one worker's fused gradient (see module docstring)."""
    bp, p = idx_ref.shape[1], idx_ref.shape[2]
    r = w2_ref.shape[0]
    tt = SAMPLE_TILE * p
    n_tiles = bp // SAMPLE_TILE

    def onehots(t):
        idxt = idx_ref[0, pl.ds(t * SAMPLE_TILE, SAMPLE_TILE), :]  # [8, P] i32
        flat = idxt.reshape(tt, 1)
        rows = flat // LANES
        cols = flat % LANES
        ohr = (
            jax.lax.broadcasted_iota(jnp.int32, (tt, r), 1) == rows
        ).astype(jnp.float32)
        ohc = (
            jax.lax.broadcasted_iota(jnp.int32, (tt, LANES), 1) == cols
        ).astype(jnp.float32)
        valt = val_ref[0, pl.ds(t * SAMPLE_TILE, SAMPLE_TILE), :].reshape(tt, 1)
        return ohr, ohc, valt

    # phase 1: margins
    for t in range(n_tiles):
        ohr, ohc, valt = onehots(t)
        m1 = jax.lax.dot_general(
            ohr, w2_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [tt, 128]
        gathered = jnp.sum(m1 * ohc, axis=-1, keepdims=True) * valt  # [tt, 1]
        m_scratch[pl.ds(t * SAMPLE_TILE, SAMPLE_TILE), :] = gathered.reshape(
            SAMPLE_TILE, p
        ).sum(axis=-1, keepdims=True)

    # coefficient rule (static python fn; traced into the kernel)
    margins = m_scratch[:, 0].reshape(bp, 1)
    yb = y_ref[0, :].reshape(bp, 1)
    coeff = coeff_fn(margins, yb)  # [bp, 1]

    # phase 2: scatter-accumulate
    g2_acc[:] = jnp.zeros_like(g2_acc)
    for t in range(n_tiles):
        ohr, ohc, valt = onehots(t)
        ct = coeff[pl.ds(t * SAMPLE_TILE, SAMPLE_TILE), :]  # [8, 1]
        cv = (jnp.broadcast_to(ct, (SAMPLE_TILE, p)).reshape(tt, 1)) * valt
        contrib = ohc * cv  # [tt, 128]
        g2_acc[:] += jax.lax.dot_general(
            ohr, contrib, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [r, 128]
    g2_ref[0, :, :] = g2_acc[:]


def pad_batch(idx: jax.Array, val: jax.Array, y: jax.Array):
    """Pad the per-worker batch dim to a SAMPLE_TILE multiple with inert
    rows (idx 0, val 0, y 0)."""
    k, b, p = idx.shape
    bp = -(-b // SAMPLE_TILE) * SAMPLE_TILE
    if bp == b:
        return idx, val, y
    pad = ((0, 0), (0, bp - b), (0, 0))
    return (
        jnp.pad(idx, pad),
        jnp.pad(val, pad),
        jnp.pad(y, ((0, 0), (0, bp - b))),
    )


@functools.partial(jax.jit, static_argnames=("coeff_fn", "interpret"))
def worker_grads(
    w2: jax.Array,
    idx: jax.Array,
    val: jax.Array,
    y: jax.Array,
    coeff_fn: Callable[[jax.Array, jax.Array], jax.Array],
    interpret: bool = False,
) -> jax.Array:
    """Fused gradients for K workers: [K, R, 128] from idx/val/y [K, B, P].

    coeff_fn(margins, labels) -> per-sample gradient coefficient, applied
    on [B, 1] arrays inside the kernel (e.g. SparseSVM.grad_coeff).
    """
    idx, val, y = pad_batch(idx, val.astype(jnp.float32), y.astype(jnp.float32))
    k, bp, p = idx.shape
    r, lanes = w2.shape
    assert lanes == LANES
    kernel = functools.partial(_worker_grad_kernel, coeff_fn=coeff_fn)
    return pl.pallas_call(
        kernel,
        grid=(k,),
        in_specs=[
            pl.BlockSpec((1, bp, p), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bp, p), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bp), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((r, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r, LANES), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, r, LANES), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((r, LANES), jnp.float32),
            pltpu.VMEM((bp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(idx, val, y, w2)
