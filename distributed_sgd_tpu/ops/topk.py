"""Jit-compiled magnitude top-k selection (gradient compression support).

One XLA program per k (jit retraces per input shape as usual): |x| through
``lax.top_k``, support sorted ascending so the wire's coordinate list (and
the decode scatter) walk memory forward.  Used by compress/ to pick the
sparsification support on whatever device the delta already lives on —
at RCV1 scale (47,236 dims) the selection is a single fused reduction
instead of a host-side argpartition over a pulled copy.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_SELECT_CACHE: Dict[int, callable] = {}


def _select_fn(k: int):
    if k not in _SELECT_CACHE:

        def sel(x):
            _, idx = jax.lax.top_k(jnp.abs(x), k)
            idx = jnp.sort(idx)  # ascending support for the wire + scatter
            return idx, x[idx]

        _SELECT_CACHE[k] = jax.jit(sel)
    return _SELECT_CACHE[k]


def resolve_k(k: float, dim: int) -> int:
    """Config's DSGD_COMPRESS_K: a fraction of dim when < 1 (the paper-style
    k/dim density), an absolute coordinate count when >= 1; clamped to
    [1, dim]."""
    if k <= 0:
        raise ValueError(f"top-k needs k > 0, got {k}")
    n = int(round(k * dim)) if k < 1.0 else int(k)
    return max(1, min(n, dim))


def topk_magnitude(x, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """(indices, values) of the k largest-|x| coordinates, indices ascending.

    Accepts a numpy or jax array; returns host numpy (int32, float32) ready
    for the wire codec.
    """
    n = int(x.shape[0])
    idx, vals = _select_fn(min(max(1, int(k)), n))(jnp.asarray(x, jnp.float32))
    return (
        np.asarray(idx, dtype=np.int32),
        np.asarray(vals, dtype=np.float32),
    )
