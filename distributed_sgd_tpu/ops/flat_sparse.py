"""Flat (CSR-style) sparse batch — the SparseArrayVector analogue.

The reference carries a second, experimental sparse representation next to
its map-backed one: `SparseArrayVector`, a CSR-ish ``(indices, values)``
array pair built for the ScalaMeter bench and not used in the training path
(math/SparseArrayVector.scala:10-47; SURVEY.md §2.1).  This module is the
TPU-native counterpart: a *flat* layout with one entry per stored nonzero,

    FlatSparseBatch(indices: int32[T], values: f32[T], rows: int32[T], n_rows)

where ``rows[t]`` says which sample entry t belongs to.  Versus the padded
``SparseBatch`` (ops/sparse.py) it wastes no lanes on padding when row nnz
varies wildly — the same trade the reference benches map-vs-CSR for
(SparseBench.scala:34-68); benches/sparse_bench.py compares them here.

Kernels mirror ops/sparse.py exactly:
- ``matvec``: per-row dots as gather + multiply + ``segment_sum`` over rows;
- ``scatter_add``: sum_i coeff_i * x_i as one flat scatter-add.

T (total stored entries) must be static for XLA, so batches are padded to a
fixed T with (index 0, value 0, row 0) entries — inert in both kernels.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sgd_tpu.ops.sparse import SparseBatch


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class FlatSparseBatch:
    """One entry per stored nonzero, row-tagged; padded entries carry 0s.

    indices: int32[T] — 0-based feature ids (0 for padding)
    values:  f32[T]   — feature values (0.0 for padding)
    rows:    int32[T] — owning sample per entry (0 for padding)
    n_rows:  int      — static batch size B (pytree aux data, so kernels
                        stay jittable with it as a compile-time constant)
    """

    indices: jax.Array
    values: jax.Array
    rows: jax.Array
    n_rows: int

    def tree_flatten(self):
        return (self.indices, self.values, self.rows), self.n_rows

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], children[2], aux)

    def _replace(self, **kw) -> "FlatSparseBatch":
        return replace(self, **kw)


def matvec(batch: FlatSparseBatch, w: jax.Array) -> jax.Array:
    """out[b] = sum over entries of row b of values * w[indices].

    The flat-layout twin of ops.sparse.matvec (Vec.scala:58 semantics);
    the row reduction is a segment sum, which XLA lowers to a TPU-friendly
    sorted-segment scatter.
    """
    prod = batch.values.astype(jnp.float32) * jnp.take(w, batch.indices).astype(jnp.float32)
    return jax.ops.segment_sum(prod, batch.rows, num_segments=batch.n_rows)


def scatter_add(batch: FlatSparseBatch, coeff: jax.Array, n_features: int) -> jax.Array:
    """out = sum_b coeff[b] * x_b — ops.sparse.scatter_add for the flat layout."""
    weighted = batch.values.astype(jnp.float32) * jnp.take(coeff.astype(jnp.float32), batch.rows)
    return jnp.zeros((n_features,), dtype=jnp.float32).at[batch.indices].add(weighted)


def from_padded(batch: SparseBatch, total: Optional[int] = None) -> FlatSparseBatch:
    """Flatten a padded [B, P] batch, dropping pad lanes (host-side).

    Prefer passing a batch of HOST (numpy) arrays: this function pulls data
    to host, and a device->host transfer can be expensive (and on some
    remote-TPU transports degrades later dispatch latency).

    total: static T to pad the flat arrays to (default: count of stored
    nonzeros, which makes the result shape data-dependent — fine outside
    jit, e.g. when packing host-resident data once).
    """
    idx = np.asarray(batch.indices)
    val = np.asarray(batch.values)
    b, p = idx.shape
    keep = val != 0
    rows = np.broadcast_to(np.arange(b, dtype=np.int32)[:, None], (b, p))[keep]
    flat_idx, flat_val = idx[keep].astype(np.int32), val[keep].astype(np.float32)
    t = int(total) if total is not None else len(flat_idx)
    if len(flat_idx) > t:
        raise ValueError(f"{len(flat_idx)} stored entries exceed total={t}")
    out_i = np.zeros(t, dtype=np.int32)
    out_v = np.zeros(t, dtype=np.float32)
    out_r = np.zeros(t, dtype=np.int32)
    out_i[: len(flat_idx)] = flat_idx
    out_v[: len(flat_val)] = flat_val
    out_r[: len(rows)] = rows
    return FlatSparseBatch(
        indices=jnp.asarray(out_i),
        values=jnp.asarray(out_v),
        rows=jnp.asarray(out_r),
        n_rows=b,
    )


def from_csr(
    row_ptr: np.ndarray,
    col_idx: np.ndarray,
    values: np.ndarray,
    total: Optional[int] = None,
) -> FlatSparseBatch:
    """Host CSR (the loader's native output, data/rcv1.py) -> flat batch,
    the same construction as SparseArrayVector.csrFormat
    (SparseArrayVector.scala:116-131) without the text round-trip."""
    nnz = np.diff(row_ptr).astype(np.int64)
    rows = np.repeat(np.arange(len(nnz), dtype=np.int32), nnz)
    t = int(total) if total is not None else len(col_idx)
    if len(col_idx) > t:
        raise ValueError(f"{len(col_idx)} stored entries exceed total={t}")
    out_i = np.zeros(t, dtype=np.int32)
    out_v = np.zeros(t, dtype=np.float32)
    out_r = np.zeros(t, dtype=np.int32)
    out_i[: len(col_idx)] = col_idx.astype(np.int32)
    out_v[: len(values)] = values.astype(np.float32)
    out_r[: len(rows)] = rows
    return FlatSparseBatch(
        indices=jnp.asarray(out_i),
        values=jnp.asarray(out_v),
        rows=jnp.asarray(out_r),
        n_rows=len(nnz),
    )
