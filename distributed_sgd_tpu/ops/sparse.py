"""Padded-sparse batch representation and its two TPU kernels.

This layer replaces the reference's L0 math kernel — the boxed
``Map[Int, spire.math.Number]`` sparse vectors and their per-sample
elementwise ops (math/Vec.scala, math/Sparse.scala) — with a fixed-shape
representation XLA can compile:

    SparseBatch(indices: int32[B, P], values: f32[B, P])

Each row holds one sample's nonzero feature (index, value) pairs padded to
width P with (index=0, value=0).  Zero-valued pads are semantically inert in
both kernels below, so no explicit mask is carried.  Static shapes are what
make this TPU-native: XLA tiling needs fixed P, so the loader buckets rows
by nnz and pads to the bucket width (data/rcv1.py) instead of carrying
dynamic sparsity the way the reference's maps do.

Kernels:

- ``matvec(batch, w) -> f32[B]``: per-sample sparse dot products
  x_i . w as a gather + multiply + row reduction.  Replaces the reference's
  `Sparse.dot` hot loop (Vec.scala:58, Sparse.scala:15-46).
- ``scatter_add(batch, coeff, n_features) -> f32[D]``: sum_i coeff_i * x_i
  as one flat segment scatter-add.  Replaces `Vec.sum` over per-sample
  gradients (Vec.scala:133-137, Slave.scala:153).

Both are pure jittable functions; under `shard_map` they run per-shard with
collectives applied by the caller (parallel/sync.py).
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SparseBatch(NamedTuple):
    """A batch of sparse rows, padded to a common nnz width.

    indices: int32[B, P] — 0-based feature ids (0 for padding)
    values:  f32[B, P]   — feature values (0.0 for padding)
    """

    indices: jax.Array
    values: jax.Array

    @property
    def batch_size(self) -> int:
        return self.values.shape[0]

    @property
    def pad_width(self) -> int:
        return self.indices.shape[1]

    @property
    def is_dense(self) -> bool:
        """Dense-layout batch (Dataset.dense): zero-width index array,
        values hold every feature.  The canonical discriminator — model
        methods route these rows to the plain-matmul kernels."""
        return self.indices.shape[1] == 0


def matvec(batch: SparseBatch, w: jax.Array) -> jax.Array:
    """Per-row sparse dot product: out[b] = sum_p values[b,p] * w[indices[b,p]].

    Pads contribute values 0 * w[0] = 0.  Accumulates in f32 regardless of
    the dtype of `values`/`w` (bf16-safe).
    """
    gathered = jnp.take(w, batch.indices, axis=0)
    prod = batch.values.astype(jnp.float32) * gathered.astype(jnp.float32)
    return jnp.sum(prod, axis=-1)


def scatter_add(batch: SparseBatch, coeff: jax.Array, n_features: int) -> jax.Array:
    """Weighted scatter of rows into a dense vector.

    out = sum_b coeff[b] * x_b, computed as one flat `.at[].add()` scatter
    (an XLA segment-sum; TPU-friendly).  Pads scatter 0.0 into feature 0.
    """
    flat_idx = batch.indices.reshape(-1)
    flat_val = (batch.values.astype(jnp.float32) * coeff.astype(jnp.float32)[:, None]).reshape(-1)
    return jnp.zeros((n_features,), dtype=jnp.float32).at[flat_idx].add(flat_val)


def pad_rows(
    rows: Sequence[Tuple[np.ndarray, np.ndarray]],
    pad_width: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side: pack variable-nnz (indices, values) rows into [B, P] arrays.

    Rows longer than `pad_width` are truncated by largest-|value| (keeps the
    heaviest features); shorter rows are zero-padded.
    """
    b = len(rows)
    out_idx = np.zeros((b, pad_width), dtype=np.int32)
    out_val = np.zeros((b, pad_width), dtype=np.float32)
    for i, (idx, val) in enumerate(rows):
        n = len(idx)
        if n > pad_width:
            keep = np.argsort(-np.abs(val))[:pad_width]
            keep.sort()
            idx, val = idx[keep], val[keep]
            n = pad_width
        out_idx[i, :n] = idx
        out_val[i, :n] = val
    return out_idx, out_val


def take_batch(indices: np.ndarray, values: np.ndarray, sample_ids: np.ndarray) -> SparseBatch:
    """Select rows `sample_ids` from packed [N, P] host arrays as a SparseBatch."""
    return SparseBatch(
        indices=jnp.asarray(indices[sample_ids]),
        values=jnp.asarray(values[sample_ids]),
    )


def nnz_per_row(values: np.ndarray) -> np.ndarray:
    return (values != 0).sum(axis=1)
