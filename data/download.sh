#!/usr/bin/env bash
# Fetch RCV1 (reference data/download.sh:1-11 equivalent): the LYRL2004
# token vector files + topic assignments from the public mirrors.  Run
# from the repo root; files land in ./data/ where load_rcv1 expects them
# (data/rcv1.py).  In no-egress environments use DSGD_SYNTHETIC instead
# (data/synthetic.py generates RCV1-shaped data).
set -euo pipefail
cd "$(dirname "$0")"

BASE="http://www.ai.mit.edu/projects/jmlr/papers/volume5/lewis04a"

for f in \
  a13-vector-files/lyrl2004_vectors_train.dat \
  a13-vector-files/lyrl2004_vectors_test_pt0.dat \
  a13-vector-files/lyrl2004_vectors_test_pt1.dat \
  a13-vector-files/lyrl2004_vectors_test_pt2.dat \
  a13-vector-files/lyrl2004_vectors_test_pt3.dat \
  a08-topic-qrels/rcv1-v2.topics.qrels
do
  name=$(basename "$f")
  if [ ! -f "$name" ]; then
    curl -fL "$BASE/$f.gz" -o "$name.gz"
    gunzip -f "$name.gz"
  fi
done
echo "RCV1 ready: $(ls -1 *.dat *.qrels 2>/dev/null | wc -l) files"
