#!/usr/bin/env bash
# Build the node image (reference build.sh:1-8 equivalent).
set -euo pipefail
cd "$(dirname "$0")"
docker build -f kube/Dockerfile -t dsgd-tpu:node .
