"""Wire-codec microbench: bytes + encode/decode wall time per codec.

The comms-bound paths ship f32 vectors at the RCV1 weight dimension
(47,236): async gossip deltas (dense after L2 regularization) and sync
fan-in gradient sums (support bounded by the batch's feature union).  This
bench measures, per codec, the actual serialized wire bytes, the
encode/decode wall time, and the reconstruction error, at the gossip shape
and across a density sweep of fan-in-like vectors.

Run: ``python -m benches.bench_comms`` (or ``python bench.py --comms``).
Prints exactly ONE JSON line on stdout; diagnostics go to stderr.

The headline field `gossip_reduction_topk_1pct` is the acceptance bar of
the compression PR: >= 20x fewer wire bytes than dense f32 on the gossip
path at k/dim = 1% (docs/COMPRESSION.md).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

DIM = 47_236  # Dataset.scala:16 — the RCV1 weight dimension
REPS = 30
DENSITIES = (1.0, 0.1, 0.01)  # gossip (dense) -> narrow fan-in supports
TOPK_FRACTIONS = (0.001, 0.01, 0.05)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _gossip_vec(rng: np.random.Generator, density: float) -> np.ndarray:
    x = rng.normal(size=DIM).astype(np.float32) * 1e-3
    if density < 1.0:
        x[rng.random(DIM) >= density] = 0.0
    return x


def _best(fn, reps: int = REPS) -> float:
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _measure(comp, x: np.ndarray) -> dict:
    from distributed_sgd_tpu.rpc import codec

    msg = comp.compress(x, dest="bench")  # warm (jit compile for topk)
    out = codec.decode_grad(msg)
    err = float(np.linalg.norm(out - x) / max(np.linalg.norm(x), 1e-12))
    wire = msg.ByteSize()
    enc_s = _best(lambda: comp.compress(x, dest="bench"))
    dec_s = _best(lambda: codec.decode_grad(msg))
    return {
        "wire_bytes": wire,
        "dense_bytes": 4 * DIM,
        "reduction": round(4 * DIM / wire, 2),
        "encode_us": round(enc_s * 1e6, 1),
        "decode_us": round(dec_s * 1e6, 1),
        "rel_l2_err_first_msg": round(err, 6),
    }


def _codecs():
    from distributed_sgd_tpu.compress import (
        NoneCompressor,
        QInt8Compressor,
        TopKCompressor,
    )
    from distributed_sgd_tpu.utils.metrics import Metrics

    out = [("none", NoneCompressor(metrics=Metrics()))]
    for f in TOPK_FRACTIONS:
        out.append((f"topk_{f:g}", TopKCompressor(k=f, metrics=Metrics())))
    out.append(("qint8", QInt8Compressor(metrics=Metrics())))
    return out


def main() -> None:
    rng = np.random.default_rng(0)
    result: dict = {"metric": "comms_codec_bench", "dim": DIM, "reps": REPS}

    # gossip shape: dense delta, the dominant wire cost (ISSUE: O(peers x
    # dim) bytes per async round)
    gossip = _gossip_vec(rng, 1.0)
    table: dict = {}
    for name, comp in _codecs():
        table[name] = _measure(comp, gossip)
        log(f"gossip {name:>11}: {table[name]['wire_bytes']:>7} B "
            f"({table[name]['reduction']:>7.2f}x)  "
            f"enc {table[name]['encode_us']:>8.1f}us  "
            f"dec {table[name]['decode_us']:>7.1f}us  "
            f"err {table[name]['rel_l2_err_first_msg']}")
    result["gossip"] = table
    result["gossip_reduction_topk_1pct"] = table["topk_0.01"]["reduction"]

    # density sweep: fan-in-like vectors where the existing dense-vs-sparse
    # auto switch already helps — what compression adds on top
    sweep: dict = {}
    for density in DENSITIES:
        x = _gossip_vec(rng, density)
        row = {}
        for name, comp in _codecs():
            m = _measure(comp, x)
            row[name] = {"wire_bytes": m["wire_bytes"],
                         "reduction": m["reduction"]}
        sweep[f"density_{density:g}"] = row
        log(f"density {density:g}: " + "  ".join(
            f"{n}={v['wire_bytes']}B" for n, v in row.items()))
    result["density_sweep"] = sweep

    print(json.dumps(result))


if __name__ == "__main__":
    main()
