"""Hierarchical multi-host gate (docs/HIERARCHY.md, ROADMAP item 1).

Three claims, measured on the 8-virtual-CPU-device harness (the same
device emulation the MULTICHIP dryruns and tier-1 tests run):

1. **Knobs-off identity** — DSGD_HOST_DEVICES=1 (default) builds no
   in-host mesh, registers with the pre-hierarchy Node wire (no
   `devices` field serialized), and leaves the master's split exactly
   `vanilla_split`.  Hard-asserted every run.
2. **Convergence parity at equal global batch** — a hierarchical fit
   (H hosts x D devices, per-host batch B*W/H) reaches the flat RPC
   topology's (W single-device workers, batch B) final loss within the
   compression PR's parity gate (<= max(1.02 * flat, flat + 0.02),
   docs/COMPRESSION.md).  Reference semantics average per-WORKER
   gradient sums, so consolidating W workers into H hosts at equal
   global batch scales the per-round update by W/H — the hierarchical
   run uses lr * H/W to keep the update identical in expectation
   (docs/HIERARCHY.md "choosing lr").
3. **>= 2x per-round throughput at equal device count** — the gated
   configuration is 2 hosts x 4 devices vs 8 workers x 1 (the dryrun's
   hierarchical topology) at equal global batch: half the weight
   broadcasts, half the gRPC replies, half the fan-in decodes per
   round, one in-host psum replacing four gRPC repliers per host.
   4 hosts x 2 devices is measured and reported alongside (ungated:
   with only 2 gRPC calls saved per round, the shared per-round floor —
   master apply, draw, dispatch — caps its loopback ratio below the
   2x bar that the 2x4 shape clears; on a real network, where the
   per-worker RPC cost dominates that floor, both shapes gain more).

Per-round time is the master's `master.sync.batch.duration` histogram
over whole fits (best-of-reps minimum — loopback on a shared host is
noisy upward, never downward), so per-epoch eval and cluster setup are
excluded from the round metric while staying inside the honest fits.

Wall times are emitted as ``*_info`` fields (ungated in
benches/regress.py — loopback wall clock on a shared host would
false-alarm at any tolerance worth having); the hard asserts above are
the real gate, and the deterministic ``hier_loss`` gates against
history at the 2% loss-class band.

Run: ``python bench.py --hier [--smoke]``.  Prints exactly ONE JSON
line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# equal global batch everywhere: flat W=8 workers at B, hier H hosts at
# B*W/H, lr scaled by H/W (see module docstring / docs/HIERARCHY.md)
N_DEVICES = 8
GLOBAL_BATCH = 200
# smoke keeps the FULL corpus shape: the RPC-plane share (where the
# hierarchical win lives) is set by dim and rounds-per-fit, and shrinking
# either turns real signal into boundary noise — smoke trims reps/epochs
FULL = dict(n=8000, n_features=47_236, nnz=76, epochs=3, reps=4, lr=0.5)
SMOKE = dict(n=8000, n_features=47_236, nnz=76, epochs=2, reps=3, lr=0.5)
MIN_SPEEDUP = 2.0  # the ISSUE bar, gated on the 2-host x 4-device shape
PARITY_REL = 1.02  # docs/COMPRESSION.md convergence-parity gate
PARITY_ABS = 0.02


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _ensure_devices(n: int) -> None:
    """An n-device virtual CPU mesh BEFORE the backend initializes: set
    the env knobs first (they are read at backend creation), then — if an
    ambient platform plugin already claimed the process — rebuild the
    backend via the config API (`jax_num_cpu_devices` where this jax has
    it; XLA_FLAGS re-parse otherwise), the dryrun_multichip approach."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n:
        from jax.extend import backend as _jex_backend

        _jex_backend.clear_backends()
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except AttributeError:  # older jax: XLA_FLAGS re-parse path
            pass
    assert len(jax.devices()) >= n, (
        f"need {n} devices, found {len(jax.devices())} — run under "
        f"XLA_FLAGS=--xla_force_host_platform_device_count={n}")


def _build(cfg: dict):
    import jax.numpy as jnp

    from distributed_sgd_tpu.data.synthetic import rcv1_like
    from distributed_sgd_tpu.models.linear import SparseSVM

    full = rcv1_like(cfg["n"], n_features=cfg["n_features"], nnz=cfg["nnz"],
                     seed=0, idf_values=True)
    test = full.slice(slice(0, max(200, cfg["n"] // 10)))
    ds = np.full(cfg["n_features"], 0.01, np.float32)

    def make_model():
        return SparseSVM(lam=1e-5, n_features=cfg["n_features"],
                         dim_sparsity=jnp.asarray(ds))

    return full, test, make_model


class _TimedCluster:
    """One topology under measurement: a live DevCluster whose fits are
    interleaved with the other topologies' (a shared-host slow phase
    hits every config instead of biasing one).  Per-round time reads the
    master.sync.batch.duration histogram, so per-epoch eval and cluster
    setup stay out of the round metric; the reported number is the
    MINIMUM over reps (loopback on a shared host is noisy upward, never
    downward)."""

    def __init__(self, train, test, make_model, n_workers, host_devices,
                 batch, lr, host_local=False):
        from distributed_sgd_tpu.core.cluster import DevCluster

        self.cluster = DevCluster(
            make_model(), train, test, n_workers=n_workers, seed=0,
            host_devices=host_devices,
            host_local=host_local and host_devices > 1)
        self.batch, self.lr = batch, lr
        self.loss = None
        self.best_round_s = float("inf")

    def warm(self, epochs: int) -> None:
        """Compile fit; its final loss is the parity sample."""
        res = self.cluster.master.fit_sync(
            max_epochs=epochs, batch_size=self.batch, learning_rate=self.lr)
        self.loss = res.losses[-1]

    def rep(self, epochs: int) -> float:
        h = self.cluster.master.metrics.histogram(
            "master.sync.batch.duration")
        c0, s0 = h.count, h.sum
        self.cluster.master.fit_sync(
            max_epochs=epochs, batch_size=self.batch, learning_rate=self.lr)
        r = (h.sum - s0) / (h.count - c0)
        self.best_round_s = min(self.best_round_s, r)
        return r

    def close(self) -> None:
        self.cluster.stop()


def _assert_knobs_off(train, test, make_model):
    """DSGD_HOST_DEVICES=1 (default) must be the pre-hierarchy engine:
    no in-host mesh, no Node.devices on the wire, vanilla split."""
    from distributed_sgd_tpu.core.cluster import DevCluster
    from distributed_sgd_tpu.core.split import vanilla_split
    from distributed_sgd_tpu.rpc import dsgd_pb2 as pb

    with DevCluster(make_model(), train, test, n_workers=2, seed=0) as c:
        assert all(w._hier is None for w in c.workers), (
            "a default worker built an in-host mesh")
        assert all(w._data_offset is None for w in c.workers)
        assert not c.master._worker_devices, (
            f"flat workers registered host shapes: "
            f"{c.master._worker_devices}")
        members = c.master._members()
        got = c.master._split_parts(vanilla_split, members)
        want = vanilla_split(len(train), len(members))
        assert all(np.array_equal(a, b) for a, b in zip(got, want)), (
            "knobs-off split diverged from vanilla_split")
    # flat registration wire: byte-identical to the pre-hierarchy Node
    flat = pb.Node(host="w", port=4001)
    assert b"devices" not in flat.SerializeToString() and \
        flat.SerializeToString() == pb.Node(
            host="w", port=4001).SerializeToString()
    assert flat.devices == 0
    log("knobs-off identity: OK (no mesh, no Node.devices, vanilla split)")


def run_bench(smoke: bool = False) -> dict:
    _ensure_devices(N_DEVICES)
    cfg = SMOKE if smoke else FULL
    label = "smoke" if smoke else "full"
    log(f"hierarchical gate ({label}): n={cfg['n']} dim={cfg['n_features']} "
        f"nnz={cfg['nnz']} global_batch={GLOBAL_BATCH} epochs={cfg['epochs']} "
        f"reps={cfg['reps']} on {N_DEVICES} virtual devices")
    train, test, make_model = _build(cfg)

    _assert_knobs_off(train, test, make_model)

    lr = cfg["lr"]
    epochs = cfg["epochs"]
    t0 = time.perf_counter()
    # equal global batch everywhere; hierarchical lr scaled by H/W (see
    # module docstring).  flat = the 1-device-per-worker baseline; 2x4 =
    # the gated hierarchical shape; 4x2 reported alongside.
    configs = [
        ("flat 8x1", N_DEVICES, 1, GLOBAL_BATCH // N_DEVICES, lr),
        ("hier 2x4", 2, N_DEVICES // 2, GLOBAL_BATCH // 2,
         lr * 2 / N_DEVICES),
        ("hier 4x2", 4, N_DEVICES // 4, GLOBAL_BATCH // 4,
         lr * 4 / N_DEVICES),
    ]
    clusters = {}
    try:
        for name, nw, hd, b, clr in configs:
            clusters[name] = _TimedCluster(train, test, make_model, nw, hd,
                                           b, clr, host_local=hd > 1)
            clusters[name].warm(epochs)
            log(f"{name}: warmed (parity loss {clusters[name].loss:.6f}, "
                f"t+{time.perf_counter() - t0:.0f}s)")
        for rep in range(cfg["reps"]):
            for name in clusters:
                r = clusters[name].rep(epochs)
                log(f"rep {rep}: {name} {r * 1e3:.2f} ms/round")
        flat_s = clusters["flat 8x1"].best_round_s
        flat_loss = clusters["flat 8x1"].loss
        h2_s = clusters["hier 2x4"].best_round_s
        h2_loss = clusters["hier 2x4"].loss
        h4_s = clusters["hier 4x2"].best_round_s
        h4_loss = clusters["hier 4x2"].loss
    finally:
        for tc in clusters.values():
            tc.close()

    speedup = flat_s / h2_s
    speedup4 = flat_s / h4_s
    parity_bound = max(PARITY_REL * flat_loss, flat_loss + PARITY_ABS)
    log(f"per-round speedup: 2x4 {speedup:.2f}x (bar >= {MIN_SPEEDUP}x), "
        f"4x2 {speedup4:.2f}x (info); parity: hier {h2_loss:.6f} / "
        f"{h4_loss:.6f} vs bound {parity_bound:.6f}")
    assert h2_loss <= parity_bound and h4_loss <= parity_bound, (
        f"hierarchical fit lost convergence parity: {h2_loss:.6f} / "
        f"{h4_loss:.6f} vs bound {parity_bound:.6f} (flat {flat_loss:.6f})")
    assert speedup >= MIN_SPEEDUP, (
        f"hierarchical 2x{N_DEVICES // 2} per-round speedup {speedup:.2f}x "
        f"under the {MIN_SPEEDUP}x bar (flat {flat_s * 1e3:.2f} ms/round, "
        f"hier {h2_s * 1e3:.2f} ms/round)")

    return {
        "metric": f"hier_rpc_{label}",
        "unit": "x",
        # the headline ratio (plain name: recorded, not direction-gated —
        # the hard assert above is the gate) + deterministic loss series
        "speedup_per_round": round(speedup, 3),
        "speedup_per_round_4x2": round(speedup4, 3),
        "hier_loss": round(h2_loss, 6),
        "hier_4x2_loss_info": round(h4_loss, 6),
        "flat_loss_info": round(flat_loss, 6),
        # loopback wall clock: recorded ungated (*_info)
        "flat_round_ms_info": round(flat_s * 1e3, 3),
        "hier_round_ms_info": round(h2_s * 1e3, 3),
        "hier_4x2_round_ms_info": round(h4_s * 1e3, 3),
        "speedup_bar_info": MIN_SPEEDUP,
        "global_batch": GLOBAL_BATCH,
        "n_devices": N_DEVICES,
        **{k: v for k, v in cfg.items()},
    }


def main(smoke: bool = False) -> None:
    result = run_bench(smoke=smoke)
    # round-over-round recording (benches/regress.py): same policy as
    # bench.py — a clean run is appended to history
    try:
        from benches import regress

        regressions, lines = regress.check(result, regress.load_history())
        result["regressed"] = regressions
        log(f"regression gate vs stored history, tolerance "
            f"{regress.DEFAULT_TOLERANCE:.0%}:")
        for ln in lines:
            log(ln)
        if regressions:
            log(f"FAIL: regressed metrics: {', '.join(regressions)} "
                f"(run NOT recorded)")
        else:
            regress.record(result)
            log("PASS: run appended to benches/history.json")
    except Exception as e:  # noqa: BLE001 - gating must not break the bench
        log(f"regression gate skipped: {e}")
        result["regressed"] = None
        result["gate_error"] = str(e)
    print(json.dumps(result))


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
