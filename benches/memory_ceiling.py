"""Measured per-chip corpus memory ceiling (VERDICT r3 item 8).

`SyncEngine.bind` materializes the full padded corpus host-side and
device-puts it once; the resident dataset then lives in HBM for the whole
fit.  This script measures, on the real chip: the HBM footprint of the
RCV1-scale corpus, the total/free HBM, and the implied max resident rows
at this row width — the number a user needs to decide when to switch to
the host-local loader path (parallel/multihost.py + per-host binds, the
pattern of tests/test_multihost_2proc.py) or a padded width cap
(load_rcv1(pad_width=...)).

Prints one JSON line; README/BASELINE record the numbers.
"""

from __future__ import annotations

import json
import sys

import numpy as np

N_ROWS = 804_414
N_FEATURES = 47_236
NNZ = 76


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from distributed_sgd_tpu.data.synthetic import rcv1_like
    from distributed_sgd_tpu.models.linear import SparseSVM
    from distributed_sgd_tpu.parallel.mesh import make_mesh
    from distributed_sgd_tpu.parallel.sync import SyncEngine

    import time

    from distributed_sgd_tpu.parallel.sync import padded_layout

    dev = jax.devices()[0]
    data = rcv1_like(N_ROWS, n_features=N_FEATURES, nnz=NNZ, seed=0)
    p = data.pad_width
    host_bytes = data.indices.nbytes + data.values.nbytes + data.labels.nbytes
    log(f"host corpus: {host_bytes/1e6:.0f} MB (P={p})")

    model = SparseSVM(lam=1e-5, n_features=N_FEATURES, regularizer="l2")
    eng = SyncEngine(model, make_mesh(1), batch_size=100, learning_rate=0.5)
    t0 = time.perf_counter()
    bound = eng.bind(data)
    jax.block_until_ready(bound.data.values)
    bind_s = time.perf_counter() - t0

    # resident-dataset device bytes are deterministic from the padded
    # layout: int32[P] + f32[P] + int32 label per padded row
    total_padded, _ = padded_layout(N_ROWS, 1, 4096)
    bytes_per_row = 8 * p + 4
    corpus_dev = total_padded * bytes_per_row
    # the tunnel device does not expose memory_stats(); use it when
    # available, else the chip's documented HBM (v5e: 16 GiB)
    stats = dev.memory_stats() or {}
    limit = int(stats.get("bytes_limit", 0)) or 16 * 1024**3
    out = {
        "metric": "corpus_hbm_footprint",
        "pad_width": p,
        "host_corpus_mb": round(host_bytes / 1e6),
        "device_corpus_mb": round(corpus_dev / 1e6),
        "bytes_per_row": bytes_per_row,
        "bind_wall_s": round(bind_s, 2),
        "hbm_limit_mb": round(limit / 1e6),
        "hbm_limit_source": "memory_stats" if stats.get("bytes_limit") else "v5e spec",
        # ~1 GB headroom held back for weights (2 x 24 MB blocked copies),
        # the one-hot step working set, and XLA scratch
        "implied_max_rows_this_width": int((limit - 1e9) / bytes_per_row),
        "device": str(dev),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
