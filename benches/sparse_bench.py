"""Kernel microbenchmarks — the reference SparseBench equivalent.

The reference benches its two sparse representations (`Sparse` map vs
`SparseArrayVector` CSR) on addition / elementwise product / dot /
scalar multiplication / normSquared over 100 real RCV1 rows
(src/test/scala/epfl/distributed/math/SparseBench.scala:22-68).  This
benches the same five ops over RCV1-shaped rows in three implementations:

- `xla`: this framework's padded-sparse batch kernels (jit'd, on the
  default JAX platform — TPU when available);
- `xla_flat`: the flat CSR-style layout (ops/flat_sparse.py), the
  SparseArrayVector counterpart in the rep-vs-rep comparison;
- `scipy`: scipy.sparse CSR on CPU (a strong conventional baseline);
- `boxed`: per-row python dict arithmetic, the reference's cost model
  (boxed per-entry ops, fresh map per operation).

Usage: python benches/sparse_bench.py [n_rows] [--gate]

`--gate` additionally emits one flat JSON line and runs it through the
round-over-round regression harness (benches/regress.py) against the
kernel history — the reference wraps exactly this bench in ScalaMeter's
RegressionReporter (SparseBench.scala:9-15).  Only the framework's own
kernel timings (`xla_*`/`xla_flat_*`, `*_s` keys) gate; the scipy/boxed
comparison baselines are recorded as ungated `*_baseline` keys.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_rows(n_rows: int, n_features: int = 47236, nnz: int = 76, seed: int = 0):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, n_features, size=(n_rows, nnz), dtype=np.int64).astype(np.int32)
    idx.sort(axis=1)
    val = rng.random((n_rows, nnz)).astype(np.float32)
    return idx, val


def timeit(fn, reps: int = 5) -> float:
    fn()  # warmup / compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def bench_xla(idx, val, w):
    import jax
    import jax.numpy as jnp

    from distributed_sgd_tpu.ops.sparse import SparseBatch, matvec, scatter_add

    d = len(w)
    batch = SparseBatch(jnp.asarray(idx), jnp.asarray(val))
    wj = jnp.asarray(w)
    coeff = jnp.ones(idx.shape[0], dtype=jnp.float32)

    dot = jax.jit(lambda b, w: matvec(b, w))
    add = jax.jit(lambda b, c: scatter_add(b, c, d))  # keyset-union sum of rows
    scal = jax.jit(lambda b: SparseBatch(b.indices, b.values * 2.0))
    prod = jax.jit(lambda b, w: b.values * jnp.take(w, b.indices))  # x * w elementwise
    norm2 = jax.jit(lambda b: jnp.sum(b.values**2, axis=-1))

    block = jax.block_until_ready
    return {
        "dot": timeit(lambda: block(dot(batch, wj))),
        "add(sum rows)": timeit(lambda: block(add(batch, coeff))),
        "scalar*": timeit(lambda: block(scal(batch))),
        "elementwise*": timeit(lambda: block(prod(batch, wj))),
        "normSquared": timeit(lambda: block(norm2(batch))),
    }


def bench_xla_flat(idx, val, w):
    import jax
    import jax.numpy as jnp

    from distributed_sgd_tpu.ops import flat_sparse
    from distributed_sgd_tpu.ops.sparse import SparseBatch

    d = len(w)
    # pass HOST arrays: from_padded is host-side, and a device->host pull
    # mid-process degrades every later dispatch on the axon TPU tunnel
    flat = flat_sparse.from_padded(SparseBatch(idx, val))
    wj = jnp.asarray(w)
    coeff = jnp.ones(idx.shape[0], dtype=jnp.float32)

    dot = jax.jit(lambda b, w: flat_sparse.matvec(b, w))
    add = jax.jit(lambda b, c: flat_sparse.scatter_add(b, c, d))
    scal = jax.jit(lambda b: b._replace(values=b.values * 2.0))
    prod = jax.jit(lambda b, w: b.values * jnp.take(w, b.indices))
    norm2 = jax.jit(
        lambda b: jax.ops.segment_sum(b.values**2, b.rows, num_segments=b.n_rows)
    )

    block = jax.block_until_ready
    return {
        "dot": timeit(lambda: block(dot(flat, wj))),
        "add(sum rows)": timeit(lambda: block(add(flat, coeff))),
        "scalar*": timeit(lambda: block(scal(flat))),
        "elementwise*": timeit(lambda: block(prod(flat, wj))),
        "normSquared": timeit(lambda: block(norm2(flat))),
    }


def bench_scipy(idx, val, w):
    from scipy import sparse

    n, p = idx.shape
    d = len(w)
    indptr = np.arange(0, n * p + 1, p)
    m = sparse.csr_matrix((val.ravel(), idx.ravel(), indptr), shape=(n, d))
    return {
        "dot": timeit(lambda: m @ w),
        "add(sum rows)": timeit(lambda: np.asarray(m.sum(axis=0))),
        "scalar*": timeit(lambda: m * 2.0),
        "elementwise*": timeit(lambda: m.multiply(w)),
        "normSquared": timeit(lambda: np.asarray(m.multiply(m).sum(axis=1))),
    }


def bench_boxed(idx, val, w):
    rows = [dict(zip(i.tolist(), v.tolist())) for i, v in zip(idx, val)]

    def dot():
        return [sum(v * w[k] for k, v in r.items()) for r in rows]

    def add():
        acc: dict = {}
        for r in rows:  # keyset-union fold, fresh map per merge (Vec.scala:133-137)
            acc = {k: acc.get(k, 0.0) + r.get(k, 0.0) for k in acc.keys() | r.keys()}
        return acc

    def scal():
        return [{k: v * 2.0 for k, v in r.items()} for r in rows]

    def prod():
        return [{k: v * w[k] for k, v in r.items()} for r in rows]

    def norm2():
        return [sum(v * v for v in r.values()) for r in rows]

    return {
        "dot": timeit(dot, reps=3),
        "add(sum rows)": timeit(add, reps=3),
        "scalar*": timeit(scal, reps=3),
        "elementwise*": timeit(prod, reps=3),
        "normSquared": timeit(norm2, reps=3),
    }


def main() -> None:
    # first non-flag argument is n_rows (SparseBench.scala:22 default 100)
    args = [a for a in sys.argv[1:] if not a.startswith("-")]
    n_rows = int(args[0]) if args else 100
    idx, val = make_rows(n_rows)
    w = np.random.default_rng(1).random(47236).astype(np.float32)

    results = {
        "xla": bench_xla(idx, val, w),
        "xla_flat": bench_xla_flat(idx, val, w),
        "scipy": bench_scipy(idx, val, w),
        "boxed": bench_boxed(idx, val, w),
    }
    ops = list(results["xla"])
    print(f"{n_rows} rows x 76 nnz, 47,236 features (median seconds)")
    print(f"{'op':>14} " + " ".join(f"{k:>12}" for k in results))
    for op in ops:
        print(f"{op:>14} " + " ".join(f"{results[k][op]:12.6f}" for k in results))

    if "--gate" in sys.argv:
        import json
        import re

        from benches import regress

        def slug(op):
            return re.sub(r"[^a-z0-9]+", "_", op.lower()).strip("_")

        run = {"metric": "sparse_kernels", "n_rows": n_rows}
        for impl, per_op in results.items():
            for op, secs in per_op.items():
                # framework kernels gate (lower-is-better _s suffix);
                # scipy/boxed are host-side comparison baselines: recorded
                # under an ungated suffix (see regress.direction)
                suffix = "_s" if impl.startswith("xla") else "_baseline"
                run[f"{impl}_{slug(op)}{suffix}"] = round(secs, 6)
        print(json.dumps(run))
        # tolerance 1.0 (2x): these are tens-of-microsecond timings on a
        # shared tunnel chip and swing ~2x run to run; the gate exists to
        # catch structural regressions (an accidental de-jit or a fallback
        # to the scalar path is 10x+), not dispatch jitter.  History is
        # per-size (timings scale with n_rows), and — unlike the epoch
        # gate, which logs every run — a FAILING kernel run is NOT
        # recorded: appending regressed values would let repeated failing
        # runs drag the median up until the regression "passes"
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            f"kernel_history_n{n_rows}.json")
        history = regress.load_history(path)
        regressions, lines = regress.check(run, history, tolerance=1.0)
        print(f"kernel gate (n_rows={n_rows}) vs {len(history)} stored "
              f"run(s), tolerance 100%:", file=sys.stderr)
        for ln in lines:
            print(ln, file=sys.stderr)
        if regressions:
            print(f"FAIL: regressed kernels: {', '.join(regressions)} "
                  f"(run NOT recorded)", file=sys.stderr)
            raise SystemExit(1)
        regress.record(run, path)
        print(f"PASS; run appended to {path}", file=sys.stderr)
        raise SystemExit(0)


if __name__ == "__main__":
    main()
