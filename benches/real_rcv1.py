"""Real-RCV1 turnkey kit (VERDICT r4 item 6): one command from nothing to
a "real RCV1" BASELINE.md section, wherever network egress exists.

This environment has zero egress, so the real LYRL2004 corpus cannot be
fetched here (BASELINE.md "Real-RCV1 status") — but everything after the
download is already proven on generated files in the reference's exact
text format (data/corpus.py + benches/data_pipeline.py).  This script
makes closing the gap turnkey for whoever has network:

    python benches/real_rcv1.py            # download -> checksum verify ->
                                           # parse gate -> full scenario ->
                                           # bench -> append BASELINE.md
    python benches/real_rcv1.py --slice 50000
                                           # same, but fit/bench on the
                                           # first 50k parsed rows — the
                                           # one-command verification run
                                           # for the FIRST egress-enabled
                                           # attempt (parse still runs at
                                           # full scale against its gate)
    python benches/real_rcv1.py --generated [--rows N] [--max-epochs E]
                                           # dry-run the IDENTICAL path on
                                           # data/corpus.py output (no
                                           # network, no BASELINE.md edit)

Checksum manifest (ROADMAP item 5a): every downloaded shard's sha256 is
verified against ``benches/rcv1_sha256.json``.  Shards the manifest does
not know yet are recorded trust-on-first-use (and flagged
``verified: false`` in the output JSON) so the SECOND run — and every
CI re-run after — fails loudly on a corrupted or truncated re-download
instead of feeding garbage to the parse gate.  The --generated dry-run
exercises the same code path against a manifest sidecar in the corpus
folder.

Stages (each timed, all results in ONE stdout JSON line):

1. files    — data/download.sh (reference data/download.sh:1-11), or
              write_rcv1_corpus for --generated;
2. parse    — load_rcv1(full=True) through the native parser; the
              reference's only perf gate on this path is parse < 40 s
              (DatasetTests.scala:11-23, JVM -Xmx12G) and it is enforced
              at full scale (reported, not enforced, on shrunken dry-runs);
3. scenario — the complete application.conf-default fit with early
              stopping (benches/full_scenario.run_scenario on the PARSED
              dataset);
4. bench    — the north-star epoch wall-clock on the parsed arrays
              (bench.tpu_epoch_seconds: same slope-fit methodology as the
              driver harness).

With real files the script appends the measured section to BASELINE.md;
the dry-run prints the section to stderr instead.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FULL_ROWS = 804_414  # DatasetTests.scala:18
PARSE_GATE_S = 40.0  # DatasetTests.scala:11-23
# sha256 manifest for the downloaded LYRL2004 shards (trust-on-first-use:
# the first egress-enabled run records, every later run verifies)
MANIFEST = os.path.join(REPO, "benches", "rcv1_sha256.json")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def verify_checksums(folder: str, manifest_path: str = MANIFEST,
                     record: bool = True) -> dict:
    """Verify every corpus shard in `folder` against the sha256 manifest.

    Known shards must match exactly (SystemExit on mismatch — a corrupted
    or truncated download must never reach the parser); unknown shards
    are recorded trust-on-first-use when `record` and reported with
    ``verified: false`` so the output JSON shows which hashes were pinned
    THIS run rather than checked against history."""
    shards = sorted(
        glob.glob(os.path.join(folder, "lyrl2004_*.dat"))
        + glob.glob(os.path.join(folder, "*.qrels")))
    manifest = {}
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
    out, changed = {}, False
    for path in shards:
        name = os.path.basename(path)
        digest = _sha256(path)
        if name in manifest:
            if manifest[name] != digest:
                raise SystemExit(
                    f"checksum mismatch for {name}: manifest "
                    f"{manifest[name][:16]}..., file {digest[:16]}... — "
                    f"corrupted/truncated download (delete the file and "
                    f"re-run, or update {manifest_path} if the upstream "
                    f"corpus legitimately changed)")
            out[name] = {"sha256": digest, "verified": True}
        else:
            manifest[name] = digest
            changed = True
            out[name] = {"sha256": digest, "verified": False}
            log(f"checksum recorded (trust-on-first-use): {name} = "
                f"{digest[:16]}...")
    if changed and record:
        with open(manifest_path, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.write("\n")
        log(f"manifest updated: {manifest_path}")
    return out


def ensure_files(folder: str, generated: bool, rows: int, seed: int = 0) -> dict:
    """Stage 1: real download, or the generated corpus in the same layout.

    Generated corpora carry a metadata sidecar; a cached folder is reused
    ONLY when its recorded row count matches `--rows` — otherwise it is
    regenerated, so a stale corpus can never masquerade as the requested
    scale."""
    train_file = os.path.join(folder, "lyrl2004_vectors_train.dat")
    t0 = time.perf_counter()
    if generated:
        meta_path = os.path.join(folder, "corpus_meta.json")
        cached_rows = None
        if os.path.exists(train_file) and os.path.exists(meta_path):
            with open(meta_path) as f:
                cached_rows = json.load(f).get("n_rows")
        if cached_rows != rows:
            if os.path.exists(train_file):
                log(f"cached corpus has {cached_rows} rows, need {rows}: "
                    f"regenerating")
            from distributed_sgd_tpu.data.corpus import write_rcv1_corpus

            meta = write_rcv1_corpus(folder, n_rows=rows,
                                     n_train=max(rows // 4, 1), seed=seed)
            with open(meta_path, "w") as f:
                json.dump(meta, f)
            log(f"generated corpus: {meta['bytes'] / 1e6:.1f} MB")
            # a regenerated corpus invalidates any sidecar manifest from a
            # previous (different-rows) generation
            sidecar = os.path.join(folder, "corpus_sha256.json")
            if os.path.exists(sidecar):
                os.remove(sidecar)
        # same verify path as the real corpus, against a folder-local
        # sidecar manifest (first run records, cached reuse verifies)
        checksums = verify_checksums(
            folder, manifest_path=os.path.join(folder, "corpus_sha256.json"))
        return {"kind": "generated", "seconds": time.perf_counter() - t0,
                "checksums": checksums}
    if not os.path.exists(train_file):
        os.makedirs(folder, exist_ok=True)
        script = os.path.join(REPO, "data", "download.sh")
        # download.sh fetches into its own directory (it cd's to its
        # dirname); when the target IS data/ run it in place, otherwise
        # copy it into `folder` first
        target = os.path.join(folder, "download.sh")
        if os.path.abspath(target) != os.path.abspath(script):
            import shutil

            shutil.copy(script, target)
        subprocess.run(["bash", target], check=True)
    return {"kind": "real", "seconds": time.perf_counter() - t0,
            "checksums": verify_checksums(folder)}


def parse_stage(folder: str, full_scale: bool) -> tuple:
    """Stage 2: native parse + pack, held to the reference's < 40 s gate."""
    from distributed_sgd_tpu.data.rcv1 import load_rcv1

    t0 = time.perf_counter()
    data = load_rcv1(folder, full=True)
    parse_s = time.perf_counter() - t0
    gate_pass = parse_s < PARSE_GATE_S
    log(f"parsed {len(data)} rows in {parse_s:.1f}s "
        f"(< {PARSE_GATE_S:.0f}s gate: "
        f"{'PASS' if gate_pass else 'FAIL'}"
        f"{'' if full_scale else ', informational at this scale'})")
    if full_scale and not gate_pass:
        raise SystemExit(
            f"parse took {parse_s:.1f}s, over the reference's "
            f"{PARSE_GATE_S:.0f}s gate (DatasetTests.scala:11-23)")
    return data, {"seconds": round(parse_s, 2), "rows": len(data),
                  "gate_pass": gate_pass, "gate_enforced": full_scale}


def row_store_stage(folder: str, data) -> dict:
    """Stage 2b: pack the parsed corpus into the mmap row store
    (data/row_store.py) — the ONE parse every later worker spin-up
    amortizes — and verify a host-slice read against the in-memory
    arrays.  After this stage, `DSGD_ROW_STORE=<folder>/rcv1.rows` (+
    `DSGD_HOST_INDEX=i`) gives the no-egress CLI worker role host-local
    loading on the real corpus: map, read one slice, serve."""
    import numpy as np

    from distributed_sgd_tpu.data.host_shard import host_slice
    from distributed_sgd_tpu.data.rcv1 import dim_sparsity, train_test_split
    from distributed_sgd_tpu.data.row_store import RowStore, build_row_store

    path = os.path.join(folder, "rcv1.rows")
    t0 = time.perf_counter()
    train, _ = train_test_split(data)
    meta = build_row_store(data, path, train_rows=len(train),
                           dim_sparsity=dim_sparsity(train))
    build_s = time.perf_counter() - t0
    store = RowStore(path)
    # spot-check: one host slice read back byte-identical
    lo, hi = host_slice(store.train_rows, 0, 3)
    hi = min(hi, lo + 1000)
    back = store.read_rows(lo, hi)
    assert np.array_equal(back.indices, data.indices[lo:hi])
    assert np.array_equal(back.values, data.values[lo:hi])
    assert np.array_equal(back.labels, data.labels[lo:hi])
    log(f"row store built: {os.path.getsize(path) / 1e6:.1f} MB at "
        f"{path} in {build_s:.1f}s (stride {meta['row_stride_bytes']} B; "
        f"slice read of {hi - lo} rows verified)")
    return {"path": path, "seconds": round(build_s, 2),
            "bytes": os.path.getsize(path),
            "row_stride_bytes": meta["row_stride_bytes"],
            "train_rows": meta["train_rows"],
            "verified_rows": hi - lo}


def scenario_stage(data, max_epochs: int) -> dict:
    """Stage 3: the full application.conf-default scenario on parsed data."""
    from benches import full_scenario

    res, doc = full_scenario.run_scenario(
        dataset=data, max_epochs=max_epochs, generator_tag="parsed corpus")
    return {
        "epochs_run": res.epochs_run,
        "final_test_loss": doc["test_losses"][-1],
        "final_test_acc": doc["test_accs"][-1],
        "test_losses": doc["test_losses"],
        "fit_wall_s": doc["fit_wall_s"],
    }


def bench_stage(data) -> dict:
    """Stage 4: north-star epoch wall-clock on the parsed arrays."""
    import bench

    epoch_s, loss, acc = bench.tpu_epoch_seconds(
        data.indices, data.values, data.labels)
    return {"epoch_seconds": round(float(epoch_s), 4),
            "loss3": round(float(loss), 4), "acc3": round(float(acc), 4)}


def baseline_section(out: dict) -> str:
    s = out["scenario"]
    b = out["bench"]
    p = out["parse"]
    return (
        "\n### Real RCV1 (measured end to end, benches/real_rcv1.py)\n\n"
        f"| quantity | value |\n|---|---|\n"
        f"| corpus | {p['rows']} rows parsed from LYRL2004 files |\n"
        f"| parse wall-clock | {p['seconds']} s "
        f"(reference gate < {PARSE_GATE_S:.0f} s, DatasetTests.scala:11-23: "
        f"{'PASS' if p['gate_pass'] else 'FAIL'}) |\n"
        f"| full-scenario fit | {s['epochs_run']} epochs, final test "
        f"loss {s['final_test_loss']} / acc {s['final_test_acc']} |\n"
        f"| sync epoch wall-clock | {b['epoch_seconds']} s "
        f"(slope fit, bench.py methodology) |\n"
    )


def slice_dataset(data, n: int):
    """First-`n`-rows view of a parsed Dataset (the --slice fast path:
    parse runs — and gates — at full scale, the fit/bench stages run on
    the slice so the first egress-enabled attempt verifies the whole
    pipeline in minutes instead of hours)."""
    from distributed_sgd_tpu.data.rcv1 import Dataset

    n = min(int(n), len(data))
    return Dataset(indices=data.indices[:n], values=data.values[:n],
                   labels=data.labels[:n], n_features=data.n_features)


def main(argv) -> int:
    generated = "--generated" in argv
    rows, max_epochs, folder = FULL_ROWS, 10, os.path.join(REPO, "data")
    slice_n = None
    for i, a in enumerate(argv):
        if a == "--rows":
            rows = int(argv[i + 1])
        elif a == "--max-epochs":
            max_epochs = int(argv[i + 1])
        elif a == "--folder":
            folder = argv[i + 1]
        elif a == "--slice":
            slice_n = int(argv[i + 1])
    if generated and folder == os.path.join(REPO, "data"):
        folder = "/tmp/rcv1_turnkey"

    out = {"study": "real_rcv1_turnkey",
           "mode": "generated" if generated else "real"}
    out["files"] = ensure_files(folder, generated, rows)
    full_scale = not generated
    data, out["parse"] = parse_stage(folder, full_scale)
    out["row_store"] = row_store_stage(folder, data)
    if slice_n is not None:
        data = slice_dataset(data, slice_n)
        out["slice"] = len(data)
        log(f"sliced to the first {len(data)} rows for the fit/bench stages")
    out["scenario"] = scenario_stage(data, max_epochs)
    out["bench"] = bench_stage(data)

    section = baseline_section(out)
    if generated or slice_n is not None:
        # a sliced epoch time is not the full-scale record either way
        log("dry-run/slice: BASELINE.md untouched; section would be:")
        log(section)
    else:
        path = os.path.join(REPO, "BASELINE.md")
        with open(path, "a") as f:
            f.write(section)
        log(f"appended the Real-RCV1 section to {path}")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
