"""Real-RCV1 turnkey kit (VERDICT r4 item 6): one command from nothing to
a "real RCV1" BASELINE.md section, wherever network egress exists.

This environment has zero egress, so the real LYRL2004 corpus cannot be
fetched here (BASELINE.md "Real-RCV1 status") — but everything after the
download is already proven on generated files in the reference's exact
text format (data/corpus.py + benches/data_pipeline.py).  This script
makes closing the gap turnkey for whoever has network:

    python benches/real_rcv1.py            # download -> parse gate ->
                                           # full scenario -> bench ->
                                           # append BASELINE.md section
    python benches/real_rcv1.py --generated [--rows N] [--max-epochs E]
                                           # dry-run the IDENTICAL path on
                                           # data/corpus.py output (no
                                           # network, no BASELINE.md edit)

Stages (each timed, all results in ONE stdout JSON line):

1. files    — data/download.sh (reference data/download.sh:1-11), or
              write_rcv1_corpus for --generated;
2. parse    — load_rcv1(full=True) through the native parser; the
              reference's only perf gate on this path is parse < 40 s
              (DatasetTests.scala:11-23, JVM -Xmx12G) and it is enforced
              at full scale (reported, not enforced, on shrunken dry-runs);
3. scenario — the complete application.conf-default fit with early
              stopping (benches/full_scenario.run_scenario on the PARSED
              dataset);
4. bench    — the north-star epoch wall-clock on the parsed arrays
              (bench.tpu_epoch_seconds: same slope-fit methodology as the
              driver harness).

With real files the script appends the measured section to BASELINE.md;
the dry-run prints the section to stderr instead.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FULL_ROWS = 804_414  # DatasetTests.scala:18
PARSE_GATE_S = 40.0  # DatasetTests.scala:11-23


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def ensure_files(folder: str, generated: bool, rows: int, seed: int = 0) -> dict:
    """Stage 1: real download, or the generated corpus in the same layout.

    Generated corpora carry a metadata sidecar; a cached folder is reused
    ONLY when its recorded row count matches `--rows` — otherwise it is
    regenerated, so a stale corpus can never masquerade as the requested
    scale."""
    train_file = os.path.join(folder, "lyrl2004_vectors_train.dat")
    t0 = time.perf_counter()
    if generated:
        meta_path = os.path.join(folder, "corpus_meta.json")
        cached_rows = None
        if os.path.exists(train_file) and os.path.exists(meta_path):
            with open(meta_path) as f:
                cached_rows = json.load(f).get("n_rows")
        if cached_rows != rows:
            if os.path.exists(train_file):
                log(f"cached corpus has {cached_rows} rows, need {rows}: "
                    f"regenerating")
            from distributed_sgd_tpu.data.corpus import write_rcv1_corpus

            meta = write_rcv1_corpus(folder, n_rows=rows,
                                     n_train=max(rows // 4, 1), seed=seed)
            with open(meta_path, "w") as f:
                json.dump(meta, f)
            log(f"generated corpus: {meta['bytes'] / 1e6:.1f} MB")
        return {"kind": "generated", "seconds": time.perf_counter() - t0}
    if not os.path.exists(train_file):
        os.makedirs(folder, exist_ok=True)
        script = os.path.join(REPO, "data", "download.sh")
        # download.sh fetches into its own directory (it cd's to its
        # dirname); when the target IS data/ run it in place, otherwise
        # copy it into `folder` first
        target = os.path.join(folder, "download.sh")
        if os.path.abspath(target) != os.path.abspath(script):
            import shutil

            shutil.copy(script, target)
        subprocess.run(["bash", target], check=True)
    return {"kind": "real", "seconds": time.perf_counter() - t0}


def parse_stage(folder: str, full_scale: bool) -> tuple:
    """Stage 2: native parse + pack, held to the reference's < 40 s gate."""
    from distributed_sgd_tpu.data.rcv1 import load_rcv1

    t0 = time.perf_counter()
    data = load_rcv1(folder, full=True)
    parse_s = time.perf_counter() - t0
    gate_pass = parse_s < PARSE_GATE_S
    log(f"parsed {len(data)} rows in {parse_s:.1f}s "
        f"(< {PARSE_GATE_S:.0f}s gate: "
        f"{'PASS' if gate_pass else 'FAIL'}"
        f"{'' if full_scale else ', informational at this scale'})")
    if full_scale and not gate_pass:
        raise SystemExit(
            f"parse took {parse_s:.1f}s, over the reference's "
            f"{PARSE_GATE_S:.0f}s gate (DatasetTests.scala:11-23)")
    return data, {"seconds": round(parse_s, 2), "rows": len(data),
                  "gate_pass": gate_pass, "gate_enforced": full_scale}


def scenario_stage(data, max_epochs: int) -> dict:
    """Stage 3: the full application.conf-default scenario on parsed data."""
    from benches import full_scenario

    res, doc = full_scenario.run_scenario(
        dataset=data, max_epochs=max_epochs, generator_tag="parsed corpus")
    return {
        "epochs_run": res.epochs_run,
        "final_test_loss": doc["test_losses"][-1],
        "final_test_acc": doc["test_accs"][-1],
        "test_losses": doc["test_losses"],
        "fit_wall_s": doc["fit_wall_s"],
    }


def bench_stage(data) -> dict:
    """Stage 4: north-star epoch wall-clock on the parsed arrays."""
    import bench

    epoch_s, loss, acc = bench.tpu_epoch_seconds(
        data.indices, data.values, data.labels)
    return {"epoch_seconds": round(float(epoch_s), 4),
            "loss3": round(float(loss), 4), "acc3": round(float(acc), 4)}


def baseline_section(out: dict) -> str:
    s = out["scenario"]
    b = out["bench"]
    p = out["parse"]
    return (
        "\n### Real RCV1 (measured end to end, benches/real_rcv1.py)\n\n"
        f"| quantity | value |\n|---|---|\n"
        f"| corpus | {p['rows']} rows parsed from LYRL2004 files |\n"
        f"| parse wall-clock | {p['seconds']} s "
        f"(reference gate < {PARSE_GATE_S:.0f} s, DatasetTests.scala:11-23: "
        f"{'PASS' if p['gate_pass'] else 'FAIL'}) |\n"
        f"| full-scenario fit | {s['epochs_run']} epochs, final test "
        f"loss {s['final_test_loss']} / acc {s['final_test_acc']} |\n"
        f"| sync epoch wall-clock | {b['epoch_seconds']} s "
        f"(slope fit, bench.py methodology) |\n"
    )


def main(argv) -> int:
    generated = "--generated" in argv
    rows, max_epochs, folder = FULL_ROWS, 10, os.path.join(REPO, "data")
    for i, a in enumerate(argv):
        if a == "--rows":
            rows = int(argv[i + 1])
        elif a == "--max-epochs":
            max_epochs = int(argv[i + 1])
        elif a == "--folder":
            folder = argv[i + 1]
    if generated and folder == os.path.join(REPO, "data"):
        folder = "/tmp/rcv1_turnkey"

    out = {"study": "real_rcv1_turnkey",
           "mode": "generated" if generated else "real"}
    out["files"] = ensure_files(folder, generated, rows)
    full_scale = not generated
    data, out["parse"] = parse_stage(folder, full_scale)
    out["scenario"] = scenario_stage(data, max_epochs)
    out["bench"] = bench_stage(data)

    section = baseline_section(out)
    if generated:
        log("dry-run: BASELINE.md untouched; section would be:")
        log(section)
    else:
        path = os.path.join(REPO, "BASELINE.md")
        with open(path, "a") as f:
            f.write(section)
        log(f"appended the Real-RCV1 section to {path}")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
